#!/usr/bin/env python
"""Referential-policy conformance check (wired tier-1 via
tests/test_join_parity_tool.py; also runnable standalone):

1. Join-plan routing: every referential template family (unique-key /
   required-reference / count-quota) must classify into a vectorized join
   plan (ops/joinkernel.py) — never the interpreter-fallback all-true
   mask — and the audit sweep must record the ``join_plan`` route reason.
2. Width parity: the capped audit over a width-4 virtual mesh must be
   BYTE-identical — rendered messages, resource identities, totals — to
   the width-1 sweep AND the interpreter oracle.  The per-shard
   segment-reduce + all_gather cross-shard merge fails fast here.
3. Key-group churn locality: one churned provider row dispatches exactly
   (dirty + its old/new key groups' reader rows) on the delta path — the
   dispatch row count is pinned to the group size computed independently
   from the raw objects, never the cluster size.  Checked at width 1 and
   under the mesh, including a churn row in the padded mesh tail.

Runs with GK_JOIN_ASSERT=1: any exact-plan cell the interpreter refuses
to render raises instead of being silently filtered.

Run: python tools/check_join_parity.py   (exit 0 clean, 1 with findings;
re-execs onto a virtual 8-device CPU mesh when fewer devices are
visible, like tools/check_mesh_parity.py).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_TEMPLATES = 6
N_RESOURCES = 60
CAP = 4096  # above any per-constraint count: totals exact everywhere
WIDTH = 4
NEW_HOST = "app-0.corp.io"


def _sig(results):
    from gatekeeper_tpu.util.synthetic import audit_result_sig

    return audit_result_sig(results)


def _driver(width):
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import build_referential_driver

    TpuDriver.DELTA_MASK_WAIT_S = 300.0  # determinism on the CPU backend
    client = build_referential_driver(N_TEMPLATES, N_RESOURCES)
    client.driver.set_mesh(width > 1, width=width)
    return client


def _oracle(mutate=None):
    from gatekeeper_tpu.util.synthetic import build_referential_oracle

    client = build_referential_oracle(N_TEMPLATES, N_RESOURCES)
    if mutate is not None:
        mutate(client)
    return client.driver.audit_capped(CAP)


def _churn_victim():
    """(victim object, expected affected reader row names) for the
    ingress-host churn — computed independently from the raw corpus."""
    from gatekeeper_tpu.util.synthetic import make_referential_objects

    objs = make_referential_objects(N_RESOURCES, 1)
    ingresses = [o for o in objs if o["kind"] == "Ingress"]
    victim = dict(ingresses[0])
    old_hosts = {r["host"] for r in victim["spec"]["rules"]}
    host_rows = {}
    for o in ingresses:
        for r in o["spec"]["rules"]:
            host_rows.setdefault(r["host"], set()).add(
                o["metadata"]["name"]
            )
    affected = set()
    for h in old_hosts | {NEW_HOST}:
        affected |= host_rows.get(h, set())
    affected.discard(victim["metadata"]["name"])
    victim = {
        **victim,
        "spec": {"rules": [{"host": NEW_HOST}]},
    }
    return victim, affected


def check_classification() -> list:
    """Every family must compile to a join plan (not interp fallback)."""
    from gatekeeper_tpu.engine.interp import TemplatePolicy
    from gatekeeper_tpu.ops.vectorizer import vectorize
    from gatekeeper_tpu.util.synthetic import make_referential_templates

    problems = []
    templates, _ = make_referential_templates(3)
    for t in templates:
        kind = t["spec"]["crd"]["spec"]["names"]["kind"]
        rego = t["spec"]["targets"][0]["rego"]
        prog = vectorize(TemplatePolicy.compile(rego))
        if prog is None or not prog.join_plans:
            problems.append(
                f"join classification: {kind} did not compile to a join "
                "plan (interpreter fallback)"
            )
        elif not prog.exact:
            problems.append(
                f"join classification: {kind} compiled inexact (some "
                "statement fell out of the plan)"
            )
    return problems


def check_width_parity() -> list:
    """Width-4 mesh sweep vs width-1 sweep vs interpreter oracle, plus
    the join_plan route-ledger attribution."""
    problems = []
    oracle_r, oracle_t, _ = _oracle()
    oracle_sig = _sig(oracle_r)
    for w in (1, WIDTH):
        client = _driver(w)
        d = client.driver
        res, totals, _ = d.audit_capped(CAP)
        stats = d.last_sweep_stats
        if stats.get("join_plans") != 3.0:
            problems.append(
                f"width {w}: sweep stats carry join_plans="
                f"{stats.get('join_plans')} (expected 3 — join kernels "
                "did not serve the sweep)"
            )
        counts = d.route_ledger.snapshot().get("counts", {})
        if not any(k.endswith("|join_plan") for k in counts):
            problems.append(
                f"width {w}: no join_plan route-ledger entry recorded "
                f"(counts {counts})"
            )
        if _sig(res) != oracle_sig:
            problems.append(
                f"width {w}: rendered results diverge from the "
                "interpreter oracle"
            )
        if totals != oracle_t:
            problems.append(
                f"width {w}: per-constraint totals diverge: "
                f"{totals} != {oracle_t}"
            )
    return problems


def check_churn_locality() -> list:
    """Delta dispatch rows == dirty + affected key-group readers, with
    post-churn byte parity, at width 1 and under the mesh."""
    problems = []
    victim, affected = _churn_victim()
    oracle_r, oracle_t, _ = _oracle(
        mutate=lambda c: c.add_data(dict(victim))
    )
    oracle_sig = _sig(oracle_r)
    for w in (1, WIDTH):
        client = _driver(w)
        d = client.driver
        d.audit_capped(CAP)  # full sweep rebases basis + join index
        client.add_data(dict(victim))
        res, totals, _ = d.audit_capped(CAP)
        stats = d.last_sweep_stats
        if stats.get("delta_rows") != float(1 + len(affected)):
            problems.append(
                f"width {w} churn locality: expected a delta dispatch of "
                f"1 dirty + {len(affected)} key-group reader rows, got "
                f"stats {stats}"
            )
        if stats.get("join_affected_rows") != float(len(affected)):
            problems.append(
                f"width {w} churn locality: join_affected_rows="
                f"{stats.get('join_affected_rows')} != {len(affected)}"
            )
        if _sig(res) != oracle_sig or totals != oracle_t:
            problems.append(
                f"width {w}: post-churn results diverge from the oracle"
            )
    return problems


def check_padded_tail_churn() -> list:
    """Churn in the mesh's padded tail slab (the last live rows before
    the capacity padding) must stay on the delta path with parity."""
    problems = []
    from gatekeeper_tpu.util.synthetic import make_referential_objects

    objs = make_referential_objects(N_RESOURCES, 1)
    pods = [o for o in objs if o["kind"] == "Pod"]
    victim = dict(pods[-1])  # among the last-packed rows -> tail slab
    victim = {
        **victim,
        "metadata": {**victim["metadata"],
                     "labels": {"team": "tailchurn"}},
    }
    oracle_r, oracle_t, _ = _oracle(
        mutate=lambda c: c.add_data(dict(victim))
    )
    client = _driver(WIDTH)
    d = client.driver
    d.audit_capped(CAP)
    client.add_data(dict(victim))
    res, totals, _ = d.audit_capped(CAP)
    stats = d.last_sweep_stats
    if "delta_rows" not in stats:
        problems.append(
            f"padded-tail churn fell off the delta path: {stats}"
        )
    if _sig(res) != _sig(oracle_r) or totals != oracle_t:
        problems.append("padded-tail churn diverges from the oracle")
    return problems


def run_checks() -> list:
    return (
        check_classification()
        + check_width_parity()
        + check_churn_locality()
        + check_padded_tail_churn()
    )


def _reexec_on_virtual_mesh() -> int:
    import subprocess

    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(8)
    env["GK_JOIN_PARITY_REEXEC"] = "1"
    env["GK_JOIN_ASSERT"] = "1"
    return subprocess.call([sys.executable, os.path.abspath(__file__)],
                           env=env)


def main() -> int:
    import jax

    if (len(jax.devices()) < WIDTH
            and not os.environ.get("GK_JOIN_PARITY_REEXEC")):
        return _reexec_on_virtual_mesh()
    os.environ.setdefault("GK_JOIN_ASSERT", "1")
    problems = run_checks()
    for p in problems:
        print(f"FINDING: {p}")
    if problems:
        print(f"{len(problems)} finding(s)")
        return 1
    print("join-parity conformance: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
