#!/usr/bin/env python
"""gklint: repo-invariant static analyzer for gatekeeper_tpu.

Checks the concurrency, tracing, failure-policy, resource-hygiene and
registry invariants this codebase has paid for at runtime (rule catalog
with incident history: docs/static-analysis.md).  Wired into tier-1 via
tests/test_gklint_tool.py; also part of `make lint`.

Usage:
  python tools/gklint.py [paths...]          lint (default: gatekeeper_tpu/)
  python tools/gklint.py --list-rules        print the rule catalog
  python tools/gklint.py --format=json       machine-readable findings
  python tools/gklint.py --write-baseline    accept current findings
  python tools/gklint.py --no-baseline       ignore the committed baseline

Exit status: 0 clean, 1 unsuppressed findings, 2 usage error.

Suppressions:  # gklint: disable=<rule>[,<rule>] -- <reason>
(same line, or a standalone comment line above; reason is mandatory).
File-level:    # gklint: disable-file=<rule> -- <reason>
Baseline:      .gklint-baseline.json at the repo root absorbs accepted
findings by (rule, path, scope); prefer fixing or inline suppression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gatekeeper_tpu import analysis  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gklint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: "
                         "gatekeeper_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <repo>/"
                         f"{analysis.BASELINE_NAME} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baseline-accepted findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--root", default=REPO,
                    help="repo root for relative paths + doc cross-checks")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in analysis.RULES)
        for rule in sorted(analysis.RULES):
            print(f"{rule:<{width}}  {analysis.RULES[rule]}")
        return 0

    root = os.path.abspath(args.root)
    paths = args.paths or [os.path.join(root, "gatekeeper_tpu")]
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = select - set(analysis.RULES)
        if unknown:
            print(f"gklint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    findings = analysis.lint(root, paths, select=select)

    baseline_path = args.baseline or os.path.join(
        root, analysis.BASELINE_NAME
    )
    if args.write_baseline:
        if select is not None or args.paths:
            # a baseline written from a narrowed run would silently DROP
            # every accepted finding outside the subset; the next full
            # run then fails on findings that were deliberately banked
            print(
                "gklint: --write-baseline requires a full default run "
                "(no --select, no explicit paths) — the baseline is "
                "whole-repo state, not a per-subset overlay",
                file=sys.stderr,
            )
            return 2
        analysis.write_baseline(baseline_path, findings)
        print(f"gklint: baseline written to {baseline_path} "
              f"({len(findings)} finding(s))")
        return 0
    if not args.no_baseline and os.path.exists(baseline_path):
        findings = analysis.apply_baseline(
            findings, analysis.load_baseline(baseline_path)
        )

    if args.format == "json":
        print(json.dumps(
            {"findings": [f.to_json() for f in findings],
             "count": len(findings)},
            indent=2,
        ))
    else:
        for f in findings:
            print(f.render(), file=sys.stderr)
        if findings:
            print(f"gklint: {len(findings)} unsuppressed finding(s)",
                  file=sys.stderr)
        else:
            print("gklint: ok")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
