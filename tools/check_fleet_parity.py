#!/usr/bin/env python
"""Fleet serving conformance check (wired tier-1 via
tests/test_fleet_parity_tool.py; also runnable standalone):

1. Replica byte-parity: the same AdmissionReview POSTed to every fleet
   replica (each a separate PROCESS restoring the same sealed snapshot)
   must produce BYTE-identical response bodies, identical to a solo
   replica serving outside the fleet — the single-process path.  A
   divergence here means shared-warmth restore drifted between
   processes, the one bug class a fleet can ship that a single process
   cannot.
2. Front-door fidelity: the body returned through the front door must be
   byte-identical to what the chosen backend answered (the door must
   never rewrite a verdict), and the X-GK-Replica attribution must name
   a real backend.
3. Oracle parity: allow/deny and the rendered violation text (sans the
   webhook's "[denied by ...]" prefix) must match a freshly loaded
   interpreter oracle evaluating the same requests byte-for-byte.
4. Event-edge fidelity (ISSUE 19): the same corpus through the
   selectors-based front door — persistent connections, batched wire
   protocol to the replicas' wire listeners — must answer byte-identical
   bodies too.  The door is a byte splice on both edges or it is wrong.

Run: python tools/check_fleet_parity.py [--edge threaded|evloop|both]
(exit 0 clean, 1 with findings).  Spawns 3 replica subprocesses; where
process spawn is unavailable the tier-1 wrapper skips cleanly.
"""

from __future__ import annotations

import http.client
import json
import os
import re
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_TEMPLATES = 4
N_RESOURCES = 48
N_REQUESTS = 24


def _sample_requests():
    from gatekeeper_tpu.util.synthetic import make_pods

    pods = make_pods(N_REQUESTS, seed=77, violation_rate=0.5)
    reqs = []
    for i, p in enumerate(pods):
        reqs.append({
            "uid": f"fleet-parity-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "fleet-parity"},
            "object": p,
        })
    return reqs


def _post(port: int, body: bytes, path: str = "/v1/admit"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _oracle_verdicts(reqs):
    from gatekeeper_tpu.util.synthetic import build_oracle

    oracle = build_oracle(N_TEMPLATES, N_RESOURCES)
    out = []
    for req in reqs:
        results = oracle.review(
            {k: req[k] for k in
             ("kind", "name", "namespace", "operation", "object")}
        ).results()
        out.append((not results, sorted(r.msg for r in results)))
    return out


def diff_verdicts(raw_bodies, oracle_verdicts) -> list:
    """Pure comparison core (unit-testable without processes):
    raw_bodies is {replica_id: [bytes per request]} including the
    'solo' single-process replica; oracle_verdicts is
    [(allowed, sorted violation messages)].  -> list of problem
    strings.  Violation text is compared byte-for-byte after stripping
    the webhook's "[denied by <constraint>] " prefix (reference
    log_denies format) — count-only parity would pass a renderer that
    produces the right number of wrong messages."""
    problems = []
    ids = sorted(raw_bodies)
    n = min(len(v) for v in raw_bodies.values())
    for i in range(n):
        bodies = {rid: raw_bodies[rid][i] for rid in ids}
        if len(set(bodies.values())) != 1:
            problems.append(
                f"request {i}: replica responses diverge "
                f"({', '.join(f'{r}={len(b)}B' for r, b in bodies.items())})"
            )
            continue
        out = json.loads(bodies[ids[0]])["response"]
        allowed = out["allowed"]
        msgs = sorted(
            re.sub(r"^\[denied by [^\]]+\] ", "", m)
            for m in (out.get("status") or {}).get(
                "message", "").split("\n") if m
        ) if not allowed else []
        o_allowed, o_msgs = oracle_verdicts[i]
        if allowed != o_allowed:
            problems.append(
                f"request {i}: fleet allowed={allowed} but the "
                f"interpreter oracle says {o_allowed}"
            )
        elif not allowed and msgs != o_msgs:
            problems.append(
                f"request {i}: fleet rendered {msgs}, "
                f"oracle {o_msgs}"
            )
    return problems


def run_checks(edge: str = "both") -> list:
    import shutil

    from gatekeeper_tpu.fleet import (
        EventFrontDoor,
        FrontDoor,
        spawn_fleet,
        spawn_replica,
    )
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.synthetic import build_driver

    problems: list = []
    root = tempfile.mkdtemp(prefix="gk-fleet-parity-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)
    solo = None
    fleet = []
    door = None
    try:
        client = build_driver(N_TEMPLATES, N_RESOURCES)
        client.audit_capped(50)
        if Snapshotter(client, snap_dir, interval_s=0.0).write_once() is None:
            return ["snapshot write failed; cannot stage the fleet"]

        reqs = _sample_requests()
        oracle_verdicts = _oracle_verdicts(reqs)

        env = {"JAX_PLATFORMS": "cpu"}
        solo = spawn_replica("solo", snap_dir, cache_dir, env=env)
        fleet = spawn_fleet(2, snapshot_dir=snap_dir, cache_dir=cache_dir,
                            env=env)
        for h in [solo] + fleet:
            if h.ready.get("restore_outcome") != "restored":
                problems.append(
                    f"replica {h.replica_id} restored "
                    f"{h.ready.get('restore_outcome')!r}, not the shared "
                    f"snapshot — parity would compare cold processes"
                )
        if problems:
            return problems
        if edge in ("threaded", "both"):
            door = FrontDoor([h.backend() for h in fleet]).start()

        raw: dict = {h.replica_id: [] for h in [solo] + fleet}
        door_bodies = []
        for i, req in enumerate(reqs):
            body = json.dumps({"request": req}).encode()
            for h in [solo] + fleet:
                st, _hd, data = _post(h.port, body)
                if st != 200:
                    problems.append(
                        f"request {i}: replica {h.replica_id} "
                        f"answered {st}"
                    )
                raw[h.replica_id].append(data)
            if door is None:
                continue
            st, hd, data = _post(door.port, body)
            if st != 200:
                problems.append(f"request {i}: front door answered {st}")
            rid = hd.get("X-GK-Replica", "")
            if rid not in raw:
                problems.append(
                    f"request {i}: front door attributed to unknown "
                    f"replica {rid!r}"
                )
            door_bodies.append(data)

        problems += diff_verdicts(raw, oracle_verdicts)

        # front-door fidelity: the forwarded body is exactly what the
        # replicas answer (replica parity already verified above)
        for i, data in enumerate(door_bodies):
            if data != raw["solo"][i]:
                problems.append(
                    f"request {i}: front door body differs from the "
                    f"replica answer (door {len(data)}B, "
                    f"replica {len(raw['solo'][i])}B)"
                )

        # event-loop edge (ISSUE 19): the same corpus through the
        # selectors door + batched wire protocol.  The replica parses
        # the AdmissionReview once at its wire listener and the door
        # splices bytes both ways, so the body must STILL be identical
        # to what the HTTP listener answers for the same request.
        if edge in ("evloop", "both"):
            missing = [h.replica_id for h in fleet if not h.wire_port]
            if missing:
                return problems + [
                    f"replicas {missing} announced no wire_port — the "
                    "event edge cannot be driven"
                ]
            evdoor = EventFrontDoor(
                [h.wire_backend() for h in fleet]).start()
            try:
                for i, req in enumerate(reqs):
                    body = json.dumps({"request": req}).encode()
                    st, hd, data = _post(evdoor.port, body)
                    if st != 200:
                        problems.append(
                            f"request {i}: event-loop door answered {st}"
                        )
                        continue
                    rid = hd.get("X-GK-Replica", "")
                    if rid not in raw:
                        problems.append(
                            f"request {i}: event-loop door attributed "
                            f"to unknown replica {rid!r}"
                        )
                    if data != raw["solo"][i]:
                        problems.append(
                            f"request {i}: event-edge body differs from "
                            f"the replica answer (edge {len(data)}B, "
                            f"replica {len(raw['solo'][i])}B)"
                        )
            finally:
                evdoor.stop()
        return problems
    finally:
        if door is not None:
            door.stop()
        for h in fleet:
            h.stop()
        if solo is not None:
            solo.stop()
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--edge", choices=("threaded", "evloop", "both"),
                    default="evloop",
                    help="which serving edge(s) to drive the corpus "
                         "through (default: evloop — the threaded "
                         "FrontDoor is deprecated and must be asked for "
                         "explicitly, or use 'both' for back-to-back)")
    args = ap.parse_args()
    problems = run_checks(edge=args.edge)
    if problems:
        print("fleet parity check FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(
        f"fleet parity ok: {N_REQUESTS} requests byte-identical across "
        f"solo + 2 fleet replicas, front-door fidelity verified on the "
        f"{args.edge} edge(s), verdicts match the interpreter oracle"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
