#!/usr/bin/env python
"""Differential replay of recorded admission decisions (ISSUE 15).

The decision log (gatekeeper_tpu/obs/decisionlog.py) archives every
admission verdict with the AdmissionReview request embedded.  This tool
closes the loop: it re-evaluates each recorded request against the
CURRENT engine and asserts verdict + message BYTE parity (via the shared
sha256 message digest), reporting any drift with route attribution — the
recorded route tier/reason next to the tier the live router chose.  The
archive thereby becomes a continuous differential oracle seeded from
real traffic: an engine change that silently flips a verdict fails here
before it fails a cluster (the dynamic half of the cross-layer
verification discipline; gklint is the static half).

What replays:

- ``admission`` records of class ``allow``/``deny`` with an unmasked
  embedded request.  Sheds, deadline expiries and internal errors are
  load/time-dependent, not engine-determined — they are skipped and
  counted (``skipped_transient``), as are masked records
  (``skipped_masked``) and audit transitions.

Seal verification: segments whose records carry ``sig`` are chain-
verified before replay; ``--require-seal`` makes any unsealed or broken
record fatal (rc 2).

Usage:

  replay_decisions.py --log-dir D --snapshot-dir S   restore the sealed
        snapshot (templates, constraints, inventory) and replay D
  replay_decisions.py --log-dir D --bug-compat       replay under
        GK_BUG_COMPAT=1 (expected to drift where docs/rego.md documents
        divergences — the seeded-oracle mode)
  replay_decisions.py --selftest                     end-to-end proof on
        a synthetic corpus: records decisions, replays them at zero
        drift, then replays under GK_BUG_COMPAT=1 and REQUIRES the
        seeded divergence to be flagged.  Wired tier-1 via
        tests/test_replay_tool.py and ``make replay-check``.

Exit codes: 0 parity (selftest: parity AND seeded drift flagged),
1 drift, 2 usage/seal/engine error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---- archive loading --------------------------------------------------------


def load_records(log_dir: str,
                 require_seal: bool = False) -> Tuple[List[dict], List[str]]:
    """Records from every completed segment under ``log_dir`` (oldest
    first), plus seal problems.  Sealed segments are chain-verified;
    with ``require_seal`` an unsealed record is a problem too."""
    from gatekeeper_tpu.obs import decisionlog as dlog

    records: List[dict] = []
    problems: List[str] = []
    for path in dlog.segment_paths(log_dir):
        # ONE read + parse per segment serves both the chain check and
        # record loading (verify_segment semantics, inlined: sealed
        # records are always chain-verified; a fully-unsealed segment
        # is a problem only under require_seal — but a MIXED segment is
        # flagged unconditionally: unsealed lines spliced between
        # sealed ones leave the chain intact, so without this check a
        # fabricated record would enter the replay corpus silently)
        prev = ""
        saw_sealed = saw_unsealed = False
        try:
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        problems.append(
                            f"{path}:{lineno}: unparseable record"
                        )
                        prev = ""
                        continue
                    sig = rec.get("sig")
                    if sig is None:
                        saw_unsealed = True
                        if require_seal:
                            problems.append(
                                f"{path}:{lineno}: record is unsealed"
                            )
                    else:
                        saw_sealed = True
                        if dlog.chain_sig(prev, rec) != sig:
                            problems.append(
                                f"{path}:{lineno}: seal chain broken "
                                "(record edited, reordered, or chained "
                                "to a tampered predecessor)"
                            )
                        prev = sig
                    records.append(rec)
        except OSError as e:
            problems.append(f"{path}: unreadable: {e}")
        if saw_sealed and saw_unsealed and not require_seal:
            problems.append(
                f"{path}: mixed sealed and unsealed records — unsealed "
                "lines in a sealed segment bypass the chain (possible "
                "insertion)"
            )
    return records, problems


# ---- replay -----------------------------------------------------------------


def replay_records(handler, records: List[dict],
                   max_drift: int = 64) -> dict:
    """Re-evaluate recorded admissions against ``handler`` (a
    ValidationHandler) and diff verdict + message digest.  Recording is
    paused for the duration so replayed requests are never re-archived
    into the corpus they came from."""
    from gatekeeper_tpu.obs import decisionlog as dlog
    from gatekeeper_tpu.obs import routeledger

    log = dlog.get_log()
    was_recording = log.record_enabled
    log.record_enabled = False
    report = {
        "replayed": 0,
        "drift": [],
        "drift_count": 0,
        "skipped_masked": 0,
        "skipped_transient": 0,
        "skipped_other": 0,
    }
    try:
        for rec in records:
            if rec.get("kind") != dlog.KIND_ADMISSION:
                report["skipped_other"] += 1
                continue
            if rec.get("masked"):
                report["skipped_masked"] += 1
                continue
            if rec.get("class") not in (dlog.CLASS_ALLOW, dlog.CLASS_DENY):
                report["skipped_transient"] += 1
                continue
            req = rec.get("request")
            if not isinstance(req, dict):
                report["skipped_other"] += 1
                continue
            resp = handler.handle(req)
            digest = dlog.message_digest(resp.message)
            recorded = rec.get("verdict") or {}
            ok = (
                bool(resp.allowed) == bool(recorded.get("allowed"))
                and int(resp.code) == int(recorded.get("code", 0))
                and digest == rec.get("message_sha256")
            )
            report["replayed"] += 1
            if not ok:
                report["drift_count"] += 1
                if len(report["drift"]) < max_drift:
                    ledger = routeledger.get_active()
                    now_route = ledger.last() if ledger is not None \
                        else None
                    report["drift"].append({
                        "uid": rec.get("uid"),
                        "seq": rec.get("seq"),
                        "recorded": {
                            "class": rec.get("class"),
                            "verdict": recorded,
                            "message_sha256": rec.get("message_sha256"),
                            "route": rec.get("route"),
                        },
                        "replayed": {
                            "allowed": bool(resp.allowed),
                            "code": int(resp.code),
                            "message_sha256": digest,
                            "message": (resp.message or "")[:256],
                            "route": (
                                {"tier": now_route[0],
                                 "reason": now_route[1]}
                                if now_route else None
                            ),
                        },
                    })
    finally:
        log.record_enabled = was_recording
    return report


def build_handler_from_snapshot(snapshot_dir: str):
    """The CLI's engine: a fresh TpuDriver client restored from the
    sealed snapshot (templates, constraints, packed inventory), handed
    to a ValidationHandler over the restored in-memory store."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.snapshot import SnapshotLoader
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    client = Client(driver=TpuDriver())
    kube = InMemoryKube()
    outcome = SnapshotLoader(snapshot_dir).restore(
        client, kube, resync=False
    )
    if outcome != "restored":
        raise RuntimeError(
            f"snapshot restore outcome {outcome!r}: the replay engine "
            "must be the archived policy set, not a cold guess"
        )
    return ValidationHandler(client, kube=kube)


# ---- selftest ---------------------------------------------------------------

# a template whose verdict flips under GK_BUG_COMPAT (docs/rego.md:
# regex.globs_match("", "") is false here, true in the reference) — the
# no-compat verdict is a DENY, so the record is always-kept under any
# sampling and the seeded divergence cannot hide in a sampled-out allow
_COMPAT_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "replayglobs"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "ReplayGlobs"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package replayglobs

violation[{"msg": msg}] {
  g := input.review.object.metadata.labels.glob
  not regex.globs_match(g, "")
  msg := sprintf("glob label %v shares no string with the empty glob on %v", [g, input.review.object.metadata.name])
}
""",
        }],
    },
}
_COMPAT_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "ReplayGlobs",
    "metadata": {"name": "replay-globs"},
    "spec": {
        "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
    },
}


def _selftest_handler(seed: int = 15):
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_templates
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    templates, constraints = make_templates(4, seed=seed)
    client = Client(driver=TpuDriver())
    for t in templates + [_COMPAT_TEMPLATE]:
        client.add_template(t)
    for cons in constraints + [_COMPAT_CONSTRAINT]:
        client.add_constraint(cons)
    return ValidationHandler(client)


def selftest_requests(n: int = 40, divergent: int = 4,
                      violation_rate: float = 0.25) -> List[dict]:
    from gatekeeper_tpu.util.synthetic import make_pods

    pods = make_pods(n, seed=15, violation_rate=violation_rate)
    for pod in pods[:divergent]:
        # the GK_BUG_COMPAT oracle rows: denied now, allowed under compat
        pod["metadata"]["labels"]["glob"] = ""
    return [{
        "uid": f"replay-{i}",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": p["metadata"]["name"],
        "namespace": p["metadata"]["namespace"],
        "operation": "CREATE",
        "object": p,
    } for i, p in enumerate(pods)]


def run_selftest(verbose: bool = True) -> int:
    """Record a synthetic corpus, replay it at zero drift against the
    live engine, then replay under GK_BUG_COMPAT=1 against a FRESH
    engine (per-call env read; a fresh client defeats the content-keyed
    request memo) and require the seeded divergence to be flagged."""
    import tempfile

    from gatekeeper_tpu.obs import decisionlog as dlog

    def say(msg):
        if verbose:
            print(f"replay_decisions selftest: {msg}")

    log_dir = tempfile.mkdtemp(prefix="gk-decisions-")
    log = dlog.get_log()
    log.configure(dir=log_dir, seal=True, sample_rate=1.0)
    log.record_enabled = True
    log.start()
    try:
        return _selftest_body(say, log, log_dir)
    finally:
        # the recorder is process-global: leave it detached and the
        # corpus removed on EVERY exit path, or later work in an
        # embedding process keeps archiving into this tmp dir
        import shutil

        log.stop()
        log.clear()
        log.configure(dir="", sample_rate=1.0, seal=False)
        log.record_enabled = True
        shutil.rmtree(log_dir, ignore_errors=True)


def _selftest_body(say, log, log_dir) -> int:
    from gatekeeper_tpu.obs import decisionlog as dlog

    handler = _selftest_handler()
    reqs = selftest_requests()
    denied = 0
    for req in reqs:
        resp = handler.handle(req)
        denied += 0 if resp.allowed else 1
    log.flush()
    records, problems = load_records(log_dir, require_seal=True)
    if problems:
        for p in problems:
            say(f"seal problem: {p}")
        return 2
    admissions = [r for r in records
                  if r.get("kind") == dlog.KIND_ADMISSION]
    if len(admissions) != len(reqs):
        say(f"recorded {len(admissions)} admissions for {len(reqs)} "
            "requests")
        return 2
    say(f"recorded {len(admissions)} admissions ({denied} denied, "
        f"sealed, {len(dlog.segment_paths(log_dir))} segment(s))")

    baseline = replay_records(handler, records)
    say(f"baseline replay: {baseline['replayed']} replayed, "
        f"{baseline['drift_count']} drift")
    if baseline["drift_count"] != 0:
        for d in baseline["drift"]:
            say(f"unexpected drift: {json.dumps(d)}")
        return 1

    prev = os.environ.get("GK_BUG_COMPAT")
    os.environ["GK_BUG_COMPAT"] = "1"
    try:
        compat = replay_records(_selftest_handler(), records)
    finally:
        if prev is None:
            os.environ.pop("GK_BUG_COMPAT", None)
        else:
            os.environ["GK_BUG_COMPAT"] = prev
    say(f"GK_BUG_COMPAT replay: {compat['replayed']} replayed, "
        f"{compat['drift_count']} drift")
    if compat["drift_count"] == 0:
        say("seeded GK_BUG_COMPAT divergence was NOT flagged — the "
            "differential oracle is blind")
        return 1
    sample = compat["drift"][0]
    say(f"seeded drift flagged (e.g. uid={sample['uid']}: recorded "
        f"{sample['recorded']['verdict']} -> replayed "
        f"allowed={sample['replayed']['allowed']})")
    say("ok")
    return 0


# ---- CLI --------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--log-dir", help="decision-log segment directory")
    ap.add_argument("--snapshot-dir",
                    help="sealed snapshot to restore the engine from")
    ap.add_argument("--require-seal", action="store_true",
                    help="fail (rc 2) on any unsealed or chain-broken "
                         "record")
    ap.add_argument("--bug-compat", action="store_true",
                    help="replay under GK_BUG_COMPAT=1 (seeded-oracle "
                         "mode: documented divergences SHOULD drift)")
    ap.add_argument("--max-drift", type=int, default=64,
                    help="drift entries detailed in the report")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic end-to-end proof (record -> zero "
                         "drift -> seeded GK_BUG_COMPAT drift flagged)")
    args = ap.parse_args(argv)

    if args.selftest:
        return run_selftest()
    if not args.log_dir or not args.snapshot_dir:
        ap.error("--log-dir and --snapshot-dir are required "
                 "(or --selftest)")
    records, problems = load_records(args.log_dir,
                                     require_seal=args.require_seal)
    for p in problems:
        print(f"replay_decisions: {p}", file=sys.stderr)
    if problems and args.require_seal:
        return 2
    try:
        handler = build_handler_from_snapshot(args.snapshot_dir)
    except Exception as e:
        print(f"replay_decisions: engine restore failed: {e}",
              file=sys.stderr)
        return 2
    prev = os.environ.get("GK_BUG_COMPAT")
    if args.bug_compat:
        os.environ["GK_BUG_COMPAT"] = "1"
    try:
        report = replay_records(handler, records,
                                max_drift=args.max_drift)
    finally:
        if args.bug_compat:
            if prev is None:
                os.environ.pop("GK_BUG_COMPAT", None)
            else:
                os.environ["GK_BUG_COMPAT"] = prev
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(
            f"replay_decisions: {report['replayed']} replayed, "
            f"{report['drift_count']} drift, "
            f"{report['skipped_transient']} transient skipped, "
            f"{report['skipped_masked']} masked skipped"
        )
        for d in report["drift"]:
            print(f"replay_decisions: DRIFT {json.dumps(d)}",
                  file=sys.stderr)
    return 1 if report["drift_count"] else 0


if __name__ == "__main__":
    sys.exit(main())
