#!/usr/bin/env python
"""Overload-robustness conformance check (ISSUE 12; wired tier-1 via
tests/test_overload_tool.py, also runnable standalone):

Two replicas restore one sealed snapshot behind the front door, with
the overload plane armed tight (replica ``--webhook-max-pending 8``,
door ``max_inflight=1`` + a 2s admission budget).  A short saturation
burst (closed-loop client threads well past capacity) drives the door;
the check asserts the overload contract of docs/failure-modes.md:

1. **sheds happen and are explicit** — past the bounds, requests answer
   429 at the door (or a 200-wrapped 429/504 verdict from the replica),
   every refusal a well-formed AdmissionReview carrying the explicit
   fail-open/closed decision — never a hang, never a bare error;
2. **sheds are fast** — door-level 429s answer in milliseconds (p99
   bounded loosely here for CI noise; bench.py overload records the
   tight single-digit-ms number);
3. **zero verdict divergence among accepted requests** — every request
   that WAS admitted through the storm answers byte-identically to a
   freshly loaded interpreter oracle (shedding drops requests, never
   accuracy);
4. **nothing unexplained** — no 502s, no connection errors, no
   responses outside the (accepted | shed | expired) taxonomy.

Run: python tools/check_overload.py [--edge threaded|evloop|both]
(exit 0 clean, 1 with findings).  ``--edge evloop`` drives the same
burst through the ISSUE 19 selectors-based front door and the replicas'
wire listeners — the overload contract is edge-independent and tier-1
proves it on both via ``--edge both`` (one fleet, both doors back to
back).  Spawns replica subprocesses; where spawn is unavailable the
tier-1 wrapper skips cleanly (same contract as check_self_heal).
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from gatekeeper_tpu.util.overloadcheck import (  # noqa: E402
    classify_response,
    verdict_matches,
)

N_TEMPLATES = 2
N_RESOURCES = 64
N_CORPUS = 48
N_CLIENTS = 10          # closed-loop threads, far past a 1-inflight door
BURST_S = 3.0
MAX_PENDING = 8         # replica-side batcher bound
MAX_INFLIGHT = 1        # door-side per-backend bound
BUDGET_S = 2.0          # door admission budget
SHED_P99_BOUND_S = 0.25  # loose CI bound; the bench records the tight one


def _requests():
    from gatekeeper_tpu.util.synthetic import make_pods

    pods = make_pods(N_CORPUS, seed=47, violation_rate=0.4)
    out = []
    for i, p in enumerate(pods):
        out.append({
            "uid": f"overload-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "overload-check"},
            "object": p,
        })
    return out


def _oracle_verdicts(reqs):
    from gatekeeper_tpu.util.synthetic import build_oracle

    oracle = build_oracle(N_TEMPLATES, N_RESOURCES)
    out = []
    for req in reqs:
        results = oracle.review(
            {k: req[k] for k in
             ("kind", "name", "namespace", "operation", "object")}
        ).results()
        out.append((not results, sorted(r.msg for r in results)))
    return out


# shared with bench.py overload so the tier-1 gate and the recorded
# artifact classify the SAME wire behavior the same way
classify = classify_response
_verdict_matches = verdict_matches


def _drive_door(door, edge: str, reqs, bodies, oracle_verdicts) -> list:
    problems: list = []
    try:
        results: list = []  # (kind, dur_s, status, out, corpus_idx)
        lock = threading.Lock()
        stop = time.monotonic() + BURST_S

        def slam(tid: int):
            i = tid
            while time.monotonic() < stop:
                idx = i % len(reqs)
                i += N_CLIENTS
                t0 = time.perf_counter()
                try:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", door.port, timeout=30)
                    conn.request(
                        "POST", "/v1/admit", body=bodies[idx],
                        headers={"Content-Type": "application/json"})
                    r = conn.getresponse()
                    data = r.read()
                    conn.close()
                    status = r.status
                except Exception:
                    status, data = 0, b""
                dur = time.perf_counter() - t0
                kind, out = classify(status, data)
                with lock:
                    results.append((kind, dur, status, out, idx))

        threads = [threading.Thread(target=slam, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            if t.is_alive():
                problems.append("a burst client wedged past the join "
                                "budget — a refusal path is hanging")
                return problems

        by_kind: dict = {}
        for kind, *_rest in results:
            by_kind[kind] = by_kind.get(kind, 0) + 1
        if not results:
            return ["the burst produced no results at all"]
        if by_kind.get("problem"):
            bad = [(st, out) for k, _d, st, out, _i in results
                   if k == "problem"][:5]
            problems.append(
                f"{by_kind['problem']} responses outside the "
                f"accepted|shed|expired taxonomy (first: {bad})"
            )
        if not by_kind.get("shed"):
            problems.append(
                f"the saturation burst never shed "
                f"({by_kind}) — the bounds did not engage"
            )
        if not by_kind.get("accepted"):
            problems.append(
                f"everything was refused ({by_kind}) — no goodput "
                "under overload is collapse by another name"
            )
        if problems:
            return problems

        # sheds fast: door-level 429s (no proxy hop on that path)
        door_sheds = sorted(
            d for k, d, st, _o, _i in results
            if k == "shed" and st == 429
        )
        if door_sheds:
            p99 = door_sheds[min(int(0.99 * len(door_sheds)),
                                 len(door_sheds) - 1)]
            if p99 > SHED_P99_BOUND_S:
                problems.append(
                    f"door-shed p99 {p99 * 1e3:.1f}ms exceeds the "
                    f"{SHED_P99_BOUND_S * 1e3:.0f}ms bound — refusals "
                    "are queueing somewhere"
                )

        # zero verdict divergence among accepted
        divergences = 0
        for kind, _d, _st, out, idx in results:
            if kind != "accepted":
                continue
            if not _verdict_matches(out, oracle_verdicts[idx]):
                divergences += 1
        if divergences:
            problems.append(
                f"{divergences} accepted verdicts diverged from the "
                "oracle during the shedding burst"
            )

        print(
            f"overload [{edge}]: {len(results)} responses in "
            f"{BURST_S:.0f}s — {by_kind}; door sheds {len(door_sheds)} "
            f"(p99 {door_sheds[-1] * 1e3:.1f}ms max) ; door stats "
            f"{json.dumps(door.stats()['retry_budget'])}",
            file=sys.stderr,
        )
        return problems
    finally:
        door.stop()


def run_checks(edge: str = "evloop") -> list:
    """Drive the saturation burst through the requested serving edge(s).

    ``edge="both"`` stages ONE snapshot + replica fleet and drives the
    threaded door and the event-loop door against it back to back —
    the fleet spawn dominates the tool's runtime, and the contract
    being asserted is a property of the doors, not of the replicas.
    """
    import shutil

    from gatekeeper_tpu.fleet import EventFrontDoor, FrontDoor, spawn_fleet
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.synthetic import build_driver

    problems: list = []
    root = tempfile.mkdtemp(prefix="gk-overload-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)
    handles: list = []
    try:
        client = build_driver(N_TEMPLATES, N_RESOURCES)
        client.audit_capped(50)
        if Snapshotter(client, snap_dir, interval_s=0.0).write_once() is None:
            return ["snapshot write failed; cannot stage the fleet"]
        reqs = _requests()
        oracle_verdicts = _oracle_verdicts(reqs)
        bodies = [json.dumps({"request": r}).encode() for r in reqs]

        handles = spawn_fleet(
            2, snapshot_dir=snap_dir, cache_dir=cache_dir,
            env={"JAX_PLATFORMS": "cpu"},
            extra_flags=["--webhook-max-pending", str(MAX_PENDING)],
        )
        edges = ("threaded", "evloop") if edge == "both" else (edge,)
        for e in edges:
            if e == "evloop":
                missing = [h.replica_id for h in handles if not h.wire_port]
                if missing:
                    problems.append(
                        f"replicas {missing} announced no wire_port — "
                        "the event edge cannot be driven")
                    continue
                door = EventFrontDoor(
                    [h.wire_backend() for h in handles],
                    probe_interval_s=0.1, max_inflight=MAX_INFLIGHT,
                    admission_budget_s=BUDGET_S,
                ).start()
            else:
                door = FrontDoor(
                    [h.backend() for h in handles], probe_interval_s=0.1,
                    max_inflight=MAX_INFLIGHT, admission_budget_s=BUDGET_S,
                ).start()
            problems.extend(
                f"[{e}] {p}"
                for p in _drive_door(door, e, reqs, bodies, oracle_verdicts))
        return problems
    finally:
        for h in handles:
            h.stop()
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--edge", choices=("threaded", "evloop", "both"),
                    default="evloop",
                    help="which serving edge to saturate (default: the "
                         "event-loop door + wire listeners; the threaded "
                         "FrontDoor is deprecated and must be asked for "
                         "explicitly; both = one fleet, both doors back "
                         "to back)")
    args = ap.parse_args()
    problems = run_checks(edge=args.edge)
    if problems:
        print("overload check FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(
        f"overload ok ({args.edge} edge): the saturation burst shed "
        "fast with explicit fail-open/closed verdicts, kept goodput, "
        "and accepted requests matched the interpreter oracle with "
        "zero divergence"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
