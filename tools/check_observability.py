#!/usr/bin/env python
"""Static observability conformance check (wired as a tier-1 test via
tests/test_observability_check.py; also runnable standalone):

1. Every Measure defined in gatekeeper_tpu/metrics/catalog.py is bound to
   at least one View in catalog_views() — an unbound measure records into
   the void and its call sites silently export nothing.
2. Every exported metric name (view name) appears in docs/metrics.md —
   the doc is the operator contract; an undocumented metric is either
   missing docs or a leftover.
3. No hot-path module times spans with the wall clock: ``time.time()`` is
   forbidden in the listed modules unless the line carries a
   ``wall-clock: ok`` annotation (legitimate uses are epoch timestamps
   for export, never durations — wall time steps under NTP and would
   corrupt span/stage math).

Run: python tools/check_observability.py   (exit 0 clean, 1 with findings)
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# modules on (or adjacent to) the admission/audit hot paths where span
# or stage timing happens; extend when instrumenting new modules
HOT_PATH_MODULES = (
    "gatekeeper_tpu/obs/trace.py",
    "gatekeeper_tpu/obs/__init__.py",
    "gatekeeper_tpu/webhook/server.py",
    "gatekeeper_tpu/webhook/policy.py",
    "gatekeeper_tpu/ops/driver.py",
    "gatekeeper_tpu/ops/npside.py",
    "gatekeeper_tpu/ops/aotcache.py",
    "gatekeeper_tpu/ops/deltasweep.py",
    "gatekeeper_tpu/faults/plane.py",
    "gatekeeper_tpu/audit/manager.py",
    "gatekeeper_tpu/metrics/catalog.py",
    "gatekeeper_tpu/logging.py",
)

_WALL_OK = "wall-clock: ok"
_TIME_CALL = re.compile(r"\btime\.time\(\)|\b_time\.time\(\)")


def check_measures_bound() -> list:
    from gatekeeper_tpu.metrics import catalog
    from gatekeeper_tpu.metrics.views import Measure

    views = catalog.catalog_views()
    bound = {v.measure.name for v in views}
    problems = []
    for attr in dir(catalog):
        m = getattr(catalog, attr)
        if isinstance(m, Measure) and m.name not in bound:
            problems.append(
                f"measure {m.name!r} ({attr}) is not bound to any View in "
                "catalog_views() — recordings against it export nothing"
            )
    return problems


def check_metrics_documented() -> list:
    from gatekeeper_tpu.metrics import catalog

    doc_path = os.path.join(REPO, "docs", "metrics.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"docs/metrics.md unreadable: {e}"]
    problems = []
    for v in catalog.catalog_views():
        if f"`{v.name}`" not in doc and v.name not in doc:
            problems.append(
                f"exported metric {v.name!r} is not documented in "
                "docs/metrics.md"
            )
    return problems


def check_monotonic_span_timing() -> list:
    problems = []
    for rel in HOT_PATH_MODULES:
        path = os.path.join(REPO, rel)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError as e:
            problems.append(f"hot-path module {rel} unreadable: {e}")
            continue
        for i, line in enumerate(lines, 1):
            if _TIME_CALL.search(line) and _WALL_OK not in line:
                problems.append(
                    f"{rel}:{i}: time.time() in a hot-path module — span/"
                    "stage timing must use a monotonic clock "
                    "(perf_counter/monotonic); annotate genuine epoch "
                    f"timestamps with '# {_WALL_OK}'"
                )
    return problems


def run_checks() -> list:
    sys.path.insert(0, REPO)
    return (
        check_measures_bound()
        + check_metrics_documented()
        + check_monotonic_span_timing()
    )


def main() -> int:
    problems = run_checks()
    for p in problems:
        print(f"check_observability: {p}", file=sys.stderr)
    if problems:
        print(f"check_observability: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print("check_observability: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
