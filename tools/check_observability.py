#!/usr/bin/env python
"""Static observability conformance check (wired as a tier-1 test via
tests/test_observability_check.py; also runnable standalone):

1. Every Measure defined in gatekeeper_tpu/metrics/catalog.py is bound to
   at least one View in catalog_views() — an unbound measure records into
   the void and its call sites silently export nothing.
2. Every exported metric name (view name) appears in docs/metrics.md —
   the doc is the operator contract; an undocumented metric is either
   missing docs or a leftover.
3. No hot-path module times spans with the wall clock: ``time.time()`` is
   forbidden in the listed modules unless the line carries a
   ``wall-clock: ok`` annotation (legitimate uses are epoch timestamps
   for export, never durations — wall time steps under NTP and would
   corrupt span/stage math).
4. Exemplar well-formedness (ISSUE 5): a registry with trace-linked
   distribution samples must render OpenMetrics that terminates with
   ``# EOF``, attaches exemplars as ``# {trace_id="<32 hex>"} value ts``
   on bucket lines, and keeps exemplars OUT of the classic text format.
5. Label-cardinality lint (ISSUE 5): any catalog view carrying a
   ``template``/``constraint`` tag key must be declared in
   catalog.CAPPED_CARDINALITY_VIEWS (i.e. fed only by the top-K-capped
   cost-ledger collector), and the collector must actually cap — an
   uncapped per-template label explodes Prometheus cardinality on a
   500-template cluster.
6. Wire-stage conformance (ISSUE 11): the front door's stable
   WIRE_STAGES set must match the documented table in docs/tracing.md,
   and every ``STAGE_*`` constant the module defines must be listed in
   WIRE_STAGES — an undocumented or unlisted stage breaks the
   stage-breakdown contract bench.py's wire-path section reports on.
7. Federated-format invariants (ISSUE 11): merging N replica scrapes
   through obs/fleetobs.py must preserve the classic exposition
   discipline — ONE HELP/TYPE header per family, no exemplars, no
   ``# EOF`` — inject ``replica_id`` into unlabelled remote samples, and
   leave samples that already carry a replica_id untouched.
8. Flight-recorder conformance (ISSUE 13): every event type in
   obs/flightrec.py EVENT_TYPES must be documented in
   docs/observability.md (the incident-chronology table is an operator
   contract), every documented ``/debug/*`` endpoint the shared router
   serves must appear there too, and the route ledger's REASONS must
   each be documented in docs/metrics.md (the route_decisions_total
   reason taxonomy).

9. Decision-log conformance (ISSUE 15): the record schema
   (decisionlog.RECORD_FIELDS) and decision taxonomy
   (decisionlog.CLASSES) must each be documented in
   docs/decision-logs.md, and a live admission record must emit no
   field outside the declared schema — the archive format is the replay
   tool's input contract.

10. Reactor-observability conformance (ISSUE 20): the `evloop_stall`
    flight-recorder event type must be declared, the `evloop.*` fault
    points registered AND documented in docs/failure-modes.md, every
    `evloop_*`/`wire_*` view documented in docs/metrics.md,
    /debug/connz routed and mentioned in docs/observability.md, and the
    reactor-health section present in docs/fleet.md — the flight deck
    is an operator contract like every other surface here.

Run: python tools/check_observability.py   (exit 0 clean, 1 with findings)
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# modules on (or adjacent to) the admission/audit hot paths where span
# or stage timing happens; extend when instrumenting new modules
HOT_PATH_MODULES = (
    "gatekeeper_tpu/obs/trace.py",
    "gatekeeper_tpu/obs/__init__.py",
    "gatekeeper_tpu/obs/costs.py",
    "gatekeeper_tpu/obs/slo.py",
    "gatekeeper_tpu/obs/debug.py",
    "gatekeeper_tpu/obs/profiler.py",
    "gatekeeper_tpu/obs/fleetobs.py",
    "gatekeeper_tpu/obs/flightrec.py",
    "gatekeeper_tpu/obs/routeledger.py",
    "gatekeeper_tpu/obs/compilestats.py",
    "gatekeeper_tpu/obs/decisionlog.py",
    "gatekeeper_tpu/obs/brownout.py",
    "gatekeeper_tpu/obs/reactorobs.py",
    "gatekeeper_tpu/ops/xlacache.py",
    "gatekeeper_tpu/ops/asynccompile.py",
    "gatekeeper_tpu/fleet/frontdoor.py",
    "gatekeeper_tpu/fleet/evloop.py",
    "gatekeeper_tpu/fleet/evdoor.py",
    "gatekeeper_tpu/fleet/wirelistener.py",
    "gatekeeper_tpu/metrics/views.py",
    "gatekeeper_tpu/metrics/exporter.py",
    "gatekeeper_tpu/webhook/server.py",
    "gatekeeper_tpu/webhook/policy.py",
    "gatekeeper_tpu/ops/driver.py",
    "gatekeeper_tpu/ops/npside.py",
    "gatekeeper_tpu/ops/aotcache.py",
    "gatekeeper_tpu/ops/deltasweep.py",
    "gatekeeper_tpu/faults/plane.py",
    "gatekeeper_tpu/audit/manager.py",
    "gatekeeper_tpu/metrics/catalog.py",
    "gatekeeper_tpu/logging.py",
)

_WALL_OK = "wall-clock: ok"
_TIME_CALL = re.compile(r"\btime\.time\(\)|\b_time\.time\(\)")


def check_measures_bound() -> list:
    from gatekeeper_tpu.metrics import catalog
    from gatekeeper_tpu.metrics.views import Measure

    views = catalog.catalog_views()
    bound = {v.measure.name for v in views}
    problems = []
    for attr in dir(catalog):
        m = getattr(catalog, attr)
        if isinstance(m, Measure) and m.name not in bound:
            problems.append(
                f"measure {m.name!r} ({attr}) is not bound to any View in "
                "catalog_views() — recordings against it export nothing"
            )
    return problems


def check_metrics_documented() -> list:
    from gatekeeper_tpu.metrics import catalog

    doc_path = os.path.join(REPO, "docs", "metrics.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"docs/metrics.md unreadable: {e}"]
    problems = []
    for v in catalog.catalog_views():
        if f"`{v.name}`" not in doc and v.name not in doc:
            problems.append(
                f"exported metric {v.name!r} is not documented in "
                "docs/metrics.md"
            )
    return problems


def check_monotonic_span_timing() -> list:
    problems = []
    for rel in HOT_PATH_MODULES:
        path = os.path.join(REPO, rel)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError as e:
            problems.append(f"hot-path module {rel} unreadable: {e}")
            continue
        for i, line in enumerate(lines, 1):
            if _TIME_CALL.search(line) and _WALL_OK not in line:
                problems.append(
                    f"{rel}:{i}: time.time() in a hot-path module — span/"
                    "stage timing must use a monotonic clock "
                    "(perf_counter/monotonic); annotate genuine epoch "
                    f"timestamps with '# {_WALL_OK}'"
                )
    return problems


_EXEMPLAR_RE = re.compile(
    r' # \{trace_id="[0-9a-f]{32}"\} [0-9.e+-]+ [0-9]+\.[0-9]+$'
)


def check_exemplar_wellformed() -> list:
    """Render a synthetic registry through both exposition formats and
    verify the exemplar contract."""
    from gatekeeper_tpu.metrics.exporter import (
        render_openmetrics,
        render_prometheus,
    )
    from gatekeeper_tpu.metrics.views import (
        AGG_DISTRIBUTION,
        Measure,
        Registry,
        View,
    )

    problems = []
    reg = Registry()
    m = Measure("exemplar_check_seconds", "synthetic", "s")
    reg.register(View("exemplar_check_seconds", m, AGG_DISTRIBUTION,
                      buckets=(0.01, 0.1, 1.0)))
    trace_id = "ab" * 16
    reg.record(m, 0.05, exemplar_trace_id=trace_id)
    reg.record(m, 5.0, exemplar_trace_id=trace_id)
    om = render_openmetrics(reg)
    if not om.endswith("# EOF\n"):
        problems.append(
            "OpenMetrics rendering does not terminate with '# EOF'"
        )
    ex_lines = [ln for ln in om.splitlines() if " # {" in ln]
    if len(ex_lines) != 2:
        problems.append(
            f"expected 2 exemplar-carrying bucket lines, got {len(ex_lines)}"
        )
    for ln in ex_lines:
        if "_bucket{" not in ln:
            problems.append(f"exemplar on a non-bucket line: {ln!r}")
        if not _EXEMPLAR_RE.search(ln):
            problems.append(f"malformed exemplar: {ln!r}")
    classic = render_prometheus(reg)
    if " # {" in classic or "# EOF" in classic:
        problems.append(
            "classic text format must carry neither exemplars nor '# EOF'"
        )
    return problems


_CARDINALITY_TAGS = {"template", "constraint"}


def check_label_cardinality() -> list:
    """Every view with a template/constraint label must be declared
    top-K-capped, and the cost-ledger collector must actually cap."""
    from gatekeeper_tpu.metrics import catalog
    from gatekeeper_tpu.metrics.views import Registry
    from gatekeeper_tpu.obs.costs import OTHER, CostLedger

    problems = []
    declared = set(getattr(catalog, "CAPPED_CARDINALITY_VIEWS", ()))
    view_names = set()
    for v in catalog.catalog_views():
        view_names.add(v.name)
        if set(v.tag_keys) & _CARDINALITY_TAGS and v.name not in declared:
            problems.append(
                f"view {v.name!r} carries a {sorted(_CARDINALITY_TAGS)} "
                "label but is not declared in "
                "catalog.CAPPED_CARDINALITY_VIEWS — per-template labels "
                "must be top-K-capped"
            )
    for name in declared - view_names:
        problems.append(
            f"CAPPED_CARDINALITY_VIEWS names unknown view {name!r}"
        )
    # functional check: K+2 templates through a top-K=2 ledger must export
    # at most K individual template labels plus the 'other' rollup
    ledger = CostLedger(top_k=2)
    for i in range(4):
        ledger.record_dispatch({f"T{i}": 1}, 0.001, 10)
    reg = Registry()
    catalog.register_catalog(reg)
    ledger.collect(reg)
    labels = {k[0] for k in reg.view_rows("cost_device_ms")}
    if len(labels - {OTHER}) > 2 or OTHER not in labels:
        problems.append(
            "cost-ledger collector exported uncapped template labels: "
            f"{sorted(labels)}"
        )
    return problems


def check_wire_stages() -> list:
    """The front door's WIRE_STAGES set vs its own STAGE_* constants and
    the docs/tracing.md stage table."""
    from gatekeeper_tpu.fleet import frontdoor

    problems = []
    stages = set(frontdoor.WIRE_STAGES)
    declared = {
        v for k, v in vars(frontdoor).items()
        if k.startswith("STAGE_") and isinstance(v, str)
    }
    for s in declared - stages:
        problems.append(
            f"frontdoor stage constant {s!r} is not listed in "
            "WIRE_STAGES — it would be invisible to the stage-breakdown "
            "contract"
        )
    for s in stages - declared:
        problems.append(
            f"WIRE_STAGES entry {s!r} has no STAGE_* constant in "
            "fleet/frontdoor.py"
        )
    doc_path = os.path.join(REPO, "docs", "tracing.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [f"docs/tracing.md unreadable: {e}"]
    for s in sorted(stages):
        if f"`{s}`" not in doc:
            problems.append(
                f"wire stage {s!r} is not documented in docs/tracing.md "
                "(the stable stage-name table)"
            )
    return problems


def check_federated_format() -> list:
    """Merge synthetic replica scrapes through obs/fleetobs.py and
    verify the classic exposition invariants survive federation."""
    from gatekeeper_tpu.metrics.exporter import render_prometheus
    from gatekeeper_tpu.metrics.views import (
        AGG_COUNT,
        AGG_DISTRIBUTION,
        Measure,
        Registry,
        View,
    )
    from gatekeeper_tpu.obs.fleetobs import merge_families, render_families

    problems = []
    reg = Registry()
    m = Measure("fed_check_seconds", "synthetic", "s")
    c = Measure("fed_check_reqs", "synthetic")
    reg.register(
        View("fed_check_seconds", m, AGG_DISTRIBUTION, buckets=(0.1, 1.0)),
        View("fed_check_total", c, AGG_COUNT, tag_keys=("outcome",)),
    )
    reg.record(m, 0.05, exemplar_trace_id="cd" * 16)
    reg.record(c, 1.0, {"outcome": "ok"})
    local = render_prometheus(reg)
    remote = (
        "# HELP gatekeeper_fed_check_total synthetic\n"
        "# TYPE gatekeeper_fed_check_total counter\n"
        'gatekeeper_fed_check_total{outcome="ok"} 3\n'
        'gatekeeper_fed_check_total{outcome="ok",replica_id="rX"} 2\n'
        "# HELP gatekeeper_fed_up synthetic\n"
        "# TYPE gatekeeper_fed_up gauge\n"
        "gatekeeper_fed_up 1\n"
    )
    out = render_families(merge_families(
        local, [("r0", remote), ("r1", remote)]
    ))
    if "# EOF" in out or " # {" in out:
        problems.append(
            "federated output leaked an OpenMetrics construct "
            "(exemplar or # EOF) into the classic format"
        )
    lines = out.splitlines()
    for kind in ("HELP", "TYPE"):
        seen = [ln.split()[2] for ln in lines
                if ln.startswith(f"# {kind} ")]
        dupes = {n for n in seen if seen.count(n) > 1}
        if dupes:
            problems.append(
                f"federated output repeats # {kind} for {sorted(dupes)} "
                "— one header per family is the classic contract"
            )
    if 'gatekeeper_fed_up{replica_id="r0"} 1' not in out \
            or 'gatekeeper_fed_up{replica_id="r1"} 1' not in out:
        problems.append(
            "federation did not inject replica_id into unlabelled "
            "remote samples"
        )
    if 'outcome="ok",replica_id="rX"' not in out:
        problems.append(
            "federation rewrote a sample that already carried its own "
            "replica_id label (replica-stamped series are authoritative)"
        )
    if out.count('gatekeeper_fed_check_total{outcome="ok"} 1') != 1:
        problems.append(
            "federation lost or duplicated the parent's own samples"
        )
    return problems


def check_flightrec_conformance() -> list:
    """The flight recorder's event-type table, the shared router's
    endpoint surface, and the route ledger's reason taxonomy must all be
    documented — they are operator contracts (ISSUE 13)."""
    from gatekeeper_tpu.obs import flightrec, routeledger
    from gatekeeper_tpu.obs.debug import get_router

    problems = []
    doc_path = os.path.join(REPO, "docs", "observability.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"docs/observability.md unreadable: {e}"]
    for etype in flightrec.EVENT_TYPES:
        if f"`{etype}`" not in doc:
            problems.append(
                f"flight-recorder event type {etype!r} is not documented "
                "in docs/observability.md (the incident-chronology table)"
            )
    for endpoint in get_router().endpoints():
        if endpoint not in doc:
            problems.append(
                f"debug endpoint {endpoint!r} is not mentioned in "
                "docs/observability.md (the surface map)"
            )
    metrics_path = os.path.join(REPO, "docs", "metrics.md")
    try:
        with open(metrics_path) as f:
            mdoc = f.read()
    except OSError as e:
        return problems + [f"docs/metrics.md unreadable: {e}"]
    for reason in routeledger.REASONS:
        if f"`{reason}`" not in mdoc:
            problems.append(
                f"route-decision reason {reason!r} is not documented in "
                "docs/metrics.md (route_decisions_total taxonomy)"
            )
    return problems


def check_decisionlog_conformance() -> list:
    """The decision log's record schema and taxonomy are operator (and
    replay-tool) contracts (ISSUE 15): every field a record may carry
    (decisionlog.RECORD_FIELDS) and every decision class
    (decisionlog.CLASSES) must be documented in docs/decision-logs.md,
    and a live admission record must emit no field outside the declared
    schema — an undocumented field silently changes the archive format
    replay depends on."""
    from gatekeeper_tpu.obs import decisionlog

    problems = []
    doc_path = os.path.join(REPO, "docs", "decision-logs.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"docs/decision-logs.md unreadable: {e}"]
    for field in decisionlog.RECORD_FIELDS:
        if f"`{field}`" not in doc:
            problems.append(
                f"decision-record field {field!r} is not documented in "
                "docs/decision-logs.md (the record-schema table)"
            )
    for dclass in decisionlog.CLASSES:
        if f"`{dclass}`" not in doc:
            problems.append(
                f"decision class {dclass!r} is not documented in "
                "docs/decision-logs.md (the taxonomy table)"
            )
    # functional half: a real record must stay inside the schema
    log = decisionlog.DecisionLog()

    class _Resp:
        allowed = False
        code = 403
        message = "check"
        annotations = None

    log.record_admission({"uid": "schema-check"}, _Resp(), 0.001,
                         budget_s=0.1)
    recs = log.snapshot()["records"]
    if not recs:
        problems.append("decision log dropped a synthetic record "
                        "(schema check could not run)")
    else:
        for field in recs[0]:
            if field not in decisionlog.RECORD_FIELDS:
                problems.append(
                    f"admission records emit undeclared field {field!r} "
                    "— add it to decisionlog.RECORD_FIELDS and the "
                    "docs/decision-logs.md schema table"
                )
    return problems


def check_reactor_conformance() -> list:
    """Reactor flight-deck contracts (ISSUE 20): event type declared,
    fault points registered + documented, metrics + endpoint + docs
    sections present."""
    from gatekeeper_tpu import faults
    from gatekeeper_tpu.metrics import catalog
    from gatekeeper_tpu.obs import flightrec
    from gatekeeper_tpu.obs.debug import get_router

    problems = []
    if getattr(flightrec, "EVLOOP_STALL", None) not in flightrec.EVENT_TYPES:
        problems.append(
            "flightrec.EVLOOP_STALL missing from EVENT_TYPES — the stall "
            "watchdog's incidents would fail the recorder's type check"
        )
    fm_path = os.path.join(REPO, "docs", "failure-modes.md")
    try:
        with open(fm_path) as f:
            fmdoc = f.read()
    except OSError as e:
        return problems + [f"docs/failure-modes.md unreadable: {e}"]
    for point in ("evloop.slow_callback", "evloop.stall"):
        if point not in faults.ALL_POINTS:
            problems.append(
                f"fault point {point!r} is not registered in "
                "faults.ALL_POINTS — gklint's unknown-fault-point rule "
                "would reject its fire site"
            )
        if f"`{point}`" not in fmdoc:
            problems.append(
                f"fault point {point!r} is not documented in "
                "docs/failure-modes.md (the fault-point table)"
            )
    if "watchdog" not in fmdoc:
        problems.append(
            "docs/failure-modes.md has no stall-watchdog row — the "
            "evloop.stall recovery story is an operator contract"
        )
    view_names = {v.name for v in catalog.catalog_views()}
    expected = {
        "evloop_lag_seconds", "evloop_tick_seconds", "evloop_utilization",
        "evloop_callbacks_per_tick", "evloop_timer_drift_seconds",
        "evloop_slow_callbacks_total", "evloop_stalls_total",
        "wire_chunks_total", "wire_chunk_records", "wire_bytes_total",
        "wire_decode_errors_total", "wire_reconnects_total",
        "wire_backlog_stall_seconds",
    }
    for name in sorted(expected - view_names):
        problems.append(
            f"reactor/wire view {name!r} is missing from catalog_views() "
            "— the flight-deck metric set is incomplete"
        )
    if "/debug/connz" not in get_router().endpoints():
        problems.append(
            "/debug/connz is not routed on the shared debug router"
        )
    fleet_path = os.path.join(REPO, "docs", "fleet.md")
    try:
        with open(fleet_path) as f:
            fleetdoc = f.read()
    except OSError as e:
        return problems + [f"docs/fleet.md unreadable: {e}"]
    if "reactor health" not in fleetdoc.lower():
        problems.append(
            "docs/fleet.md has no reactor-health section — the flight "
            "deck's operator story must live next to the edge it watches"
        )
    return problems


def run_checks() -> list:
    sys.path.insert(0, REPO)
    return (
        check_measures_bound()
        + check_metrics_documented()
        + check_monotonic_span_timing()
        + check_exemplar_wellformed()
        + check_label_cardinality()
        + check_wire_stages()
        + check_federated_format()
        + check_flightrec_conformance()
        + check_decisionlog_conformance()
        + check_reactor_conformance()
    )


def main() -> int:
    problems = run_checks()
    for p in problems:
        print(f"check_observability: {p}", file=sys.stderr)
    if problems:
        print(f"check_observability: {len(problems)} problem(s)",
              file=sys.stderr)
        return 1
    print("check_observability: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
