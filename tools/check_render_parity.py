#!/usr/bin/env python
"""Render-plan conformance check (wired tier-1 via
tests/test_render_parity_tool.py; also runnable standalone):

1. Byte parity: every template in the parity corpus whose program binds a
   render plan must produce byte-identical violations (msg AND details,
   order included) to the interpreter across the adversarial resource
   set.  A plan-compiler regression fails fast here, before it could
   silently ship wrong deny messages.
2. Classification coverage: across the full corpus (parity fixtures +
   the synthetic bench families), >= 90% of template cells must classify
   to the compiled tiers (static/slots) — the interpreter fallback is
   the exception, not the rule.

Run: python tools/check_render_parity.py  (exit 0 clean, 1 with findings)
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
COVERAGE_FLOOR = 0.9


def _corpus_modules():
    sys.path.insert(0, REPO)
    from tests import render_corpus

    return render_corpus


def check_byte_parity() -> list:
    from gatekeeper_tpu.engine.interp import TemplatePolicy
    from gatekeeper_tpu.engine.value import freeze
    from gatekeeper_tpu.ops import renderplan as rp
    from gatekeeper_tpu.ops.vectorizer import vectorize

    rc = _corpus_modules()
    problems = []
    for name, template, constraint, _tier in rc.corpus():
        tgt = template["spec"]["targets"][0]
        pol = TemplatePolicy.compile(tgt["rego"], tuple(tgt.get("libs") or ()))
        plan = rp.bind(vectorize(pol), pol, constraint)
        if plan is None:
            continue
        params = freeze(constraint["spec"].get("parameters", {}))
        for obj in rc.resources():
            review = rc.review_of(obj)
            want = pol.eval_violations(freeze(review), params, freeze({}))
            got = plan.apply(rp.RowView(review))
            if got != want:
                problems.append(
                    f"render parity: {name} diverges from the interpreter "
                    f"on resource {obj['metadata'].get('name')!r}: "
                    f"plan={got!r} interp={want!r}"
                )
    return problems


def check_classification_coverage() -> list:
    from gatekeeper_tpu.engine.interp import TemplatePolicy
    from gatekeeper_tpu.ops import renderplan as rp
    from gatekeeper_tpu.ops.vectorizer import vectorize
    from gatekeeper_tpu.util.synthetic import make_templates

    rc = _corpus_modules()
    total = planned = 0
    entries = [(t, c) for _n, t, c, _tier in rc.corpus()]
    syn_templates, syn_constraints = make_templates(60)
    entries += list(zip(syn_templates, syn_constraints))
    for template, constraint in entries:
        tgt = template["spec"]["targets"][0]
        pol = TemplatePolicy.compile(tgt["rego"], tuple(tgt.get("libs") or ()))
        plan = rp.bind(vectorize(pol), pol, constraint)
        total += 1
        planned += plan is not None
    ratio = planned / total if total else 0.0
    if ratio < COVERAGE_FLOOR:
        return [
            f"render classification: only {planned}/{total} "
            f"({ratio:.1%}) of corpus templates compile to the "
            f"static/slots tiers (floor {COVERAGE_FLOOR:.0%})"
        ]
    return []


def run_checks() -> list:
    return check_byte_parity() + check_classification_coverage()


def main() -> int:
    problems = run_checks()
    for p in problems:
        print(f"FINDING: {p}")
    if problems:
        print(f"{len(problems)} finding(s)")
        return 1
    print("render-plan conformance: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
