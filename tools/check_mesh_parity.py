#!/usr/bin/env python
"""Sharded-mesh conformance check (wired tier-1 via
tests/test_mesh_parity_tool.py; also runnable standalone):

1. Width parity: the capped audit sweep over a width-4 virtual mesh must
   produce BYTE-identical results — violation messages, resource
   identities, per-constraint totals and exactness markers — to the
   width-1 (single-device) sweep AND to the interpreter oracle over a
   fast synthetic corpus.  A sharding regression (slab padding, the
   per-shard [C, 1+K] reduction merge, global row-index translation)
   fails fast here, before it could ship wrong audit results.
2. Churn locality: after a full sweep, a small churn batch must ride the
   O(churn) delta path under the mesh — the dispatch row count equals
   the churned row count, never the cluster size.

Run: python tools/check_mesh_parity.py   (exit 0 clean, 1 with findings;
re-execs onto a virtual 8-device CPU mesh when fewer devices are
visible, exactly like the bench's mesh lane).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# fast corpus: large enough that capacity (16 buckets to 64) pads across
# 4 slabs and every policy family appears; small enough for tier-1.
# CAP exceeds any per-constraint violation count here, so every candidate
# renders and totals are exact on every tier — the oracle comparison is
# then a FULL byte comparison, not a cap-order artifact.
N_TEMPLATES = 12
N_RESOURCES = 60
CAP = 100
WIDTH = 4
CHURN = 5


def _result_sig(results):
    from gatekeeper_tpu.util.synthetic import audit_result_sig

    return audit_result_sig(results)


def _driver(width):
    from gatekeeper_tpu.util.synthetic import build_driver

    client = build_driver(N_TEMPLATES, N_RESOURCES)
    driver = client.driver
    driver.set_mesh(width > 1, width=width)
    return client, driver


def _oracle():
    """A separately-loaded InterpDriver with the identical corpus
    (util/synthetic.build_oracle — see its docstring for why the oracle
    must be its own instance)."""
    from gatekeeper_tpu.util.synthetic import build_oracle

    return build_oracle(N_TEMPLATES, N_RESOURCES).driver


def check_width_parity() -> list:
    """Width-4 mesh sweep vs width-1 sweep vs interpreter oracle."""
    problems = []
    _c1, d1 = _driver(1)
    res1, totals1, _t = d1.audit_capped(CAP)
    _c4, d4 = _driver(WIDTH)
    res4, totals4, _t = d4.audit_capped(CAP)
    if d4.last_sweep_stats.get("shards") != float(WIDTH):
        problems.append(
            f"mesh parity: width-{WIDTH} sweep ran on "
            f"{d4.last_sweep_stats.get('shards')} shard(s) — the mesh "
            "path did not serve the audit (breaker fallback?)"
        )
    oracle, ototals, _t = _oracle().audit_capped(CAP)
    comparisons = (
        (f"width-{WIDTH} vs width-1", res4, totals4, res1, totals1),
        ("width-1 vs interp oracle", res1, totals1, oracle, ototals),
        (f"width-{WIDTH} vs interp oracle", res4, totals4, oracle,
         ototals),
    )
    for tag, got_r, got_t, ref_r, ref_t in comparisons:
        if _result_sig(got_r) != _result_sig(ref_r):
            problems.append(
                f"mesh parity: rendered results diverge ({tag})"
            )
        if got_t != ref_t:
            problems.append(
                f"mesh parity: per-constraint totals diverge ({tag}): "
                f"{got_t} != {ref_t}"
            )
    return problems


def check_churn_locality() -> list:
    """A churn batch after a full mesh sweep must dispatch O(churn) rows
    to the owning shards, not resweep the cluster."""
    from gatekeeper_tpu.util.synthetic import make_pods

    problems = []
    client, driver = _driver(WIDTH)
    driver.audit_capped(CAP)  # full sweep rebases the delta state
    pods = make_pods(N_RESOURCES)[7: 7 + CHURN]
    for p in pods:
        p["metadata"].setdefault("labels", {})["churned"] = "yes"
        client.add_data(p)
    driver.audit_capped(CAP)
    stats = driver.last_sweep_stats
    if stats.get("delta_rows") != float(CHURN):
        problems.append(
            "mesh churn locality: expected an O(churn) delta dispatch of "
            f"{CHURN} rows under the width-{WIDTH} mesh, got stats {stats}"
        )
    return problems


def run_checks() -> list:
    return check_width_parity() + check_churn_locality()


def _reexec_on_virtual_mesh() -> int:
    """Standalone runs on hosts with < WIDTH devices re-exec onto the
    virtual CPU mesh (the bench/test recipe)."""
    import subprocess

    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(8)
    env["GK_MESH_PARITY_REEXEC"] = "1"
    return subprocess.call([sys.executable, os.path.abspath(__file__)],
                           env=env)


def main() -> int:
    import jax

    if (len(jax.devices()) < WIDTH
            and not os.environ.get("GK_MESH_PARITY_REEXEC")):
        return _reexec_on_virtual_mesh()
    problems = run_checks()
    for p in problems:
        print(f"FINDING: {p}")
    if problems:
        print(f"{len(problems)} finding(s)")
        return 1
    print("mesh-parity conformance: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
