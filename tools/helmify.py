#!/usr/bin/env python
"""Helm-chart generator: deploy/gatekeeper.yaml -> charts/gatekeeper-tpu/.

The analogue of the reference's kustomize->helm converter
(/root/reference/cmd/build/helmify/main.go:1-199 + replacements.go): the
flattened deployment manifest is the single source of truth, split into one
chart template file per (kind, name) — CRDs into crds/ (Helm v3) — with
deploy-time knobs rewritten to `{{ .Values.* }}` references, and
values.yaml carrying the defaults extracted from the manifest itself, so
chart and raw manifest can never drift.

Run: python tools/helmify.py   (idempotent; writes charts/gatekeeper-tpu)
Verified by tests/test_helmify.py, which regenerates and round-trips the
chart against deploy/gatekeeper.yaml.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, "deploy", "gatekeeper.yaml")
CHART = os.path.join(REPO, "charts", "gatekeeper-tpu")

CHART_YAML = """\
apiVersion: v2
name: gatekeeper-tpu
description: TPU-native Gatekeeper-class policy controller (vectorized audit + admission)
type: application
version: 3.1.0
appVersion: "3.1.0"
"""

HELPERS_TPL = """\
{{- define "gatekeeper-tpu.labels" -}}
app: gatekeeper-tpu
chart: {{ .Chart.Name }}
release: {{ .Release.Name }}
heritage: {{ .Release.Service }}
{{- end }}
"""

# deploy-time knobs: literal text in deploy/gatekeeper.yaml -> (values key,
# template expression).  The default value recorded in values.yaml is
# extracted from the manifest text, mirroring replacements.go's table.
# ORDER MATTERS: "containerPort: 8443" must rewrite before "port: 8443".
REPLACEMENTS = [
    ("image: gatekeeper-tpu:latest",
     "image", "image: {{ .Values.image.repository }}:{{ .Values.image.tag }}"),
    ("replicas: 3",
     "replicas", "replicas: {{ .Values.replicas }}"),
    ("- --audit-interval=60",
     "auditInterval", "- --audit-interval={{ .Values.auditInterval }}"),
    ("- --constraint-violations-limit=20",
     "constraintViolationsLimit",
     "- --constraint-violations-limit={{ .Values.constraintViolationsLimit }}"),
    ("- --driver=tpu", "driver", "- --driver={{ .Values.driver }}"),
    ("- --port=8443", "webhookPort", "- --port={{ .Values.webhookPort }}"),
    ("containerPort: 8443",
     "webhookPort", "containerPort: {{ .Values.webhookPort }}"),
    ("port: 8443", "webhookPort", "port: {{ .Values.webhookPort }}"),
    ("containerPort: 8888",
     "prometheusPort", "containerPort: {{ .Values.prometheusPort }}"),
    ('google.com/tpu: "1"',
     "tpuResource",
     '{{ .Values.tpuResource }}: "{{ .Values.tpuCount }}"'),
    # boolean flag present in the manifest -> gated on a value (default
    # matches the manifest: enabled)
    ("- --log-denies",
     "logDenies",
     "{{- if .Values.logDenies }}\n"
     "            - --log-denies\n"
     "            {{- end }}"),
    # repeatable flag -> range over a list value
    ("- --exempt-namespace=gatekeeper-system",
     "exemptNamespaces",
     "{{- range .Values.exemptNamespaces }}\n"
     "            - --exempt-namespace={{ . }}\n"
     "            {{- end }}"),
    # flags NOT in the manifest, exposed as off-by-default conditionals:
    # anchored on existing arg lines so the chart stays a pure derivation
    # of the manifest (at default values these render to the anchor alone)
    ("- --operation=webhook\n            - --operation=status",
     "emitAdmissionEvents",
     "- --operation=webhook\n"
     "            - --operation=status\n"
     "            {{- if .Values.emitAdmissionEvents }}\n"
     "            - --emit-admission-events\n"
     "            {{- end }}"),
    ("- --operation=audit\n            - --operation=status",
     "auditFromCache",
     "- --operation=audit\n"
     "            - --operation=status\n"
     "            {{- if .Values.auditFromCache }}\n"
     "            - --audit-from-cache\n"
     "            {{- end }}\n"
     "            {{- if .Values.emitAuditEvents }}\n"
     "            - --emit-audit-events\n"
     "            {{- end }}"),
]

# every key here is referenced by a template expression in REPLACEMENTS —
# a knob with no template reference would be silently discarded at install
VALUES_DEFAULTS = {
    "image": {"repository": "gatekeeper-tpu", "tag": "latest"},
    "replicas": 3,
    "auditInterval": 60,
    "constraintViolationsLimit": 20,
    "driver": "tpu",
    "webhookPort": 8443,
    "prometheusPort": 8888,
    "tpuResource": "google.com/tpu",
    "tpuCount": 1,
    "logDenies": True,  # the deploy manifest enables it
    "exemptNamespaces": ["gatekeeper-system"],
    "emitAdmissionEvents": False,
    "auditFromCache": False,
    "emitAuditEvents": False,
}

_KIND_RE = re.compile(r"^kind:\s+(\S+)\s*$", re.MULTILINE)
# exactly two spaces: metadata.name (helmify main.go:26-27)
_NAME_RE = re.compile(r"^  name:\s+(\S+)\s*$", re.MULTILINE)


def split_docs(text: str):
    docs = []
    for chunk in re.split(r"^---\s*$", text, flags=re.MULTILINE):
        chunk = chunk.strip("\n")
        if not chunk.strip() or all(
            line.strip().startswith("#") or not line.strip()
            for line in chunk.splitlines()
        ):
            continue
        docs.append(chunk)
    return docs


def doc_identity(doc: str):
    km = _KIND_RE.search(doc)
    nm = _NAME_RE.search(doc)
    if not km or not nm:
        raise ValueError(f"document without kind/name: {doc[:120]!r}")
    return km.group(1).strip("\"'"), nm.group(1).strip("\"'")


def template_doc(doc: str) -> str:
    for literal, _key, repl in REPLACEMENTS:
        doc = doc.replace(literal, repl)
    return doc


def render_values(values: dict, indent: int = 0) -> str:
    import json

    lines = []
    pad = "  " * indent
    for k, v in values.items():
        if isinstance(v, dict):
            lines.append(f"{pad}{k}:")
            lines.append(render_values(v, indent + 1))
        else:
            lines.append(f"{pad}{k}: {json.dumps(v)}")
    return "\n".join(lines)


def generate() -> dict:
    """Write the chart; returns {relative path: content}."""
    with open(MANIFEST) as f:
        manifest = f.read()
    out = {
        "Chart.yaml": CHART_YAML,
        "values.yaml": render_values(VALUES_DEFAULTS) + "\n",
        "templates/_helpers.tpl": HELPERS_TPL,
    }
    for doc in split_docs(manifest):
        kind, name = doc_identity(doc)
        fname = f"{name}-{kind.lower()}.yaml"
        if kind == "CustomResourceDefinition":
            rel = f"crds/{fname}"  # Helm v3 crds dir (main.go:20)
            content = doc  # CRDs install as-is, never templated
        else:
            rel = f"templates/{fname}"
            content = template_doc(doc)
        out[rel] = content.rstrip("\n") + "\n"
    for rel, content in out.items():
        path = os.path.join(CHART, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
    return out


def _render_blocks(text: str, values: dict) -> str:
    """Evaluate the {{- if .Values.x }} / {{- range .Values.x }} line
    blocks this generator emits (non-nested)."""
    out = []
    lines = text.splitlines()
    i = 0
    end_re = re.compile(r"\s*\{\{- end \}\}\s*$")
    if_re = re.compile(r"\s*\{\{- if \.Values\.(\w+) \}\}\s*$")
    range_re = re.compile(r"\s*\{\{- range \.Values\.(\w+) \}\}\s*$")
    while i < len(lines):
        m_if = if_re.match(lines[i])
        m_rg = range_re.match(lines[i])
        if m_if or m_rg:
            body = []
            i += 1
            while not end_re.match(lines[i]):
                body.append(lines[i])
                i += 1
            i += 1  # the {{- end }} line
            if m_if:
                if values.get(m_if.group(1)):
                    out.extend(body)
            else:
                for item in values.get(m_rg.group(1), ()):
                    out.extend(b.replace("{{ . }}", str(item)) for b in body)
            continue
        out.append(lines[i])
        i += 1
    return "\n".join(out)


def render_chart(values: dict) -> str:
    """Minimal chart renderer (no helm binary in this image): evaluates the
    if/range blocks and {{ .Values.* }} expressions this generator emits.
    Used by the round-trip test to prove chart == manifest at default
    values."""
    rendered = []
    for rel in sorted(os.listdir(os.path.join(CHART, "crds"))):
        with open(os.path.join(CHART, "crds", rel)) as f:
            rendered.append(f.read().rstrip("\n"))
    tpl_dir = os.path.join(CHART, "templates")
    for rel in sorted(os.listdir(tpl_dir)):
        if rel.startswith("_"):
            continue
        with open(os.path.join(tpl_dir, rel)) as f:
            text = _render_blocks(f.read(), values)

        def sub(m):
            cur = values
            for part in m.group(1).split(".")[2:]:
                cur = cur[part]
            return str(cur).lower() if isinstance(cur, bool) else str(cur)

        text = re.sub(r"\{\{ (\.Values[.\w]+) \}\}", sub, text)
        rendered.append(text.rstrip("\n"))
    return "\n---\n".join(rendered) + "\n"


if __name__ == "__main__":
    files = generate()
    print(f"wrote {len(files)} chart files to {CHART}", file=sys.stderr)
