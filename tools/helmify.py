#!/usr/bin/env python
"""Helm-chart generator: deploy/gatekeeper.yaml -> charts/gatekeeper-tpu/.

The analogue of the reference's kustomize->helm converter
(/root/reference/cmd/build/helmify/main.go:1-199 + replacements.go): the
flattened deployment manifest is the single source of truth, split into one
chart template file per (kind, name) — CRDs into crds/ (Helm v3) — with
deploy-time knobs rewritten to `{{ .Values.* }}` references, and
values.yaml carrying the defaults extracted from the manifest itself, so
chart and raw manifest can never drift.

Run: python tools/helmify.py   (idempotent; writes charts/gatekeeper-tpu)
Verified by tests/test_helmify.py, which regenerates and round-trips the
chart against deploy/gatekeeper.yaml.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MANIFEST = os.path.join(REPO, "deploy", "gatekeeper.yaml")
CHART = os.path.join(REPO, "charts", "gatekeeper-tpu")

CHART_YAML = """\
apiVersion: v2
name: gatekeeper-tpu
description: TPU-native Gatekeeper-class policy controller (vectorized audit + admission)
type: application
version: 3.1.0
appVersion: "3.1.0"
"""

HELPERS_TPL = """\
{{- define "gatekeeper-tpu.labels" -}}
app: gatekeeper-tpu
chart: {{ .Chart.Name }}
release: {{ .Release.Name }}
heritage: {{ .Release.Service }}
{{- end }}
"""

# deploy-time knobs: literal text in deploy/gatekeeper.yaml -> (values key,
# template expression).  The default value recorded in values.yaml is
# extracted from the manifest text, mirroring replacements.go's table.
# ORDER MATTERS: "containerPort: 8443" must rewrite before "port: 8443".
REPLACEMENTS = [
    ("image: gatekeeper-tpu:latest",
     "image", "image: {{ .Values.image.repository }}:{{ .Values.image.tag }}"),
    ("imagePullPolicy: IfNotPresent",
     "image", "imagePullPolicy: {{ .Values.image.pullPolicy }}"),
    ("replicas: 3",
     "replicas", "replicas: {{ .Values.replicas }}"),
    ("- --log-level=INFO",
     "logLevel", "- --log-level={{ .Values.logLevel }}"),
    ("- --audit-chunk-size=0",
     "auditChunkSize",
     "- --audit-chunk-size={{ .Values.auditChunkSize }}"),
    # pod scheduling knobs (reference charts/gatekeeper/values.yaml:14-16):
    # the nodeSelector literal anchors the affinity/tolerations
    # conditionals, which are absent from the manifest (empty defaults)
    ("      nodeSelector:\n        kubernetes.io/os: linux",
     "nodeSelector",
     "      nodeSelector:\n"
     "        {{- toYaml .Values.nodeSelector | nindent 8 }}\n"
     "      {{- if .Values.affinity }}\n"
     "      affinity:\n"
     "        {{- toYaml .Values.affinity | nindent 8 }}\n"
     "      {{- end }}\n"
     "      {{- if .Values.tolerations }}\n"
     "      tolerations:\n"
     "        {{- toYaml .Values.tolerations | nindent 8 }}\n"
     "      {{- end }}"),
    ("      annotations:\n"
     "        container.seccomp.security.alpha.kubernetes.io/manager: "
     "runtime/default",
     "podAnnotations",
     "      annotations:\n"
     "        {{- toYaml .Values.podAnnotations | nindent 8 }}"),
    ("          resources:\n"
     "            limits:\n"
     "              cpu: 1000m\n"
     "              memory: 512Mi\n"
     '              google.com/tpu: "1"\n'
     "            requests:\n"
     "              cpu: 100m\n"
     "              memory: 256Mi",
     "resources",
     "          resources:\n"
     "            limits:\n"
     "              cpu: {{ .Values.resources.limits.cpu }}\n"
     "              memory: {{ .Values.resources.limits.memory }}\n"
     '              {{ .Values.tpuResource }}: "{{ .Values.tpuCount }}"\n'
     "            requests:\n"
     "              cpu: {{ .Values.resources.requests.cpu }}\n"
     "              memory: {{ .Values.resources.requests.memory }}"),
    ("- --audit-interval=60",
     "auditInterval", "- --audit-interval={{ .Values.auditInterval }}"),
    ("- --constraint-violations-limit=20",
     "constraintViolationsLimit",
     "- --constraint-violations-limit={{ .Values.constraintViolationsLimit }}"),
    ("- --driver=tpu", "driver", "- --driver={{ .Values.driver }}"),
    ("- --port=8443", "webhookPort", "- --port={{ .Values.webhookPort }}"),
    ("containerPort: 8443",
     "webhookPort", "containerPort: {{ .Values.webhookPort }}"),
    ("port: 8443", "webhookPort", "port: {{ .Values.webhookPort }}"),
    ("containerPort: 8888",
     "prometheusPort", "containerPort: {{ .Values.prometheusPort }}"),
    # boolean flag present in the manifest -> gated on a value (default
    # matches the manifest: enabled)
    ("- --log-denies",
     "logDenies",
     "{{- if .Values.logDenies }}\n"
     "            - --log-denies\n"
     "            {{- end }}"),
    # repeatable flag -> range over a list value
    ("- --exempt-namespace=gatekeeper-system",
     "exemptNamespaces",
     "{{- range .Values.exemptNamespaces }}\n"
     "            - --exempt-namespace={{ . }}\n"
     "            {{- end }}"),
    # flags NOT in the manifest, exposed as off-by-default conditionals:
    # anchored on existing arg lines so the chart stays a pure derivation
    # of the manifest (at default values these render to the anchor alone)
    ("- --operation=webhook\n            - --operation=status",
     "emitAdmissionEvents",
     "- --operation=webhook\n"
     "            - --operation=status\n"
     "            {{- if .Values.emitAdmissionEvents }}\n"
     "            - --emit-admission-events\n"
     "            {{- end }}"),
    ("- --operation=audit\n            - --operation=status",
     "auditFromCache",
     "- --operation=audit\n"
     "            - --operation=status\n"
     "            {{- if .Values.auditFromCache }}\n"
     "            - --audit-from-cache\n"
     "            {{- end }}\n"
     "            {{- if .Values.emitAuditEvents }}\n"
     "            - --emit-audit-events\n"
     "            {{- end }}"),
]

# every key here is referenced by a template expression in REPLACEMENTS —
# a knob with no template reference would be silently discarded at install
VALUES_DEFAULTS = {
    "image": {
        "repository": "gatekeeper-tpu",
        "tag": "latest",
        "pullPolicy": "IfNotPresent",
    },
    "replicas": 3,
    "auditInterval": 60,
    "constraintViolationsLimit": 20,
    "auditFromCache": False,
    "auditChunkSize": 0,
    "disableValidatingWebhook": False,
    "logLevel": "INFO",
    "driver": "tpu",
    "webhookPort": 8443,
    "prometheusPort": 8888,
    "tpuResource": "google.com/tpu",
    "tpuCount": 1,
    "logDenies": True,  # the deploy manifest enables it
    "exemptNamespaces": ["gatekeeper-system"],
    "emitAdmissionEvents": False,
    "emitAuditEvents": False,
    "nodeSelector": {"kubernetes.io/os": "linux"},
    "affinity": {},
    "tolerations": [],
    "podAnnotations": {
        "container.seccomp.security.alpha.kubernetes.io/manager":
            "runtime/default",
    },
    "resources": {
        "limits": {"cpu": "1000m", "memory": "512Mi"},
        "requests": {"cpu": "100m", "memory": "256Mi"},
    },
}

# README parameter table: every key of the reference chart's values
# surface (/root/reference/charts/gatekeeper/values.yaml:1-25) plus the
# TPU-specific knobs.  tests/test_helmify.py asserts the reference key
# set is covered.
README_PARAMS = [
    ("auditInterval", "The frequency with which audit is run", "`60`"),
    ("constraintViolationsLimit",
     "The maximum # of audit violations reported on a constraint", "`20`"),
    ("auditFromCache",
     "Take the roster of resources to audit from the inventory cache",
     "`false`"),
    ("auditChunkSize",
     "Chunk size for listing cluster resources for audit", "`0`"),
    ("disableValidatingWebhook", "Disable ValidatingWebhook", "`false`"),
    ("emitAdmissionEvents",
     "Emit K8s events in gatekeeper namespace for admission violations",
     "`false`"),
    ("emitAuditEvents",
     "Emit K8s events in gatekeeper namespace for audit violations",
     "`false`"),
    ("logLevel", "Minimum log level", "`INFO`"),
    ("logDenies", "Log all denies (reference --log-denies flag)", "`true`"),
    ("image.pullPolicy", "The image pull policy", "`IfNotPresent`"),
    ("image.repository", "Image repository", "`gatekeeper-tpu`"),
    ("image.tag", "The image tag to use", "`latest`"),
    ("resources", "The resource request/limits for the container image",
     "limits: 1 CPU, 512Mi, requests: 100m CPU, 256Mi"),
    ("nodeSelector", "The node selector to use for pod scheduling",
     "`kubernetes.io/os: linux`"),
    ("affinity", "The node affinity to use for pod scheduling", "`{}`"),
    ("tolerations", "The tolerations to use for pod scheduling", "`[]`"),
    ("replicas", "The number of webhook replicas to deploy", "`3`"),
    ("podAnnotations", "The annotations to add to the pods",
     "`container.seccomp.security.alpha.kubernetes.io/manager: "
     "runtime/default`"),
    ("exemptNamespaces", "Namespaces exempted from admission",
     "`[gatekeeper-system]`"),
    ("driver", "Evaluation backend (`tpu` or `interp`)", "`tpu`"),
    ("webhookPort", "Webhook HTTPS port", "`8443`"),
    ("prometheusPort", "Prometheus metrics port", "`8888`"),
    ("tpuResource", "Accelerator resource name requested by the pods",
     "`google.com/tpu`"),
    ("tpuCount", "Accelerators per pod", "`1`"),
]


def render_readme() -> str:
    rows = "\n".join(
        f"| {k} | {d} | {v} |" for k, d, v in README_PARAMS
    )
    return f"""\
# gatekeeper-tpu Helm Chart

TPU-native Gatekeeper-class policy controller: validating admission
webhook plus audit, evaluating constraints on a vectorized JAX/TPU
backend.

## Install

```bash
helm install gatekeeper-tpu ./charts/gatekeeper-tpu
```

## Parameters

| Parameter | Description | Default |
|:----------|:------------|:--------|
{rows}

## Contributing Changes

This chart is autogenerated from the static manifest
`deploy/gatekeeper.yaml` by `tools/helmify.py` (the analogue of the
reference's `cmd/build/helmify`).  Edit the manifest and/or the
generator and run `python tools/helmify.py`; `tests/test_helmify.py`
fails if the committed chart drifts from the generator output.
"""

_KIND_RE = re.compile(r"^kind:\s+(\S+)\s*$", re.MULTILINE)
# exactly two spaces: metadata.name (helmify main.go:26-27)
_NAME_RE = re.compile(r"^  name:\s+(\S+)\s*$", re.MULTILINE)


def split_docs(text: str):
    docs = []
    for chunk in re.split(r"^---\s*$", text, flags=re.MULTILINE):
        chunk = chunk.strip("\n")
        if not chunk.strip() or all(
            line.strip().startswith("#") or not line.strip()
            for line in chunk.splitlines()
        ):
            continue
        docs.append(chunk)
    return docs


def doc_identity(doc: str):
    km = _KIND_RE.search(doc)
    nm = _NAME_RE.search(doc)
    if not km or not nm:
        raise ValueError(f"document without kind/name: {doc[:120]!r}")
    return km.group(1).strip("\"'"), nm.group(1).strip("\"'")


def template_doc(doc: str) -> str:
    for literal, _key, repl in REPLACEMENTS:
        doc = doc.replace(literal, repl)
    return doc


def render_values(values: dict, indent: int = 0) -> str:
    import json

    lines = []
    pad = "  " * indent
    for k, v in values.items():
        if isinstance(v, dict) and v:
            lines.append(f"{pad}{k}:")
            lines.append(render_values(v, indent + 1))
        else:
            # empty dicts inline as {} — a dangling "key:" parses as null
            lines.append(f"{pad}{k}: {json.dumps(v)}")
    return "\n".join(lines)


def generate() -> dict:
    """Write the chart; returns {relative path: content}."""
    with open(MANIFEST) as f:
        manifest = f.read()
    out = {
        "Chart.yaml": CHART_YAML,
        "values.yaml": render_values(VALUES_DEFAULTS) + "\n",
        "README.md": render_readme(),
        "templates/_helpers.tpl": HELPERS_TPL,
    }
    for doc in split_docs(manifest):
        kind, name = doc_identity(doc)
        fname = f"{name}-{kind.lower()}.yaml"
        if kind == "CustomResourceDefinition":
            rel = f"crds/{fname}"  # Helm v3 crds dir (main.go:20)
            content = doc  # CRDs install as-is, never templated
        else:
            rel = f"templates/{fname}"
            content = template_doc(doc)
            if kind == "ValidatingWebhookConfiguration":
                # reference chart knob: the whole webhook registration
                # is omitted when disableValidatingWebhook=true
                content = (
                    "{{- if not .Values.disableValidatingWebhook }}\n"
                    + content.rstrip("\n")
                    + "\n{{- end }}"
                )
        out[rel] = content.rstrip("\n") + "\n"
    for rel, content in out.items():
        path = os.path.join(CHART, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(content)
    return out


def _to_yaml(v, indent: int) -> str:
    """Tiny toYaml: dicts/lists of scalars and nested dicts, at the
    given absolute indent (first line unindented; callers place it)."""
    import json

    pad = " " * indent
    if isinstance(v, dict):
        lines = []
        for k, val in v.items():
            if isinstance(val, (dict, list)) and val:
                lines.append(f"{pad}{k}:")
                lines.append(_to_yaml(val, indent + 2))
            else:
                lines.append(f"{pad}{k}: {json.dumps(val)}")
        return "\n".join(lines)
    if isinstance(v, list):
        lines = []
        for item in v:
            body = _to_yaml(item, indent + 2)
            lines.append(f"{pad}- {body[indent + 2:]}" if isinstance(
                item, (dict, list)) else f"{pad}- {json.dumps(item)}")
        return "\n".join(lines)
    return f"{pad}{json.dumps(v)}"


def _render_blocks(text: str, values: dict) -> str:
    """Evaluate the {{- if [not] .Values.x }} / {{- range .Values.x }} /
    {{- toYaml .Values.x | nindent N }} line blocks this generator emits
    (non-nested)."""
    out = []
    lines = text.splitlines()
    i = 0
    end_re = re.compile(r"\s*\{\{- end \}\}\s*$")
    if_re = re.compile(r"\s*\{\{- if (not )?\.Values\.(\w+) \}\}\s*$")
    range_re = re.compile(r"\s*\{\{- range \.Values\.(\w+) \}\}\s*$")
    toyaml_re = re.compile(
        r"\s*\{\{- toYaml \.Values\.(\w+) \| nindent (\d+) \}\}\s*$"
    )
    while i < len(lines):
        m_if = if_re.match(lines[i])
        m_rg = range_re.match(lines[i])
        m_ty = toyaml_re.match(lines[i])
        if m_ty:
            v = values.get(m_ty.group(1))
            if v:
                out.append(_to_yaml(v, int(m_ty.group(2))))
            i += 1
            continue
        if m_if or m_rg:
            body = []
            i += 1
            while not end_re.match(lines[i]):
                body.append(lines[i])
                i += 1
            i += 1  # the {{- end }} line
            if m_if:
                truthy = bool(values.get(m_if.group(2)))
                if truthy != bool(m_if.group(1)):  # group(1): "not "
                    # recurse: toYaml lines may sit inside an if body
                    out.extend(
                        _render_blocks("\n".join(body), values).splitlines()
                    )
            else:
                for item in values.get(m_rg.group(1), ()):
                    out.extend(b.replace("{{ . }}", str(item)) for b in body)
            continue
        out.append(lines[i])
        i += 1
    return "\n".join(out)


def render_chart(values: dict) -> str:
    """Minimal chart renderer (no helm binary in this image): evaluates the
    if/range blocks and {{ .Values.* }} expressions this generator emits.
    Used by the round-trip test to prove chart == manifest at default
    values."""
    rendered = []
    for rel in sorted(os.listdir(os.path.join(CHART, "crds"))):
        with open(os.path.join(CHART, "crds", rel)) as f:
            rendered.append(f.read().rstrip("\n"))
    tpl_dir = os.path.join(CHART, "templates")
    for rel in sorted(os.listdir(tpl_dir)):
        if rel.startswith("_"):
            continue
        with open(os.path.join(tpl_dir, rel)) as f:
            text = _render_blocks(f.read(), values)

        def sub(m):
            cur = values
            for part in m.group(1).split(".")[2:]:
                cur = cur[part]
            return str(cur).lower() if isinstance(cur, bool) else str(cur)

        text = re.sub(r"\{\{ (\.Values[.\w]+) \}\}", sub, text)
        rendered.append(text.rstrip("\n"))
    return "\n---\n".join(rendered) + "\n"


if __name__ == "__main__":
    files = generate()
    print(f"wrote {len(files)} chart files to {CHART}", file=sys.stderr)
