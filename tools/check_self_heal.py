#!/usr/bin/env python
"""Self-healing conformance check (ISSUE 8; wired tier-1 via
tests/test_self_heal_tool.py, also runnable standalone):

Two supervised replicas restore one sealed snapshot behind the front
door.  A parity-checked request stream runs against the door; mid-stream
one replica is SIGKILLed.  The check asserts:

1. **zero failed admissions** — every request in the stream answers 200
   (the front door's immediate-ejection + bounded retry covers the kill
   window);
2. **zero verdict divergence** — every answer (before, during and after
   the kill) matches a freshly loaded interpreter oracle: allow/deny AND
   the rendered violation text (sans the "[denied by ...]" prefix);
3. **auto-restart, warm** — the supervisor detects the exit, respawns
   the replica from the shared snapshot + AOT cache (restore_outcome
   "restored", never cold), re-points the front door at the new port,
   and the revived replica serves parity-checked traffic again.

Run: python tools/check_self_heal.py  (exit 0 clean, 1 with findings).
Spawns replica subprocesses; where spawn is unavailable the tier-1
wrapper skips cleanly (same contract as check_fleet_parity).
"""

from __future__ import annotations

import http.client
import json
import os
import re
import signal
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_TEMPLATES = 2
# the stream's pods reference namespaces ns-0..ns-{N_STREAM-1}; the
# corpus must cover them — a standalone replica only seeds Namespace
# objects for the restored pack's rows (fleet/replica.py)
N_RESOURCES = 64
N_STREAM = 60          # requests in the parity-checked stream
KILL_AT = 20           # stream index at which one replica is killed
RECOVERY_BUDGET_S = 30.0


def _requests():
    from gatekeeper_tpu.util.synthetic import make_pods

    pods = make_pods(N_STREAM, seed=41, violation_rate=0.5)
    out = []
    for i, p in enumerate(pods):
        out.append({
            "uid": f"self-heal-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "self-heal"},
            "object": p,
        })
    return out


def _oracle_verdicts(reqs):
    from gatekeeper_tpu.util.synthetic import build_oracle

    oracle = build_oracle(N_TEMPLATES, N_RESOURCES)
    out = []
    for req in reqs:
        results = oracle.review(
            {k: req[k] for k in
             ("kind", "name", "namespace", "operation", "object")}
        ).results()
        out.append((not results, sorted(r.msg for r in results)))
    return out


def _post(port: int, body: bytes):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", "/v1/admit", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _check_verdict(i: int, data: bytes, oracle_verdicts, problems: list):
    try:
        out = json.loads(data)["response"]
    except Exception as e:
        problems.append(f"request {i}: unparseable body ({e})")
        return
    allowed = out["allowed"]
    msgs = sorted(
        re.sub(r"^\[denied by [^\]]+\] ", "", m)
        for m in (out.get("status") or {}).get("message", "").split("\n")
        if m
    ) if not allowed else []
    o_allowed, o_msgs = oracle_verdicts[i]
    if allowed != o_allowed or (not allowed and msgs != o_msgs):
        problems.append(
            f"request {i}: verdict diverged from the oracle "
            f"(fleet {allowed}/{msgs} oracle {o_allowed}/{o_msgs})"
        )


def run_checks() -> list:
    import shutil

    from gatekeeper_tpu.fleet import FrontDoor, ReplicaSupervisor
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.synthetic import build_driver

    problems: list = []
    root = tempfile.mkdtemp(prefix="gk-self-heal-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)
    sup = None
    door = None
    try:
        client = build_driver(N_TEMPLATES, N_RESOURCES)
        client.audit_capped(50)
        if Snapshotter(client, snap_dir, interval_s=0.0).write_once() is None:
            return ["snapshot write failed; cannot stage the fleet"]
        reqs = _requests()
        oracle_verdicts = _oracle_verdicts(reqs)

        door_box: dict = {}

        def on_change(rid, backend):
            d = door_box.get("door")
            if d is None:
                return
            if backend is None:
                d.suspend(rid)
            else:
                d.set_backend(rid, backend["host"], backend["port"])

        sup = ReplicaSupervisor(
            snapshot_dir=snap_dir, cache_dir=cache_dir,
            env={"JAX_PLATFORMS": "cpu"},
            heartbeat_s=0.25, miss_threshold=2, backoff_base_s=0.1,
            on_backend_change=on_change,
        )
        handles = sup.start(2)
        for h in handles:
            if h.ready.get("restore_outcome") != "restored":
                problems.append(
                    f"replica {h.replica_id} came up "
                    f"{h.ready.get('restore_outcome')!r}, not warm"
                )
        if problems:
            return problems
        door = FrontDoor(
            [h.backend() for h in handles], probe_interval_s=0.1
        ).start()
        door_box["door"] = door

        victim = handles[1]
        killed_at = None
        for i, req in enumerate(reqs):
            if i == KILL_AT:
                os.kill(victim.proc.pid, signal.SIGKILL)
                killed_at = time.monotonic()
            body = json.dumps({"request": req}).encode()
            st, _hd, data = _post(door.port, body)
            if st != 200:
                problems.append(
                    f"request {i}: front door answered {st} "
                    f"({'during' if i >= KILL_AT else 'before'} the kill "
                    f"window) — a FAILED admission"
                )
                continue
            _check_verdict(i, data, oracle_verdicts, problems)

        # the supervisor restarts the victim warm and re-points the door
        deadline = killed_at + RECOVERY_BUDGET_S
        rid = victim.replica_id
        while time.monotonic() < deadline:
            st = sup.status()[rid]
            if st["state"] == "running" and st["restarts"] >= 1:
                break
            time.sleep(0.1)
        st = sup.status()[rid]
        if st["state"] != "running" or st["restarts"] < 1:
            problems.append(
                f"replica {rid} was not auto-restarted within "
                f"{RECOVERY_BUDGET_S:.0f}s: {st}"
            )
            return problems
        recovery_s = time.monotonic() - killed_at
        new_handle = [h for h in sup.handles()
                      if h.replica_id == rid][0]
        if new_handle.ready.get("restore_outcome") != "restored":
            problems.append(
                f"restarted replica {rid} came up "
                f"{new_handle.ready.get('restore_outcome')!r} — the warm "
                f"path regressed"
            )

        # post-recovery: both replicas serve parity-checked traffic
        served: set = set()
        for i, req in enumerate(reqs[:16]):
            body = json.dumps({"request": req}).encode()
            st_code, hd, data = _post(door.port, body)
            if st_code != 200:
                problems.append(
                    f"post-recovery request {i}: front door answered "
                    f"{st_code}"
                )
                continue
            served.add(hd.get("X-GK-Replica", ""))
            _check_verdict(i, data, oracle_verdicts, problems)
        if rid not in served:
            problems.append(
                f"restarted replica {rid} took no post-recovery traffic "
                f"(served by {sorted(served)})"
            )
        print(f"self-heal: recovery in {recovery_s:.2f}s "
              f"(spawn-to-ready {st['last_restart_s']}s), "
              f"door stats {json.dumps(door.stats())}", file=sys.stderr)
        return problems
    finally:
        if door is not None:
            door.stop()
        if sup is not None:
            sup.stop()
        shutil.rmtree(root, ignore_errors=True)


def main() -> int:
    problems = run_checks()
    if problems:
        print("self-heal check FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(
        f"self-heal ok: {N_STREAM}-request parity stream survived a "
        f"SIGKILL at request {KILL_AT} with zero failed admissions and "
        f"zero verdict divergence; the replica auto-restarted warm and "
        f"rejoined the front door"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
