#!/usr/bin/env python
"""Headline benchmark: END-TO-END audit sweep on TPU.

Config (BASELINE.md "synthetic"): N constraint templates x M cluster
resources.  The measured sweep is the production steady state — one object
mutated since the last sweep — and includes everything the audit manager
pays: incremental review re-pack, the fused device dispatch (match kernel +
all vectorized violation programs), host render of up to cap violations
per constraint
(--constraint-violations-limit = 20, reference pkg/audit/manager.go:49), and
the update-list build.

Baseline note (see BASELINE.md): the reference is Go; no Go toolchain exists
in this image and installs are forbidden, so the reference harness cannot
run here.  vs_baseline is computed against this repo's Python interpreter
oracle measured on a slice of the same workload, DERATED by 50x as a
conservative stand-in for OPA's Go topdown (documented in BASELINE.md;
the raw interp rate is logged to stderr so the derate is auditable).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
All diagnostics go to stderr.  Override sizes with BENCH_TEMPLATES /
BENCH_RESOURCES / BENCH_BASELINE_SLICE; select configs with BENCH_CONFIG in
{synthetic, agilebank, latency, batch1m}.
"""

from __future__ import annotations

import json
import os
import sys
import time

GO_TOPDOWN_DERATE = 50.0  # conservative Go-vs-Python-interp speed factor


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def load_yaml_dir(pattern):
    import glob

    import yaml

    out = []
    for f in sorted(glob.glob(pattern)):
        with open(f) as fh:
            docs = [d for d in yaml.safe_load_all(fh) if d]
        out.extend(docs)
    return out


def bench_agilebank():
    """BASELINE config 'agilebank': full demo policy set x N mixed
    resources, from-cache audit sweep (end-to-end incl. render)."""
    import time as _t

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_COPIES", "1000"))
    base = "/root/reference/demo/agilebank"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    resources = load_yaml_dir(f"{base}/good_resources/*.yaml") + load_yaml_dir(
        f"{base}/bad_resources/*.yaml"
    )
    import copy as _copy

    total = 0
    for i in range(n_copies):
        for r in resources:
            r2 = _copy.deepcopy(r)
            r2["metadata"]["name"] = f"{r['metadata'].get('name', 'x')}-{i}"
            c.add_data(r2)
            total += 1
    log(f"agilebank: {n_cons} constraints x {total} resources")
    c.audit()  # compile + warm
    # mutate one object so the sweep is honest steady-state, not a cache hit
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-epoch-bump"}})
    t0 = _t.time()
    results = c.audit().results()
    dur = _t.time() - t0
    log(f"agilebank end-to-end audit: {dur*1000:.0f}ms, "
        f"{len(results)} violations")
    print(json.dumps({
        "metric": f"agilebank end-to-end audit ({total} resources)",
        "value": round(dur, 3),
        "unit": "s",
        "vs_baseline": 0,
    }))


def bench_latency():
    """BASELINE config 'demo/basic': single-review admission latency
    through the full webhook handler (p50/p99), targeting <=2ms p99."""
    import time as _t

    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.webhook import ValidationHandler

    base = "/root/reference/demo/basic"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
    handler = ValidationHandler(c, kube=InMemoryKube())
    req = {
        "uid": "u", "kind": {"group": "", "version": "v1",
                             "kind": "Namespace"},
        "name": "test", "namespace": "", "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "test", "labels": {}}},
    }
    for _ in range(20):  # warm: compile + caches
        handler.handle(req)
    times = []
    for _ in range(int(os.environ.get("BENCH_ITERS", "500"))):
        t0 = _t.perf_counter()
        handler.handle(req)
        times.append(_t.perf_counter() - t0)
    arr = np.array(times) * 1000
    log(f"admission latency ms: p50={np.percentile(arr, 50):.2f} "
        f"p99={np.percentile(arr, 99):.2f} max={arr.max():.2f}")
    print(json.dumps({
        "metric": "admission handler p99 latency (demo/basic, deny path)",
        "value": round(float(np.percentile(arr, 99)), 3),
        "unit": "ms",
        "vs_baseline": 0,
    }))


def bench_batch1m():
    """BASELINE config 'mesh': 1M admission-review batch streamed through
    review_batch in device-sized chunks (the streaming-webhook shape)."""
    import time as _t

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_TEMPLATES", "10"))
    n_reviews = int(os.environ.get("BENCH_REVIEWS", "1000000"))
    chunk = int(os.environ.get("BENCH_CHUNK", "65536"))
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    pods = make_pods(min(n_reviews, 4096), seed=5)
    reqs = []
    for i in range(len(pods)):
        p = pods[i]
        reqs.append({
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "object": p,
        })
    driver = c.driver

    def batch_of(start, n):
        return [reqs[(start + j) % len(reqs)] for j in range(n)]

    # warm with the exact batch sizes the timed loop dispatches (full chunk
    # + the final partial chunk) so no XLA compile lands in the timed region
    driver.review_batch(batch_of(0, min(chunk, n_reviews)))
    tail = n_reviews % chunk
    if tail and n_reviews > chunk:
        driver.review_batch(batch_of(0, tail))
    t0 = _t.time()
    done = 0
    while done < n_reviews:
        n = min(chunk, n_reviews - done)
        driver.review_batch(batch_of(done, n))
        done += n
    dur = _t.time() - t0
    rate = n_reviews / dur
    log(f"batch1m: {n_reviews} reviews x {n_templates} constraints in "
        f"{dur:.1f}s ({rate:.0f} reviews/s)")
    print(json.dumps({
        "metric": f"streamed admission reviews/sec ({n_templates} constraints, chunk {chunk})",
        "value": round(rate, 1),
        "unit": "reviews/s",
        "vs_baseline": 0,
    }))


def bench_ingest():
    """VERDICT r1 item 6: template-ingest storm with interleaved reviews
    under async compile.  Reports ingest-to-first-eval p50 — the latency a
    review pays when it lands right after a template mutation (served from
    the interpreter while XLA compiles in the background)."""
    import time as _t

    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    templates, constraints = make_templates(n_templates)
    pod = make_pods(1, seed=3, violation_rate=1.0)[0]
    req = {
        "uid": "u",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": pod,
    }
    c = Client(driver=TpuDriver(async_compile=True))
    lat = []
    t0 = _t.time()
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
        s = _t.perf_counter()
        c.review(req)  # lands mid-storm; interp-served while compiling
        lat.append(_t.perf_counter() - s)
    storm_s = _t.time() - t0
    c.driver.wait_ready(timeout=600.0)
    ready_s = _t.time() - t0
    arr = np.array(lat) * 1000
    log(f"ingest storm: {n_templates} templates in {storm_s:.1f}s "
        f"(device-ready at {ready_s:.1f}s); interleaved review latency "
        f"p50={np.percentile(arr, 50):.1f}ms p99={np.percentile(arr, 99):.1f}ms")
    c.driver._compiler.stop()
    print(json.dumps({
        "metric": f"ingest-to-first-eval p50 ({n_templates}-template storm, async compile)",
        "value": round(float(np.percentile(arr, 50)), 3),
        "unit": "ms",
        "vs_baseline": 0,
    }))


def main():
    config = os.environ.get("BENCH_CONFIG", "synthetic")
    if config == "agilebank":
        return bench_agilebank()
    if config == "latency":
        return bench_latency()
    if config == "batch1m":
        return bench_batch1m()
    if config == "ingest":
        return bench_ingest()

    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    n_resources = int(os.environ.get("BENCH_RESOURCES", "100000"))
    baseline_slice = int(os.environ.get("BENCH_BASELINE_SLICE", "20"))
    cap = int(os.environ.get("BENCH_CAP", "20"))

    import jax

    log(f"devices: {jax.devices()}")

    from gatekeeper_tpu.util.synthetic import build_driver, make_pods, make_templates

    t0 = time.time()
    client = build_driver(n_templates, n_resources)
    driver = client.driver
    log(f"workload built: {n_templates} templates x {n_resources} resources "
        f"in {time.time()-t0:.1f}s")

    # ---- cold sweep: review build + pack + XLA compile + device + render
    t0 = time.time()
    res, totals = client.audit_capped(cap)
    cold_s = time.time() - t0
    n_results = len(res.results())
    n_capped = sum(1 for v in totals.values() if v[1] == "resources")
    log(f"cold end-to-end capped audit: {cold_s:.1f}s "
        f"({n_results} violations kept, {n_capped}/{len(totals)} constraints at cap)")

    # ---- steady state: one object mutated since the last sweep ----------
    times = []
    for i in range(5):
        p = make_pods(1, seed=1000 + i, violation_rate=1.0)[0]
        p["metadata"]["name"] = f"bench-delta-{i}"
        client.add_data(p)
        t0 = time.time()
        res, totals = client.audit_capped(cap)
        times.append(time.time() - t0)
        s = driver.last_sweep_stats
        log(f"  sweep {i}: {times[-1]*1000:.1f}ms | pack {s.get('pack_ms', 0):.1f} "
            f"device {s.get('device_ms', 0):.1f} fetch {s.get('fetch_ms', 0):.1f} "
            f"render {s.get('render_ms', 0):.1f} ms | fetch {s.get('fetch_bytes', 0)/1e3:.1f}KB "
            f"fallback_rows {s.get('fallback_rows', 0):.0f} "
            f"rendered_cells {s.get('rendered_cells', 0):.0f}")
    sweep_s = min(times)
    n_results = len(res.results())
    log(f"steady-state end-to-end sweep (1 mutation): {sweep_s*1000:.1f}ms "
        f"({n_results} violations kept)")

    # mask-kernel throughput for continuity with round-1 reporting
    cells = len(driver._ordered_constraints()) * driver._audit_pack.n_rows
    log(f"device cells per sweep: {cells} "
        f"({cells/sweep_s/1e6:.1f}M cell-evals/s end-to-end)")

    # ---- baseline: interpreter oracle on a slice, derated (BASELINE.md) --
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver

    templates, constraints = make_templates(n_templates)
    ci = Client(driver=InterpDriver())
    for t in templates:
        ci.add_template(t)
    for c in constraints:
        ci.add_constraint(c)
    for p in make_pods(baseline_slice, seed=1):
        ci.add_data(p)
    t0 = time.time()
    ci.audit()
    interp_s = time.time() - t0
    interp_cells = n_templates * baseline_slice
    interp_rate = interp_cells / interp_s
    est_ref_rate = interp_rate * GO_TOPDOWN_DERATE
    est_ref_sweep_s = cells / est_ref_rate
    log(f"interp oracle: {interp_rate:.0f} evals/s; estimated Go-topdown "
        f"reference ({GO_TOPDOWN_DERATE:.0f}x derate): {est_ref_rate:.0f} "
        f"evals/s -> {est_ref_sweep_s:.0f}s for this sweep")

    print(
        json.dumps(
            {
                "metric": (
                    f"end-to-end audit sweep seconds ({n_templates} templates"
                    f" x {n_resources} resources, cap {cap}, steady-state)"
                ),
                "value": round(sweep_s, 3),
                "unit": "s",
                "vs_baseline": round(est_ref_sweep_s / sweep_s, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
