#!/usr/bin/env python
"""Headline benchmark: END-TO-END audit sweep on TPU, plus every other
BASELINE.md target config folded into the same artifact.

The default run (BENCH_CONFIG unset or "all") measures:
  - synthetic 500x100k steady-state capped audit sweep (the headline,
    BASELINE north star <1s on one v5e chip) with a pack/device/fetch/render
    breakdown and a bandwidth-roofline utilization estimate
  - admission p99 latency on demo/basic (north star <=2ms)
  - PSP library x 1k Pods audit (the reference benchmark's own fixtures)
  - agilebank full policy set x ~10k mixed resources audit
  - 1M-review streamed batch throughput (the "mesh" config shape)
  - template-ingest storm p50 (async compile, interp-served mid-storm)
  - constraint-count scaling curve N in {5..2000} (the reference's
    BenchmarkValidationHandler sweep, policy_benchmark_test.go:269)
  - multi-chip scaling of the device sweep on a virtual 8-device CPU mesh
    (subprocess; the real env exposes one chip)

and prints ONE JSON line: the headline metric/value/unit/vs_baseline plus
the secondary configs as extra keys.  Set BENCH_CONFIG to
{synthetic, latency, psp, agilebank, batch1m, ingest, curve, mesh} to run one
config alone (it then prints its own single JSON line).

Baseline note (see BASELINE.md): the reference is Go; no Go toolchain exists
in this image and installs are forbidden, so the reference harness cannot
run here.  vs_baseline is computed against this repo's Python interpreter
oracle measured on a slice of the same workload, DERATED by 50x as a
conservative stand-in for OPA's Go topdown (documented in BASELINE.md;
the raw interp rate is logged to stderr so the derate is auditable).

All diagnostics go to stderr.  Override sizes with BENCH_TEMPLATES /
BENCH_RESOURCES / BENCH_BASELINE_SLICE / BENCH_COPIES / BENCH_REVIEWS /
BENCH_INGEST_TEMPLATES / BENCH_CURVE.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

GO_TOPDOWN_DERATE = 50.0  # conservative Go-vs-Python-interp speed factor

# v5e lite HBM bandwidth for the roofline estimate (public spec: 819 GB/s)
V5E_HBM_GBPS = 819.0


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def settle_warmups():
    """Join the driver's background warm-ups (base-mask resolve + delta
    executable compile).  Production audit sweeps are interval-spaced, so
    these always finish between sweeps; the bench's back-to-back loop
    must wait explicitly or every sweep lands in the warm window and
    falls back to a full sweep."""
    from gatekeeper_tpu.ops import deltasweep

    for t in list(deltasweep._BG_THREADS):
        t.join(timeout=300)


def load_yaml_dir(pattern):
    import glob

    import yaml

    out = []
    for f in sorted(glob.glob(pattern)):
        with open(f) as fh:
            docs = [d for d in yaml.safe_load_all(fh) if d]
        out.extend(docs)
    return out


def bench_agilebank() -> dict:
    """BASELINE config 'agilebank': full demo policy set x N mixed
    resources, from-cache audit sweep (end-to-end incl. render)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_COPIES", "1000"))
    base = "/root/reference/demo/agilebank"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    resources = load_yaml_dir(f"{base}/good_resources/*.yaml") + load_yaml_dir(
        f"{base}/bad_resources/*.yaml"
    )
    import copy as _copy

    total = 0
    for i in range(n_copies):
        for r in resources:
            r2 = _copy.deepcopy(r)
            r2["metadata"]["name"] = f"{r['metadata'].get('name', 'x')}-{i}"
            c.add_data(r2)
            total += 1
    log(f"agilebank: {n_cons} constraints x {total} resources")
    c.audit_capped(20)  # compile + warm (full sweep)
    settle_warmups()  # base-mask + delta executable compile off-path
    # warm the delta path too, then time an honest steady-state sweep:
    # one object mutated since the last sweep
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-warm-bump"}})
    c.audit_capped(20)
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-epoch-bump"}})
    t0 = time.time()
    res, _totals = c.audit_capped(20)
    dur = time.time() - t0
    log(f"agilebank end-to-end capped audit: {dur*1000:.0f}ms, "
        f"{len(res.results())} violations kept")
    return {
        "metric": f"agilebank end-to-end audit ({total} resources)",
        "value": round(dur, 3),
        "unit": "s",
        "vs_baseline": 0,
    }


def bench_psp() -> dict:
    """BASELINE config 'PSP library x 1k Pods': the reference benchmark's
    own fixtures (pkg/webhook/testdata/psp-all-violations: 5 PSP
    templates/constraints + violating pods, policy_benchmark_test.go:265-271)
    scaled to ~1k cached Pods, steady-state capped audit."""
    import copy as _copy

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_PSP_COPIES", "200"))
    base = "/root/reference/pkg/webhook/testdata/psp-all-violations"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/psp-templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/psp-constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    pods = load_yaml_dir(f"{base}/psp-pods/*.yaml")
    total = 0
    for i in range(n_copies):
        for p in pods:
            p2 = _copy.deepcopy(p)
            p2["metadata"]["name"] = f"{p['metadata'].get('name', 'p')}-{i}"
            p2["metadata"].setdefault("namespace", "default")
            c.add_data(p2)
            total += 1
    log(f"psp: {n_cons} constraints x {total} pods")
    c.audit_capped(20)  # compile + warm (full sweep)
    settle_warmups()  # base-mask + delta executable compile off-path
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "psp-warm"}})
    c.audit_capped(20)  # warm the delta path
    p = _copy.deepcopy(pods[0])
    p["metadata"]["name"] = "psp-delta"
    p["metadata"].setdefault("namespace", "default")
    c.add_data(p)
    t0 = time.time()
    res, _totals = c.audit_capped(20)
    dur = time.time() - t0
    log(f"psp end-to-end capped audit: {dur*1000:.0f}ms, "
        f"{len(res.results())} violations kept")
    return {
        "metric": f"PSP library end-to-end audit ({n_cons} constraints x {total} pods)",
        "value": round(dur, 3),
        "unit": "s",
        "vs_baseline": 0,
    }


def bench_latency() -> dict:
    """BASELINE config 'demo/basic': single-review admission latency
    through the full webhook handler (p50/p99), targeting <=2ms p99."""
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.webhook import ValidationHandler

    base = "/root/reference/demo/basic"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
    handler = ValidationHandler(c, kube=InMemoryKube())
    req = {
        "uid": "u", "kind": {"group": "", "version": "v1",
                             "kind": "Namespace"},
        "name": "test", "namespace": "", "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "test", "labels": {}}},
    }
    for _ in range(20):  # warm: compile + caches
        handler.handle(req)
    # the production webhook server freezes long-lived state out of the
    # cyclic GC after warmup (webhook/server.py); do the same here — in the
    # combined run the synthetic sweep's 100k-object inventory is resident
    # in this process and a gen-2 GC pause otherwise lands in the p99
    import gc

    gc.collect()
    gc.freeze()
    # k runs inside one invocation: the >=2ms target must hold on bad runs
    # (relay/load variance), so the artifact reports median AND max p99
    # across runs, not one lucky sample
    n_runs = int(os.environ.get("BENCH_LATENCY_RUNS", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "500"))
    p50s, p99s = [], []
    for r in range(n_runs):
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            handler.handle(req)
            times.append(time.perf_counter() - t0)
        arr = np.array(times) * 1000
        p50s.append(float(np.percentile(arr, 50)))
        p99s.append(float(np.percentile(arr, 99)))
        log(f"admission latency run {r}: p50={p50s[-1]:.2f} "
            f"p99={p99s[-1]:.2f} max={arr.max():.2f} ms")
    p50, p99 = float(np.median(p50s)), float(np.median(p99s))
    log(f"admission latency ms over {n_runs} runs: p99 median={p99:.2f} "
        f"max={max(p99s):.2f}")
    srv_runs = [
        _server_level_latency(c, req)
        for _ in range(int(os.environ.get("BENCH_SERVER_RUNS", "3")))
    ]
    srv_p50 = float(np.median([r[0] for r in srv_runs]))
    srv_p99 = float(np.median([r[1] for r in srv_runs]))
    log(f"admission SERVER latency ms (TLS+batcher, {len(srv_runs)} runs): "
        f"p50 median={srv_p50:.2f} p99 median={srv_p99:.2f} "
        f"p99 max={max(r[1] for r in srv_runs):.2f}")
    stage_p50 = _stage_breakdown(handler, req)
    log(f"admission per-stage p50 ms: {stage_p50}")
    return {
        "stage_p50_ms": stage_p50,
        "metric": "admission handler p99 latency (demo/basic, deny path)",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": 0,
        "p50_ms": round(p50, 3),
        "p99_runs_ms": [round(x, 3) for x in p99s],
        "p99_max_ms": round(max(p99s), 3),
        "server_p99_ms": round(srv_p99, 3),
        "server_p50_ms": round(srv_p50, 3),
        "server_p99_runs_ms": [round(r[1], 3) for r in srv_runs],
        "server_p99_max_ms": round(max(r[1] for r in srv_runs), 3),
    }


def _stage_breakdown(handler, req, iters=50):
    """Per-stage p50s of the admission path from the always-on tracer
    (obs/trace.py): each request runs under a root span; the stage spans
    (cache_lookup / pack / dispatch / render) are aggregated so future
    perf PRs can claim stage-level wins from the BENCH artifact."""
    import numpy as np

    from gatekeeper_tpu.obs import trace as obstrace

    tracer = obstrace.get_tracer()
    tracer.clear()
    for _ in range(iters):
        with obstrace.root_span("admission"):
            handler.handle(req)
    samples = {}
    for t in tracer.traces(limit=iters):
        for stage, ms in obstrace.stage_breakdown(t).items():
            samples.setdefault(stage, []).append(ms)
    tracer.clear()
    return {
        stage: round(float(np.percentile(v, 50)), 4)
        for stage, v in sorted(samples.items())
    }


def _server_level_latency(client, req):
    """p50/p99 through the PRODUCTION path: HTTPS webhook server +
    micro-batcher + handler — what the apiserver actually observes (the
    <=2ms north star applies here, not just to the bare handler).  Where
    `cryptography` is unavailable (fleet replicas behind a TLS-terminating
    front door run exactly this way, docs/fleet.md), the server is driven
    over plain HTTP instead of skipping the measurement."""
    import json as _json
    import ssl

    import numpy as np

    try:
        from gatekeeper_tpu.certs import CertRotator
    except ImportError:
        CertRotator = None
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.webhook import (
        MicroBatcher, ValidationHandler, WebhookServer,
    )

    kube = InMemoryKube()
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        if CertRotator is not None:
            certfile, keyfile = CertRotator(kube).write_cert_files(td)
        else:
            certfile = keyfile = None
            log("server-level latency: 'cryptography' unavailable — "
                "measuring plain HTTP (TLS-terminating front door mode)")
        mb = MicroBatcher(client)
        handler = ValidationHandler(mb, kube=kube)
        srv = WebhookServer(handler, port=0, certfile=certfile, keyfile=keyfile)
        srv.start()
        try:
            body = _json.dumps({"request": req}).encode()
            # persistent connection, as the apiserver's webhook client uses
            # (keep-alive; the server speaks HTTP/1.1)
            import http.client

            if certfile is not None:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
                conn = http.client.HTTPSConnection(
                    "127.0.0.1", srv.port, context=ctx, timeout=10
                )
            else:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=10
                )

            def once():
                conn.request("POST", "/v1/admit", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                return _json.loads(resp.read())

            for _ in range(30):
                once()
            import gc

            gc.collect()
            gc.freeze()  # keep warmup garbage out of the timed p99
            times = []
            for _ in range(int(os.environ.get("BENCH_SERVER_ITERS", "300"))):
                t0 = time.perf_counter()
                once()
                times.append(time.perf_counter() - t0)
            arr = np.array(times) * 1000
            return float(np.percentile(arr, 50)), float(np.percentile(arr, 99))
        finally:
            srv.stop()
            mb.stop()


def bench_batch1m() -> dict:
    """BASELINE config 'mesh': 1M admission-review batch streamed through
    review_batch in device-sized chunks (the streaming-webhook shape)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_TEMPLATES_1M", "10"))
    n_reviews = int(os.environ.get("BENCH_REVIEWS", "1000000"))
    chunk = int(os.environ.get("BENCH_CHUNK", "65536"))
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    pods = make_pods(min(n_reviews, 4096), seed=5)
    reqs = []
    for i in range(len(pods)):
        p = pods[i]
        reqs.append({
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "object": p,
        })
    driver = c.driver

    def batch_of(start, n):
        return [reqs[(start + j) % len(reqs)] for j in range(n)]

    # warm with the exact batch sizes the timed loop dispatches (full chunk
    # + the final partial chunk) so no XLA compile lands in the timed region
    driver.review_batch(batch_of(0, min(chunk, n_reviews)))
    tail = n_reviews % chunk
    if tail and n_reviews > chunk:
        driver.review_batch(batch_of(0, tail))
    t0 = time.time()
    done = 0
    while done < n_reviews:
        n = min(chunk, n_reviews - done)
        driver.review_batch(batch_of(done, n))
        done += n
    dur = time.time() - t0
    rate = n_reviews / dur
    log(f"batch1m: {n_reviews} reviews x {n_templates} constraints in "
        f"{dur:.1f}s ({rate:.0f} reviews/s)")
    return {
        "metric": f"streamed admission reviews/sec ({n_templates} constraints, chunk {chunk})",
        "value": round(rate, 1),
        "unit": "reviews/s",
        "vs_baseline": 0,
    }


def bench_ingest() -> dict:
    """Template-ingest storm with interleaved reviews under async compile.

    TWO traffic shapes (reference contract: ingest never degrades
    admission, pkg/controller/constrainttemplate/stats_reporter.go:33-37):
    - repeat-content: ONE fixed request interleaved with every install —
      the replica/retry-storm shape, served by the whole-request memo
      with change-log repair.
    - unique-content: a DISTINCT object per interleaved review (the shape
      the r4 verdict demanded) — memo never hits; served by the
      incremental host-side numpy mask (ops/npside.py) with the exact
      interpreter render on positives.
    """
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_INGEST_TEMPLATES", "500"))
    templates, constraints = make_templates(n_templates)
    pod = make_pods(1, seed=3, violation_rate=1.0)[0]
    req = {
        "uid": "u",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": pod,
    }
    # unique-content traffic: compliant unique pods (clusters converge to
    # compliance; violating requests additionally pay the per-violation
    # interpreter render, reported separately below)
    upods = make_pods(n_templates, seed=29, violation_rate=0.0)
    vpods = make_pods(64, seed=31, violation_rate=1.0)

    def upod_req(p, i):
        return {
            "uid": f"u{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "bench"},
            "object": p,
        }

    c = Client(driver=TpuDriver(async_compile=True))
    # production webhook processes freeze long-lived state out of the
    # cyclic GC and take the collector off the admission path entirely
    # (webhook/server.py start(): freeze + disable + background sweeps);
    # the storm mirrors that policy or collections land in its p99
    import gc

    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        return _bench_ingest_storm(
            c, templates, constraints, req, upods, upod_req, vpods,
            n_templates,
        )
    finally:
        # a mid-storm exception must not leave the collector off for
        # every later folded config (main() swallows and continues)
        gc.enable()
        gc.unfreeze()
        c.driver._compiler.stop()


def _bench_ingest_storm(c, templates, constraints, req, upods, upod_req,
                        vpods, n_templates):
    import numpy as np

    lat, ulat, waits, evals = [], [], [], []
    t0 = time.time()
    for i, (t, k) in enumerate(zip(templates, constraints)):
        c.add_template(t)
        c.add_constraint(k)
        s = time.perf_counter()
        c.review(req)  # repeat content: memo + change-log repair
        lat.append(time.perf_counter() - s)
        s = time.perf_counter()
        c.review(upod_req(upods[i], i))  # unique content: np mask serve
        ulat.append(time.perf_counter() - s)
        stats = getattr(c.driver, "last_review_stats", {})
        waits.append(stats.get("lock_wait_ms", 0.0))
        evals.append(stats.get("eval_ms", 0.0))
    storm_s = time.time() - t0
    c.driver.wait_ready(timeout=600.0)
    ready_s = time.time() - t0
    # violating unique requests at full install (every render is a real
    # violation: the exactness filter can't be cheated)
    vlat = []
    for i, p in enumerate(vpods):
        s = time.perf_counter()
        c.review(upod_req(p, 10_000 + i))
        vlat.append(time.perf_counter() - s)
    arr = np.array(lat) * 1000
    uarr = np.array(ulat) * 1000
    varr = np.array(vlat) * 1000
    p50 = float(np.percentile(arr, 50))
    p99 = float(np.percentile(arr, 99))
    u50 = float(np.percentile(uarr, 50))
    u99 = float(np.percentile(uarr, 99))
    w50 = float(np.percentile(np.array(waits), 50))
    e50 = float(np.percentile(np.array(evals), 50))
    w99 = float(np.percentile(np.array(waits), 99))
    e99 = float(np.percentile(np.array(evals), 99))
    log(f"ingest storm: {n_templates} templates in {storm_s:.1f}s "
        f"(device-ready at {ready_s:.1f}s); repeat-content p50={p50:.2f}ms "
        f"p99={p99:.2f}ms; UNIQUE-content p50={u50:.2f}ms p99={u99:.2f}ms "
        f"(lock-wait p50 {w50:.2f}/p99 {w99:.2f}ms, "
        f"eval p50 {e50:.2f}/p99 {e99:.2f}ms); violating-unique "
        f"p50={float(np.percentile(varr, 50)):.2f}ms "
        f"p99={float(np.percentile(varr, 99)):.2f}ms")
    return {
        "metric": f"ingest-to-first-eval p50 ({n_templates}-template storm, async compile)",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": 0,
        "p99_ms": round(p99, 3),
        "unique_p50_ms": round(u50, 3),
        "unique_p99_ms": round(u99, 3),
        "violating_unique_p50_ms": round(float(np.percentile(varr, 50)), 3),
        "violating_unique_p99_ms": round(float(np.percentile(varr, 99)), 3),
        "queue_wait_p50_ms": round(w50, 3),
        "eval_p50_ms": round(e50, 3),
    }


def bench_render() -> dict:
    """Compiled violation rendering (ISSUE 4): violating-unique admission
    latency at full install — the deny path, where every flagged cell
    must produce its message — plus the raw render throughput and the
    plan-tier cell mix.  Same traffic shape as the ingest config's
    violating phase, isolated from the storm so the number measures
    rendering, not compile contention."""
    import gc

    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.metrics.views import global_registry
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_RENDER_TEMPLATES", "500"))
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
    vpods = make_pods(64, seed=31, violation_rate=1.0)

    def req(p, i):
        return {
            "uid": f"u{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "bench"},
            "object": p,
        }

    def tier_counts():
        out = {"static": 0.0, "slots": 0.0, "interp": 0.0}
        try:
            for key, v in global_registry().view_rows(
                "render_cells_total"
            ).items():
                if key and key[0] in out:
                    out[key[0]] += v
        except Exception:
            # best-effort bench telemetry: a registry shape change costs
            # the tier breakdown, not the run — but say so in the record
            out["error"] = "render_cells_total unavailable"
        return out

    c.review(req(make_pods(1, seed=9, violation_rate=1.0)[0], 1))  # warm
    # the counter is process-global and cumulative: snapshot it so the
    # reported plan mix covers THIS config's cells only (under
    # BENCH_CONFIG=all several earlier configs render too)
    tiers0 = tier_counts()
    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        # three rounds of fresh unique pods; the reported p50 is the best
        # round — pure host work, so the minimum is the true cost and
        # everything above it is scheduler noise (same convention as
        # calibrate_routing's host-path measurements)
        rounds = []
        cells, render_ms = 0.0, 0.0
        for r, pods in enumerate(
            (vpods, make_pods(64, seed=33, violation_rate=1.0),
             make_pods(64, seed=35, violation_rate=1.0))
        ):
            lat = []
            for i, p in enumerate(pods):
                s = time.perf_counter()
                c.review(req(p, (r + 1) * 10_000 + i))
                lat.append((time.perf_counter() - s) * 1e3)
                st = c.driver.last_render_stats
                cells += st.get("cells", 0.0)
                render_ms += (
                    st.get("plan_ms", 0.0) + st.get("interp_ms", 0.0)
                )
            rounds.append(np.array(lat))
    finally:
        gc.enable()
        gc.unfreeze()
    arr = min(rounds, key=lambda a: float(np.percentile(a, 50)))
    p50 = float(np.percentile(arr, 50))
    tiers = {
        k: v - tiers0.get(k, 0.0) for k, v in tier_counts().items()
    }
    planned = tiers["static"] + tiers["slots"]
    total = planned + tiers["interp"]
    cells_per_s = cells / (render_ms / 1e3) if render_ms else 0.0
    log(
        f"render: violating-unique p50={p50:.2f}ms "
        f"p99={float(np.percentile(arr, 99)):.2f}ms; "
        f"{cells:.0f} cells in {render_ms:.1f}ms "
        f"({cells_per_s:,.0f} cells/s); plan mix "
        f"static={tiers['static']:.0f} slots={tiers['slots']:.0f} "
        f"interp={tiers['interp']:.0f}"
        + (f" ({planned / total:.1%} compiled)" if total else "")
    )
    return {
        "metric": f"violating-unique admission p50 "
                  f"({n_templates} templates, compiled render)",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": 0,
        "ingest_violating_unique_p50_ms": round(p50, 3),
        "ingest_violating_unique_p99_ms": round(
            float(np.percentile(arr, 99)), 3),
        "render_cells_per_s": round(cells_per_s, 1),
        "render_cells": cells,
        "render_plan_fraction": round(planned / total, 4) if total else None,
        "render_cells_static": tiers["static"],
        "render_cells_slots": tiers["slots"],
        "render_cells_interp": tiers["interp"],
    }


def bench_slo() -> dict:
    """Cost-attribution overhead (ISSUE 5): the violating-unique
    admission p50 with the cost ledger enabled vs disabled, interleaved
    round-robin so co-tenant noise hits both arms alike.  Also exercises
    the SLO collect hook + OpenMetrics exemplar rendering once so the
    artifact records that the whole attribution surface works."""
    import gc

    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.metrics.views import Registry
    from gatekeeper_tpu.metrics.exporter import render_openmetrics
    from gatekeeper_tpu.obs import costs as obscosts
    from gatekeeper_tpu.obs import slo as obsslo
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_SLO_TEMPLATES", "500"))
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)

    def req(p, i):
        return {
            "uid": f"u{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "bench"},
            "object": p,
        }

    ledger = obscosts.get_ledger()
    was_enabled = ledger.enabled
    ledger.clear()
    c.review(req(make_pods(1, seed=9, violation_rate=1.0)[0], 1))  # warm
    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        # 3 interleaved rounds per arm, fresh unique pods every batch so
        # the request memo never serves either arm; best-round p50 per
        # arm (host work: the minimum is the true cost, the rest is
        # scheduler noise — the render config's convention)
        p50s = {False: [], True: []}
        seq = 0
        for r in range(3):
            for enabled in (False, True):
                ledger.enabled = enabled
                pods = make_pods(
                    64, seed=101 + 10 * r + enabled, violation_rate=1.0
                )
                lat = []
                for p in pods:
                    seq += 1
                    s = time.perf_counter()
                    c.review(req(p, seq))
                    lat.append((time.perf_counter() - s) * 1e3)
                p50s[enabled].append(float(np.percentile(lat, 50)))
    finally:
        gc.enable()
        gc.unfreeze()
        ledger.enabled = was_enabled
    p50_off = min(p50s[False])
    p50_on = min(p50s[True])
    overhead_pct = (
        (p50_on - p50_off) / p50_off * 100.0 if p50_off else 0.0
    )
    # attribution sanity on the same traffic: the ledger saw every
    # template, the top-K export caps labels, exemplars render
    snap = ledger.snapshot(top=10)
    reg = Registry()
    obscosts.collect_hook(reg)
    obsslo.collect_hook(reg)
    om = render_openmetrics(reg)
    exporting_ok = (
        om.endswith("# EOF\n")
        and len(snap["templates"]) == 10
        and bool(reg.view_rows("slo_burn_rate"))
    )
    ledger.clear()
    log(
        f"slo: violating-unique p50 ledger-off={p50_off:.2f}ms "
        f"on={p50_on:.2f}ms overhead={overhead_pct:+.2f}%; "
        f"window tracked {snap['tracked_templates']} templates; "
        f"export {'ok' if exporting_ok else 'BROKEN'}"
    )
    return {
        "metric": f"cost-attribution overhead on violating-unique "
                  f"admission p50 ({n_templates} templates)",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "vs_baseline": 0,
        "cost_attribution_overhead_pct": round(overhead_pct, 2),
        "ingest_p50_ms_ledger_off": round(p50_off, 3),
        "ingest_p50_ms_ledger_on": round(p50_on, 3),
        "cost_tracked_templates": snap["tracked_templates"],
        "cost_export_ok": exporting_ok,
    }


def bench_restart() -> dict:
    """Warm-restart recovery (SURVEY §5.4; the reference rebuilds all
    derived state on boot in seconds, pkg/controller/controller.go:124-126).

    Two fresh subprocesses over the synthetic corpus, sharing the
    persistent caches: the first populates the XLA-compile AND
    serialized-executable (AOT) caches; the second is the measured warm
    restart — process start to first full capped sweep.  The AOT cache is
    what removes the fused programs' TRACE time, which the XLA compile
    cache alone cannot save."""
    import subprocess

    n_t = int(os.environ.get("BENCH_RESTART_TEMPLATES",
                             os.environ.get("BENCH_TEMPLATES", "500")))
    n_r = int(os.environ.get("BENCH_RESTART_RESOURCES",
                             os.environ.get("BENCH_RESOURCES", "100000")))
    cache_dir = os.environ.get(
        "GK_XLA_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla-cache"),
    )
    code = f"N_T, N_R, CACHE = {n_t}, {n_r}, {cache_dir!r}\n" + r"""
import json, sys, time
sys.path.insert(0, ".")
from gatekeeper_tpu.ops import aotcache, xlacache
xlacache.enable(CACHE)
aotcache.enable(CACHE + "/aot")
from gatekeeper_tpu.util.synthetic import make_pods, make_templates
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.ops.driver import TpuDriver
# corpus generation is bench-harness cost, not restart cost (a real
# restart replays existing objects from the API server); the clock
# starts at the replay
templates, constraints = make_templates(N_T)
pods = make_pods(N_R, 1)
t0 = time.time()
client = Client(driver=TpuDriver())
for t in templates:
    client.add_template(t)
for c in constraints:
    client.add_constraint(c)
t_tmpl = time.time()
for p in pods:
    client.add_data(p)
t_built = time.time()
res, _totals = client.audit_capped(20)
t_ready = time.time()
n = len(res.results())
print(json.dumps({
    "template_ingest_s": round(t_tmpl - t0, 3),
    "data_replay_s": round(t_built - t_tmpl, 3),
    "first_sweep_s": round(t_ready - t_built, 3),
    "ready_s": round(t_ready - t0, 3),
    "violations": n,
}))
"""
    out = {}
    for label in ("populate", "warm"):
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=1200,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            log(f"restart[{label}] failed: {proc.stderr[-500:]}")
            raise RuntimeError("restart bench subprocess failed")
        line = proc.stdout.strip().splitlines()[-1]
        out[label] = json.loads(line)
        log(f"restart[{label}]: {out[label]} (wall {time.time()-t0:.1f}s)")
    warm = out["warm"]
    return {
        "metric": f"warm-restart to first full sweep ({n_t}x{n_r})",
        "value": warm["ready_s"],
        "unit": "s",
        "vs_baseline": 0,
        "template_ingest_s": warm["template_ingest_s"],
        "data_replay_s": warm["data_replay_s"],
        "first_sweep_s": warm["first_sweep_s"],
        "populate_ready_s": out["populate"]["ready_s"],
    }


def bench_warm_resume() -> dict:
    """Warm resume via the state snapshot subsystem (docs/snapshots.md,
    ISSUE 3): restart-to-first-completed-capped-sweep with a snapshot
    (restore + RV delta resync) vs the cold rebuild (relist + intern +
    pack), both in fresh subprocesses sharing warm XLA/AOT caches so the
    delta is exactly what the snapshot saves.  The warm phase re-packs
    only the churned rows — `warm_repacked_rows` in the artifact proves
    the delta-resync-only claim."""
    import shutil
    import subprocess

    n_t = int(os.environ.get("BENCH_WARM_TEMPLATES",
                             os.environ.get("BENCH_TEMPLATES", "500")))
    n_r = int(os.environ.get("BENCH_WARM_RESOURCES",
                             os.environ.get("BENCH_RESOURCES", "100000")))
    # churn while "down" defaults to 0.2% of the corpus, capped at the
    # driver's delta-sweep row bound so the restored basis serves the
    # first sweep (a pod reschedule is seconds; beyond the bound the
    # restore still works, the first sweep is just a full dispatch)
    churn = int(os.environ.get(
        "BENCH_WARM_CHURN", str(max(1, min(200, n_r // 500)))))
    cache_dir = os.environ.get(
        "GK_XLA_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla-cache"),
    )
    snap_dir = os.environ.get(
        "GK_SNAPSHOT_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".snapshots-bench"),
    )
    shutil.rmtree(snap_dir, ignore_errors=True)
    code = (
        f"N_T, N_R, CHURN = {n_t}, {n_r}, {churn}\n"
        f"CACHE, SNAP = {cache_dir!r}, {snap_dir!r}\n"
        + r"""
import json, os, sys, time
sys.path.insert(0, ".")
MODE = os.environ["BENCH_WARM_MODE"]  # populate | cold | warm
from gatekeeper_tpu.ops import aotcache, xlacache
xlacache.enable(CACHE)
aotcache.enable(CACHE + "/aot")
from gatekeeper_tpu.util.synthetic import make_pods, make_templates
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.ops.driver import TpuDriver

# the cluster: deterministic corpus + creation order, so every phase's
# kube assigns identical resourceVersions (corpus build is harness cost)
templates, constraints = make_templates(N_T)
kube = InMemoryKube()
for p in make_pods(N_R, 1):
    kube.create(p)
if MODE == "warm":
    # churn while "down": CHURN pods move their RV past the snapshot's
    # (an image retag — content change without widening any padded dim)
    gvk = ("", "v1", "Pod")
    for obj in kube.list(gvk)[:CHURN]:
        ctrs = obj.get("spec", {}).get("containers") or [{}]
        ctrs[0]["image"] = str(ctrs[0].get("image", "")) + "-churned"
        kube.update(obj)

out = {"mode": MODE}
t0 = time.time()
client = Client(driver=TpuDriver())
# pin the sweep sharding OFF the mesh so multi-device hosts measure the
# same thing (the snapshot basis is width-stamped: a width-drifted
# restore would drop it and turn the warm measurement into a cold one)
client.driver.set_mesh(False)
if MODE in ("populate", "cold"):
    for t in templates:
        client.add_template(t)
    for c in constraints:
        client.add_constraint(c)
    t_tmpl = time.time()
    for gvk in kube.list_gvks():
        for obj in kube.list(gvk):
            client.add_data(obj)
    t_built = time.time()
    res, _totals = client.audit_capped(20)
    t_ready = time.time()
    out.update({
        "template_ingest_s": round(t_tmpl - t0, 3),
        "data_replay_s": round(t_built - t_tmpl, 3),
        "first_sweep_s": round(t_ready - t_built, 3),
        "ready_s": round(t_ready - t0, 3),
        "violations": len(res.results()),
    })
    if MODE == "populate":
        from gatekeeper_tpu.snapshot import Snapshotter
        path = Snapshotter(client, SNAP).write_once()
        if path is None:
            raise RuntimeError("snapshot write failed")
        out["snapshot_bytes"] = sum(
            os.path.getsize(os.path.join(path, f)) for f in os.listdir(path))
else:
    from gatekeeper_tpu.ops.auditpack import AuditPackCache
    from gatekeeper_tpu.snapshot import SnapshotLoader
    packs = {"n": 0}
    orig = AuditPackCache._pack_row
    def counting(self, *a, **k):
        packs["n"] += 1
        return orig(self, *a, **k)
    AuditPackCache._pack_row = counting
    loader = SnapshotLoader(SNAP)
    outcome = loader.restore(client, kube)
    t_restored = time.time()
    res, _totals = client.audit_capped(20)
    t_ready = time.time()
    stats = dict(client.driver.last_sweep_stats)
    out.update({
        "restore_outcome": outcome,
        "delta_restored": loader.delta_restored,
        "resync": loader.stats,
        "restore_s": round(t_restored - t0, 3),
        "first_sweep_s": round(t_ready - t_restored, 3),
        "first_sweep_delta_rows": stats.get("delta_rows"),
        "ready_s": round(t_ready - t0, 3),
        "violations": len(res.results()),
        "repacked_rows": packs["n"],
    })
print(json.dumps(out))
"""
    )
    out = {}
    for mode in ("populate", "cold", "warm"):
        t0 = time.time()
        env = dict(os.environ, BENCH_WARM_MODE=mode)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=1800, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            log(f"warm_resume[{mode}] failed: {proc.stderr[-500:]}")
            raise RuntimeError("warm_resume bench subprocess failed")
        out[mode] = json.loads(proc.stdout.strip().splitlines()[-1])
        log(f"warm_resume[{mode}]: {out[mode]} (wall {time.time()-t0:.1f}s)")
    cold, warm = out["cold"], out["warm"]
    if warm["violations"] != cold["violations"]:
        log(
            f"warm_resume: violation mismatch cold={cold['violations']} "
            f"warm={warm['violations']}"
        )
    speedup = (
        round(cold["ready_s"] / warm["ready_s"], 2)
        if warm["ready_s"] > 0 else None
    )
    return {
        "metric": f"warm-resume speedup to first sweep ({n_t}x{n_r})",
        "value": speedup,
        "unit": "x",
        "vs_baseline": 0,
        "warm_resume_speedup": speedup,
        "warm_resume_ready_s": warm["ready_s"],
        "warm_resume_first_sweep_ms": round(warm["first_sweep_s"] * 1e3, 1),
        "warm_resume_restore_s": warm["restore_s"],
        "warm_resume_repacked_rows": warm["repacked_rows"],
        "warm_resume_resync": warm["resync"],
        "warm_resume_outcome": warm["restore_outcome"],
        "warm_resume_delta_restored": warm.get("delta_restored"),
        "warm_resume_delta_rows": warm.get("first_sweep_delta_rows"),
        "warm_resume_violations_match": warm["violations"] == cold["violations"],
        "cold_ready_s": cold["ready_s"],
        "cold_first_sweep_s": cold["first_sweep_s"],
        "snapshot_bytes": out["populate"].get("snapshot_bytes"),
        "churned_rows": churn,
    }


def bench_curve() -> dict:
    """The reference's constraint-count scaling sweep
    (policy_benchmark_test.go:269: N in {5,10,50,100,200,1000,2000}):
    admission-handler latency per N through the production hybrid driver.
    Exposes where recompile/padding buckets would bite."""
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates
    from gatekeeper_tpu.webhook import ValidationHandler

    counts = [int(x) for x in os.environ.get(
        "BENCH_CURVE", "5,10,50,100,200,1000,2000").split(",")]
    # two regimes per N: UNIQUE-content requests (true evaluation scaling —
    # the whole-request memo cannot hit) and REPEAT-content requests (what
    # replica/retry storms look like; served by the request memo)
    uniq_pods = make_pods(4096, seed=9, violation_rate=0.0)

    def req_for(pod):
        return {
            "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": pod["metadata"]["name"],
            "namespace": pod["metadata"]["namespace"],
            "operation": "CREATE", "userInfo": {"username": "bench"},
            "object": pod,
        }

    req = req_for(uniq_pods[0])
    curve = {}
    curve_memo = {}
    curve_device = {}
    curve_interp = {}
    curve_np = {}
    routes = {}
    routez_wins = {}
    cal_logged = None
    for n in counts:
        templates, constraints = make_templates(n)
        c = Client(driver=TpuDriver())
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        kube = InMemoryKube()
        # every review namespace must exist: a missing namespace sends the
        # request down the error path (LookupError + traceback logging),
        # and the curve would measure THAT instead of policy evaluation
        # (the reference benchmark's fakeNsGetter always succeeds,
        # policy_benchmark_test.go:52-66)
        for ns_name in {p["metadata"]["namespace"] for p in uniq_pods}:
            kube.create({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": ns_name}})
        handler = ValidationHandler(c, kube=kube)
        iters = max(10, min(100, 20000 // max(n, 1)))
        for _ in range(3):
            handler.handle(req)
        # startup calibration: the measured cost model picks the route
        cal = c.driver.calibrate_routing()
        if cal and cal_logged is None:
            cal_logged = {k: round(v, 3) for k, v in cal.items()}
            log(f"routing calibration: {cal_logged}")
        routes[n] = c.driver._route_eval(n)
        # route explainability (ISSUE 13): the decision just recorded
        # lands in this driver's ledger — keep its per-shape win row so
        # the artifact carries the ledger's view of the frontier, not
        # just the return value
        routez_wins[n] = next(
            (
                row["wins"]
                for row in c.driver.route_ledger.tier_wins()
                if row["per_review_cells"] == n and row["n_reviews"] == 1
            ),
            {},
        )

        def series(offset, forced=None):
            # distinct pod offset per series: unique content must not hit
            # request-memo entries another series populated
            saved = c.driver.DEVICE_MIN_CELLS
            cal_saved = c.driver._route_cal
            np_saved = c.driver.np_serve_enabled
            if forced == "interp":
                c.driver.DEVICE_MIN_CELLS = 1 << 30
                c.driver._route_cal = None
                c.driver.np_serve_enabled = False
            elif forced == "np":
                c.driver.DEVICE_MIN_CELLS = 1 << 30
                c.driver._route_cal = None
                c.driver.NP_MIN_CELLS = 0
                c.driver.np_serve_enabled = True
            elif forced == "device":
                c.driver.DEVICE_MIN_CELLS = 0
            ts = []
            try:
                for j in range(iters):
                    r = req_for(uniq_pods[(offset + j) % len(uniq_pods)])
                    t0 = time.perf_counter()
                    handler.handle(r)
                    ts.append(time.perf_counter() - t0)
            finally:
                c.driver.DEVICE_MIN_CELLS = saved
                c.driver._route_cal = cal_saved
                c.driver.np_serve_enabled = np_saved
                c.driver.NP_MIN_CELLS = TpuDriver.NP_MIN_CELLS
            return float(np.percentile(np.array(ts) * 1000, 50))

        # adaptive (production default), then the three forced paths so
        # the crossovers are visible in the artifact
        p50 = series(7)
        curve[n] = round(p50, 3)
        curve_interp[n] = round(series(1100, "interp"), 3)
        curve_np[n] = round(series(3300, "np"), 3)
        curve_device[n] = round(series(2200, "device"), 3)
        # repeat-content: identical object, fresh uid (request-memo hits)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            handler.handle(req)
            ts.append(time.perf_counter() - t0)
        m50 = float(np.percentile(np.array(ts) * 1000, 50))
        curve_memo[n] = round(m50, 3)
        log(f"curve N={n}: adaptive p50 {p50:.2f}ms (route={routes[n]}), "
            f"interp {curve_interp[n]:.2f}ms, np {curve_np[n]:.2f}ms, "
            f"device {curve_device[n]:.2f}ms, "
            f"repeat(memo) {m50:.2f}ms ({iters} iters)")
    # route-accuracy audit: at every N the adaptive route should name the
    # measured-fastest forced series (the r4 verdict's mis-route demand)
    agree = sum(
        1 for n in counts
        if routes[n] == min(
            [(curve_interp[n], "interp"), (curve_np[n], "np"),
             (curve_device[n], "device")]
        )[1]
    )
    log(f"curve route accuracy: {agree}/{len(counts)} Ns picked the "
        f"measured-fastest path")
    # the exact shape frontier where the compiled tier starts winning
    # (ISSUE 13: consumed from the route ledger rather than inferred) —
    # None means the compiled tier lost at every measured shape
    sorted_ns = sorted(counts)
    device_ns = [n for n in sorted_ns if routes[n] == "device"]
    frontier = {
        "device_first_cells": device_ns[0] if device_ns else None,
        "host_last_cells": max(
            (n for n in sorted_ns if routes[n] != "device"), default=None
        ),
    }
    log(f"curve route frontier: {frontier} (ledger wins: {routez_wins})")
    return {
        "metric": "admission handler p50 vs constraint count (unique-content)",
        "value": curve[max(counts)],
        "unit": "ms",
        "vs_baseline": 0,
        "curve_p50_ms": curve,
        "curve_repeat_p50_ms": curve_memo,
        "curve_interp_p50_ms": curve_interp,
        "curve_np_p50_ms": curve_np,
        "curve_device_p50_ms": curve_device,
        "curve_route": routes,
        "curve_route_accuracy": f"{agree}/{len(counts)}",
        "curve_routez_wins": routez_wins,
        "curve_route_frontier": frontier,
        "routing_calibration": cal_logged,
    }


def bench_mesh() -> dict:
    """Multi-chip scaling of the device sweep, measured on a virtual
    8-device CPU mesh in a subprocess (the bench env exposes ONE real
    chip).  Virtual devices share one host's cores, so this validates the
    sharded path's overhead/correctness at scale rather than wall-clock
    speedup; the scaling factor is reported as measured."""
    import subprocess

    n_t = int(os.environ.get("BENCH_MESH_TEMPLATES", "48"))
    n_r = int(os.environ.get("BENCH_MESH_ROWS", "8192"))
    code = f"N_T, N_R = {n_t}, {n_r}\n" + r"""
import time, json, sys
import jax, numpy as np
import jax.numpy as jnp
sys.path.insert(0, ".")
from gatekeeper_tpu.util.synthetic import build_driver

client = build_driver(N_T, N_R)
driver = client.driver
out = {}
for mesh_on in (False, True):
    # set_mesh invalidates every topology-keyed cache (placements, sweep
    # cache, delta basis) in one call
    driver.set_mesh(mesh_on)
    client.audit_capped(20)  # compile + warm
    # honest steady state: invalidate the sweep cache, keep executables
    ts = []
    for i in range(3):
        driver._audit_cache = None
        driver._delta_state = None
        t0 = time.perf_counter()
        client.audit_capped(20)
        ts.append(time.perf_counter() - t0)
    out["mesh" if mesh_on else "single"] = min(ts)

# device-only scaling series: the fused packed-only kernel at 1/2/4/8
# shards, N chained executions per dispatch (optimization_barrier per
# iteration so XLA cannot CSE), median per-sweep time.  Virtual devices
# share one host's cores, so the honest signal is per-shard WORK (rows
# per device falls ~1/N) plus the measured wall series as context.
from gatekeeper_tpu.parallel.mesh import audit_mesh, shard_review_side

driver.set_mesh(False)
with driver._lock:
    K = driver._audit_topk(20)
    fn, _o, cp, gparams, _crow = driver._audit_inputs(K)
raw = fn.__wrapped__
ap = driver._audit_pack
N_REP = 8
series = {}
shard_rows = {}
for k in (1, 2, 4, 8):
    mesh = audit_mesh(k)
    rv_p, cols_p, target = shard_review_side(mesh, ap.capacity, ap.rp, ap.cols)
    with driver._lock:
        driver._cs_device_cache = None
        cs_p, gp_p = driver._constraint_device_side(cp.arrays, gparams, None, mesh)

    def rep_n(rv, cs, cols, gp):
        def body(carry, _):
            a, b, c, d = jax.lax.optimization_barrier((rv, cs, cols, gp))
            packed = raw(a, b, c, d)
            return carry + packed[0, 0], None
        c0, _ = jax.lax.scan(body, jnp.int32(0), None, length=N_REP)
        return c0

    with mesh:
        rj = jax.jit(rep_n)
        rj(rv_p, cs_p, cols_p, gp_p).block_until_ready()  # compile
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            rj(rv_p, cs_p, cols_p, gp_p).block_until_ready()
            ts.append(time.perf_counter() - t0)
    series[k] = float(np.median(ts)) / N_REP * 1e3
    shard_rows[k] = target // k
out["device_scaling_ms"] = series
out["rows_per_shard"] = shard_rows
print(json.dumps(out))
"""
    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(8)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(f"mesh subprocess failed: {proc.stderr[-2000:]}")
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    factor = data["single"] / data["mesh"] if data["mesh"] else 0.0
    log(f"mesh scaling (virtual 8-dev CPU, 48x8192): single {data['single']*1000:.0f}ms "
        f"mesh {data['mesh']*1000:.0f}ms -> x{factor:.2f} "
        f"(virtual devices share one host: overhead check, not speedup)")
    scaling = data.get("device_scaling_ms", {})
    if scaling:
        log("mesh device-only series (N-rep chained, virtual CPU devices): "
            + ", ".join(f"{k} shard(s) {v:.1f}ms"
                        f" ({data['rows_per_shard'][k]} rows/shard)"
                        for k, v in sorted(scaling.items(),
                                           key=lambda kv: int(kv[0]))))
    return {
        "metric": "virtual 8-device mesh sweep vs single device",
        "value": round(factor, 3),
        "unit": "x",
        "vs_baseline": 0,
        "single_s": round(data["single"], 4),
        "mesh_s": round(data["mesh"], 4),
        "device_scaling_ms": {
            str(k): round(v, 3) for k, v in scaling.items()
        },
        "rows_per_shard": data.get("rows_per_shard", {}),
    }


def bench_mesh_curve() -> dict:
    """The production sharded audit across mesh widths 1/2/4/8 on the
    virtual CPU mesh (subprocess; the bench env exposes ONE real chip),
    recorded as MULTICHIP_r06.  Per width: interpreter-oracle parity on
    a moderate corpus (byte-identical verdicts + rendered messages +
    totals), warm full-resweep wall time and rows-per-shard at the
    full-scale corpus (the ~linear per-shard work signal — virtual
    devices share one host's cores, so wall time is an overhead check,
    not a speedup claim), and the O(churn) delta check: 200 churned rows
    dispatch 200 rows, never the cluster."""
    import subprocess

    n_t = int(os.environ.get("BENCH_MESH_CURVE_TEMPLATES", "48"))
    n_r = int(os.environ.get("BENCH_MESH_CURVE_ROWS", "8192"))
    p_t = int(os.environ.get("BENCH_MESH_CURVE_PARITY_TEMPLATES", "12"))
    p_r = int(os.environ.get("BENCH_MESH_CURVE_PARITY_ROWS", "512"))
    churn = int(os.environ.get("BENCH_MESH_CURVE_CHURN", "200"))
    code = (
        f"N_T, N_R, P_T, P_R, CHURN = {n_t}, {n_r}, {p_t}, {p_r}, {churn}\n"
        + r"""
import json, sys, time
sys.path.insert(0, ".")
import numpy as np
from gatekeeper_tpu.util.synthetic import (
    audit_result_sig as sig, build_driver, build_oracle, make_pods,
)

WIDTHS = (1, 2, 4, 8)
PARITY_CAP = 4096  # above any per-constraint count: totals exact everywhere

# interpreter oracle on the parity corpus (build_oracle: own instance,
# same corpus and parity signature as the tool and the tests)
oracle = build_oracle(P_T, P_R)
oracle_r, oracle_t, _ = oracle.driver.audit_capped(PARITY_CAP)
oracle_sig = sig(oracle_r)

parity_client = build_driver(P_T, P_R)
curve_client = build_driver(N_T, N_R)
curve = {}
for w in WIDTHS:
    # parity against the interpreter oracle at this width
    pd = parity_client.driver
    pd.set_mesh(w > 1, width=w)
    got_r, got_t, _ = pd.audit_capped(PARITY_CAP)
    parity = sig(got_r) == oracle_sig and got_t == oracle_t

    # full-scale warm resweep + per-shard work at this width
    cd = curve_client.driver
    cd.set_mesh(w > 1, width=w)
    curve_client.audit_capped(20)  # compile + place + warm
    ts = []
    for _ in range(3):
        # honest steady state: drop the sweep cache and the delta basis,
        # keep placements and executables
        cd._audit_cache = None
        cd._delta_state = None
        t0 = time.perf_counter()
        curve_client.audit_capped(20)
        ts.append(time.perf_counter() - t0)
    stats = dict(cd.last_sweep_stats)
    # capacity-slab based at every width (driver emits it for width 1
    # too), so the parent's linearity check compares like with like
    rows_per_shard = int(stats["rows_per_shard"])

    # O(churn) delta under this width: in-place churn of CHURN objects
    curve_client.audit_capped(20)  # rebase the delta basis
    pods = make_pods(N_R, 1)[:CHURN]
    for p in pods:
        p["metadata"].setdefault("labels", {})["churn"] = f"w{w}"
        curve_client.add_data(p)
    t0 = time.perf_counter()
    curve_client.audit_capped(20)
    delta_s = time.perf_counter() - t0
    dstats = dict(cd.last_sweep_stats)

    curve[str(w)] = {
        "parity": bool(parity),
        "warm_full_resweep_s": round(min(ts), 4),
        "rows_per_shard": rows_per_shard,
        "shards": stats.get("shards"),
        "delta_rows_dispatched": dstats.get("delta_rows"),
        "delta_owning_shards": dstats.get("delta_shards"),
        "delta_sweep_s": round(delta_s, 4),
    }
print(json.dumps({"curve": curve}))
"""
    )
    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(8)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh_curve subprocess failed: {proc.stderr[-2000:]}")
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    curve = data["curve"]
    all_parity = all(v["parity"] for v in curve.values())
    # rows_per_shard * width == slab-padded capacity: padding adds < width
    # rows total, so linear-within-padding is 0 <= excess < width
    linear = all(
        0 <= v["rows_per_shard"] * int(w) - curve["1"]["rows_per_shard"]
        < int(w)
        for w, v in curve.items()
    )
    for w, v in sorted(curve.items(), key=lambda kv: int(kv[0])):
        log(f"mesh_curve width {w}: parity={v['parity']} "
            f"resweep {v['warm_full_resweep_s']*1000:.0f}ms "
            f"{v['rows_per_shard']} rows/shard, delta "
            f"{v['delta_rows_dispatched']} rows "
            f"({v['delta_sweep_s']*1000:.0f}ms)")
    log(f"mesh_curve: parity_all={all_parity} rows_per_shard "
        f"linear={linear} (virtual devices share one host: per-shard "
        f"work is the scaling signal, wall time the overhead check)")
    out = {
        "metric": f"mesh width curve 1/2/4/8 (virtual CPU, {n_t}x{n_r})",
        "value": 1.0 if all_parity else 0.0,
        "unit": "parity",
        "vs_baseline": 0,
        "parity_all_widths": all_parity,
        "rows_per_shard_linear": linear,
        "templates": n_t,
        "rows": n_r,
        "churn_rows": churn,
        "curve": curve,
    }
    record = {
        "config": {
            "templates": n_t, "rows": n_r,
            "parity_templates": p_t, "parity_rows": p_r,
            "churn_rows": churn,
            "mesh": "virtual 8-device CPU (subprocess)",
        },
        "parity_all_widths": all_parity,
        "rows_per_shard_linear": linear,
        "curve": curve,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r06.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"mesh_curve recorded: {path}")
    return out


def bench_referential() -> dict:
    """Referential policies (ISSUE 14): the cross-resource join/aggregate
    kernel subsystem.  Subprocess on the virtual 8-device CPU mesh:

    - parity: a referential corpus (unique-key / required-reference /
      count-quota) audited at widths 1 and 4 must be BYTE-identical to
      the interpreter oracle (verdicts + rendered messages + totals),
      with GK_JOIN_ASSERT armed and every family served by a join plan
      (the `join_plan` route-ledger reason present, never interp
      fallback);
    - throughput: warm steady-state full join sweep wall time -> rows/s
      at the full-scale corpus;
    - delta locality: a CHURN-row batch rides the O(key-group) delta
      path — dispatch rows == dirty + affected readers — and the
      delta-vs-full speedup is recorded.

    Recorded as REF_r14.json."""
    import subprocess

    n_t = int(os.environ.get("BENCH_REF_TEMPLATES", "24"))
    n_r = int(os.environ.get("BENCH_REF_ROWS", "6000"))
    p_t = int(os.environ.get("BENCH_REF_PARITY_TEMPLATES", "6"))
    p_r = int(os.environ.get("BENCH_REF_PARITY_ROWS", "240"))
    churn = int(os.environ.get("BENCH_REF_CHURN", "20"))
    code = (
        f"N_T, N_R, P_T, P_R, CHURN = {n_t}, {n_r}, {p_t}, {p_r}, {churn}\n"
        + r"""
import json, sys, time
sys.path.insert(0, ".")
from gatekeeper_tpu.ops.driver import TpuDriver
TpuDriver.DELTA_MASK_WAIT_S = 300.0
from gatekeeper_tpu.util.synthetic import (
    audit_result_sig as sig, build_referential_driver,
    build_referential_oracle, make_referential_objects,
)
CAP = 4096

# --- parity at widths 1 and 4 vs the interpreter oracle ---
oracle = build_referential_oracle(P_T, P_R)
t0 = time.perf_counter()
oracle_r, oracle_t, _ = oracle.driver.audit_capped(CAP)
oracle_s = time.perf_counter() - t0
oracle_sig = sig(oracle_r)
parity = {}
for w in (1, 4):
    c = build_referential_driver(P_T, P_R)
    d = c.driver
    d.set_mesh(w > 1, width=w)
    res, tot, _ = d.audit_capped(CAP)
    st = dict(d.last_sweep_stats)
    counts = d.route_ledger.snapshot()["counts"]
    parity[str(w)] = {
        "parity": sig(res) == oracle_sig and tot == oracle_t,
        "join_plans": st.get("join_plans"),
        "join_plan_routed": any(
            k.endswith("|join_plan") for k in counts
        ),
    }

# --- full-scale join sweep throughput + delta locality ---
client = build_referential_driver(N_T, N_R)
d = client.driver
client.audit_capped(20)  # compile + place + index build
full_ts = []
for _ in range(3):
    d._audit_cache = None
    d._delta_state = None  # honest steady state; placements stay warm
    t0 = time.perf_counter()
    client.audit_capped(20)
    full_ts.append(time.perf_counter() - t0)
full_s = min(full_ts)
rows = d.last_sweep_stats["rows"]

client.audit_capped(20)  # rebase the delta basis + join index
objs = make_referential_objects(N_R, 1)
ingresses = [o for o in objs if o["kind"] == "Ingress"]
pods = [o for o in objs if o["kind"] == "Pod"
        and str(o["metadata"]["labels"]["team"]).startswith("team-")]

def churn_hosts(batch, tag):
    for o in batch:
        o = dict(o)
        o["spec"] = {"rules": [{"host": f"moved-{tag}-{o['metadata']['name']}.corp.io"}]}
        client.add_data(o)

def churn_neutral(batch, tag):
    # content churn that leaves every join key unchanged — the common
    # production case (status/annotation updates)
    for o in batch:
        o = dict(o)
        o["metadata"] = {**o["metadata"],
                         "annotations": {"touched": tag}}
        client.add_data(o)

# prime the delta executable's row-width bucket (one-time XLA compile,
# shared by every later churn batch of this magnitude)
churn_neutral(pods[:CHURN], "prime")
client.audit_capped(20)
assert d.last_sweep_stats.get("delta_rows") is not None, d.last_sweep_stats

# (a) NEUTRAL churn: keys unchanged -> zero affected readers, zero
# re-renders; the delta-vs-full dispatch win in its pure form
churn_neutral(pods[CHURN:2 * CHURN], "live")
t0 = time.perf_counter()
client.audit_capped(20)
neutral_s = time.perf_counter() - t0
nstats = dict(d.last_sweep_stats)

# (b) KEY churn: hosts move -> the old/new key groups' readers
# co-dispatch and re-render.  Compared against a FULL sweep doing the
# SAME work (same churn magnitude, basis dropped), since both arms pay
# the interpreter re-render of the legitimately-invalidated cells.
churn_hosts(ingresses[:CHURN], "key")
t0 = time.perf_counter()
client.audit_capped(20)
key_delta_s = time.perf_counter() - t0
kstats = dict(d.last_sweep_stats)

churn_hosts(ingresses[CHURN:2 * CHURN], "full")
d._audit_cache = None
d._delta_state = None
t0 = time.perf_counter()
client.audit_capped(20)
key_full_s = time.perf_counter() - t0

print(json.dumps({
    "parity": parity,
    "oracle_sweep_s": round(oracle_s, 4),
    "full_sweep_s": round(full_s, 4),
    "rows": rows,
    "join_rows_per_s": round(rows / full_s, 1),
    "delta_neutral_s": round(neutral_s, 4),
    "delta_neutral_rows": nstats.get("delta_rows"),
    "delta_neutral_affected": nstats.get("join_affected_rows"),
    "delta_vs_full_speedup": round(full_s / max(neutral_s, 1e-9), 2),
    "delta_keychurn_s": round(key_delta_s, 4),
    "delta_keychurn_rows": kstats.get("delta_rows"),
    "join_affected_rows": kstats.get("join_affected_rows"),
    "full_after_keychurn_s": round(key_full_s, 4),
    "keychurn_speedup": round(key_full_s / max(key_delta_s, 1e-9), 2),
}))
"""
    )
    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(8)
    env["GK_JOIN_ASSERT"] = "1"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"referential subprocess failed: {proc.stderr[-2000:]}")
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    parity_all = all(
        v["parity"] and v["join_plan_routed"]
        for v in data["parity"].values()
    )
    log(f"referential: parity_all={parity_all} "
        f"join sweep {data['full_sweep_s']*1000:.0f}ms "
        f"({data['join_rows_per_s']:.0f} rows/s at {n_t}x{n_r}); "
        f"neutral churn delta {data['delta_neutral_rows']} rows in "
        f"{data['delta_neutral_s']*1000:.0f}ms "
        f"({data['delta_vs_full_speedup']}x vs full); key churn "
        f"{data['delta_keychurn_rows']} rows "
        f"({data['join_affected_rows']} group readers) in "
        f"{data['delta_keychurn_s']*1000:.0f}ms vs full "
        f"{data['full_after_keychurn_s']*1000:.0f}ms "
        f"({data['keychurn_speedup']}x)")
    out = {
        "metric": f"referential join sweep parity+throughput ({n_t}x{n_r})",
        "value": 1.0 if parity_all else 0.0,
        "unit": "parity",
        "vs_baseline": 0,
        "referential_parity": parity_all,
        "join_rows_per_s": data["join_rows_per_s"],
        "delta_vs_full_speedup": data["delta_vs_full_speedup"],
        **data,
    }
    record = {
        "config": {
            "templates": n_t, "rows": n_r,
            "parity_templates": p_t, "parity_rows": p_r,
            "churn_rows": churn,
            "families": ["unique-key", "required-reference",
                         "count-quota"],
            "mesh": "virtual 8-device CPU (subprocess), widths 1+4",
        },
        "parity": parity_all,
        **data,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "REF_r14.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"referential recorded: {path}")
    return out


def bench_multihost() -> dict:
    """Two REAL OS processes joined via jax.distributed (gRPC coordinator,
    the DCN control-plane analogue), 4 virtual CPU devices each, one
    8-device (host, data) mesh: the fused capped-audit reduction runs SPMD
    across both processes (tests/test_multihost.py recipe, SURVEY §5.8).
    Reports parity vs the single-process sweep, warm sweep wall time, and
    the bytes crossing the host boundary per sweep (the replicated
    [C, 1+K] reduction — nothing [C, R]-sized ever crosses DCN)."""
    import socket
    import subprocess

    n_t = int(os.environ.get("BENCH_MH_TEMPLATES",
                             os.environ.get("BENCH_TEMPLATES", "500")))
    n_r = int(os.environ.get("BENCH_MH_ROWS",
                             os.environ.get("BENCH_RESOURCES", "100000")))
    worker = f"N_T, N_R = {n_t}, {n_r}\n" + r"""
import os, sys, json, time
sys.path.insert(0, ".")
import numpy as np
import jax
from gatekeeper_tpu.parallel.multihost import (
    init_distributed, multihost_audit_mesh, multihost_capped_sweep,
)

pid = int(os.environ["GK_PROC"])
init_distributed(os.environ["GK_COORD"], 2, pid)
from gatekeeper_tpu.util.synthetic import build_driver

client = build_driver(N_T, N_R, seed=0)
driver = client.driver
driver.set_mesh(False)  # the local auto-mesh must not eat the global one
K = 64
ordered, counts, topk = multihost_capped_sweep(driver, K=K)  # compile+warm
ts = []
for _ in range(3):  # every call re-dispatches (no result cache here)
    t0 = time.perf_counter()
    ordered, counts, topk = multihost_capped_sweep(driver, K=K)
    ts.append(time.perf_counter() - t0)

parity = None
if pid == 0:  # one reference single-process sweep is enough for parity
    driver2 = build_driver(N_T, N_R, seed=0).driver
    driver2.set_mesh(False)
    sweep = driver2._audit_sweep(K)
    _r, _o, _m, ref_counts, ref_topk = sweep
    k = min(topk.shape[1], ref_topk.shape[1])
    parity = bool((counts == ref_counts).all()
                  and (topk[:, :k] == ref_topk[:, :k]).all())
# per-host DCN contribution: its own [C, 1+K] reduction (the all_gather
# payload it sends; it receives the other hosts' equal share)
packed_bytes = int((counts.shape[0]) * (1 + K) * 4)
print(json.dumps({"pid": pid, "parity": parity,
                  "sweep_s": min(ts), "packed_bytes": packed_bytes}),
      flush=True)
"""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    procs = []
    for pid in range(2):
        env = virtual_mesh_env(4)
        env.update(GK_COORD=coord, GK_PROC=str(pid))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=1800)
            if p.returncode != 0:
                raise RuntimeError(
                    f"multihost worker rc={p.returncode}:\n{err[-2000:]}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    parity = all(o["parity"] for o in outs if o["parity"] is not None)
    sweep_s = max(o["sweep_s"] for o in outs)
    dcn_bytes = outs[0]["packed_bytes"]
    log(f"multihost (2 procs x 4 virtual devices, {n_t}x{n_r}): "
        f"parity={parity} warm sweep {sweep_s*1000:.0f}ms, "
        f"~{dcn_bytes/1e3:.1f}KB ([C,1+K] reduction) crossing the host "
        f"boundary per sweep")
    return {
        "metric": f"2-process multihost capped sweep (DCN lane, {n_t}x{n_r})",
        "value": round(sweep_s, 4),
        "unit": "s",
        "vs_baseline": 0,
        "parity": parity,
        "sweep_s": round(sweep_s, 4),
        "templates": n_t,
        "rows": n_r,
        "dcn_bytes_per_sweep": dcn_bytes,
    }


def bench_synthetic() -> dict:
    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    n_resources = int(os.environ.get("BENCH_RESOURCES", "100000"))
    baseline_slice = int(os.environ.get("BENCH_BASELINE_SLICE", "20"))
    cap = int(os.environ.get("BENCH_CAP", "20"))

    from gatekeeper_tpu.util.synthetic import build_driver, make_pods, make_templates

    t0 = time.time()
    client = build_driver(n_templates, n_resources)
    driver = client.driver
    log(f"workload built: {n_templates} templates x {n_resources} resources "
        f"in {time.time()-t0:.1f}s")

    # long-lived-state GC hygiene, as a production audit pod would do
    # (webhook/server.py does the same at startup): without it, gen-2
    # collections scanning the 100k-object inventory inject 100ms+ pauses
    # into steady-state sweeps.  Unfrozen at the end of this config so the
    # other configs in a combined run keep normal GC behavior.
    import gc

    gc.collect()
    gc.freeze()

    # ---- cold sweep: review build + pack + XLA compile + device + render
    t0 = time.time()
    res, totals = client.audit_capped(cap)
    cold_s = time.time() - t0
    settle_warmups()  # base-mask + delta executable compile off-path
    n_results = len(res.results())
    n_capped = sum(1 for v in totals.values() if v[1] == "resources")
    log(f"cold end-to-end capped audit: {cold_s:.1f}s "
        f"({n_results} violations kept, {n_capped}/{len(totals)} constraints at cap)")

    # ---- steady state: one object mutated since the last sweep.  The
    # production path is the INCREMENTAL delta sweep: only the changed
    # rows are re-evaluated on device and folded into the resident
    # per-constraint reduction (ops/deltasweep.py)
    times = []
    best_stats = {}
    for i in range(5):
        p = make_pods(1, seed=1000 + i, violation_rate=1.0)[0]
        p["metadata"]["name"] = f"bench-delta-{i}"
        client.add_data(p)
        t0 = time.time()
        res, totals = client.audit_capped(cap)
        times.append(time.time() - t0)
        s = driver.last_sweep_stats
        log(f"  sweep {i}: {times[-1]*1000:.1f}ms | pack {s.get('pack_ms', 0):.1f} "
            f"device {s.get('device_ms', 0):.1f} fetch {s.get('fetch_ms', 0):.1f} "
            f"render {s.get('render_ms', 0):.1f} ms | fetch {s.get('fetch_bytes', 0)/1e3:.1f}KB "
            f"delta_rows {s.get('delta_rows', 0):.0f} "
            f"fallback_rows {s.get('fallback_rows', 0):.0f} "
            f"rendered_cells {s.get('rendered_cells', 0):.0f}")
        if times[-1] == min(times):
            best_stats = dict(s)
    sweep_s = min(times)
    n_results = len(res.results())
    cells = len(driver._ordered_constraints()) * driver._audit_pack.n_rows
    delta_rows = int(best_stats.get("delta_rows", 0))
    log(f"steady-state end-to-end sweep (1 mutation): {sweep_s*1000:.1f}ms "
        f"({n_results} violations kept); covers {cells} constraint x resource "
        f"cells incrementally ({delta_rows} changed rows re-evaluated on device)")

    # ---- warm FULL resweep (no incremental state): the non-delta number,
    # and the honest basis for the device-utilization estimate
    p = make_pods(1, seed=2000, violation_rate=1.0)[0]
    p["metadata"]["name"] = "bench-full-resweep"
    client.add_data(p)
    driver._delta_state = None
    driver._audit_cache = None
    t0 = time.time()
    client.audit_capped(cap)
    full_s = time.time() - t0
    full_stats = dict(driver.last_sweep_stats)
    log(f"warm full resweep (incremental state dropped): {full_s*1000:.1f}ms "
        f"| device {full_stats.get('device_ms', 0):.1f}ms "
        f"({cells/full_s/1e6:.1f}M cell-evals/s end-to-end)")

    # ---- CLEAN on-device sweep time + bandwidth utilization.  N
    # back-to-back executions of the fused packed-only sweep kernel run
    # inside ONE dispatch (lax.scan with an optimization_barrier per
    # iteration, carry data-dependent on each result, so XLA can neither
    # CSE nor reorder them); the relay's dispatch RTT amortizes across N
    # and is subtracted via a separately-timed trivial dispatch.  The
    # published device_util is measured against the v5e HBM roofline —
    # the artifact field the near-roofline claim rests on.
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        N_REP_LO = int(os.environ.get("BENCH_DEVICE_REPS_LO", "200"))
        N_REP = int(os.environ.get("BENCH_DEVICE_REPS", "2000"))
        with driver._lock:
            K = driver._audit_topk(cap)
            fn, _ord2, cp2, gp2, _crow2 = driver._audit_inputs(K)
            rv_d, cols_d = driver._audit_device_inputs()
            cs_d, gp_d = driver._constraint_device_side(
                cp2.arrays, gp2, None, None
            )
        raw = fn.__wrapped__
        fused_raw = driver._fused.__wrapped__  # plain (mask, autoreject)
        from gatekeeper_tpu.ops.matchkernel import match_kernel as _mk

        def _rep_jit(body_fn, reps):
            # every integer/bool review-side leaf is xor-folded with an
            # OPAQUE carry-derived zero: the kernel's data roots become
            # loop-variant, so XLA cannot hoist the (otherwise genuinely
            # loop-invariant) body out of the scan — observed always on
            # XLA:CPU and intermittently per-body on TPU, which made
            # variant timings mutually inconsistent.  The xor fuses into
            # each consumer's first read (no extra HBM pass; measured
            # zero inflation vs the unperturbed body on CPU).
            def _perturb(tree, zero):
                def fold(x):
                    if x.dtype == jnp.bool_:
                        return x ^ (zero != 0)
                    if jnp.issubdtype(x.dtype, jnp.integer):
                        return x ^ zero.astype(x.dtype)
                    return x

                return jax.tree_util.tree_map(fold, tree)

            def rep_n(rv, cs, cols, gp):
                def body(carry, _):
                    rv2, cs2, cols2, gp2_ = jax.lax.optimization_barrier(
                        (rv, cs, cols, gp))
                    zero = jax.lax.optimization_barrier(carry & 0)
                    rv2 = _perturb(rv2, zero)
                    cols2 = _perturb(cols2, zero)
                    return body_fn(carry, rv2, cs2, cols2, gp2_), None

                c, _ = jax.lax.scan(body, jnp.int32(0), None, length=reps)
                return c

            return jax.jit(rep_n)

        def _timed(jitted):
            # MIN over several runs: relay noise is one-sided (additive
            # spikes on top of a stable floor), so the minimum converges
            # to the true total and min-based slopes stay consistent
            # where median-based ones flapped between runs
            ts = []
            for _ in range(7):
                t0 = time.perf_counter()
                jitted(rv_d, cs_d, cols_d, gp_d).block_until_ready()
                ts.append(time.perf_counter() - t0)
            return float(min(ts))

        def _chained(body_fn, reps=None):
            """Per-iteration time of a barrier-chained scan, estimated by
            a CASCADE: slope between two scan lengths (cancels the relay
            RTT exactly), at two length pairs, then plain RTT subtraction.
            XLA may legitimately hoist the loop-invariant body out of the
            scan (observed always on XLA:CPU, intermittently on TPU, and
            it varies with trip count) — a collapsed estimator reports
            None rather than a fake zero, and the caller publishes null.
            body_fn(carry, rv, cs, cols, gp) -> new carry; it must depend
            on EVERY output element (a [0,0] probe would let XLA's slice
            pushdown dead-code the rest of the grid)."""
            hi = max(2, reps or N_REP)
            lo = max(1, min(N_REP_LO, hi // 10))
            floor_ms = 0.002  # below this, the estimator didn't resolve

            def compiled(n):
                j = _rep_jit(body_fn, n)
                j(rv_d, cs_d, cols_d, gp_d).block_until_ready()
                return j

            jit_lo, jit_hi = compiled(lo), compiled(hi)
            t_lo, t_hi = _timed(jit_lo), _timed(jit_hi)
            if hi > lo:
                per = (t_hi - t_lo) / (hi - lo) * 1e3
                if per > floor_ms:
                    return per
            if lo > 1:
                # built lazily: the common path never needs the 1-rep jit
                t_1 = _timed(compiled(1))
                per = (t_lo - t_1) / (lo - 1) * 1e3
                if per > floor_ms:
                    return per
            per = (t_hi - rtt) / hi * 1e3
            return per if per > floor_ms else None

        tiny = jax.jit(lambda x: x + 1)
        xd = jax.device_put(np.int32(1))
        tiny(xd).block_until_ready()
        rtts = []
        for _ in range(5):
            t0 = time.perf_counter()
            tiny(xd).block_until_ready()
            rtts.append(time.perf_counter() - t0)
        rtt = float(np.median(rtts))

        # the breakdown the 2.25x roofline gap demands (r4 verdict #4):
        # full kernel, mask-only (difference = reduction cost), match-only
        # (difference = violation-program cost), and a pure input-bytes
        # traversal (the ACHIEVABLE bandwidth for these arrays on this
        # chip, a tighter bound than the spec-sheet roofline)
        device_sweep_ms = _chained(
            lambda k, rv, cs, c, gp:
                k + raw(rv, cs, c, gp).sum(dtype=jnp.int32))
        mask_only_ms = _chained(
            lambda k, rv, cs, c, gp:
                k + fused_raw(rv, cs, c, gp)[0].sum(dtype=jnp.int32))
        match_only_ms = _chained(
            lambda k, rv, cs, c, gp:
                k + _mk(rv, cs)[0].sum(dtype=jnp.int32))

        in_bytes = sum(
            a.nbytes for a in jax.tree_util.tree_leaves(
                (driver._audit_pack.rp, driver._audit_pack.cols)))
        cs_bytes = sum(
            a.nbytes for a in jax.tree_util.tree_leaves((cs_d, gp_d)))
        # the [C, R] mask is an XLA-internal intermediate: the
        # hierarchical reduction fuses into the mask producer, so no
        # mask-sized array is ever written to (or re-read from) HBM —
        # the bandwidth bound is the one pass over the packed inputs +
        # the replicated constraint side
        roofline_ms = (in_bytes + cs_bytes) / (V5E_HBM_GBPS * 1e9) * 1e3

        def _touch(k, rv, cs, c, gp):
            # sum ONLY the perturbed (loop-variant) trees: cs/gp and
            # float-leaf sums would stay loop-invariant and hoistable,
            # silently undercounting the traversal.  rv+cols are ~all of
            # in_bytes (the constraint side is KB-scale next to the row
            # pack), so the measured bound keeps its meaning.
            tot = k
            for leaf in jax.tree_util.tree_leaves((rv, c)):
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    continue
                tot = tot + leaf.astype(jnp.int32).sum(dtype=jnp.int32)
            return tot

        # the traversal kernel is ~10x cheaper than the sweep; give it
        # 10x the reps so it resolves above relay RTT jitter
        bytes_touch_ms = _chained(_touch, reps=N_REP * 10)

        # structural sanity: full >= mask-only >= match-only (supersets).
        # A variant that resolved BELOW its subset was noise-corrupted —
        # null it rather than publish an impossible figure.
        if (device_sweep_ms is not None and mask_only_ms is not None
                and device_sweep_ms < mask_only_ms * 0.9):
            device_sweep_ms = None
        if (mask_only_ms is not None and match_only_ms is not None
                and mask_only_ms < match_only_ms * 0.9):
            mask_only_ms = None
        # plausibility gate: a sweep "faster than reading its inputs from
        # HBM once" means the scan kept the working set chip-resident
        # (VMEM) across iterations — a flattering artifact of the repeat
        # harness, not the cost a production sweep streaming from HBM
        # pays.  The conservative claim nulls rather than publishes it.
        if (device_sweep_ms is not None
                and jax.default_backend() != "cpu"
                and device_sweep_ms < roofline_ms / 1.2):
            device_sweep_ms = None

        C = len(driver._ordered_constraints())
        ap = driver._audit_pack

        def _r(x):
            return round(x, 4) if x is not None else None

        def _delta(a, b):
            if a is None or b is None:
                return None
            return round(max(0.0, a - b), 4)

        # every derived figure is null when its estimator didn't resolve
        # (XLA hoisted the scan body; see _chained) — never a fake zero
        util = (
            round(roofline_ms / device_sweep_ms, 4)
            if device_sweep_ms else None
        )
        util_measured = (
            round(bytes_touch_ms / device_sweep_ms, 4)
            if device_sweep_ms and bytes_touch_ms else None
        )
        device_cells_per_s = (
            cells / (device_sweep_ms / 1e3) if device_sweep_ms else None
        )
        achieved_gbps = (
            (in_bytes + cs_bytes) / 1e9 / (device_sweep_ms / 1e3)
            if device_sweep_ms else None
        )
        c_padded = len(driver._constraint_side()[1].arrays["valid"])
        device_breakdown = {
            "full_ms": _r(device_sweep_ms),
            "mask_only_ms": _r(mask_only_ms),
            "reduction_ms": _delta(device_sweep_ms, mask_only_ms),
            "match_only_ms": _r(match_only_ms),
            "programs_ms": _delta(mask_only_ms, match_only_ms),
            "bytes_touch_ms": _r(bytes_touch_ms),
            "pad_row_frac": round(1.0 - ap.n_rows / max(ap.capacity, 1), 4),
            "pad_constraint_frac": round(1.0 - C / max(c_padded, 1), 4),
        }
        log("on-device sweep: "
            + (f"{device_sweep_ms:.3f}ms/sweep" if device_sweep_ms
               else "UNRESOLVED (estimator cascade collapsed)")
            + f" (chained-scan slope {N_REP_LO}/{N_REP} reps; relay RTT "
            f"~{rtt*1e3:.0f}ms cancels in the difference) = "
            + (f"{device_cells_per_s/1e9:.2f}B cell-evals/s, "
               if device_cells_per_s else "")
            + (f"{achieved_gbps:.0f}GB/s" if achieved_gbps is not None
               else "n/a GB/s")
            + f" touched vs {V5E_HBM_GBPS:.0f}GB/s HBM -> "
            + (f"{util*100:.1f}%" if util is not None else "n/a")
            + " of the spec-sheet input roofline, "
            + (f"{util_measured*100:.1f}%" if util_measured is not None
               else "unresolved fraction")
            + " of the measured-traversal bound "
            f"(roofline {roofline_ms:.2f}ms: inputs {in_bytes/1e6:.0f}MB + "
            f"constraint side {cs_bytes/1e6:.0f}MB; the [C,R] mask fuses "
            f"away and never touches HBM); breakdown "
            f"{device_breakdown}")
    except Exception as e:  # pragma: no cover
        log(f"on-device measurement failed: {e!r}")
        roofline_ms, device_sweep_ms, device_cells_per_s = 0.0, None, None
        util, util_measured, device_breakdown = None, None, {}

    # ---- baseline: interpreter oracle on a slice, derated (BASELINE.md) --
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver

    templates, constraints = make_templates(n_templates)
    ci = Client(driver=InterpDriver())
    for t in templates:
        ci.add_template(t)
    for c in constraints:
        ci.add_constraint(c)
    for p in make_pods(baseline_slice, seed=1):
        ci.add_data(p)
    t0 = time.time()
    ci.audit()
    interp_s = time.time() - t0
    interp_cells = n_templates * baseline_slice
    interp_rate = interp_cells / interp_s
    est_ref_rate = interp_rate * GO_TOPDOWN_DERATE
    est_ref_sweep_s = cells / est_ref_rate
    log(f"interp oracle: {interp_rate:.0f} evals/s; estimated Go-topdown "
        f"reference ({GO_TOPDOWN_DERATE:.0f}x derate): {est_ref_rate:.0f} "
        f"evals/s -> {est_ref_sweep_s:.0f}s for this sweep")

    gc.unfreeze()  # the other configs in a combined run want normal GC

    return {
        "metric": (
            f"end-to-end audit sweep seconds ({n_templates} templates"
            f" x {n_resources} resources, cap {cap}, steady-state)"
        ),
        "value": round(sweep_s, 3),
        "unit": "s",
        "vs_baseline": round(est_ref_sweep_s / sweep_s, 1),
        "cold_sweep_s": round(cold_s, 3),
        "full_resweep_s": round(full_s, 3),
        # cells covered per second: the incremental sweep verifies the full
        # C x R grid per interval while re-evaluating only changed rows
        "coverage_cells_per_s": round(cells / sweep_s, 1),
        "delta_rows_per_sweep": delta_rows,
        "sweep_breakdown_ms": {
            k: round(best_stats.get(k, 0.0), 2)
            for k in ("pack_ms", "device_ms", "fetch_ms", "render_ms")
        },
        "sweep_fetch_bytes": best_stats.get("fetch_bytes", 0.0),
        "full_sweep_device_ms": round(full_stats.get("device_ms", 0.0), 2),
        # clean ON-DEVICE numbers (min-based two-length chained-scan
        # slope — the relay RTT cancels in the difference; null when the
        # estimator cascade could not resolve consistently): the fields
        # the near-roofline claim rests on; full_sweep_device_ms above
        # stays relay-inclusive for honesty
        "device_sweep_ms": (
            round(device_sweep_ms, 4) if device_sweep_ms is not None
            else None),
        "device_cell_evals_per_s": (
            round(device_cells_per_s, 1) if device_cells_per_s is not None
            else None),
        "hbm_roofline_ms": round(roofline_ms, 2),
        "device_util": util,
        "device_util_measured": util_measured,
        "device_breakdown": device_breakdown,
    }


def _pipelined_drive(port: int, req_b: bytes, n_total: int,
                     n_clients: int = 2, window: int = 256,
                     timeout: float = 300.0):
    """Closed-loop persistent PIPELINED clients (EDGE_r19 satellite 1,
    shared with the edge-observability config): each keeps ``window``
    requests in flight on one connection and counts fixed-length
    responses by byte arithmetic, so the client side stays cheap enough
    not to mask the door.  Requires every response to be
    byte-length-identical (one fixed request body; trace ids and
    replica ids are fixed-width)."""
    import socket
    import threading

    done: dict = {}

    def _c(tid: int, n: int) -> None:
        s = socket.create_connection(("127.0.0.1", port),
                                     timeout=timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(timeout)
        batch = req_b * 16
        sent = got_b = recv = 0
        rlen = None
        buf = b""
        try:
            while recv < n:
                while sent - recv < window and sent < n:
                    s.sendall(batch)
                    sent += 16
                data = s.recv(1 << 20)
                if not data:
                    break
                if rlen is None:
                    buf += data
                    i = buf.find(b"\r\n\r\n")
                    if i < 0:
                        continue
                    m = re.search(
                        r"content-length:\s*(\d+)",
                        buf[:i].decode("latin-1").lower())
                    rlen = i + 4 + int(m.group(1))
                    got_b = len(buf)
                    buf = b""
                else:
                    got_b += len(data)
                recv = got_b // rlen
        finally:
            done[tid] = min(recv, n)
            try:
                s.close()
            except OSError:
                pass

    per = n_total // n_clients
    ts = [threading.Thread(target=_c, args=(i, per))
          for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout + 60.0)
        if t.is_alive():
            raise RuntimeError("edge pipelined client wedged "
                               "(no completion in time)")
    return sum(done.values()), time.perf_counter() - t0


def _stub_wire_responder(canned: bytes):
    """In-process GKW1 stub: answers every request record of every
    chunk with ``canned`` (a real AdmissionReview body), parsing only
    the frame skeleton — the EDGE_r19 door-capacity recipe, isolating
    the door's data plane from engine throughput.  Returns the bound
    listening socket (close it to stop the accept thread)."""
    import socket
    import struct
    import threading

    from gatekeeper_tpu.fleet import wireproto as _wp

    _hdrS = _wp._HDR
    _reqS = _wp._REQ
    resp_mid = struct.pack("!HI", 200, len(canned)) + canned
    resp_rec = 10 + len(canned)
    rid_pack = struct.Struct("!I").pack

    lsock = socket.socket()
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)

    def _conn(sk):
        sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        rbuf = bytearray()
        try:
            while True:
                d = sk.recv(1 << 20)
                if not d:
                    return
                rbuf += d
                out: list = []
                while len(rbuf) >= _hdrS.size:
                    _m, _k, count, plen = _hdrS.unpack_from(rbuf, 0)
                    if len(rbuf) < _hdrS.size + plen:
                        break
                    off = _hdrS.size
                    for _ in range(count):
                        rid, _dl, pl, tl, bl = _reqS.unpack_from(
                            rbuf, off)
                        off += _reqS.size + pl + tl + bl
                        out.append(rid_pack(rid))
                        out.append(resp_mid)
                    del rbuf[:_hdrS.size + plen]
                if out:
                    n_recs = len(out) // 2
                    sk.sendall(_hdrS.pack(
                        _wp.MAGIC, _wp.KIND_RESPONSE, n_recs,
                        n_recs * resp_rec) + b"".join(out))
        except OSError:
            return

    def _accept():
        while True:
            try:
                sk, _addr = lsock.accept()
            except OSError:
                return
            threading.Thread(target=_conn, args=(sk,),
                             daemon=True).start()

    threading.Thread(target=_accept, daemon=True).start()
    return lsock


def bench_fleet() -> dict:
    """Fleet serving (docs/fleet.md, ISSUE 7): N webhook-only replica
    processes restore ONE shared sealed snapshot + AOT cache, sit behind
    the stdlib front door, and are measured on

      - warm time-to-device-ready per replica (spawn -> first admission
        answered end to end; the <5s shared-warmth claim),
      - client-observed admission latency through the front door under
        low sequential load and under concurrent load, attributed per
        replica via the X-GK-Replica header,
      - verdict parity: byte-identical AdmissionReview bodies across
        replicas for identical requests, and allow/deny + message
        parity against a fresh interpreter oracle,
      - combined saturated throughput: every replica streams its
        restored corpus through review_batch concurrently (the batch1m
        chunk shape, in-process per replica so the HTTP framing cost —
        measured separately above — does not mask engine throughput).

    BENCH_EDGE selects the front door serving the fleet sections:
    "evloop" (default — the selectors reactor over the replicas' wire
    listeners) or "threaded" (the deprecated thread-per-request
    FrontDoor, kept measurable behind this explicit opt-in; see
    docs/fleet.md).  The dedicated event-edge rounds (EDGE_r19) run in
    either mode.
    """
    import http.client as _httpc
    import shutil
    import tempfile
    import threading

    from gatekeeper_tpu.fleet import EventFrontDoor, FrontDoor, spawn_fleet
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.synthetic import (
        build_driver,
        build_oracle,
        make_pods,
    )

    n_replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", "3"))
    n_templates = int(os.environ.get("BENCH_FLEET_TEMPLATES", "2"))
    n_resources = int(os.environ.get("BENCH_FLEET_RESOURCES", "2048"))
    n_stream = int(os.environ.get("BENCH_FLEET_REVIEWS", "400000"))
    chunk = int(os.environ.get("BENCH_FLEET_CHUNK", "16384"))
    n_latency = int(os.environ.get("BENCH_FLEET_LATENCY_N", "400"))
    n_parity = int(os.environ.get("BENCH_FLEET_PARITY_N", "64"))
    edge_kind = os.environ.get("BENCH_EDGE", "evloop")
    if edge_kind not in ("evloop", "threaded"):
        raise RuntimeError(f"BENCH_EDGE={edge_kind!r}: expected "
                           "'evloop' or 'threaded'")

    root = tempfile.mkdtemp(prefix="gk-fleet-bench-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)

    # ---- shared warmth: populate once, snapshot once ----------------------
    client = build_driver(n_templates, n_resources)
    client.audit_capped(50)  # pack + sweep basis for the snapshot
    name = Snapshotter(client, snap_dir, interval_s=0.0).write_once()
    log(f"fleet: snapshot {name}")

    # admission sample: reuse the corpus generator at a different seed so
    # requests are fresh content (no audit-pack identity), same families
    sample_pods = make_pods(max(n_latency, n_parity), seed=99,
                            violation_rate=0.3)

    def admit_body(i: int) -> bytes:
        p = sample_pods[i % len(sample_pods)]
        return json.dumps({"request": {
            "uid": f"fleet-bench-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "fleet-bench"},
            "object": p,
        }}).encode()

    def post(port: int, body: bytes, conn=None):
        c = conn or _httpc.HTTPConnection("127.0.0.1", port, timeout=60)
        c.request("POST", "/v1/admit", body=body,
                  headers={"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), r.read(), c

    # ---- oracle verdicts (fresh interpreter, same corpus) -----------------
    oracle = build_oracle(n_templates, n_resources)
    oracle_verdicts = []
    for i in range(n_parity):
        p = sample_pods[i % len(sample_pods)]
        resp = oracle.review({
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "object": p,
        })
        results = resp.results()
        oracle_verdicts.append(
            (not results, tuple(sorted(r.msg for r in results)))
        )

    # one throwaway replica seeds the shared XLA/AOT cache (the running
    # fleet's steady state); every MEASURED replica then models the
    # scale-up case the <5s claim is about — joining a warm fleet
    seed = spawn_fleet(
        1, snapshot_dir=snap_dir, cache_dir=cache_dir,
        env={"JAX_PLATFORMS": "cpu"},
    )[0]
    seed_ready_s = seed.ready_s
    seed_outcome = seed.ready.get("restore_outcome")
    seed.stop()
    log(f"fleet: cache-seed replica ready={seed_ready_s}s "
        f"({seed_outcome})")

    handles = spawn_fleet(
        n_replicas, snapshot_dir=snap_dir, cache_dir=cache_dir,
        env={"JAX_PLATFORMS": "cpu"},
    )
    door = None
    try:
        for h in handles:
            if h.ready.get("restore_outcome") != "restored":
                raise RuntimeError(
                    f"replica {h.replica_id} came up COLD "
                    f"({h.ready.get('restore_outcome')}): the shared-"
                    f"warmth bench would measure the wrong thing"
                )
        log("fleet: " + ", ".join(
            f"{h.replica_id} ready={h.ready_s}s spawn={h.spawn_s}s"
            for h in handles
        ))

        # the event door is the default serving edge (satellite of
        # ISSUE 20: the threaded FrontDoor is deprecated and must be
        # asked for explicitly with BENCH_EDGE=threaded)
        if edge_kind == "threaded":
            door = FrontDoor([h.backend() for h in handles]).start()
        else:
            no_wire = [h.replica_id for h in handles if not h.wire_port]
            if no_wire:
                raise RuntimeError(
                    f"replicas {no_wire} announced no wire port — the "
                    "default evloop edge cannot serve (BENCH_EDGE="
                    "threaded to force the deprecated door)")
            door = EventFrontDoor(
                [h.wire_backend() for h in handles]).start()

        # ---- parity: byte-identical across replicas, verdicts vs oracle --
        parity = True
        parity_vs_oracle = True
        for i in range(n_parity):
            body = admit_body(i)
            raws = []
            for h in handles:
                _st, _hd, data, _c = post(h.port, body)
                raws.append(data)
            if len(set(raws)) != 1:
                parity = False
                log(f"fleet: replica divergence on request {i}")
            out = json.loads(raws[0])["response"]
            allowed = out["allowed"]
            # message CONTENT parity, not just count: strip the
            # webhook's "[denied by <constraint>] " prefix (reference
            # log_denies format) so the rendered violation text is
            # compared byte-for-byte against the oracle's
            msgs = tuple(sorted(
                re.sub(r"^\[denied by [^\]]+\] ", "", m)
                for m in (out.get("status") or {}).get(
                    "message", "").split("\n") if m
            )) if not allowed else ()
            o_allowed, o_msgs = oracle_verdicts[i]
            if allowed != o_allowed or (not allowed and msgs != o_msgs):
                parity_vs_oracle = False
                log(f"fleet: oracle divergence on request {i}: "
                    f"fleet={allowed}/{msgs} "
                    f"oracle={o_allowed}/{o_msgs}")

        # ---- latency through the front door ------------------------------
        # low load: one sequential client (the inline fast path / p99
        # floor); saturating: 4x clients hammering concurrently
        def drive(n: int, conn_state: dict) -> list:
            out = []
            conn = conn_state.get("conn")
            for i in range(n):
                body = admit_body(i)
                t0 = time.perf_counter()
                try:
                    _st, hd, _data, conn = post(
                        door.port, body, conn)
                except Exception:
                    conn = None
                    continue
                out.append((
                    (time.perf_counter() - t0) * 1e3,
                    hd.get("X-GK-Replica", ""),
                ))
            conn_state["conn"] = conn
            return out

        def pct(xs, q):
            if not xs:
                return None
            return round(xs[min(int(q * len(xs)), len(xs) - 1)], 3)

        seq = drive(n_latency, {})
        seq_ms = sorted(ms for ms, _r in seq)

        # ---- wire-path observability (ISSUE 11, recorded OBS_r11) --------
        # The front door traced every request above: per-stage p50/p99
        # from the parent tracer's wire traces, the no-dark-time share
        # (stage p50s vs the wire p50), the federated /metrics view, and
        # one seeded slow request assembled across processes.
        from gatekeeper_tpu.fleet.frontdoor import WIRE_STAGES
        from gatekeeper_tpu.obs import fleetobs
        from gatekeeper_tpu.obs import trace as obstrace

        fed = fleetobs.MetricsFederator(lambda: [
            {"replica_id": h.replica_id, "host": h.host,
             "port": h.metrics_port} for h in handles
        ])
        col = fleetobs.TraceCollector(lambda: [
            {"replica_id": h.replica_id, "host": h.host, "port": h.port}
            for h in handles
        ])
        door.attach_observability(federator=fed, collector=col)

        wire = [t for t in obstrace.get_tracer().traces()
                if t.get("root") == "wire"]
        from gatekeeper_tpu.obs.trace import stage_breakdown as _sb

        per_stage: dict = {s: [] for s in WIRE_STAGES}
        durations = []
        coverage = []
        for t in wire:
            bd = _sb(t)
            durations.append(t["duration_ms"])
            if t["duration_ms"] > 0:
                coverage.append(
                    sum(bd.get(s, 0.0) for s in WIRE_STAGES)
                    / t["duration_ms"]
                )
            for s in WIRE_STAGES:
                per_stage[s].append(bd.get(s, 0.0))
        durations.sort()
        stage_p50 = {s: pct(sorted(xs), 0.50) for s, xs in
                     per_stage.items()}
        stage_p99 = {s: pct(sorted(xs), 0.99) for s, xs in
                     per_stage.items()}
        wire_p50 = pct(durations, 0.50) or 0.0
        wire_p99 = pct(durations, 0.99)
        stage_share = (
            round(sum(v for v in stage_p50.values() if v) / wire_p50, 4)
            if wire_p50 else None
        )
        coverage.sort()
        log(f"fleet: wire p50={wire_p50}ms, stage-sum share="
            f"{stage_share}, median per-trace coverage="
            f"{pct(coverage, 0.5)}")

        # federated /metrics through the door: replica series must be
        # replica_id-labelled and the wire stage families present
        conn_m = _httpc.HTTPConnection("127.0.0.1", door.port, timeout=30)
        conn_m.request("GET", "/metrics")
        fed_text = conn_m.getresponse().read().decode()
        conn_m.close()
        fed_ok = (
            "gatekeeper_frontdoor_stage_seconds" in fed_text
            and 'replica_id="r0"' in fed_text
            and "gatekeeper_fleet_scrape_ok" in fed_text
            and "# EOF" not in fed_text
        )
        log(f"fleet: federated /metrics ok={fed_ok} "
            f"({len(fed_text.splitlines())} lines)")

        # seeded slow request: one latency fault on r0's batcher entry,
        # installed over the WARM replica's command pipe — the next
        # admission the door routes to r0 carries ~+80ms, and its trace
        # must assemble across processes under ONE trace_id
        slow_ms = 80.0
        chaos_reply = handles[0].command({"cmd": "chaos", "spec": {
            "seed": 11,
            "rules": [{"point": "webhook.enqueue", "mode": "latency",
                       "latency_s": slow_ms / 1e3, "count": 1}],
        }})
        if chaos_reply.get("error") or not chaos_reply.get("enabled"):
            # the seeded slow request is ACCEPTANCE evidence: a failed
            # fault install must fail the bench loudly, not silently
            # record slow_trace_joined=null
            raise RuntimeError(
                f"slow-request chaos seed failed: {chaos_reply}")
        state: dict = {}
        for _ in range(4 * len(handles)):
            drive(1, state)
        handles[0].command({"cmd": "chaos", "spec": None})

        def _find_joined():
            assembled = col.assemble(min_ms=slow_ms * 0.8)
            for entry in assembled["traces"]:
                if len(entry["processes"]) > 1 \
                        and entry["root"] == "wire":
                    has_wire = any(
                        sp.get("process") == "frontdoor"
                        and (sp.get("attrs") or {}).get("stage")
                        for sp in entry["spans"]
                    )
                    has_replica = any(
                        sp.get("process") not in (None, "frontdoor")
                        for sp in entry["spans"]
                    )
                    if has_wire and has_replica:
                        return {
                            "trace_id": entry["trace_id"],
                            "duration_ms": entry["duration_ms"],
                            "processes": entry["processes"],
                            "stage_breakdown": entry["stage_breakdown"],
                        }
            return None

        # the replica half completes asynchronously relative to the
        # door's response: poll briefly before declaring the join absent
        slow_joined = None
        for _ in range(20):
            slow_joined = _find_joined()
            if slow_joined is not None:
                break
            time.sleep(0.25)
        log(f"fleet: seeded slow trace joined: {slow_joined}")

        threads_out: list = []
        lock = threading.Lock()

        def _client():
            got = drive(n_latency, {})
            with lock:
                threads_out.extend(got)

        tt0 = time.perf_counter()
        clients = [threading.Thread(target=_client) for _ in range(4)]
        for t in clients:
            t.start()
        for t in clients:
            # bounded: a wedged driver must fail the bench, not hang it
            t.join(timeout=600.0)
            if t.is_alive():
                raise RuntimeError("bench latency client wedged (no "
                                   "result within 600s)")
        http_wall = time.perf_counter() - tt0
        http_rps = len(threads_out) / http_wall if threads_out else 0.0

        per_replica: dict = {}
        for ms, rid in threads_out:
            per_replica.setdefault(rid, []).append(ms)
        replica_lat = {
            rid: {
                "n": len(xs),
                "p50_ms": pct(sorted(xs), 0.50),
                "p99_ms": pct(sorted(xs), 0.99),
            }
            for rid, xs in sorted(per_replica.items())
        }

        # ---- combined saturated throughput (in-replica streams) ----------
        stream_out: dict = {}

        def _stream(h):
            stream_out[h.replica_id] = h.command(
                {"cmd": "stream", "n": n_stream, "chunk": chunk}
            )

        # best of 3 rounds: this box's co-tenancy swings host-path rates
        # ±30% run to run (the render bench takes min-of-3 for the same
        # reason); later rounds also stream with every replica's jit warm
        best = None
        for rnd in range(3):
            stream_out.clear()
            streams = [
                threading.Thread(target=_stream, args=(h,))
                for h in handles
            ]
            for t in streams:
                t.start()
            for t in streams:
                # bounded: a wedged replica stream fails the round loudly
                t.join(timeout=600.0)
                if t.is_alive():
                    raise RuntimeError("fleet stream thread wedged (no "
                                       "completion within 600s)")
            # the combined rate is measured over the union of the
            # replicas' TIMED windows (child-reported wall stamps,
            # warmup excluded) — the parent's own wall would bill each
            # child's jit warmup and command framing against engine
            # throughput
            wall = (
                max(s["t1_wall"] for s in stream_out.values())
                - min(s["t0_wall"] for s in stream_out.values())
            )
            rate = n_stream * len(handles) / wall
            log(f"fleet: round {rnd}: {rate:.0f} reviews/s over "
                f"{len(handles)} replicas ({wall:.1f}s wall)")
            if best is None or rate > best[0]:
                best = (rate, wall, dict(stream_out))
        combined, stream_wall, stream_out = best

        # ---- profiler overhead (ISSUE 11 acceptance: within 5%) ----------
        # The SAME warm replicas stream with the sampler off then on
        # (runtime re-rate over the command pipe — no respawn, no cold
        # jit).  This box's co-tenancy swings short windows ±30%, so the
        # estimate is PAIRED: off/on back-to-back, the ratio taken
        # within each pair (drift hits both arms of a pair almost
        # equally), the ARM ORDER alternated per pair (monotonic drift
        # would otherwise systematically tax whichever arm runs
        # second), median over pairs.
        n_overhead = int(os.environ.get("BENCH_FLEET_OVERHEAD_REVIEWS",
                                        str(n_stream)))
        n_pairs = int(os.environ.get("BENCH_FLEET_OVERHEAD_PAIRS", "5"))
        from gatekeeper_tpu.obs.profiler import DEFAULT_HZ as prof_hz

        def _profiler_round(hz: float) -> float:
            for h in handles:
                h.command({"cmd": "profiler", "hz": hz})
            outp: dict = {}
            errs: list = []

            def _s(h):
                try:
                    outp[h.replica_id] = h.command(
                        {"cmd": "stream", "n": n_overhead,
                         "chunk": chunk}
                    )
                except Exception as e:  # surfaced after the joins
                    errs.append((h.replica_id, e))

            ts = [threading.Thread(target=_s, args=(h,)) for h in handles]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=600.0)
                if t.is_alive():
                    raise RuntimeError(
                        "profiler-overhead stream wedged (no completion "
                        "within 600s)")
            if errs or len(outp) != len(handles):
                # a partial round would silently inflate the recorded
                # overhead number (numerator counts every replica)
                raise RuntimeError(
                    f"profiler-overhead round incomplete: errors={errs},"
                    f" replied={sorted(outp)}")
            wall = (max(s["t1_wall"] for s in outp.values())
                    - min(s["t0_wall"] for s in outp.values()))
            return round(n_overhead * len(handles) / wall, 1)

        rates_off, rates_on, pair_ratios = [], [], []
        for i in range(n_pairs):
            if i % 2 == 0:
                off = _profiler_round(0.0)
                on = _profiler_round(prof_hz)
            else:
                on = _profiler_round(prof_hz)
                off = _profiler_round(0.0)
            rates_off.append(off)
            rates_on.append(on)
            pair_ratios.append(on / off)
        # estimator: median(on)/median(off) over the position-balanced
        # arms — a pairwise-ratio median is hostage to whichever pair a
        # co-tenant burst lands in; arm medians reject those outliers
        med_off = sorted(rates_off)[len(rates_off) // 2]
        med_on = sorted(rates_on)[len(rates_on) // 2]
        profiler_overhead_pct = round((1.0 - med_on / med_off) * 100.0,
                                      2)
        log(f"fleet: profiler overhead {profiler_overhead_pct}% "
            f"(median off={med_off} on={med_on}, paired ratios="
            f"{[round(r, 3) for r in pair_ratios]}, off={rates_off}, "
            f"on={rates_on})")
        # the sampler's own output, from a replica that just streamed
        conn_p = _httpc.HTTPConnection(
            "127.0.0.1", handles[0].port, timeout=30)
        conn_p.request("GET", "/debug/profilez")
        profilez = conn_p.getresponse().read().decode()
        conn_p.close()
        profilez_lines = len(profilez.splitlines())

        obs_wire = {
            "wire_p50_ms": wire_p50,
            "wire_p99_ms": wire_p99,
            "wire_traces": len(wire),
            "stage_p50_ms": stage_p50,
            "stage_p99_ms": stage_p99,
            "stage_share_of_p50": stage_share,
            "trace_coverage_p50": pct(coverage, 0.50),
            "client_seq_p50_ms": pct(seq_ms, 0.50),
            "federated_metrics_ok": fed_ok,
            "federated_metrics_lines": len(fed_text.splitlines()),
            "slow_trace_joined": slow_joined,
            "profiler_overhead_pct": profiler_overhead_pct,
            "profiler_rates_off": rates_off,
            "profiler_rates_on": rates_on,
            "profilez_lines": profilez_lines,
            "fleet_reviews_per_s": round(combined, 1),
        }
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "OBS_r11.json"), "w") as f:
            json.dump(obs_wire, f, indent=2, sort_keys=True)

        # ---- event-loop edge (ISSUE 19, recorded EDGE_r19) ---------------
        # The selectors-based serving edge over the SAME warm replicas:
        #   (a) persistent-connection latency with per-stage p50s from
        #       the ring traces (sample 1.0), against the front
        #       section's stage numbers above (the BENCH_EDGE door);
        #   (b) the door-capacity headline against an in-process stub
        #       wire responder — the front door's own data plane
        #       (accept/parse/route/splice/write), isolated from engine
        #       throughput, with 2% head sampling as a high-rate
        #       deployment would run it;
        #   (c) the honest end-to-end pipelined rate through the real
        #       replicas (engine-bound, reported as such);
        #   (d) a connect-per-request round — the old clients' shape —
        #       reported separately;
        #   (e) the ISSUE 12 overload contract re-proven on this edge:
        #       tight-bounded door, 10x closed-loop saturation, shed
        #       p99 and zero verdict divergence vs the oracle.
        import gc

        from gatekeeper_tpu.util.overloadcheck import (
            ACCEPTED,
            PROBLEM,
            SHED,
            classify_response,
            verdict_matches,
        )

        n_edge_lat = int(os.environ.get("BENCH_EDGE_LATENCY_N", "400"))
        n_edge_cap = int(os.environ.get("BENCH_EDGE_CAP_REVIEWS", "40000"))
        # best-of like the stream rounds, but deeper: the capacity
        # rounds are ~1s each and this box's co-tenant bursts can sink
        # half of them (observed swing 27k..63k for identical code)
        cap_rounds = int(os.environ.get("BENCH_EDGE_CAP_ROUNDS", "5"))
        n_edge_e2e = int(os.environ.get("BENCH_EDGE_E2E_REVIEWS", "4000"))
        n_edge_conn = int(os.environ.get("BENCH_EDGE_CONNECT_N", "300"))
        overload_s = float(os.environ.get("BENCH_EDGE_OVERLOAD_S", "3.0"))

        missing_wire = [h.replica_id for h in handles if not h.wire_port]
        if missing_wire:
            raise RuntimeError(
                f"replicas {missing_wire} announced no wire port — the "
                "event-edge rounds would measure nothing")

        # Quiesce the co-tenants before measuring the edge — everything
        # here shares ONE core with the reactor, and each periodic
        # wakeup lands as a preemption inside some stage window:
        #   - the paired profiler rounds above END with the replicas'
        #     sampling profiler armed (the last pair's second arm is
        #     "on"), so every replica would keep waking at DEFAULT_HZ;
        #   - the front-section door is done serving: its prober
        #     re-probes the fleet every 250ms.  stats() below reads
        #     counters, which survive stop().
        for h in handles:
            h.command({"cmd": "profiler", "hz": 0.0})
        door.stop()

        edoor = EventFrontDoor([h.wire_backend() for h in handles]).start()
        odoor = None
        cap_lsock = None
        try:
            # The bench process carries several hundred MB of heap by
            # this point (parity oracles, per-round samples); a gen-2
            # collection walking it mid-round is a multi-ms stall billed
            # to whatever stage it lands in.  Freeze the existing heap
            # out of the collector and disable cycle collection for the
            # measured rounds — refcounting still frees the per-request
            # garbage, which is cycle-free on the hot path.
            gc.collect()
            gc.freeze()
            gc.disable()

            # -- (a) persistent-connection latency, everything traced --
            obstrace.get_tracer().configure(sample_rate=1.0)
            e_conn = None
            e_tids: list = []
            e_ms: list = []
            last_body = b""
            for i in range(n_edge_lat):
                body = admit_body(i)
                t0 = time.perf_counter()
                _st, hd, last_body, e_conn = post(edoor.port, body, e_conn)
                e_ms.append((time.perf_counter() - t0) * 1e3)
                e_tids.append(hd.get("X-GK-Trace-Id", ""))
            if e_conn is not None:
                e_conn.close()
            e_ms_sorted = sorted(e_ms)
            tidset = set(t for t in e_tids if t)
            e_wire = [t for t in obstrace.get_tracer().traces()
                      if t["trace_id"] in tidset]
            e_per_stage: dict = {s: [] for s in WIRE_STAGES}
            e_durs = []
            for t in e_wire:
                bd = _sb(t)
                e_durs.append(t["duration_ms"])
                for s in WIRE_STAGES:
                    e_per_stage[s].append(bd.get(s, 0.0))
            e_durs.sort()
            e_stage_p50 = {s: pct(sorted(xs), 0.50)
                           for s, xs in e_per_stage.items()}
            e_stage_p99 = {s: pct(sorted(xs), 0.99)
                           for s, xs in e_per_stage.items()}
            stage_p50_vs_front = {
                s: {f"{edge_kind}_ms": stage_p50.get(s),
                    "evloop_ms": e_stage_p50.get(s)}
                for s in WIRE_STAGES
            }
            log(f"fleet: event edge wire p50={pct(e_durs, 0.50)}ms over "
                f"{len(e_wire)} traces; stage p50 vs {edge_kind} front: "
                + ", ".join(
                    f"{s} {e_stage_p50.get(s)}/{stage_p50.get(s)}"
                    for s in ("accept", "proxy_connect", "write_back")))

            # -- (b) door-capacity headline: stub wire responder -------
            # One fixed request body; the stub answers every request
            # record with the latency round's REAL AdmissionReview
            # bytes, parsing only the frame skeleton (req ids) so the
            # responder does not tax the core the door is measured on.
            canned = last_body or b"{}"
            cap_lsock = _stub_wire_responder(canned)
            cap_door = EventFrontDoor(
                [{"host": "127.0.0.1",
                  "port": cap_lsock.getsockname()[1],
                  "probe_port": 0, "replica_id": "stub"}],
                probe_interval_s=3600.0,
            ).start()
            cap_body = admit_body(0)
            cap_req = (
                b"POST /v1/admit HTTP/1.1\r\nHost: bench\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(cap_body)
            ) + cap_body
            obstrace.get_tracer().configure(sample_rate=0.02)
            cap_best = None
            cap_runs = []
            try:
                for rnd in range(cap_rounds):
                    got, wall = _pipelined_drive(
                        cap_door.port, cap_req, n_edge_cap)
                    rate = got / wall if wall else 0.0
                    cap_runs.append(round(rate, 1))
                    log(f"fleet: edge capacity round {rnd}: {got} reqs "
                        f"in {wall:.2f}s = {rate:.0f}/s")
                    if cap_best is None or rate > cap_best:
                        cap_best = rate
            finally:
                obstrace.get_tracer().configure(sample_rate=1.0)
                cap_door.stop()

            # -- (c) honest end-to-end pipelined rate ------------------
            e2e_got, e2e_wall = _pipelined_drive(
                edoor.port, cap_req, n_edge_e2e)
            e2e_rate = e2e_got / e2e_wall if e2e_wall else 0.0
            log(f"fleet: edge e2e {e2e_got} reviews in {e2e_wall:.2f}s "
                f"= {e2e_rate:.0f}/s through {n_replicas} replicas")

            # -- (d) connect-per-request, reported separately ----------
            t0 = time.perf_counter()
            conn_ok = 0
            for i in range(n_edge_conn):
                _st, _hd, _data, c = post(edoor.port, cap_body)
                conn_ok += 1 if _st == 200 else 0
                c.close()
            conn_wall = time.perf_counter() - t0
            conn_rps = conn_ok / conn_wall if conn_wall else 0.0
            log(f"fleet: edge connect-per-request {conn_rps:.0f}/s "
                f"({conn_ok}/{n_edge_conn} ok)")

            # -- (e) overload contract re-proof on this edge -----------
            # GC back on: bench_overload (OVERLOAD_r12) ran its storm
            # with the collector enabled, and this round re-proves that
            # contract on the new edge under the same conditions.
            gc.unfreeze()
            gc.enable()
            # Shed latency is read DOOR-SIDE from the wire traces, the
            # same way bench_overload records shed_answer_p99_ms: ten
            # closed-loop storm clients share this process's GIL with
            # the reactor, so their client-clock timings measure thread
            # scheduling, not the door.  A deep ring holds the storm.
            obstrace.configure(buffer_size=4096, sample_rate=1.0)
            obstrace.get_tracer().clear()
            odoor = EventFrontDoor(
                [h.wire_backend() for h in handles],
                max_inflight=1, admission_budget_s=2.0,
            ).start()
            o_lock = threading.Lock()
            o_counts: dict = {}
            o_shed_ms: list = []
            o_retry_after = 0
            o_mismatches: list = []
            o_problems: list = []
            n_storm = 10

            def _storm(tid: int) -> None:
                nonlocal o_retry_after
                conn = None
                end = time.monotonic() + overload_s
                i = tid
                while time.monotonic() < end:
                    body = admit_body(i % n_parity)
                    t0 = time.perf_counter()
                    try:
                        st, hd, data, conn = post(
                            odoor.port, body, conn)
                    except Exception as e:
                        conn = None
                        with o_lock:
                            o_problems.append(f"conn_error:{e!r}")
                        continue
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    kind, out_resp = classify_response(st, data)
                    with o_lock:
                        o_counts[kind] = o_counts.get(kind, 0) + 1
                        if kind == SHED and st == 429:
                            o_shed_ms.append(dt_ms)
                            if hd.get("Retry-After"):
                                o_retry_after += 1
                        if kind == ACCEPTED:
                            want = oracle_verdicts[i % n_parity]
                            if not verdict_matches(
                                    out_resp, (want[0], list(want[1]))):
                                o_mismatches.append(i % n_parity)
                        if kind == PROBLEM:
                            o_problems.append(f"status={st}")
                    i += n_storm

            storm_ts = [threading.Thread(target=_storm, args=(i,))
                        for i in range(n_storm)]
            for t in storm_ts:
                t.start()
            for t in storm_ts:
                t.join(timeout=overload_s + 120.0)
                if t.is_alive():
                    raise RuntimeError("edge overload storm client "
                                       "wedged")
            shed_door_ms: list = []
            for t in obstrace.get_tracer().traces():
                if t.get("root") != "wire":
                    continue
                rs = next((s for s in t.get("spans", ())
                           if s.get("name") == "wire"), None)
                if rs is None:
                    continue
                if (rs.get("attrs") or {}).get("outcome") == "shed":
                    shed_door_ms.append(t["duration_ms"])
            shed_door_ms.sort()
            shed_p99 = pct(shed_door_ms, 0.99)
            o_shed_ms.sort()
            log(f"fleet: edge overload: {o_counts}, shed p99="
                f"{shed_p99}ms door-side over {len(shed_door_ms)} "
                f"traces (client-clock p99={pct(o_shed_ms, 0.99)}ms), "
                f"divergences={len(o_mismatches)}, "
                f"problems={len(o_problems)}")

            edge = {
                "edge": "evloop (selectors reactor, batched wire "
                        "protocol)",
                "door_capacity_rps": round(cap_best or 0.0, 1),
                "door_capacity_runs_rps": cap_runs,
                "door_capacity_reviews": n_edge_cap,
                "door_capacity_sample_rate": 0.02,
                "door_capacity_note": (
                    "front-door data plane vs an in-process stub wire "
                    "responder answering real AdmissionReview bytes — "
                    "isolates the rebuilt component from engine "
                    "throughput; best of rounds (single shared core, "
                    "co-tenant noise)"),
                "e2e_pipelined_rps": round(e2e_rate, 1),
                "e2e_pipelined_reviews": e2e_got,
                "connect_per_request_rps": round(conn_rps, 1),
                "seq_p50_ms": pct(e_ms_sorted, 0.50),
                "seq_p99_ms": pct(e_ms_sorted, 0.99),
                "wire_p50_ms": pct(e_durs, 0.50),
                "wire_p99_ms": pct(e_durs, 0.99),
                "wire_traces": len(e_wire),
                "stage_p50_ms": e_stage_p50,
                "stage_p99_ms": e_stage_p99,
                "front_door_edge": edge_kind,
                "stage_p50_vs_front_door": stage_p50_vs_front,
                "overload": {
                    "counts": o_counts,
                    "shed_p99_ms": shed_p99,
                    "shed_p99_note": (
                        "door answer time from the wire traces "
                        "(accept..write_back), the OVERLOAD_r12 "
                        "shed_answer_p99_ms methodology — the storm "
                        "clients share the door's GIL, so their "
                        "client-clock timings measure scheduling"),
                    "shed_answer_n": len(shed_door_ms),
                    "shed_client_p99_ms": pct(o_shed_ms, 0.99),
                    "sheds_with_retry_after": o_retry_after,
                    "verdict_divergences": len(o_mismatches),
                    "problems": o_problems[:20],
                    "burst_s": overload_s,
                    "clients": n_storm,
                    "max_inflight": 1,
                },
            }
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "EDGE_r19.json"), "w") as f:
                json.dump(edge, f, indent=2, sort_keys=True)
        finally:
            # idempotent under an exception mid-rounds (gc.enable on an
            # enabled collector and unfreeze with nothing frozen are
            # both no-ops); ring size back to the boot default
            gc.unfreeze()
            gc.enable()
            obstrace.configure(
                buffer_size=int(os.environ.get("GK_TRACE_BUFFER",
                                               "256")))
            if odoor is not None:
                odoor.stop()
            if cap_lsock is not None:
                try:
                    cap_lsock.close()
                except OSError:
                    pass
            edoor.stop()

        return {
            "metric": (
                f"combined streamed reviews/s, {n_replicas} replicas x "
                f"{n_templates} constraints (shared warm snapshot)"
            ),
            "value": round(combined, 1),
            "unit": "reviews/s",
            "vs_baseline": 0,
            "fleet_reviews_per_s": round(combined, 1),
            "fleet_replicas": n_replicas,
            "fleet_templates": n_templates,
            "fleet_stream_chunk": chunk,
            "fleet_stream_wall_s": round(stream_wall, 2),
            "fleet_replica_stream": {
                rid: {
                    "reviews_per_s": s.get("reviews_per_s"),
                    "s": s.get("s"),
                }
                for rid, s in sorted(stream_out.items())
            },
            "fleet_ready_s": {
                h.replica_id: h.ready_s for h in handles
            },
            "fleet_spawn_s": {
                h.replica_id: h.spawn_s for h in handles
            },
            "fleet_ready_max_s": max(h.ready_s for h in handles),
            "fleet_cold_seed_ready_s": seed_ready_s,
            "fleet_restore_outcomes": {
                h.replica_id: h.ready.get("restore_outcome")
                for h in handles
            },
            "fleet_parity_across_replicas": parity,
            "fleet_parity_vs_oracle": parity_vs_oracle,
            "fleet_seq_p50_ms": pct(seq_ms, 0.50),
            "fleet_seq_p99_ms": pct(seq_ms, 0.99),
            "fleet_http_reviews_per_s": round(http_rps, 1),
            "fleet_replica_latency": replica_lat,
            "fleet_frontdoor": door.stats(),
            "obs_wire": obs_wire,
            "edge": edge,
            "edge_door_capacity_rps": edge["door_capacity_rps"],
            "edge_e2e_pipelined_rps": edge["e2e_pipelined_rps"],
            "edge_connect_per_request_rps": edge[
                "connect_per_request_rps"],
        }
    finally:
        if door is not None:
            door.stop()
        for h in handles:
            h.stop()
        shutil.rmtree(root, ignore_errors=True)


def bench_edge_obs() -> dict:
    """Reactor flight deck (ISSUE 20, recorded EDGEOBS_r20): the event
    edge's observability plane measured on the door's own data plane.

      (a) steady-state telemetry overhead: the EDGE_r19 door-capacity
          recipe (event door vs an in-process stub wire responder
          answering real AdmissionReview bytes) run as PAIRED rounds —
          reactor telemetry detached (the loop's pre-ISSUE-20 dispatch:
          ``_telem is None``, one untaken branch per site) vs attached
          (the shipped default), arm order alternated per pair,
          median-of-arms estimator (the profiler-overhead methodology:
          co-tenant drift hits both arms of a pair almost equally);
      (b) the door-capacity headline with telemetry ON — the number a
          deployment actually gets — against EDGE_r19's recorded
          capacity (acceptance: within 5%);
      (c) a seeded 250ms ``evloop.slow_callback`` stall (latency rule
          on the heartbeat's registered fault point) caught END TO END:
          the culprit table and the flight-recorder ``evloop_stall``
          event name the heartbeat callback, the cross-thread watchdog
          captures the reactor stack MID-stall within one scan period
          of the budget and dumps an incident, the next heartbeat's
          skew surfaces in ``evloop_lag_seconds``, and the
          force-sampled tick lands in the tick histogram.
    """
    import gc
    import tempfile

    from gatekeeper_tpu import faults
    from gatekeeper_tpu.fleet.evdoor import EventFrontDoor
    from gatekeeper_tpu.fleet.wirelistener import _envelope
    from gatekeeper_tpu.metrics.exporter import render_prometheus
    from gatekeeper_tpu.obs import flightrec, reactorobs
    from gatekeeper_tpu.obs import trace as obstrace
    from gatekeeper_tpu.util.synthetic import make_pods
    from gatekeeper_tpu.webhook.policy import AdmissionResponse

    n_cap = int(os.environ.get("BENCH_EDGEOBS_CAP_REVIEWS", "40000"))
    n_pairs = int(os.environ.get("BENCH_EDGEOBS_PAIRS", "8"))
    stall_s = float(os.environ.get("BENCH_EDGEOBS_STALL_S", "0.25"))
    # the watchdog samples the breadcrumb every WATCHDOG_TICK_S, so the
    # drill budget must undercut the stall by at least one scan period
    # or only an exact-boundary scan could catch it mid-flight; the
    # production default (STALL_BUDGET_S) is unchanged
    budget_s = float(os.environ.get("BENCH_EDGEOBS_BUDGET_S", "0.15"))

    # one fixed request; the stub answers every record with one fixed
    # realistic AdmissionReview allow body, so the pipelined clients
    # count responses by byte arithmetic (the EDGE_r19 recipe)
    pod = make_pods(1, seed=99, violation_rate=0.3)[0]
    req_json = json.dumps({"request": {
        "uid": "edge-obs-0",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE",
        "userInfo": {"username": "edge-obs"},
        "object": pod,
    }}).encode()
    cap_req = (
        b"POST /v1/admit HTTP/1.1\r\nHost: bench\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\n\r\n" % len(req_json)
    ) + req_json
    canned = _envelope(AdmissionResponse(True).to_dict(uid="edge-obs-0"))

    lsock = _stub_wire_responder(canned)
    door = EventFrontDoor(
        [{"host": "127.0.0.1", "port": lsock.getsockname()[1],
          "probe_port": 0, "replica_id": "stub"}],
        probe_interval_s=3600.0,
    ).start()
    loop = door._loop
    out: dict = {"edge": "evloop (selectors reactor, batched wire "
                         "protocol) vs in-process stub wire responder"}
    try:
        # ---- (a)+(b) paired capacity rounds ---------------------------
        obstrace.get_tracer().configure(sample_rate=0.02)
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            _pipelined_drive(door.port, cap_req, max(2000, n_cap // 8))

            def _cap_round(telemetry_on: bool) -> float:
                if telemetry_on:
                    reactorobs.attach(loop, "evdoor")
                else:
                    reactorobs.detach(loop)
                got, wall = _pipelined_drive(door.port, cap_req, n_cap)
                return round(got / wall, 1) if wall else 0.0

            rates_off, rates_on = [], []
            for i in range(n_pairs):
                if i % 2 == 0:
                    off = _cap_round(False)
                    on = _cap_round(True)
                else:
                    on = _cap_round(True)
                    off = _cap_round(False)
                rates_off.append(off)
                rates_on.append(on)
                log(f"edge_obs: pair {i}: off={off}/s on={on}/s")
        finally:
            gc.unfreeze()
            gc.enable()
            obstrace.get_tracer().configure(sample_rate=1.0)
            reactorobs.attach(loop, "evdoor")  # shipped default state
        med_off = sorted(rates_off)[len(rates_off) // 2]
        med_on = sorted(rates_on)[len(rates_on) // 2]
        overhead_pct = round((1.0 - med_on / med_off) * 100.0, 2)
        cap_best = max(rates_on)

        prior = None
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "EDGE_r19.json")) as f:
                prior = json.load(f).get("door_capacity_rps")
        except OSError:
            pass
        vs_prior = (round(cap_best / prior, 4)
                    if prior else None)
        out.update({
            "telemetry_overhead_pct": overhead_pct,
            "rates_off_rps": rates_off,
            "rates_on_rps": rates_on,
            "overhead_note": (
                "paired off/on rounds, arm order alternated per pair, "
                "median-of-arms; off = reactor telemetry detached "
                "(the pre-ISSUE-20 loop)"),
            "door_capacity_rps": cap_best,
            "door_capacity_off_rps": max(rates_off),
            "capacity_on_vs_off": round(cap_best / max(rates_off), 4),
            "capacity_control_note": (
                "the off-arm best is a SAME-RUN control: this box is "
                "one shared core and run-to-run host steal swings "
                "rates ±30% (EDGE_r19 documents 27k..63k for identical "
                "code), so on-vs-off within one run isolates telemetry "
                "cost from host drift"),
            "door_capacity_reviews": n_cap,
            "door_capacity_sample_rate": 0.02,
            "edge_r19_capacity_rps": prior,
            "capacity_vs_edge_r19": vs_prior,
            "capacity_within_5pct": (vs_prior is not None
                                     and vs_prior >= 0.95),
        })
        log(f"edge_obs: overhead {overhead_pct}% (median off={med_off} "
            f"on={med_on}); capacity {cap_best}/s vs EDGE_r19 {prior}/s")

        # ---- (c) the seeded stall, end to end -------------------------
        ddir = tempfile.mkdtemp(prefix="gk-edgeobs-flightrec-")
        flightrec.get_recorder().configure(dump_dir=ddir)
        flightrec.get_recorder().clear()
        reactorobs.detach(loop)
        telem = reactorobs.attach(loop, "evdoor", stall_budget_s=budget_s)

        def _tick_sum() -> float:
            m = re.search(
                r'gatekeeper_evloop_tick_seconds_sum\{[^}]*'
                r'loop="evdoor"[^}]*\}\s+([0-9.eE+-]+)',
                render_prometheus())
            return float(m.group(1)) if m else 0.0

        tick_sum0 = _tick_sum()
        plane = faults.install(seed=20)
        plane.add(faults.EVLOOP_SLOW_CALLBACK,
                  faults.FaultRule(mode=faults.LATENCY,
                                   latency_s=stall_s, count=1))
        lag_max = 0.0
        slow_ev = wd_ev = None
        deadline = time.monotonic() + 5.0
        try:
            while time.monotonic() < deadline:
                if telem.lag > lag_max:
                    lag_max = telem.lag
                for ev in flightrec.get_recorder().events():
                    if ev.get("type") != flightrec.EVLOOP_STALL:
                        continue
                    if ev.get("via") == "slow_callback":
                        slow_ev = ev
                    elif ev.get("via") == "watchdog":
                        wd_ev = ev
                if slow_ev and wd_ev and lag_max > 0.05:
                    break
                time.sleep(0.005)
        finally:
            faults.uninstall()
        culprits = telem.culprits()
        culprit = culprits[0]["callback"] if culprits else None

        # the force-sampled stalled tick must surface in the histogram
        # once the 0.5s flush cadence passes
        tick_delta = 0.0
        hist_deadline = time.monotonic() + 3.0
        while time.monotonic() < hist_deadline:
            tick_delta = _tick_sum() - tick_sum0
            if tick_delta >= stall_s * 0.8:
                break
            time.sleep(0.05)

        held_ms = (wd_ev or {}).get("held_ms")
        excess_ms = (round(held_ms - budget_s * 1e3, 1)
                     if held_ms is not None else None)
        stack = (wd_ev or {}).get("stack") or []
        out["stall"] = {
            "seeded_latency_ms": round(stall_s * 1e3, 1),
            "watchdog_budget_ms": round(budget_s * 1e3, 1),
            "watchdog_tick_ms": round(
                reactorobs.WATCHDOG_TICK_S * 1e3, 1),
            "culprit": culprit,
            "culprit_named_ok": bool(culprit and "_beat" in culprit),
            "slow_callback_event": (
                {k: slow_ev[k] for k in
                 ("callback", "kind", "duration_ms") if k in slow_ev}
                if slow_ev else None),
            "watchdog_held_ms": held_ms,
            "watchdog_excess_ms": excess_ms,
            "within_one_watchdog_period": (
                excess_ms is not None and excess_ms
                <= reactorobs.WATCHDOG_TICK_S * 1e3 + 25.0),
            "stack_names_culprit": any("_beat" in fr for fr in stack),
            "stack_depth": len(stack),
            "lag_seconds_max": round(lag_max, 4),
            "lag_visible": lag_max >= 0.1,
            "tick_hist_sum_delta_s": round(tick_delta, 4),
            "tick_hist_saw_stall": tick_delta >= stall_s * 0.8,
            "incident_dumps": sorted(os.listdir(ddir)),
        }
        log(f"edge_obs: stall drill: culprit={culprit} "
            f"lag_max={lag_max * 1e3:.1f}ms held={held_ms}ms "
            f"dumps={out['stall']['incident_dumps']}")
    finally:
        door.stop()
        try:
            lsock.close()
        except OSError:
            pass

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "EDGEOBS_r20.json"), "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
    return {
        "metric": ("reactor telemetry overhead on the event-edge door "
                   "capacity (paired off/on rounds)"),
        "value": out.get("telemetry_overhead_pct"),
        "unit": "%",
        "vs_baseline": 0,
        **out,
    }


def bench_chaos_fleet() -> dict:
    """Self-healing fleet under chaos (ISSUE 8, recorded as CHAOS_r08):
    two supervised replicas restore one sealed snapshot behind the front
    door; seeded fault points crash one replica (`fleet.replica_crash`,
    an error-mode rule pulsed in-child -> hard exit rc 23) and wedge the
    other (`fleet.replica_wedge`, a hang-mode rule parking its command
    pipe) MID-LOAD, while a sequential client streams parity-checked
    admissions through the door.  Recorded:

      - failed admissions (non-200 through the door) — the acceptance
        criterion is ZERO: the door's immediate ejection + bounded
        retry covers every kill window;
      - verdict parity vs a fresh interpreter oracle before/during/
        after each failure (allow/deny + rendered message bytes);
      - per-failure recovery: eject->readmit wall seconds and the
        supervisor's warm spawn-to-ready (< 5s criterion);
      - a zero-failure rolling restart (drain stats included);
      - mesh degradation (subprocess, virtual 4-device mesh): a stalled
        collective trips the watchdog -> breaker -> width 4 -> 2, with
        byte-parity preserved at the narrower width.
    """
    import re as _re
    import shutil
    import tempfile

    from gatekeeper_tpu.fleet import FrontDoor, ReplicaSupervisor
    from gatekeeper_tpu.fleet.replica import spawn_replica
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.synthetic import (
        build_driver,
        build_oracle,
        make_pods,
    )

    n_templates = int(os.environ.get("BENCH_CHAOS_TEMPLATES", "2"))
    n_resources = int(os.environ.get("BENCH_CHAOS_RESOURCES", "64"))
    duration_s = float(os.environ.get("BENCH_CHAOS_DURATION_S", "25"))
    crash_after = int(os.environ.get("BENCH_CHAOS_CRASH_AFTER", "80"))
    wedge_after = int(os.environ.get("BENCH_CHAOS_WEDGE_AFTER", "40"))

    root = tempfile.mkdtemp(prefix="gk-chaos-fleet-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)

    client = build_driver(n_templates, n_resources)
    client.audit_capped(50)
    assert Snapshotter(client, snap_dir, interval_s=0.0).write_once()

    n_corpus = min(n_resources, 48)
    pods = make_pods(n_corpus, seed=31, violation_rate=0.4)
    reqs = []
    for i, p in enumerate(pods):
        reqs.append({
            "uid": f"chaos-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "chaos-bench"},
            "object": p,
        })
    oracle = build_oracle(n_templates, n_resources)
    oracle_verdicts = []
    for req in reqs:
        results = oracle.review(
            {k: req[k] for k in
             ("kind", "name", "namespace", "operation", "object")}
        ).results()
        oracle_verdicts.append((not results, sorted(r.msg for r in results)))

    base_env = {"JAX_PLATFORMS": "cpu"}
    # the seeded fault specs ride into each child via GK_CHAOS
    # (faults.install_from_spec); restarts come back CLEAN — the
    # supervisor respawns with its own env
    crash_env = dict(base_env, GK_CHAOS=json.dumps({
        "seed": 8, "rules": [{
            "point": "fleet.replica_crash", "mode": "error",
            "after": crash_after, "count": 1,
        }],
    }))
    wedge_env = dict(base_env, GK_CHAOS=json.dumps({
        "seed": 8, "rules": [{
            "point": "fleet.replica_wedge", "mode": "hang",
            "hang_s": 120.0, "after": wedge_after, "count": 1,
        }],
    }))

    events = []  # (t, replica_id, "eject"|"readmit")
    door_box = {}

    def on_change(rid, backend):
        d = door_box.get("door")
        events.append((time.monotonic(), rid,
                       "eject" if backend is None else "readmit"))
        if d is None:
            return
        if backend is None:
            d.suspend(rid)
        else:
            d.set_backend(rid, backend["host"], backend["port"])

    sup = ReplicaSupervisor(
        snapshot_dir=snap_dir, cache_dir=cache_dir, env=base_env,
        heartbeat_s=0.25, miss_threshold=2, backoff_base_s=0.1,
        on_backend_change=on_change,
    )
    door = None
    try:
        # chaos-armed initial spawns, adopted under supervision (the
        # supervisor's own restarts use the clean env)
        h_wedge = spawn_replica("r0", snap_dir, cache_dir, env=wedge_env)
        h_crash = spawn_replica("r1", snap_dir, cache_dir, env=crash_env)
        for h in (h_wedge, h_crash):
            assert h.ready.get("restore_outcome") == "restored", h.ready
            sup.adopt(h)
        sup.start_monitor()
        door = FrontDoor(
            [h_wedge.backend(), h_crash.backend()], probe_interval_s=0.1
        ).start()
        door_box["door"] = door
        log(f"chaos_fleet: r0(wedge@~{wedge_after} pings) "
            f"r1(crash@~{crash_after} pulses) streaming {duration_s}s")

        import http.client as _httpc

        def post(body):
            c = _httpc.HTTPConnection("127.0.0.1", door.port, timeout=30)
            try:
                c.request("POST", "/v1/admit", body=body,
                          headers={"Content-Type": "application/json"})
                r = c.getresponse()
                return r.status, r.read()
            finally:
                c.close()

        total = failed = divergences = 0
        t_start = time.monotonic()
        i = 0
        while time.monotonic() - t_start < duration_s:
            req = reqs[i % len(reqs)]
            body = json.dumps({"request": req}).encode()
            try:
                st, data = post(body)
            except Exception:
                st, data = 0, b""
            total += 1
            if st != 200:
                failed += 1
            else:
                out = json.loads(data)["response"]
                allowed = out["allowed"]
                msgs = sorted(
                    _re.sub(r"^\[denied by [^\]]+\] ", "", m)
                    for m in (out.get("status") or {}).get(
                        "message", "").split("\n") if m
                ) if not allowed else []
                o_allowed, o_msgs = oracle_verdicts[i % len(reqs)]
                if allowed != o_allowed or (
                    not allowed and msgs != o_msgs
                ):
                    divergences += 1
            i += 1
            time.sleep(0.002)  # pace: the stream must span both faults

        # both chaos victims must have been restarted warm by now
        recovery = {}
        for rid in ("r0", "r1"):
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                st = sup.status()[rid]
                if st["state"] == "running" and st["restarts"] >= 1:
                    break
                time.sleep(0.1)
            st = sup.status()[rid]
            ejects = [t for t, r, k in events if r == rid and k == "eject"]
            readmits = [t for t, r, k in events
                        if r == rid and k == "readmit" and t > (
                            ejects[0] if ejects else 0)]
            recovery[rid] = {
                "state": st["state"],
                "restarts": st["restarts"],
                "last_exit_rc": st["last_exit_rc"],
                "spawn_to_ready_s": st["last_restart_s"],
                "eject_to_readmit_s": round(
                    readmits[0] - ejects[0], 3
                ) if ejects and readmits else None,
            }
        new_handles = {h.replica_id: h for h in sup.handles()}
        restore_outcomes = {
            rid: h.ready.get("restore_outcome")
            for rid, h in new_handles.items()
        }

        # zero-failure rolling restart with drain stats (the upgrade path)
        rolled = sup.rolling_restart(drain_deadline_ms=500.0)
        roll_ok = all(r.get("ok") for r in rolled.values())

        stats = door.stats()
        log(f"chaos_fleet: {total} reqs, {failed} failed, "
            f"{divergences} divergences, recovery={recovery}, "
            f"door retries={stats['retries']}")

        mesh = _chaos_mesh_stall()
        log(f"chaos_fleet: mesh stall {mesh}")

        ok = (
            failed == 0 and divergences == 0
            and all(r["state"] == "running" and r["restarts"] >= 1
                    for r in recovery.values())
            and all((r["spawn_to_ready_s"] or 99) < 5.0
                    for r in recovery.values())
            and all(v == "restored" for v in restore_outcomes.values())
            and mesh.get("parity_during") and mesh.get("parity_after")
            and mesh.get("width_after") == 2
        )
        out = {
            "metric": (
                "chaos fleet: failed admissions with one replica crashed "
                "+ one wedged mid-load (2 supervised replicas)"
            ),
            "value": float(failed),
            "unit": "failed_admissions",
            "vs_baseline": 0,
            "chaos_ok": ok,
            "chaos_requests": total,
            "chaos_failed_admissions": failed,
            "chaos_verdict_divergences": divergences,
            "chaos_recovery": recovery,
            "chaos_restore_outcomes": restore_outcomes,
            "chaos_rolling_restart": {
                rid: {"ok": r.get("ok"),
                      "drain_ms": (r.get("drain") or {}).get("drain_ms"),
                      "drained": (r.get("drain") or {}).get("drained"),
                      "restart_s": r.get("restart_s")}
                for rid, r in rolled.items()
            },
            "chaos_rolling_ok": roll_ok,
            "chaos_frontdoor": stats,
            "chaos_mesh_stall": mesh,
            "chaos_config": {
                "templates": n_templates, "resources": n_resources,
                "duration_s": duration_s, "crash_after": crash_after,
                "wedge_after": wedge_after,
            },
        }
        record = {k: v for k, v in out.items()
                  if k not in ("metric", "value", "unit", "vs_baseline")}
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "CHAOS_r08.json")
        with open(path, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
        log(f"chaos_fleet recorded: {path}")
        return out
    finally:
        if door is not None:
            door.stop()
        sup.stop()
        shutil.rmtree(root, ignore_errors=True)


def bench_overload() -> dict:
    """Overload robustness (ISSUE 12, recorded as OVERLOAD_r12): 2
    replicas restore one sealed snapshot behind the overload-armed front
    door (per-backend inflight bound, 1s admission budget, retry
    budget); closed-loop client fleets drive 1x/2x/5x/10x the
    saturation concurrency through the door.  The fleets HONOR the shed
    contract — a 429's Retry-After paces them, capped at
    BENCH_OVERLOAD_BACKOFF_S so they stay far more aggressive than the
    door asks — because that is what the header is for; an extra
    no-backoff phase records the abusive floor (a tight shed/retry loop
    that on this one-core box steals the door's own CPU), where sheds
    must STILL answer fast with exact verdicts.  Recorded per level:
    offered and GOODPUT rates (no congestive collapse: goodput at 10x
    must hold >= 70% of the 1x peak), accepted-request p50/p99 (p99
    within the admission budget), shed counts by layer, and shed-answer
    latency (door-side, from the wire traces: the single-digit-ms
    criterion).  Verdict parity vs a fresh interpreter oracle is
    checked on EVERY accepted response at every level — shedding drops
    requests, never accuracy.  A seeded `fleet.overload_storm` chaos
    phase then proves zero divergence while shedding under injected
    slow-replica latency, and the brownout ladder is observed stepping
    UP under the storm and RECOVERING to level 0 with hysteresis."""
    import http.client as _httpc
    import shutil
    import tempfile
    import threading

    from gatekeeper_tpu import faults as _faults
    from gatekeeper_tpu.faults import FaultRule
    from gatekeeper_tpu.fleet import FrontDoor, spawn_fleet
    from gatekeeper_tpu.obs import brownout as obsbrownout
    from gatekeeper_tpu.obs import trace as obstrace
    from gatekeeper_tpu.snapshot import Snapshotter
    from gatekeeper_tpu.util.overloadcheck import (
        classify_response,
        verdict_matches,
    )
    from gatekeeper_tpu.util.synthetic import (
        build_driver,
        build_oracle,
        make_pods,
    )

    n_templates = int(os.environ.get("BENCH_OVERLOAD_TEMPLATES", "2"))
    n_resources = int(os.environ.get("BENCH_OVERLOAD_RESOURCES", "256"))
    n_corpus = int(os.environ.get("BENCH_OVERLOAD_CORPUS", "64"))
    phase_s = float(os.environ.get("BENCH_OVERLOAD_PHASE_S", "6"))
    levels = [int(x) for x in os.environ.get(
        "BENCH_OVERLOAD_LEVELS", "1,2,5,10").split(",")]
    base_clients = int(os.environ.get("BENCH_OVERLOAD_BASE_CLIENTS", "2"))
    max_inflight = int(os.environ.get("BENCH_OVERLOAD_INFLIGHT", "1"))
    budget_s = float(os.environ.get("BENCH_OVERLOAD_BUDGET_S", "1.0"))
    max_pending = int(os.environ.get("BENCH_OVERLOAD_MAX_PENDING", "64"))

    root = tempfile.mkdtemp(prefix="gk-overload-bench-")
    snap_dir = os.path.join(root, "snap")
    cache_dir = os.path.join(root, "cache")
    os.makedirs(snap_dir)
    os.makedirs(cache_dir)

    client = build_driver(n_templates, n_resources)
    client.audit_capped(50)
    assert Snapshotter(client, snap_dir, interval_s=0.0).write_once()

    pods = make_pods(n_corpus, seed=61, violation_rate=0.4)
    reqs = []
    for i, p in enumerate(pods):
        reqs.append({
            "uid": f"ov-{i}",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "userInfo": {"username": "overload-bench"},
            "object": p,
        })
    bodies = [json.dumps({"request": r}).encode() for r in reqs]
    oracle = build_oracle(n_templates, n_resources)
    oracle_verdicts = []
    for req in reqs:
        results = oracle.review(
            {k: req[k] for k in
             ("kind", "name", "namespace", "operation", "object")}
        ).results()
        oracle_verdicts.append(
            (not results, sorted(r.msg for r in results)))

    def verdict_ok(out: dict, idx: int) -> bool:
        # shared normalization with tools/check_overload.py: the tier-1
        # gate and this artifact must judge the same bytes the same way
        return verdict_matches(out, oracle_verdicts[idx])

    handles = spawn_fleet(
        2, snapshot_dir=snap_dir, cache_dir=cache_dir,
        env={"JAX_PLATFORMS": "cpu"},
        extra_flags=["--webhook-max-pending", str(max_pending)],
    )
    door = None
    ctl = obsbrownout.get_controller()
    try:
        for h in handles:
            assert h.ready.get("restore_outcome") == "restored", h.ready
        door = FrontDoor(
            [h.backend() for h in handles], probe_interval_s=0.1,
            max_inflight=max_inflight, admission_budget_s=budget_s,
        ).start()
        # a deep trace ring: door-side shed latency is read from the
        # wire traces (outcome attr), and the storm produces thousands
        obstrace.configure(buffer_size=4096, sample_rate=1.0)
        # the bench parent IS the door process: its global brownout
        # controller sees every door shed via record_shed, so the
        # ladder is driven by REAL signals (no actions wired — the
        # parent has no audit/profiler to degrade; the ladder itself
        # is the observable)
        ctl.reset()
        ctl.start()
        level_series: list = []  # (wall_s, level) across the whole run
        series_stop = threading.Event()
        t_bench0 = time.monotonic()

        def poll_levels():
            while not series_stop.wait(0.1):
                level_series.append(
                    (round(time.monotonic() - t_bench0, 1), ctl.level))

        poller = threading.Thread(target=poll_levels, daemon=True)
        poller.start()

        # warm both replicas through the door (jit, memos, connections)
        for i in range(16):
            st, _hd, _b = _door_post(door.port, bodies[i % len(bodies)])
            assert st in (200, 429), st

        # shed-backoff the client fleet applies on a 429: the shed
        # contract's Retry-After is 1s — these clients are IMPATIENT
        # (they cap the advertised wait at this fraction) but not
        # abusive; a separate no-backoff phase records the abusive
        # floor.  On this one-core box the load generators share the
        # GIL with the door, so a no-backoff fleet's shed loop consumes
        # the very CPU goodput needs — precisely the storm Retry-After
        # exists to prevent
        backoff_s = float(os.environ.get("BENCH_OVERLOAD_BACKOFF_S",
                                         "0.25"))

        def run_phase(n_clients: int, duration: float,
                      backoff=None):
            # per-phase trace isolation: door-side latency (sheds AND
            # accepted) is read from the wire ring afterwards, so it
            # must hold only THIS phase's requests
            obstrace.get_tracer().clear()
            backoff = backoff_s if backoff is None else backoff
            results: list = []
            lock = threading.Lock()
            stop_at = time.monotonic() + duration

            def slam(tid: int):
                # one persistent keep-alive connection per client: a
                # real apiserver reuses connections, and a fresh
                # connection per request would bill a handler-thread
                # spawn to every shed
                conn = None
                i = tid
                while time.monotonic() < stop_at:
                    idx = i % len(reqs)
                    i += n_clients
                    t0 = time.perf_counter()
                    try:
                        if conn is None:
                            conn = _httpc.HTTPConnection(
                                "127.0.0.1", door.port, timeout=30)
                        conn.request(
                            "POST", "/v1/admit", body=bodies[idx],
                            headers={
                                "Content-Type": "application/json"})
                        r = conn.getresponse()
                        data = r.read()
                        st = r.status
                        retry_after = r.getheader("Retry-After")
                    except Exception:
                        st, data, retry_after = 0, b"", None
                        try:
                            if conn is not None:
                                conn.close()
                        except OSError:
                            pass
                        conn = None
                    dur = time.perf_counter() - t0
                    with lock:
                        results.append((st, dur, data, idx))
                    if st == 429 and backoff > 0:
                        try:
                            wait = min(float(retry_after or 1.0),
                                       backoff)
                        except ValueError:
                            wait = backoff
                        time.sleep(wait)
                if conn is not None:
                    try:
                        conn.close()
                    except OSError:
                        pass

            ts = [threading.Thread(target=slam, args=(t,))
                  for t in range(n_clients)]
            t0 = time.monotonic()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=duration + 120)
                if t.is_alive():
                    raise RuntimeError("overload client wedged — a "
                                       "refusal path is hanging")
            wall = time.monotonic() - t0
            return results, wall

        # shared taxonomy with tools/check_overload.py (one copy: the
        # tier-1 gate and this artifact cannot drift apart)
        classify = classify_response

        def pct(xs, q):
            if not xs:
                return None
            xs = sorted(xs)
            return round(xs[min(int(q * len(xs)), len(xs) - 1)], 3)

        def wire_latencies():
            """{outcome: [duration_ms]} over this phase's wire traces —
            the DOOR's answer time (accept..write_back), free of the
            co-located load generators' client-thread scheduling noise
            (a real apiserver does not share the door's GIL)."""
            out: dict = {}
            for t in obstrace.get_tracer().traces():
                if t.get("root") != "wire":
                    continue
                rootspan = next(
                    (s for s in t.get("spans", ())
                     if s.get("name") == "wire"), None)
                if rootspan is None:
                    continue
                oc = (rootspan.get("attrs") or {}).get("outcome")
                if oc:
                    out.setdefault(oc, []).append(t["duration_ms"])
            return out

        phase_out = {}
        divergence_box = [0]

        def measure(label: str, n_clients: int, backoff=None,
                    duration=None):
            results, wall = run_phase(
                n_clients, phase_s if duration is None else duration,
                backoff=backoff,
            )
            counts: dict = {}
            accepted_client_ms, divergences = [], 0
            door_shed = replica_shed = expired = errors = 0
            for st, dur, data, idx in results:
                kind, out = classify(st, data)
                counts[kind] = counts.get(kind, 0) + 1
                if kind == "accepted":
                    accepted_client_ms.append(dur * 1e3)
                    if not verdict_ok(out, idx):
                        divergences += 1
                elif kind == "shed":
                    if st == 429:
                        door_shed += 1
                    else:
                        replica_shed += 1
                elif kind == "expired":
                    expired += 1
                else:
                    errors += 1
            wire = wire_latencies()
            shed_wire_ms = wire.get("shed", [])
            ok_wire_ms = wire.get("ok", [])
            divergence_box[0] += divergences
            accepted = counts.get("accepted", 0)
            phase_out[label] = {
                "clients": n_clients,
                "offered_rps": round(len(results) / wall, 1),
                "goodput_rps": round(accepted / wall, 1),
                "accepted": accepted,
                "accepted_p50_ms": pct(ok_wire_ms, 0.50),
                "accepted_p99_ms": pct(ok_wire_ms, 0.99),
                "accepted_client_p50_ms": pct(accepted_client_ms, 0.50),
                "accepted_client_p99_ms": pct(accepted_client_ms, 0.99),
                "door_sheds": door_shed,
                "replica_sheds": replica_shed,
                "expired": expired,
                "errors": errors,
                "verdict_divergences": divergences,
                "shed_answer_p50_ms": pct(shed_wire_ms, 0.50),
                "shed_answer_p99_ms": pct(shed_wire_ms, 0.99),
                "shed_answer_n": len(shed_wire_ms),
                "brownout_level_end": ctl.level,
            }
            log(f"overload {label} ({n_clients} clients): "
                f"{phase_out[label]}")

        for mult in levels:
            measure(f"{mult}x", base_clients * mult)
        # the abusive floor: the same 10x fleet IGNORING Retry-After —
        # a tight shed/retry loop that (on this one-core box) steals
        # the door's own CPU.  Recorded for honesty: sheds must stay
        # fast and verdicts exact even under the storm the contract
        # exists to prevent; the goodput criterion applies to the
        # protocol-conformant fleet above
        measure(f"{levels[-1]}x_nobackoff",
                base_clients * levels[-1], backoff=0.0, duration=4.0)
        divergences_total = divergence_box[0]

        # ---- seeded chaos storm: shedding must never corrupt verdicts ----
        plane = _faults.install(seed=12)
        plane.add("fleet.overload_storm",
                  FaultRule(mode="latency", latency_s=0.25))
        storm_results, storm_wall = run_phase(base_clients * 6, 4.0)
        _faults.uninstall()
        storm_counts: dict = {}
        storm_divergences = 0
        for st, dur, data, idx in storm_results:
            kind, out = classify(st, data)
            storm_counts[kind] = storm_counts.get(kind, 0) + 1
            if kind == "accepted" and not verdict_ok(out, idx):
                storm_divergences += 1
        storm_level_peak = max(
            (lv for _t, lv in level_series), default=0)
        log(f"overload chaos storm: {storm_counts}, divergences="
            f"{storm_divergences}, ladder peak={storm_level_peak}")

        # ---- recovery: the ladder must step back DOWN with hysteresis ----
        recovered = False
        recovery_deadline = time.monotonic() + 60.0
        while time.monotonic() < recovery_deadline:
            if ctl.level == 0:
                recovered = True
                break
            time.sleep(0.25)
        recovery_s = round(time.monotonic() - t_bench0, 1)
        series_stop.set()
        poller.join(timeout=5)

        goodput_1x = phase_out[f"{levels[0]}x"]["goodput_rps"]
        goodput_peak = max(p["goodput_rps"] for p in phase_out.values())
        top = f"{levels[-1]}x"
        goodput_top = phase_out[top]["goodput_rps"]
        ratio = round(goodput_top / max(goodput_1x, 1e-9), 3)
        shed_p99 = phase_out[top]["shed_answer_p99_ms"]
        accepted_p99 = phase_out[top]["accepted_p99_ms"]
        ok = (
            ratio >= 0.7
            and divergences_total == 0
            and storm_divergences == 0
            and storm_counts.get("shed", 0) > 0
            and (shed_p99 is not None and shed_p99 < 10.0)
            and (accepted_p99 is not None
                 and accepted_p99 <= budget_s * 1e3)
            and storm_level_peak >= 1
            and recovered
        )
        out = {
            "metric": (
                f"goodput at {top} offered load as a fraction of the "
                f"1x saturation goodput (2 replicas, overload-armed "
                f"door)"
            ),
            "value": ratio,
            "unit": "goodput_ratio",
            "vs_baseline": 0,
            "overload_ok": ok,
            "overload_goodput_ratio_10x": ratio,
            "overload_goodput_1x_rps": goodput_1x,
            "overload_goodput_peak_rps": goodput_peak,
            "overload_phases": phase_out,
            "overload_shed_answer_p99_ms": shed_p99,
            "overload_accepted_p99_ms": accepted_p99,
            "overload_budget_ms": budget_s * 1e3,
            "overload_verdict_divergences": divergences_total,
            "overload_chaos": {
                "storm_counts": storm_counts,
                "storm_divergences": storm_divergences,
                "ladder_peak_level": storm_level_peak,
                "ladder_recovered": recovered,
                "recovered_by_s": recovery_s,
            },
            "overload_brownout_series": level_series[-400:],
            "overload_frontdoor": door.stats(),
            "overload_config": {
                "templates": n_templates, "resources": n_resources,
                "phase_s": phase_s, "levels": levels,
                "base_clients": base_clients,
                "max_inflight": max_inflight,
                "budget_s": budget_s, "max_pending": max_pending,
            },
        }
        record = {k: v for k, v in out.items()
                  if k not in ("metric", "value", "unit", "vs_baseline")}
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "OVERLOAD_r12.json")
        with open(path, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
        log(f"overload recorded: {path}")
        return out
    finally:
        ctl.stop()
        ctl.reset()
        if door is not None:
            door.stop()
        for h in handles:
            h.stop()
        shutil.rmtree(root, ignore_errors=True)


def _door_post(port: int, body: bytes, timeout: float = 60):
    import http.client as _httpc

    conn = _httpc.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/admit", body=body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _chaos_mesh_stall() -> dict:
    """Mesh-degradation leg of the chaos bench (subprocess on a virtual
    4-device CPU mesh, like mesh_curve): a seeded `mesh.dispatch_stall`
    hang wedges the sharded sweep's collective; the watchdog abandons
    it, the breaker serves interpreter-parity verdicts, the sweep
    re-shards 4 -> 2, and the rebased width-2 sweep stays byte-parity
    with the interpreter oracle."""
    import subprocess

    code = r"""
import json, sys, time
sys.path.insert(0, ".")
from gatekeeper_tpu import faults
from gatekeeper_tpu.faults import FaultRule
from gatekeeper_tpu.parallel.mesh import DISPATCH_LOCK
from gatekeeper_tpu.util.synthetic import (
    audit_result_sig as sig, build_driver, build_oracle,
)

N_T, N_R, CAP = 8, 512, 4096
oracle = build_oracle(N_T, N_R)
oracle_r, oracle_t, _ = oracle.driver.audit_capped(CAP)
want = (sig(oracle_r), oracle_t)

client = build_driver(N_T, N_R)
drv = client.driver
drv.mesh_watchdog_s = 0.5
drv.set_mesh(True, width=4)

plane = faults.install(seed=8)
plane.add("mesh.dispatch_stall",
          FaultRule(mode="hang", hang_s=30.0, count=1))
got_r, got_t, _ = drv.audit_capped(CAP)
parity_during = (sig(got_r), got_t) == want
breaker_state = drv.breaker.state
width_after = drv.mesh_layout()
stalls = DISPATCH_LOCK.revocations

plane.release_hangs()
time.sleep(0.5)          # the abandoned dispatch finishes alone
plane.clear("mesh.dispatch_stall")
drv.mesh_watchdog_s = 120.0   # the width-2 rebase compiles in-region
probe_ok = drv.breaker.probe_now()
got_r, got_t, _ = drv.audit_capped(CAP)
parity_after = (sig(got_r), got_t) == want
stats = dict(drv.last_sweep_stats)
faults.uninstall()
print(json.dumps({
    "parity_during": parity_during, "parity_after": parity_after,
    "breaker_during": breaker_state, "probe_recovered": probe_ok,
    "width_before": 4, "width_after": width_after,
    "gate_revocations": stalls,
    "rebase_shards": stats.get("shards"),
}))
"""
    from gatekeeper_tpu.parallel.mesh import virtual_mesh_env

    env = virtual_mesh_env(4)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"chaos mesh subprocess failed: {proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_obs_engine() -> dict:
    """ISSUE 13 proof config -> OBS_r13.json, three sections:

      1. engine-telemetry overhead: the route ledger + compile stats
         measured on the in-process fleet-shape review stream with
         PAIRED off/on arms (alternating order, arm medians — the
         OBS_r11 profiler estimator), acceptance <3%;
      2. route explainability: a calibrated shape sweep whose
         /debug/routez tier-win table must reproduce the live
         `_route_eval` choices (the BENCH_r05 curve_route frontier,
         re-measured on this box's calibration);
      3. a SEEDED breaker trip (fault plane on tpu.dispatch) proving the
         flight-recorder dump carries trip -> tier fallback -> recovery
         in causal order.
    """
    from gatekeeper_tpu import faults
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.obs import compilestats, flightrec
    from gatekeeper_tpu.obs.debug import get_router
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_OBS_TEMPLATES", "10"))
    n_stream = int(os.environ.get("BENCH_OBS_REVIEWS", "300000"))
    n_pairs = int(os.environ.get("BENCH_OBS_PAIRS", "5"))
    chunk = int(os.environ.get("BENCH_OBS_CHUNK", "256"))

    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    driver = c.driver
    pods = make_pods(4096, seed=13)
    reqs = [{
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": p["metadata"]["name"],
        "namespace": p["metadata"]["namespace"],
        "operation": "CREATE",
        "object": p,
    } for p in pods]

    def batch_of(start, n):
        return [reqs[(start + j) % len(reqs)] for j in range(n)]

    # warm every chunk shape, then calibrate so stream routing runs the
    # production (measured cost model) decision path
    driver.review_batch(batch_of(0, chunk))
    tail = n_stream % chunk
    if tail:
        driver.review_batch(batch_of(0, tail))
    cal = driver.calibrate_routing()
    cal_out = {k: round(v, 3) for k, v in cal.items()} if cal else None
    log(f"obs_engine: calibration {cal_out}")

    # ---- 1. paired telemetry overhead --------------------------------------
    ledger = driver.route_ledger
    stats = compilestats.get_stats()

    def stream_round() -> float:
        t0 = time.perf_counter()
        done = 0
        while done < n_stream:
            n = min(chunk, n_stream - done)
            driver.review_batch(batch_of(done, n))
            done += n
        return round(n_stream / (time.perf_counter() - t0), 1)

    def set_telemetry(on: bool):
        ledger.enabled = on
        stats.enabled = on

    rates_off, rates_on = [], []
    try:
        for i in range(n_pairs):
            # alternate arm order: monotonic co-tenant drift must not
            # systematically tax whichever arm runs second
            order = (False, True) if i % 2 == 0 else (True, False)
            for on in order:
                set_telemetry(on)
                (rates_on if on else rates_off).append(stream_round())
    finally:
        set_telemetry(True)
    # estimator: MEDIAN OF PAIR RATIOS — this box's co-tenancy swings
    # round rates ±7%, far above the plane's real cost (one ledger
    # record per 256-review chunk).  Within a back-to-back pair the
    # drift hits both arms almost equally (order alternated), and the
    # median over pairs rejects a burst landing inside any single pair;
    # the arm medians ride along in the artifact for cross-checking
    pair_ratios = sorted(on / off for on, off in zip(rates_on, rates_off))
    ratio = pair_ratios[len(pair_ratios) // 2]
    overhead_pct = round((1.0 - ratio) * 100.0, 2)
    med_off = sorted(rates_off)[len(rates_off) // 2]
    med_on = sorted(rates_on)[len(rates_on) // 2]
    log(f"obs_engine: telemetry overhead {overhead_pct}% "
        f"(pair ratios={[round(r, 4) for r in pair_ratios]}, "
        f"median off={med_off} on={med_on}, off={rates_off}, "
        f"on={rates_on})")

    # ---- 2. /debug/routez vs the live route frontier -----------------------
    ledger.clear()
    curve_ns = [int(x) for x in os.environ.get(
        "BENCH_CURVE", "5,10,50,100,200,1000,2000").split(",")]
    live_routes = {n: driver._route_eval(n, n_reviews=1) for n in curve_ns}
    batch_routes = {
        r: driver._route_eval(n_templates * r, n_reviews=r)
        for r in (1, 8, 64, 256, 1024, 4096)
    }
    code, _ctype, body = get_router().handle("/debug/routez", "limit=64")
    assert code == 200, f"/debug/routez answered {code}"
    routez = json.loads(body)
    wins_by_shape = {
        (row["per_review_cells"], row["n_reviews"]): row["wins"]
        for row in routez["tier_wins"]
    }
    matches = all(
        max(wins_by_shape.get((n, 1), {}).items(),
            key=lambda kv: kv[1], default=(None, 0))[0] == live_routes[n]
        for n in curve_ns
    )
    device_ns = [n for n in sorted(curve_ns) if live_routes[n] == "device"]
    frontier = {
        "device_first_cells": device_ns[0] if device_ns else None,
        "host_last_cells": max(
            (n for n in sorted(curve_ns) if live_routes[n] != "device"),
            default=None,
        ),
    }
    log(f"obs_engine: routez matches live routes: {matches}; "
        f"routes={live_routes}; batch_routes={batch_routes}")

    # ---- 3. seeded breaker trip -> flight-recorder dump --------------------
    import tempfile

    rec = flightrec.get_recorder()
    rec.clear()
    dump_dir = tempfile.mkdtemp(prefix="gk-flightrec-")
    rec.configure(dump_dir=dump_dir)
    c2 = Client(driver=TpuDriver(breaker_threshold=3,
                                 breaker_cooldown_s=0.5))
    for t, k in zip(templates[:5], constraints[:5]):
        c2.add_template(t)
        c2.add_constraint(k)
    d2 = c2.driver
    d2.DEVICE_MIN_CELLS = 0  # force the device tier (instance override)
    d2.review_batch(batch_of(0, 1))  # warm: device path healthy
    plane = faults.install(seed=13)
    from gatekeeper_tpu.faults import FaultRule

    plane.add(faults.TPU_DISPATCH,
              FaultRule(mode=faults.ERROR, probability=1.0, count=3))
    try:
        for i in range(3):  # three failed dispatches trip the breaker
            d2.review_batch(batch_of(100 + i, 1))
        assert d2.breaker.state == "open", d2.breaker.state
        # diverted while open: the ledger records breaker_open and the
        # tier flip lands in the flight recorder
        d2.review_batch(batch_of(200, 1))
        # recovery: the background probe's next dispatch succeeds (the
        # fault rule is spent) and closes the breaker
        t0 = time.perf_counter()
        while d2.breaker.state != "closed":
            if time.perf_counter() - t0 > 30.0:
                raise RuntimeError(
                    f"breaker did not recover (state={d2.breaker.state})")
            time.sleep(0.05)
    finally:
        faults.uninstall()
    d2.review_batch(batch_of(300, 1))  # back on the device tier
    code, _ctype, body = get_router().handle("/debug/flightrecz", "dump=1")
    assert code == 200, f"/debug/flightrecz answered {code}"
    fpayload = json.loads(body)
    events = fpayload["events"]

    def first_seq(pred):
        return next((e["seq"] for e in events if pred(e)), None)

    trip_seq = first_seq(
        lambda e: e["type"] == "breaker_transition"
        and e.get("new") == "open"
    )
    fallback_seq = first_seq(
        lambda e: e["type"] == "route_flip"
        and e.get("reason") in ("breaker_open", "device_failed")
        and (trip_seq is None or e["seq"] > trip_seq)
    )
    recovery_seq = first_seq(
        lambda e: e["type"] == "breaker_transition"
        and e.get("new") == "closed"
        and (fallback_seq is None or e["seq"] > fallback_seq)
    )
    causal = (
        trip_seq is not None and fallback_seq is not None
        and recovery_seq is not None
        and trip_seq < fallback_seq < recovery_seq
    )
    log(f"obs_engine: flight recording trip={trip_seq} "
        f"fallback={fallback_seq} recovery={recovery_seq} "
        f"causal={causal} ({len(events)} events, "
        f"dump={fpayload.get('dumped_to')})")

    # compile provenance for the corpus (populated by every aot_jit build
    # this config triggered; xlacache counters availability rides along)
    compilez = stats.snapshot(limit=0)
    out = {
        "metric": "engine-telemetry overhead on the in-process stream "
                  f"({n_templates} constraints, chunk {chunk})",
        "value": overhead_pct,
        "unit": "%",
        "vs_baseline": 0,
        "engine_telemetry_overhead_pct": overhead_pct,
        "telemetry_pair_ratios": [round(r, 4) for r in pair_ratios],
        "telemetry_arm_median_overhead_pct": round(
            (1.0 - med_on / med_off) * 100.0, 2),
        "telemetry_rates_off": rates_off,
        "telemetry_rates_on": rates_on,
        "routing_calibration": cal_out,
        "routez_live_routes": {str(k): v for k, v in live_routes.items()},
        "routez_batch_routes": {
            str(k): v for k, v in batch_routes.items()
        },
        "routez_tier_wins": routez["tier_wins"],
        "routez_matches_live": bool(matches),
        "route_frontier": frontier,
        "compile_provenance_mix": compilez["provenance_mix"],
        "compile_epoch_lag": compilez["compile_epoch_lag"],
        "xlacache_counters_available": compilez["xlacache"][
            "counters_available"],
        "flightrec": {
            "dump_path": fpayload.get("dumped_to"),
            "event_count": len(events),
            "trip_seq": trip_seq,
            "fallback_seq": fallback_seq,
            "recovery_seq": recovery_seq,
            "causal_order_ok": causal,
        },
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "OBS_r13.json"), "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
    assert overhead_pct < 3.0, (
        f"engine telemetry overhead {overhead_pct}% >= 3%")
    assert causal, "flight recording lost the trip->fallback->recovery order"
    return out


def bench_decisions() -> dict:
    """ISSUE 15 proof config -> DECLOG_r15.json, three sections:

      1. decision-log overhead: recording (ring + queue + writer, seal
         on, the production 1% head-sampling posture) measured on the
         in-process handler-level admission stream with PAIRED off/on
         arms — many short interleaved rounds in alternating order,
         overhead from the ratio of per-arm PER-REQUEST latency
         MEDIANS.  This box shows multi-second co-tenant slowdowns of
         10-40% that dwarf the effect size; round-level throughput
         ratios are at their mercy (a slow spell poisons a whole
         round), but a slow spell only poisons the minority of
         individual requests it covers, so the median over ~10k
         per-request samples per arm stays on the deterministic cost
         (direct percentile probes put it at +1.6-2.1% across
         p10-p50) — acceptance <3%.  The stream carries
         UNIQUE-content requests (distinct objects/uids, as production
         CREATE traffic does) so the baseline reflects real per-request
         evaluation, not the request-memo fast path;
      2. always-keep proof: under 1% head sampling, EVERY served
         denial, shed, deadline expiry and fail-closed error must be
         captured (allows sample down to ~1%);
      3. differential replay: tools/replay_decisions.py reports ZERO
         drift replaying the recorded corpus against the live engine,
         while a seeded GK_BUG_COMPAT divergence IS flagged.
    """
    import shutil
    import sys as _sys
    import tempfile

    from gatekeeper_tpu import deadline as gk_deadline
    from gatekeeper_tpu.obs import decisionlog as dlog

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import replay_decisions as rp

    import gc as _gc

    n_stream = int(os.environ.get("BENCH_DECLOG_REQS", "600"))
    n_pairs = int(os.environ.get("BENCH_DECLOG_PAIRS", "20"))
    n_keep = int(os.environ.get("BENCH_DECLOG_KEEP_REQS", "3000"))

    os.environ.pop("GK_BUG_COMPAT", None)
    handler = rp._selftest_handler()
    reqs = rp.selftest_requests(n=400, divergent=8)
    # the overhead stream uses a production-shaped violation rate (~5%,
    # the synthetic default) — the always-keep/replay sections keep the
    # deny-rich corpus above
    reqs_ov = rp.selftest_requests(n=400, divergent=0,
                                   violation_rate=0.05)

    # unique-content request stream: every request a distinct object +
    # uid (production CREATE traffic), so each handle pays real
    # evaluation instead of the content-keyed request-memo fast path
    def uniq(i):
        r = reqs_ov[i % len(reqs_ov)]
        obj = json.loads(json.dumps(r["object"]))
        obj["metadata"]["labels"]["req"] = f"r{i}"
        return {**r, "uid": f"u{i}", "object": obj}

    total = n_stream * n_pairs * 2 + 500
    uniq_reqs = [uniq(i) for i in range(total)]
    cursor = [0]

    def stream_round(n, sink=None):
        start = cursor[0]
        cursor[0] += n
        clock = time.perf_counter
        if sink is None:
            for i in range(start, start + n):
                handler.handle(uniq_reqs[i])
            return
        for i in range(start, start + n):
            t0 = clock()
            handler.handle(uniq_reqs[i])
            sink.append(clock() - t0)

    log_dir = tempfile.mkdtemp(prefix="gk-declog-bench-")
    dl = dlog.get_log()
    dl.clear()
    # the production posture: sealed segments, 1% head sampling
    dl.configure(dir=log_dir, seal=True, sample_rate=0.01)
    dl.start()

    # ---- 1. paired recording overhead --------------------------------------
    stream_round(500)  # warm compiles/caches off the clock
    lat_off, lat_on = [], []
    # production admission serving runs with the cyclic GC off the hot
    # path (WebhookServer.start freezes + disables it); measuring the
    # handler stream bare would attribute gen-2 collection spikes to
    # whichever arm they land in
    _gc.collect()
    _gc.freeze()
    _gc.disable()
    try:
        for i in range(n_pairs):
            # many SHORT interleaved rounds with alternating arm order
            # spread each arm's samples across the whole wall-clock
            # window; per-request latency MEDIANS then shrug off the
            # minority of samples a co-tenant slow spell poisons
            order = (False, True) if i % 2 == 0 else (True, False)
            for on in order:
                dl.record_enabled = on
                stream_round(n_stream, lat_on if on else lat_off)
    finally:
        _gc.enable()
        _gc.unfreeze()
    dl.record_enabled = True

    def pctl(samples, q):
        s = sorted(samples)
        return s[min(len(s) - 1, int(q * len(s)))]

    med_off = pctl(lat_off, 0.50)
    med_on = pctl(lat_on, 0.50)
    overhead_pct = round((med_on / med_off - 1.0) * 100.0, 2)
    lat_stats = {
        arm: {f"p{int(q * 100)}_us": round(pctl(samples, q) * 1e6, 2)
              for q in (0.10, 0.50, 0.90)}
        for arm, samples in (("off", lat_off), ("on", lat_on))
    }
    log(f"decisions: recording overhead {overhead_pct}% "
        f"(per-request latency medians, n={len(lat_off)}/arm, "
        f"stats={lat_stats})")

    # ---- 2. always-keep under 1% head sampling -----------------------------
    # stop (final drain + rotate) BEFORE clearing the dir, or leftover
    # phase-1 records flush into the recreated dir and pollute the count
    dl.stop()
    dl.clear()
    shutil.rmtree(log_dir, ignore_errors=True)
    dl.configure(dir=log_dir, seal=True, sample_rate=0.01)
    dl.start()
    served = {"allow": 0, "deny": 0, "shed": 0, "expired": 0, "error": 0}
    for i in range(n_keep):
        resp = handler.handle(reqs[i % len(reqs)])
        served["allow" if resp.allowed else "deny"] += 1

    class _Shed:
        def review(self, obj, tracing=False):
            raise gk_deadline.OverloadShed("bench shed")

    class _Boom:
        def review(self, obj, tracing=False):
            raise RuntimeError("bench fail-closed")

    class _Expired:
        # the batcher's refusal shape: expired budgets raise
        # DeadlineExceeded before any evaluation (webhook/server.py)
        def review(self, obj, tracing=False):
            raise gk_deadline.DeadlineExceeded("bench expired")

    from gatekeeper_tpu.webhook.policy import ValidationHandler

    for n, shim, key in ((40, _Shed(), "shed"), (40, _Boom(), "error"),
                         (40, _Expired(), "expired")):
        h = ValidationHandler(shim)
        for i in range(n):
            h.handle(reqs[i % len(reqs)])
            served[key] += 1
    dl.flush()
    records, seal_problems = rp.load_records(log_dir, require_seal=True)
    recorded = {}
    for r in records:
        if r.get("kind") == dlog.KIND_ADMISSION:
            recorded[r["class"]] = recorded.get(r["class"], 0) + 1
    always_kept = all(
        recorded.get(k, 0) == served[k]
        for k in ("deny", "shed", "expired", "error")
    )
    allow_frac = recorded.get("allow", 0) / max(served["allow"], 1)
    log(f"decisions: served={served} recorded={recorded} "
        f"always_kept={always_kept} allow_keep_frac={allow_frac:.4f} "
        f"seal_problems={len(seal_problems)} "
        f"segments={len(dlog.segment_paths(log_dir))}")

    # ---- 3. differential replay: zero drift + seeded divergence ------------
    baseline = rp.replay_records(handler, records)
    os.environ["GK_BUG_COMPAT"] = "1"
    try:
        compat = rp.replay_records(rp._selftest_handler(), records)
    finally:
        os.environ.pop("GK_BUG_COMPAT", None)
    log(f"decisions: replay baseline {baseline['replayed']} replayed / "
        f"{baseline['drift_count']} drift; GK_BUG_COMPAT "
        f"{compat['drift_count']} drift")
    dl.stop()
    dl.clear()
    # dir="" detaches the archive dir: later configs must not keep
    # archiving into this bench's temp dir
    dl.configure(dir="", sample_rate=1.0, seal=False)
    shutil.rmtree(log_dir, ignore_errors=True)

    out = {
        "metric": "decision-log recording overhead on the in-process "
                  "handler stream (sealed segments, ring + queue + "
                  "writer)",
        "value": overhead_pct,
        "unit": "%",
        "vs_baseline": 0,
        "decision_log_overhead_pct": overhead_pct,
        "decision_latency_stats": lat_stats,
        "decision_latency_samples_per_arm": len(lat_off),
        "sample_rate": 0.01,
        "served": served,
        "recorded_classes": recorded,
        "always_keep_complete": bool(always_kept),
        "allow_keep_fraction": round(allow_frac, 4),
        "seal_problems": len(seal_problems),
        "replay": {
            "replayed": baseline["replayed"],
            "drift": baseline["drift_count"],
            "skipped_transient": baseline["skipped_transient"],
            "bug_compat_drift": compat["drift_count"],
            "bug_compat_example": (compat["drift"][0]
                                   if compat["drift"] else None),
        },
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "DECLOG_r15.json"), "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)
    assert overhead_pct < 3.0, (
        f"decision-log overhead {overhead_pct}% >= 3%")
    assert always_kept, (
        f"always-keep incomplete: served={served} recorded={recorded}")
    assert not seal_problems, seal_problems
    assert baseline["drift_count"] == 0, baseline["drift"]
    assert compat["drift_count"] > 0, (
        "seeded GK_BUG_COMPAT divergence was not flagged")
    return out


CONFIGS = {
    "synthetic": bench_synthetic,
    "latency": bench_latency,
    "psp": bench_psp,
    "agilebank": bench_agilebank,
    "batch1m": bench_batch1m,
    "ingest": bench_ingest,
    "render": bench_render,
    "slo": bench_slo,
    "curve": bench_curve,
    "restart": bench_restart,
    "warm_resume": bench_warm_resume,
    "mesh": bench_mesh,
    "mesh_curve": bench_mesh_curve,
    "multihost": bench_multihost,
    "referential": bench_referential,
    "fleet": bench_fleet,
    "edge_obs": bench_edge_obs,
    "chaos_fleet": bench_chaos_fleet,
    "overload": bench_overload,
    "obs_engine": bench_obs_engine,
    "decisions": bench_decisions,
}

# secondary configs folded into the default run, with the extra-key name
# their headline value lands under
_FOLDED = [
    ("latency", "admission_p99_ms"),
    ("psp", "psp_audit_s"),
    ("agilebank", "agilebank_audit_s"),
    # ingest runs BEFORE the 1M-review streaming config (minimal reorder):
    # the storm's unique-content p99 is numpy-allocation-sensitive and
    # measurably degrades on the bloated post-streaming heap
    ("ingest", "ingest_p50_ms"),
    ("render", "render_violating_unique_p50_ms"),
    ("batch1m", "streamed_reviews_per_s"),
    ("curve", "curve_p50_ms"),
    ("restart", "warm_restart_ready_s"),
    ("warm_resume", "warm_resume_speedup"),
    ("mesh", "mesh_scaling_x8"),
    ("mesh_curve", "mesh_curve_parity"),
    ("referential", "referential_parity"),
    ("multihost", "multihost_sweep_s"),
    ("fleet", "fleet_reviews_per_s"),
    ("chaos_fleet", "chaos_failed_admissions"),
    ("overload", "overload_goodput_ratio_10x"),
    ("obs_engine", "engine_telemetry_overhead_pct"),
    ("decisions", "decision_log_overhead_pct"),
]


def main():
    config = os.environ.get("BENCH_CONFIG", "all")
    import jax

    log(f"devices: {jax.devices()}")
    # persistent XLA compile cache (restart-recovery path, SURVEY §5.4):
    # cold_sweep_s reflects a warm cache when prior runs populated it —
    # the entry count below makes that auditable in the artifact's stderr
    cache_dir = os.environ.get(
        "GK_XLA_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla-cache"),
    )
    if cache_dir:
        from gatekeeper_tpu.ops.aotcache import enable as enable_aot_cache
        from gatekeeper_tpu.ops.xlacache import enable as enable_xla_cache

        enable_aot_cache(os.path.join(cache_dir, "aot"))
        if enable_xla_cache(cache_dir):
            try:
                n = len(os.listdir(cache_dir))
            except OSError:
                n = 0
            log(f"xla cache: {cache_dir} ({n} entries pre-run)")
    if config != "all":
        print(json.dumps(CONFIGS[config]()))
        return

    out = bench_synthetic()
    for name, key in _FOLDED:
        t0 = time.time()
        try:
            sub = CONFIGS[name]()
        except Exception as e:
            log(f"[{name}] FAILED after {time.time()-t0:.0f}s: {e!r}")
            out[key] = None
            continue
        log(f"[{name}] done in {time.time()-t0:.0f}s")
        if name == "curve":
            out[key] = sub["curve_p50_ms"]
            out["curve_device_p50_ms"] = sub.get("curve_device_p50_ms")
            out["curve_interp_p50_ms"] = sub.get("curve_interp_p50_ms")
            out["curve_np_p50_ms"] = sub.get("curve_np_p50_ms")
            out["curve_route"] = sub.get("curve_route")
            out["curve_route_accuracy"] = sub.get("curve_route_accuracy")
            out["routing_calibration"] = sub.get("routing_calibration")
        else:
            out[key] = sub["value"]
        if name == "latency":
            out["admission_stage_p50_ms"] = sub.get("stage_p50_ms")
            out["admission_p50_ms"] = sub.get("p50_ms")
            out["admission_p99_runs_ms"] = sub.get("p99_runs_ms")
            out["admission_p99_max_ms"] = sub.get("p99_max_ms")
            out["admission_server_p99_ms"] = sub.get("server_p99_ms")
            out["admission_server_p50_ms"] = sub.get("server_p50_ms")
            out["admission_server_p99_max_ms"] = sub.get("server_p99_max_ms")
        if name == "mesh":
            out["mesh_device_scaling"] = sub.get("device_scaling_ms")
        if name == "mesh_curve":
            out["mesh_curve"] = sub.get("curve")
            out["mesh_curve_rows_per_shard_linear"] = sub.get(
                "rows_per_shard_linear")
        if name == "restart":
            out["warm_restart_template_ingest_s"] = sub.get(
                "template_ingest_s")
            out["warm_restart_data_replay_s"] = sub.get("data_replay_s")
            out["warm_restart_first_sweep_s"] = sub.get("first_sweep_s")
            out["restart_populate_ready_s"] = sub.get("populate_ready_s")
        if name == "warm_resume":
            for k in (
                "warm_resume_first_sweep_ms", "warm_resume_ready_s",
                "warm_resume_restore_s", "warm_resume_repacked_rows",
                "warm_resume_resync", "warm_resume_outcome",
                "warm_resume_violations_match", "cold_ready_s",
                "snapshot_bytes",
            ):
                out[k] = sub.get(k)
        if name == "ingest":
            out["ingest_p99_ms"] = sub.get("p99_ms")
            out["ingest_unique_p50_ms"] = sub.get("unique_p50_ms")
            out["ingest_unique_p99_ms"] = sub.get("unique_p99_ms")
            out["ingest_violating_unique_p50_ms"] = sub.get(
                "violating_unique_p50_ms")
            out["ingest_violating_unique_p99_ms"] = sub.get(
                "violating_unique_p99_ms")
            out["ingest_queue_wait_p50_ms"] = sub.get("queue_wait_p50_ms")
        if name == "render":
            for k in (
                "render_cells_per_s", "render_plan_fraction",
                "render_cells_static", "render_cells_slots",
                "render_cells_interp",
            ):
                out[k] = sub.get(k)
        if name == "fleet":
            ow = sub.get("obs_wire") or {}
            out["obs_wire_stage_share"] = ow.get("stage_share_of_p50")
            out["obs_wire_p50_ms"] = ow.get("wire_p50_ms")
            out["obs_profiler_overhead_pct"] = ow.get(
                "profiler_overhead_pct")
            out["edge_door_capacity_rps"] = sub.get(
                "edge_door_capacity_rps")
            out["edge_e2e_pipelined_rps"] = sub.get(
                "edge_e2e_pipelined_rps")
            out["edge_connect_per_request_rps"] = sub.get(
                "edge_connect_per_request_rps")
        if name == "multihost":
            out["multihost"] = {
                k: sub.get(k) for k in
                ("parity", "sweep_s", "dcn_bytes_per_sweep")
            }
        if name == "obs_engine":
            out["route_frontier"] = sub.get("route_frontier")
            out["routez_matches_live"] = sub.get("routez_matches_live")
            out["flightrec_causal_order_ok"] = (
                sub.get("flightrec") or {}
            ).get("causal_order_ok")
        if name == "decisions":
            out["decision_always_keep_complete"] = sub.get(
                "always_keep_complete")
            out["decision_replay_drift"] = (
                sub.get("replay") or {}
            ).get("drift")
            out["decision_bug_compat_drift"] = (
                sub.get("replay") or {}
            ).get("bug_compat_drift")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
