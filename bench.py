#!/usr/bin/env python
"""Headline benchmark: END-TO-END audit sweep on TPU, plus every other
BASELINE.md target config folded into the same artifact.

The default run (BENCH_CONFIG unset or "all") measures:
  - synthetic 500x100k steady-state capped audit sweep (the headline,
    BASELINE north star <1s on one v5e chip) with a pack/device/fetch/render
    breakdown and a bandwidth-roofline utilization estimate
  - admission p99 latency on demo/basic (north star <=2ms)
  - PSP library x 1k Pods audit (the reference benchmark's own fixtures)
  - agilebank full policy set x ~10k mixed resources audit
  - 1M-review streamed batch throughput (the "mesh" config shape)
  - template-ingest storm p50 (async compile, interp-served mid-storm)
  - constraint-count scaling curve N in {5..2000} (the reference's
    BenchmarkValidationHandler sweep, policy_benchmark_test.go:269)
  - multi-chip scaling of the device sweep on a virtual 8-device CPU mesh
    (subprocess; the real env exposes one chip)

and prints ONE JSON line: the headline metric/value/unit/vs_baseline plus
the secondary configs as extra keys.  Set BENCH_CONFIG to
{synthetic, latency, psp, agilebank, batch1m, ingest, curve, mesh} to run one
config alone (it then prints its own single JSON line).

Baseline note (see BASELINE.md): the reference is Go; no Go toolchain exists
in this image and installs are forbidden, so the reference harness cannot
run here.  vs_baseline is computed against this repo's Python interpreter
oracle measured on a slice of the same workload, DERATED by 50x as a
conservative stand-in for OPA's Go topdown (documented in BASELINE.md;
the raw interp rate is logged to stderr so the derate is auditable).

All diagnostics go to stderr.  Override sizes with BENCH_TEMPLATES /
BENCH_RESOURCES / BENCH_BASELINE_SLICE / BENCH_COPIES / BENCH_REVIEWS /
BENCH_INGEST_TEMPLATES / BENCH_CURVE.
"""

from __future__ import annotations

import json
import os
import sys
import time

GO_TOPDOWN_DERATE = 50.0  # conservative Go-vs-Python-interp speed factor

# v5e lite HBM bandwidth for the roofline estimate (public spec: 819 GB/s)
V5E_HBM_GBPS = 819.0


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def load_yaml_dir(pattern):
    import glob

    import yaml

    out = []
    for f in sorted(glob.glob(pattern)):
        with open(f) as fh:
            docs = [d for d in yaml.safe_load_all(fh) if d]
        out.extend(docs)
    return out


def bench_agilebank() -> dict:
    """BASELINE config 'agilebank': full demo policy set x N mixed
    resources, from-cache audit sweep (end-to-end incl. render)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_COPIES", "1000"))
    base = "/root/reference/demo/agilebank"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    resources = load_yaml_dir(f"{base}/good_resources/*.yaml") + load_yaml_dir(
        f"{base}/bad_resources/*.yaml"
    )
    import copy as _copy

    total = 0
    for i in range(n_copies):
        for r in resources:
            r2 = _copy.deepcopy(r)
            r2["metadata"]["name"] = f"{r['metadata'].get('name', 'x')}-{i}"
            c.add_data(r2)
            total += 1
    log(f"agilebank: {n_cons} constraints x {total} resources")
    c.audit_capped(20)  # compile + warm (full sweep)
    # warm the delta path too (its jit compiles on first use), then time an
    # honest steady-state sweep: one object mutated since the last sweep
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-warm-bump"}})
    c.audit_capped(20)
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-epoch-bump"}})
    t0 = time.time()
    res, _totals = c.audit_capped(20)
    dur = time.time() - t0
    log(f"agilebank end-to-end capped audit: {dur*1000:.0f}ms, "
        f"{len(res.results())} violations kept")
    return {
        "metric": f"agilebank end-to-end audit ({total} resources)",
        "value": round(dur, 3),
        "unit": "s",
        "vs_baseline": 0,
    }


def bench_psp() -> dict:
    """BASELINE config 'PSP library x 1k Pods': the reference benchmark's
    own fixtures (pkg/webhook/testdata/psp-all-violations: 5 PSP
    templates/constraints + violating pods, policy_benchmark_test.go:265-271)
    scaled to ~1k cached Pods, steady-state capped audit."""
    import copy as _copy

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_PSP_COPIES", "200"))
    base = "/root/reference/pkg/webhook/testdata/psp-all-violations"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/psp-templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/psp-constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    pods = load_yaml_dir(f"{base}/psp-pods/*.yaml")
    total = 0
    for i in range(n_copies):
        for p in pods:
            p2 = _copy.deepcopy(p)
            p2["metadata"]["name"] = f"{p['metadata'].get('name', 'p')}-{i}"
            p2["metadata"].setdefault("namespace", "default")
            c.add_data(p2)
            total += 1
    log(f"psp: {n_cons} constraints x {total} pods")
    c.audit_capped(20)  # compile + warm (full sweep)
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "psp-warm"}})
    c.audit_capped(20)  # warm the delta path
    p = _copy.deepcopy(pods[0])
    p["metadata"]["name"] = "psp-delta"
    p["metadata"].setdefault("namespace", "default")
    c.add_data(p)
    t0 = time.time()
    res, _totals = c.audit_capped(20)
    dur = time.time() - t0
    log(f"psp end-to-end capped audit: {dur*1000:.0f}ms, "
        f"{len(res.results())} violations kept")
    return {
        "metric": f"PSP library end-to-end audit ({n_cons} constraints x {total} pods)",
        "value": round(dur, 3),
        "unit": "s",
        "vs_baseline": 0,
    }


def bench_latency() -> dict:
    """BASELINE config 'demo/basic': single-review admission latency
    through the full webhook handler (p50/p99), targeting <=2ms p99."""
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.webhook import ValidationHandler

    base = "/root/reference/demo/basic"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
    handler = ValidationHandler(c, kube=InMemoryKube())
    req = {
        "uid": "u", "kind": {"group": "", "version": "v1",
                             "kind": "Namespace"},
        "name": "test", "namespace": "", "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "test", "labels": {}}},
    }
    for _ in range(20):  # warm: compile + caches
        handler.handle(req)
    # the production webhook server freezes long-lived state out of the
    # cyclic GC after warmup (webhook/server.py); do the same here — in the
    # combined run the synthetic sweep's 100k-object inventory is resident
    # in this process and a gen-2 GC pause otherwise lands in the p99
    import gc

    gc.collect()
    gc.freeze()
    times = []
    for _ in range(int(os.environ.get("BENCH_ITERS", "500"))):
        t0 = time.perf_counter()
        handler.handle(req)
        times.append(time.perf_counter() - t0)
    arr = np.array(times) * 1000
    p50, p99 = np.percentile(arr, 50), np.percentile(arr, 99)
    log(f"admission latency ms: p50={p50:.2f} p99={p99:.2f} max={arr.max():.2f}")
    srv_p50, srv_p99 = _server_level_latency(c, req)
    log(f"admission SERVER latency ms (TLS+batcher): p50={srv_p50:.2f} p99={srv_p99:.2f}")
    return {
        "metric": "admission handler p99 latency (demo/basic, deny path)",
        "value": round(float(p99), 3),
        "unit": "ms",
        "vs_baseline": 0,
        "p50_ms": round(float(p50), 3),
        "server_p99_ms": round(float(srv_p99), 3),
        "server_p50_ms": round(float(srv_p50), 3),
    }


def _server_level_latency(client, req):
    """p50/p99 through the PRODUCTION path: HTTPS webhook server +
    micro-batcher + handler — what the apiserver actually observes (the
    <=2ms north star applies here, not just to the bare handler)."""
    import json as _json
    import ssl

    import numpy as np

    from gatekeeper_tpu.certs import CertRotator
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.webhook import (
        MicroBatcher, ValidationHandler, WebhookServer,
    )

    kube = InMemoryKube()
    rot = CertRotator(kube)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        certfile, keyfile = rot.write_cert_files(td)
        mb = MicroBatcher(client)
        handler = ValidationHandler(mb, kube=kube)
        srv = WebhookServer(handler, port=0, certfile=certfile, keyfile=keyfile)
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            body = _json.dumps({"request": req}).encode()
            # persistent connection, as the apiserver's webhook client uses
            # (keep-alive; the server speaks HTTP/1.1)
            import http.client

            conn = http.client.HTTPSConnection(
                "127.0.0.1", srv.port, context=ctx, timeout=10
            )

            def once():
                conn.request("POST", "/v1/admit", body=body,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                return _json.loads(resp.read())

            for _ in range(30):
                once()
            import gc

            gc.collect()
            gc.freeze()  # keep warmup garbage out of the timed p99
            times = []
            for _ in range(int(os.environ.get("BENCH_SERVER_ITERS", "300"))):
                t0 = time.perf_counter()
                once()
                times.append(time.perf_counter() - t0)
            arr = np.array(times) * 1000
            return float(np.percentile(arr, 50)), float(np.percentile(arr, 99))
        finally:
            srv.stop()
            mb.stop()


def bench_batch1m() -> dict:
    """BASELINE config 'mesh': 1M admission-review batch streamed through
    review_batch in device-sized chunks (the streaming-webhook shape)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_TEMPLATES_1M", "10"))
    n_reviews = int(os.environ.get("BENCH_REVIEWS", "1000000"))
    chunk = int(os.environ.get("BENCH_CHUNK", "65536"))
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    pods = make_pods(min(n_reviews, 4096), seed=5)
    reqs = []
    for i in range(len(pods)):
        p = pods[i]
        reqs.append({
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": p["metadata"]["name"],
            "namespace": p["metadata"]["namespace"],
            "operation": "CREATE",
            "object": p,
        })
    driver = c.driver

    def batch_of(start, n):
        return [reqs[(start + j) % len(reqs)] for j in range(n)]

    # warm with the exact batch sizes the timed loop dispatches (full chunk
    # + the final partial chunk) so no XLA compile lands in the timed region
    driver.review_batch(batch_of(0, min(chunk, n_reviews)))
    tail = n_reviews % chunk
    if tail and n_reviews > chunk:
        driver.review_batch(batch_of(0, tail))
    t0 = time.time()
    done = 0
    while done < n_reviews:
        n = min(chunk, n_reviews - done)
        driver.review_batch(batch_of(done, n))
        done += n
    dur = time.time() - t0
    rate = n_reviews / dur
    log(f"batch1m: {n_reviews} reviews x {n_templates} constraints in "
        f"{dur:.1f}s ({rate:.0f} reviews/s)")
    return {
        "metric": f"streamed admission reviews/sec ({n_templates} constraints, chunk {chunk})",
        "value": round(rate, 1),
        "unit": "reviews/s",
        "vs_baseline": 0,
    }


def bench_ingest() -> dict:
    """Template-ingest storm with interleaved reviews under async compile.
    Reports ingest-to-first-eval p50 — the latency a review pays when it
    lands right after a template mutation (served from the interpreter
    while XLA compiles in the background)."""
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    n_templates = int(os.environ.get("BENCH_INGEST_TEMPLATES", "500"))
    templates, constraints = make_templates(n_templates)
    pod = make_pods(1, seed=3, violation_rate=1.0)[0]
    req = {
        "uid": "u",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": pod,
    }
    c = Client(driver=TpuDriver(async_compile=True))
    lat = []
    t0 = time.time()
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
        s = time.perf_counter()
        c.review(req)  # lands mid-storm; interp-served while compiling
        lat.append(time.perf_counter() - s)
    storm_s = time.time() - t0
    c.driver.wait_ready(timeout=600.0)
    ready_s = time.time() - t0
    arr = np.array(lat) * 1000
    p50 = float(np.percentile(arr, 50))
    log(f"ingest storm: {n_templates} templates in {storm_s:.1f}s "
        f"(device-ready at {ready_s:.1f}s); interleaved review latency "
        f"p50={p50:.1f}ms p99={np.percentile(arr, 99):.1f}ms")
    c.driver._compiler.stop()
    return {
        "metric": f"ingest-to-first-eval p50 ({n_templates}-template storm, async compile)",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": 0,
    }


def bench_curve() -> dict:
    """The reference's constraint-count scaling sweep
    (policy_benchmark_test.go:269: N in {5,10,50,100,200,1000,2000}):
    admission-handler latency per N through the production hybrid driver.
    Exposes where recompile/padding buckets would bite."""
    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates
    from gatekeeper_tpu.webhook import ValidationHandler

    counts = [int(x) for x in os.environ.get(
        "BENCH_CURVE", "5,10,50,100,200,1000,2000").split(",")]
    # two regimes per N: UNIQUE-content requests (true evaluation scaling —
    # the whole-request memo cannot hit) and REPEAT-content requests (what
    # replica/retry storms look like; served by the request memo)
    uniq_pods = make_pods(4096, seed=9, violation_rate=0.0)

    def req_for(pod):
        return {
            "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": pod["metadata"]["name"],
            "namespace": pod["metadata"]["namespace"],
            "operation": "CREATE", "userInfo": {"username": "bench"},
            "object": pod,
        }

    req = req_for(uniq_pods[0])
    curve = {}
    curve_memo = {}
    for n in counts:
        templates, constraints = make_templates(n)
        c = Client(driver=TpuDriver())
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        kube = InMemoryKube()
        # every review namespace must exist: a missing namespace sends the
        # request down the error path (LookupError + traceback logging),
        # and the curve would measure THAT instead of policy evaluation
        # (the reference benchmark's fakeNsGetter always succeeds,
        # policy_benchmark_test.go:52-66)
        for ns_name in {p["metadata"]["namespace"] for p in uniq_pods}:
            kube.create({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": ns_name}})
        handler = ValidationHandler(c, kube=kube)
        iters = max(10, min(100, 20000 // max(n, 1)))
        for _ in range(3):
            handler.handle(req)
        # unique-content: every iteration evaluates a different object
        ts = []
        for j in range(iters):
            r = req_for(uniq_pods[(j + 7) % len(uniq_pods)])
            t0 = time.perf_counter()
            handler.handle(r)
            ts.append(time.perf_counter() - t0)
        p50 = float(np.percentile(np.array(ts) * 1000, 50))
        curve[n] = round(p50, 3)
        # repeat-content: identical object, fresh uid (request-memo hits)
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            handler.handle(req)
            ts.append(time.perf_counter() - t0)
        m50 = float(np.percentile(np.array(ts) * 1000, 50))
        curve_memo[n] = round(m50, 3)
        log(f"curve N={n}: unique p50 {p50:.2f}ms, repeat(memo) p50 "
            f"{m50:.2f}ms ({iters} iters)")
    return {
        "metric": "admission handler p50 vs constraint count (unique-content)",
        "value": curve[max(counts)],
        "unit": "ms",
        "vs_baseline": 0,
        "curve_p50_ms": curve,
        "curve_repeat_p50_ms": curve_memo,
    }


def bench_mesh() -> dict:
    """Multi-chip scaling of the device sweep, measured on a virtual
    8-device CPU mesh in a subprocess (the bench env exposes ONE real
    chip).  Virtual devices share one host's cores, so this validates the
    sharded path's overhead/correctness at scale rather than wall-clock
    speedup; the scaling factor is reported as measured."""
    import subprocess

    n_t = int(os.environ.get("BENCH_MESH_TEMPLATES", "48"))
    n_r = int(os.environ.get("BENCH_MESH_ROWS", "8192"))
    code = f"N_T, N_R = {n_t}, {n_r}\n" + r"""
import time, json, sys
import jax, numpy as np
sys.path.insert(0, ".")
from gatekeeper_tpu.util.synthetic import build_driver

client = build_driver(N_T, N_R)
driver = client.driver
out = {}
for mesh_on in (False, True):
    driver.mesh_enabled = mesh_on
    driver._mesh_cache = None
    driver._audit_cache = None
    driver._audit_dev = None
    driver._cs_device_cache = None
    driver._delta_state = None  # both sides must run the FULL sharded sweep
    client.audit_capped(20)  # compile + warm
    # honest steady state: invalidate the sweep cache, keep executables
    ts = []
    for i in range(3):
        driver._audit_cache = None
        driver._delta_state = None
        t0 = time.perf_counter()
        client.audit_capped(20)
        ts.append(time.perf_counter() - t0)
    out["mesh" if mesh_on else "single"] = min(ts)
print(json.dumps(out))
"""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append("--xla_force_host_platform_device_count=8")
    env["XLA_FLAGS"] = " ".join(kept)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(f"mesh subprocess failed: {proc.stderr[-2000:]}")
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    factor = data["single"] / data["mesh"] if data["mesh"] else 0.0
    log(f"mesh scaling (virtual 8-dev CPU, 48x8192): single {data['single']*1000:.0f}ms "
        f"mesh {data['mesh']*1000:.0f}ms -> x{factor:.2f} "
        f"(virtual devices share one host: overhead check, not speedup)")
    return {
        "metric": "virtual 8-device mesh sweep vs single device",
        "value": round(factor, 3),
        "unit": "x",
        "vs_baseline": 0,
        "single_s": round(data["single"], 4),
        "mesh_s": round(data["mesh"], 4),
    }


def bench_synthetic() -> dict:
    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    n_resources = int(os.environ.get("BENCH_RESOURCES", "100000"))
    baseline_slice = int(os.environ.get("BENCH_BASELINE_SLICE", "20"))
    cap = int(os.environ.get("BENCH_CAP", "20"))

    from gatekeeper_tpu.util.synthetic import build_driver, make_pods, make_templates

    t0 = time.time()
    client = build_driver(n_templates, n_resources)
    driver = client.driver
    log(f"workload built: {n_templates} templates x {n_resources} resources "
        f"in {time.time()-t0:.1f}s")

    # long-lived-state GC hygiene, as a production audit pod would do
    # (webhook/server.py does the same at startup): without it, gen-2
    # collections scanning the 100k-object inventory inject 100ms+ pauses
    # into steady-state sweeps.  Unfrozen at the end of this config so the
    # other configs in a combined run keep normal GC behavior.
    import gc

    gc.collect()
    gc.freeze()

    # ---- cold sweep: review build + pack + XLA compile + device + render
    t0 = time.time()
    res, totals = client.audit_capped(cap)
    cold_s = time.time() - t0
    n_results = len(res.results())
    n_capped = sum(1 for v in totals.values() if v[1] == "resources")
    log(f"cold end-to-end capped audit: {cold_s:.1f}s "
        f"({n_results} violations kept, {n_capped}/{len(totals)} constraints at cap)")

    # ---- steady state: one object mutated since the last sweep.  The
    # production path is the INCREMENTAL delta sweep: only the changed
    # rows are re-evaluated on device and folded into the resident
    # per-constraint reduction (ops/deltasweep.py)
    times = []
    best_stats = {}
    for i in range(5):
        p = make_pods(1, seed=1000 + i, violation_rate=1.0)[0]
        p["metadata"]["name"] = f"bench-delta-{i}"
        client.add_data(p)
        t0 = time.time()
        res, totals = client.audit_capped(cap)
        times.append(time.time() - t0)
        s = driver.last_sweep_stats
        log(f"  sweep {i}: {times[-1]*1000:.1f}ms | pack {s.get('pack_ms', 0):.1f} "
            f"device {s.get('device_ms', 0):.1f} fetch {s.get('fetch_ms', 0):.1f} "
            f"render {s.get('render_ms', 0):.1f} ms | fetch {s.get('fetch_bytes', 0)/1e3:.1f}KB "
            f"delta_rows {s.get('delta_rows', 0):.0f} "
            f"fallback_rows {s.get('fallback_rows', 0):.0f} "
            f"rendered_cells {s.get('rendered_cells', 0):.0f}")
        if times[-1] == min(times):
            best_stats = dict(s)
    sweep_s = min(times)
    n_results = len(res.results())
    cells = len(driver._ordered_constraints()) * driver._audit_pack.n_rows
    delta_rows = int(best_stats.get("delta_rows", 0))
    log(f"steady-state end-to-end sweep (1 mutation): {sweep_s*1000:.1f}ms "
        f"({n_results} violations kept); covers {cells} constraint x resource "
        f"cells incrementally ({delta_rows} changed rows re-evaluated on device)")

    # ---- warm FULL resweep (no incremental state): the non-delta number,
    # and the honest basis for the device-utilization estimate
    p = make_pods(1, seed=2000, violation_rate=1.0)[0]
    p["metadata"]["name"] = "bench-full-resweep"
    client.add_data(p)
    driver._delta_state = None
    driver._audit_cache = None
    t0 = time.time()
    client.audit_capped(cap)
    full_s = time.time() - t0
    full_stats = dict(driver.last_sweep_stats)
    log(f"warm full resweep (incremental state dropped): {full_s*1000:.1f}ms "
        f"| device {full_stats.get('device_ms', 0):.1f}ms "
        f"({cells/full_s/1e6:.1f}M cell-evals/s end-to-end)")

    # ---- utilization estimate: HBM bandwidth roofline for the FULL fused
    # sweep (the computation that actually touches every input byte and the
    # [C, R] candidate mask); at v5e's 819 GB/s that bound is the floor.
    import jax
    import numpy as np

    try:
        in_bytes = sum(
            a.nbytes for a in jax.tree_util.tree_leaves(
                (driver._audit_pack.rp, driver._audit_pack.cols))
        )
        cs_bytes = 0
        if driver._cs_device_cache:
            cs_bytes = sum(
                a.nbytes for a in jax.tree_util.tree_leaves(
                    driver._cs_device_cache[1]))
        C = len(driver._ordered_constraints())
        mask_bytes = C * driver._audit_pack.capacity  # bool
        roofline_ms = (in_bytes + cs_bytes + 2 * mask_bytes) / (
            V5E_HBM_GBPS * 1e9) * 1e3
        device_ms = full_stats.get("device_ms", 0.0) or float("nan")
        util = roofline_ms / device_ms if device_ms else 0.0
        log(f"utilization: full-sweep device portion {device_ms:.1f}ms vs HBM "
            f"roofline {roofline_ms:.2f}ms (inputs {in_bytes/1e6:.0f}MB + "
            f"constraint side {cs_bytes/1e6:.0f}MB + mask 2x{mask_bytes/1e6:.0f}MB "
            f"@ {V5E_HBM_GBPS:.0f}GB/s) -> {util*100:.1f}% of bandwidth bound "
            f"(rest is relay/dispatch overhead of this env's network-tunneled "
            f"device; on-device compute measured at ~0.2ms)")
    except Exception as e:  # pragma: no cover
        log(f"utilization estimate failed: {e}")
        roofline_ms, util = 0.0, 0.0

    # ---- baseline: interpreter oracle on a slice, derated (BASELINE.md) --
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver

    templates, constraints = make_templates(n_templates)
    ci = Client(driver=InterpDriver())
    for t in templates:
        ci.add_template(t)
    for c in constraints:
        ci.add_constraint(c)
    for p in make_pods(baseline_slice, seed=1):
        ci.add_data(p)
    t0 = time.time()
    ci.audit()
    interp_s = time.time() - t0
    interp_cells = n_templates * baseline_slice
    interp_rate = interp_cells / interp_s
    est_ref_rate = interp_rate * GO_TOPDOWN_DERATE
    est_ref_sweep_s = cells / est_ref_rate
    log(f"interp oracle: {interp_rate:.0f} evals/s; estimated Go-topdown "
        f"reference ({GO_TOPDOWN_DERATE:.0f}x derate): {est_ref_rate:.0f} "
        f"evals/s -> {est_ref_sweep_s:.0f}s for this sweep")

    gc.unfreeze()  # the other configs in a combined run want normal GC

    return {
        "metric": (
            f"end-to-end audit sweep seconds ({n_templates} templates"
            f" x {n_resources} resources, cap {cap}, steady-state)"
        ),
        "value": round(sweep_s, 3),
        "unit": "s",
        "vs_baseline": round(est_ref_sweep_s / sweep_s, 1),
        "cold_sweep_s": round(cold_s, 3),
        "full_resweep_s": round(full_s, 3),
        # cells covered per second: the incremental sweep verifies the full
        # C x R grid per interval while re-evaluating only changed rows
        "coverage_cells_per_s": round(cells / sweep_s, 1),
        "delta_rows_per_sweep": delta_rows,
        "sweep_breakdown_ms": {
            k: round(best_stats.get(k, 0.0), 2)
            for k in ("pack_ms", "device_ms", "fetch_ms", "render_ms")
        },
        "sweep_fetch_bytes": best_stats.get("fetch_bytes", 0.0),
        "full_sweep_device_ms": round(full_stats.get("device_ms", 0.0), 2),
        "hbm_roofline_ms": round(roofline_ms, 2),
        "full_sweep_bandwidth_util": round(util, 4),
    }


CONFIGS = {
    "synthetic": bench_synthetic,
    "latency": bench_latency,
    "psp": bench_psp,
    "agilebank": bench_agilebank,
    "batch1m": bench_batch1m,
    "ingest": bench_ingest,
    "curve": bench_curve,
    "mesh": bench_mesh,
}

# secondary configs folded into the default run, with the extra-key name
# their headline value lands under
_FOLDED = [
    ("latency", "admission_p99_ms"),
    ("psp", "psp_audit_s"),
    ("agilebank", "agilebank_audit_s"),
    ("batch1m", "streamed_reviews_per_s"),
    ("ingest", "ingest_p50_ms"),
    ("curve", "curve_p50_ms"),
    ("mesh", "mesh_scaling_x8"),
]


def main():
    config = os.environ.get("BENCH_CONFIG", "all")
    import jax

    log(f"devices: {jax.devices()}")
    # persistent XLA compile cache (restart-recovery path, SURVEY §5.4):
    # cold_sweep_s reflects a warm cache when prior runs populated it —
    # the entry count below makes that auditable in the artifact's stderr
    cache_dir = os.environ.get(
        "GK_XLA_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".xla-cache"),
    )
    if cache_dir:
        from gatekeeper_tpu.ops.xlacache import enable as enable_xla_cache

        if enable_xla_cache(cache_dir):
            try:
                n = len(os.listdir(cache_dir))
            except OSError:
                n = 0
            log(f"xla cache: {cache_dir} ({n} entries pre-run)")
    if config != "all":
        print(json.dumps(CONFIGS[config]()))
        return

    out = bench_synthetic()
    for name, key in _FOLDED:
        t0 = time.time()
        try:
            sub = CONFIGS[name]()
        except Exception as e:
            log(f"[{name}] FAILED after {time.time()-t0:.0f}s: {e!r}")
            out[key] = None
            continue
        log(f"[{name}] done in {time.time()-t0:.0f}s")
        if name == "curve":
            out[key] = sub["curve_p50_ms"]
        else:
            out[key] = sub["value"]
        if name == "latency":
            out["admission_p50_ms"] = sub.get("p50_ms")
            out["admission_server_p99_ms"] = sub.get("server_p99_ms")
            out["admission_server_p50_ms"] = sub.get("server_p50_ms")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
