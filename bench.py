#!/usr/bin/env python
"""Headline benchmark: batched audit sweep throughput on TPU.

Config (BASELINE.md "synthetic"): N constraint templates x M cluster
resources, evaluated as one fused device computation (match kernel + all
vectorized violation programs, counts reduced on device).  The baseline is
the interpreter oracle (the architectural equivalent of the reference's
single-threaded topdown evaluation, reference
vendor/.../topdown/query.go:319) measured on a slice of the same workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
All diagnostics go to stderr.  Override sizes with BENCH_TEMPLATES /
BENCH_RESOURCES / BENCH_BASELINE_SLICE env vars.
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def main():
    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    n_resources = int(os.environ.get("BENCH_RESOURCES", "100000"))
    baseline_slice = int(os.environ.get("BENCH_BASELINE_SLICE", "20"))

    import jax

    log(f"devices: {jax.devices()}")

    from gatekeeper_tpu.engine.value import thaw
    from gatekeeper_tpu.util.synthetic import build_driver, make_pods, make_templates

    t0 = time.time()
    client = build_driver(n_templates, n_resources)
    driver = client.driver
    log(f"workload built: {n_templates} templates x {n_resources} resources "
        f"in {time.time()-t0:.1f}s")

    reviews = [
        driver.target.make_audit_review(thaw(o), api, k, n, ns)
        for o, api, k, n, ns in driver.store.iter_objects()
    ]

    t0 = time.time()
    fn, ordered, rp, cp, cols, group_params = driver._device_inputs(reviews)
    pack_s = time.time() - t0
    log(f"host packing (ingest-side cost): {pack_s:.1f}s")

    raw = fn.__wrapped__

    def counted(rv, cs, c, gp):
        mask, autoreject = raw(rv, cs, c, gp)
        return mask.sum(axis=1), autoreject.sum(axis=1)

    counted_jit = jax.jit(counted)
    args = (rp.arrays, cp.arrays, cols, group_params)

    t0 = time.time()
    counts, rejects = counted_jit(*args)
    counts.block_until_ready()
    log(f"first sweep (incl. compile): {time.time()-t0:.1f}s")

    times = []
    for _ in range(5):
        t0 = time.time()
        counts, rejects = counted_jit(*args)
        counts.block_until_ready()
        times.append(time.time() - t0)
    sweep_s = min(times)
    import numpy as np

    total_violations = int(np.asarray(counts).sum())
    C, R = len(ordered), len(reviews)
    cells = C * R
    evals_per_sec = cells / sweep_s
    log(f"steady-state sweep: {sweep_s*1000:.1f}ms for {cells} "
        f"constraint-evals ({evals_per_sec/1e6:.2f}M evals/s), "
        f"{total_violations} violating cells")

    # ---- baseline: interpreter oracle on a slice --------------------------
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver

    templates, constraints = make_templates(n_templates)
    ci = Client(driver=InterpDriver())
    for t in templates:
        ci.add_template(t)
    for c in constraints:
        ci.add_constraint(c)
    for p in make_pods(baseline_slice, seed=1):
        ci.add_data(p)
    t0 = time.time()
    ci.audit()
    interp_s = time.time() - t0
    interp_cells = n_templates * baseline_slice
    interp_rate = interp_cells / interp_s
    log(f"interp baseline: {interp_s:.1f}s for {interp_cells} evals "
        f"({interp_rate:.0f} evals/s)")

    print(
        json.dumps(
            {
                "metric": f"audit constraint-evals/sec ({n_templates} templates x {n_resources} resources, fused TPU sweep)",
                "value": round(evals_per_sec, 1),
                "unit": "evals/s",
                "vs_baseline": round(evals_per_sec / interp_rate, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
