#!/usr/bin/env python
"""Headline benchmark: batched audit sweep throughput on TPU.

Config (BASELINE.md "synthetic"): N constraint templates x M cluster
resources, evaluated as one fused device computation (match kernel + all
vectorized violation programs, counts reduced on device).  The baseline is
the interpreter oracle (the architectural equivalent of the reference's
single-threaded topdown evaluation, reference
vendor/.../topdown/query.go:319) measured on a slice of the same workload.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
All diagnostics go to stderr.  Override sizes with BENCH_TEMPLATES /
BENCH_RESOURCES / BENCH_BASELINE_SLICE env vars.
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def load_yaml_dir(pattern):
    import glob

    import yaml

    out = []
    for f in sorted(glob.glob(pattern)):
        with open(f) as fh:
            docs = [d for d in yaml.safe_load_all(fh) if d]
        out.extend(docs)
    return out


def bench_agilebank():
    """BASELINE config 'agilebank': full demo policy set x N mixed
    resources, from-cache audit sweep (end-to-end incl. render)."""
    import time as _t

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    n_copies = int(os.environ.get("BENCH_COPIES", "1000"))
    base = "/root/reference/demo/agilebank"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    n_cons = 0
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
        n_cons += 1
    resources = load_yaml_dir(f"{base}/good_resources/*.yaml") + load_yaml_dir(
        f"{base}/bad_resources/*.yaml"
    )
    import copy as _copy

    total = 0
    for i in range(n_copies):
        for r in resources:
            r2 = _copy.deepcopy(r)
            r2["metadata"]["name"] = f"{r['metadata'].get('name', 'x')}-{i}"
            c.add_data(r2)
            total += 1
    log(f"agilebank: {n_cons} constraints x {total} resources")
    c.audit()  # compile + warm
    t0 = _t.time()
    results = c.audit().results()
    dur = _t.time() - t0
    # audit cache hit: mutate one object to force repack for honest timing
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bench-epoch-bump"}})
    t0 = _t.time()
    results = c.audit().results()
    dur_repack = _t.time() - t0
    log(f"agilebank audit: cached {dur*1000:.0f}ms / repack "
        f"{dur_repack*1000:.0f}ms, {len(results)} violations")
    print(json.dumps({
        "metric": f"agilebank end-to-end audit ({total} resources)",
        "value": round(dur_repack, 3),
        "unit": "s",
        "vs_baseline": 0,
    }))


def bench_latency():
    """BASELINE config 'demo/basic': single-review admission latency
    through the full webhook handler (p50/p99), targeting <=2ms p99."""
    import time as _t

    import numpy as np

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.webhook import ValidationHandler

    base = "/root/reference/demo/basic"
    c = Client(driver=TpuDriver())
    for t in load_yaml_dir(f"{base}/templates/*.yaml"):
        c.add_template(t)
    for cons in load_yaml_dir(f"{base}/constraints/*.yaml"):
        c.add_constraint(cons)
    handler = ValidationHandler(c, kube=InMemoryKube())
    req = {
        "uid": "u", "kind": {"group": "", "version": "v1",
                             "kind": "Namespace"},
        "name": "test", "namespace": "", "operation": "CREATE",
        "userInfo": {"username": "bench"},
        "object": {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": "test", "labels": {}}},
    }
    for _ in range(20):  # warm: compile + caches
        handler.handle(req)
    times = []
    for _ in range(int(os.environ.get("BENCH_ITERS", "500"))):
        t0 = _t.perf_counter()
        handler.handle(req)
        times.append(_t.perf_counter() - t0)
    arr = np.array(times) * 1000
    log(f"admission latency ms: p50={np.percentile(arr, 50):.2f} "
        f"p99={np.percentile(arr, 99):.2f} max={arr.max():.2f}")
    print(json.dumps({
        "metric": "admission handler p99 latency (demo/basic, deny path)",
        "value": round(float(np.percentile(arr, 99)), 3),
        "unit": "ms",
        "vs_baseline": 0,
    }))


def main():
    config = os.environ.get("BENCH_CONFIG", "synthetic")
    if config == "agilebank":
        return bench_agilebank()
    if config == "latency":
        return bench_latency()

    n_templates = int(os.environ.get("BENCH_TEMPLATES", "500"))
    n_resources = int(os.environ.get("BENCH_RESOURCES", "100000"))
    baseline_slice = int(os.environ.get("BENCH_BASELINE_SLICE", "20"))

    import jax

    log(f"devices: {jax.devices()}")

    from gatekeeper_tpu.engine.value import thaw
    from gatekeeper_tpu.util.synthetic import build_driver, make_pods, make_templates

    t0 = time.time()
    client = build_driver(n_templates, n_resources)
    driver = client.driver
    log(f"workload built: {n_templates} templates x {n_resources} resources "
        f"in {time.time()-t0:.1f}s")

    reviews = [
        driver.target.make_audit_review(thaw(o), api, k, n, ns)
        for o, api, k, n, ns in driver.store.iter_objects()
    ]

    t0 = time.time()
    fn, ordered, rp, cp, cols, group_params = driver._device_inputs(reviews)
    pack_s = time.time() - t0
    log(f"host packing (ingest-side cost): {pack_s:.1f}s")

    raw = fn.__wrapped__

    def counted(rv, cs, c, gp):
        mask, autoreject = raw(rv, cs, c, gp)
        return mask.sum(axis=1), autoreject.sum(axis=1)

    counted_jit = jax.jit(counted)
    args = (rp.arrays, cp.arrays, cols, group_params)

    t0 = time.time()
    counts, rejects = counted_jit(*args)
    counts.block_until_ready()
    log(f"first sweep (incl. compile): {time.time()-t0:.1f}s")

    times = []
    for _ in range(5):
        t0 = time.time()
        counts, rejects = counted_jit(*args)
        counts.block_until_ready()
        times.append(time.time() - t0)
    sweep_s = min(times)
    import numpy as np

    total_violations = int(np.asarray(counts).sum())
    C, R = len(ordered), len(reviews)
    cells = C * R
    evals_per_sec = cells / sweep_s
    log(f"steady-state sweep: {sweep_s*1000:.1f}ms for {cells} "
        f"constraint-evals ({evals_per_sec/1e6:.2f}M evals/s), "
        f"{total_violations} violating cells")

    # ---- baseline: interpreter oracle on a slice --------------------------
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver

    templates, constraints = make_templates(n_templates)
    ci = Client(driver=InterpDriver())
    for t in templates:
        ci.add_template(t)
    for c in constraints:
        ci.add_constraint(c)
    for p in make_pods(baseline_slice, seed=1):
        ci.add_data(p)
    t0 = time.time()
    ci.audit()
    interp_s = time.time() - t0
    interp_cells = n_templates * baseline_slice
    interp_rate = interp_cells / interp_s
    log(f"interp baseline: {interp_s:.1f}s for {interp_cells} evals "
        f"({interp_rate:.0f} evals/s)")

    print(
        json.dumps(
            {
                "metric": f"audit constraint-evals/sec ({n_templates} templates x {n_resources} resources, fused TPU sweep)",
                "value": round(evals_per_sec, 1),
                "unit": "evals/s",
                "vs_baseline": round(evals_per_sec / interp_rate, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
