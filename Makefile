# gatekeeper-tpu build/test entry points (the reference's Makefile roles:
# native-test, docker-build, deploy).

IMG ?= gatekeeper-tpu:latest
NAMESPACE ?= gatekeeper-system

.PHONY: manifests
manifests:  ## regenerate charts/gatekeeper-tpu from deploy/gatekeeper.yaml
	python tools/helmify.py

.PHONY: test
test:
	python -m pytest tests/ -q

.PHONY: bench
bench:
	python bench.py

.PHONY: docker-build
docker-build:
	docker build -t $(IMG) .

.PHONY: deploy
deploy:
	kubectl apply -f deploy/gatekeeper.yaml

.PHONY: uninstall
uninstall:
	kubectl delete -f deploy/gatekeeper.yaml --ignore-not-found

.PHONY: lint
lint:  ## gklint invariants + observability/parity conformance checks
	python -m compileall -q gatekeeper_tpu
	python tools/gklint.py gatekeeper_tpu/
	python tools/check_observability.py

.PHONY: obs-check
obs-check: lint  ## observability conformance + gklint (alias of lint so the two never drift)

.PHONY: replay-check
replay-check:  ## decision-log differential-replay selftest (zero drift + seeded GK_BUG_COMPAT drift flagged)
	python tools/replay_decisions.py --selftest

.PHONY: lint-baseline
lint-baseline:  ## accept current gklint findings into .gklint-baseline.json
	python tools/gklint.py --write-baseline
