"""Black-box flight recorder (gatekeeper_tpu/obs/flightrec.py): ring
bounds and causal ordering, shed-burst coalescing, atomic dumps with
retention, the event-source feeds (snapshot/shed/brownout/breaker), and
the /debug/flightrecz endpoint contract (ISSUE 13)."""

import json
import os

import pytest

from gatekeeper_tpu.obs import flightrec
from gatekeeper_tpu.obs.flightrec import FlightRecorder


@pytest.fixture()
def clean_singleton():
    """Isolate tests that drive the module-level recorder (subsystem
    feeds record into it from anywhere)."""
    rec = flightrec.get_recorder()
    rec.clear()
    yield rec
    rec.clear()
    rec.configure(dump_dir="")


class TestRing:
    def test_events_carry_seq_in_causal_order(self):
        rec = FlightRecorder()
        rec.record(flightrec.BREAKER_TRANSITION, old="closed", new="open")
        rec.record(flightrec.MESH_DEGRADE, from_width=4, to_width=2)
        rec.record(flightrec.BREAKER_TRANSITION, old="open", new="closed")
        events = rec.events()
        assert [e["type"] for e in events] == [
            "breaker_transition", "mesh_degrade", "breaker_transition",
        ]
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
        for e in events:
            assert "t" in e and "mono" in e and "replica_id" in e

    def test_ring_is_bounded_keeping_newest(self):
        rec = FlightRecorder(maxlen=16)
        for i in range(50):
            rec.record(flightrec.ROUTE_FLIP, i=i)
        events = rec.events()
        assert len(events) == 16
        assert events[-1]["i"] == 49 and events[0]["i"] == 34

    def test_limit_keeps_newest(self):
        rec = FlightRecorder()
        for i in range(5):
            rec.record(flightrec.ROUTE_FLIP, i=i)
        got = rec.events(limit=2)
        assert [e["i"] for e in got] == [3, 4]
        # limit=0 means none, not everything (the [-0:] slice trap)
        assert rec.events(limit=0) == []

    def test_recorder_defect_never_raises(self):
        rec = FlightRecorder()
        rec._ring = None  # induced defect
        rec.record(flightrec.ROUTE_FLIP)  # must swallow (counted drop)


class TestShedBursts:
    def test_sheds_coalesce_into_one_burst_event(self):
        rec = FlightRecorder()
        for _ in range(7):
            rec.note_shed("queue_full")
        rec.note_shed("door_inflight", n=3)
        events = rec.events()  # flushes pending windows
        bursts = {e["reason"]: e for e in events
                  if e["type"] == flightrec.SHED_BURST}
        assert bursts["queue_full"]["count"] == 7
        assert bursts["door_inflight"]["count"] == 3
        assert len(events) == 2  # never one entry per shed

    def test_new_window_emits_new_burst(self, monkeypatch):
        rec = FlightRecorder()
        rec.note_shed("queue_full", 2)
        # age the pending window past SHED_WINDOW_S without sleeping
        with rec._lock:
            rec._sheds["queue_full"][1] -= flightrec.SHED_WINDOW_S + 1.0
        rec.note_shed("queue_full", 5)  # flushes the old window first
        events = [e for e in rec.events()
                  if e["type"] == flightrec.SHED_BURST]
        assert [e["count"] for e in events] == [2, 5]


class TestDump:
    def test_dump_writes_atomic_json_artifact(self, tmp_path):
        rec = FlightRecorder()
        rec.configure(dump_dir=str(tmp_path))
        rec.record(flightrec.BREAKER_TRANSITION, old="closed", new="open")
        rec.note_shed("queue_full", 4)
        path = rec.dump("unit_test")
        assert path and os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["reason"] == "unit_test"
        assert payload["event_count"] == len(payload["events"]) == 2
        types = {e["type"] for e in payload["events"]}
        assert types == {"breaker_transition", "shed_burst"}
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
        assert rec.dumps == 1 and rec.last_dump_path == path

    def test_dump_without_dir_is_noop(self):
        rec = FlightRecorder()
        rec.record(flightrec.MESH_DEGRADE, from_width=2, to_width=1)
        assert rec.dump("nowhere") is None

    def test_retention_keeps_newest_dumps(self, tmp_path):
        rec = FlightRecorder()
        rec.configure(dump_dir=str(tmp_path), retain=3)
        rec.record(flightrec.ROUTE_FLIP)
        for _ in range(6):
            rec.dump("retention")
        files = [n for n in os.listdir(tmp_path)
                 if n.startswith("flightrec-")]
        assert len(files) == 3
        # the newest dump survives
        assert os.path.basename(rec.last_dump_path) in files


class TestExitHook:
    def test_atexit_dump_on_process_death(self, tmp_path):
        """A dying process with a configured dir leaves one artifact
        behind (the atexit half of the death hook)."""
        import subprocess
        import sys

        code = (
            "from gatekeeper_tpu.obs import flightrec\n"
            f"rec = flightrec.get_recorder().configure(dump_dir={str(tmp_path)!r})\n"
            "rec.install_exit_hook()\n"
            "rec.record(flightrec.BREAKER_TRANSITION, old='closed',"
            " new='open')\n"
        )
        subprocess.run(
            [sys.executable, "-c", code], check=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        dumps = [n for n in os.listdir(tmp_path)
                 if "process_exit" in n and n.endswith(".json")]
        assert len(dumps) == 1
        with open(tmp_path / dumps[0]) as f:
            payload = json.load(f)
        assert payload["reason"] == "process_exit"
        assert payload["events"][0]["type"] == "breaker_transition"

    def test_clean_exit_with_no_events_dumps_nothing(self, tmp_path):
        import subprocess
        import sys

        code = (
            "from gatekeeper_tpu.obs import flightrec\n"
            f"rec = flightrec.get_recorder().configure(dump_dir={str(tmp_path)!r})\n"
            "rec.install_exit_hook()\n"
        )
        subprocess.run(
            [sys.executable, "-c", code], check=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert not list(tmp_path.iterdir())


class TestEventSources:
    def test_snapshot_outcome_feeds_recorder(self, clean_singleton):
        from gatekeeper_tpu.metrics.catalog import record_snapshot_outcome

        record_snapshot_outcome("fallback")
        events = clean_singleton.events()
        assert any(
            e["type"] == flightrec.SNAPSHOT_RESTORE
            and e["outcome"] == "fallback"
            for e in events
        )

    def test_record_shed_feeds_recorder(self, clean_singleton):
        from gatekeeper_tpu.metrics.catalog import record_shed

        record_shed("deadline_expired", 5)
        events = clean_singleton.events()
        bursts = [e for e in events if e["type"] == flightrec.SHED_BURST]
        assert bursts and bursts[0]["count"] == 5
        assert bursts[0]["reason"] == "deadline_expired"

    def test_brownout_step_feeds_recorder(self, clean_singleton):
        from gatekeeper_tpu.obs.brownout import BrownoutController

        t = [1000.0]
        ctl = BrownoutController(clock=lambda: t[0])
        ctl.set_providers(queue_frac=lambda: 1.0)
        ctl.tick()
        t[0] += ctl.UP_AFTER_S + 0.1
        ctl.tick()
        assert ctl.level == 1
        events = clean_singleton.events()
        steps = [e for e in events if e["type"] == flightrec.BROWNOUT_STEP]
        assert steps and steps[-1]["new"] == 1 and steps[-1]["old"] == 0

    def test_slo_alert_edge_feeds_recorder_and_dumps(
        self, clean_singleton, tmp_path
    ):
        from gatekeeper_tpu.obs.slo import SLOEngine

        clean_singleton.configure(dump_dir=str(tmp_path))
        t = [50_000.0]
        eng = SLOEngine(clock=lambda: t[0])
        eng.add_objective("x", 0.999)
        eng.record("x", False, n=eng.min_alert_events)
        eng.evaluate()
        events = clean_singleton.events()
        alerts = [e for e in events if e["type"] == flightrec.SLO_ALERT]
        assert alerts and alerts[0]["edge"] == "activated"
        assert alerts[0]["objective"] == "x"
        # the activation paged: an automatic dump landed on disk
        dumps = [n for n in os.listdir(tmp_path)
                 if "slo_page" in n and n.endswith(".json")]
        assert dumps
        # the clear edge records too (events age out of every window);
        # both SRE pairs (fast, slow) fire, so each edge appears per pair
        t[0] += 22_000.0
        eng.evaluate()
        edges = [e["edge"] for e in clean_singleton.events()
                 if e["type"] == flightrec.SLO_ALERT]
        assert "cleared" in edges
        assert edges.index("cleared") > edges.index("activated")


class TestDebugEndpoint:
    def test_flightrecz_serves_ring(self, clean_singleton):
        from gatekeeper_tpu.obs.debug import get_router

        clean_singleton.record(flightrec.MESH_DEGRADE,
                               from_width=8, to_width=4)
        code, ctype, body = get_router().handle("/debug/flightrecz",
                                                "limit=10")
        assert code == 200 and ctype == "application/json"
        payload = json.loads(body)
        assert payload["events"][-1]["type"] == "mesh_degrade"
        assert "dumped_to" not in payload

    def test_flightrecz_dump_param(self, clean_singleton, tmp_path):
        from gatekeeper_tpu.obs.debug import get_router

        clean_singleton.configure(dump_dir=str(tmp_path))
        clean_singleton.record(flightrec.ROUTE_FLIP, from_tier="np",
                               to_tier="device")
        code, _ctype, body = get_router().handle("/debug/flightrecz",
                                                 "dump=1")
        payload = json.loads(body)
        assert code == 200
        assert payload["dumped_to"] and os.path.exists(
            payload["dumped_to"])

    @pytest.mark.parametrize("query", ["limit=abc", "dump=x", "limit=-1"])
    def test_bad_params_are_json_400(self, query):
        from gatekeeper_tpu.obs.debug import get_router

        code, ctype, body = get_router().handle("/debug/flightrecz", query)
        assert code == 400 and ctype == "application/json"
        assert "must be" in json.loads(body)["error"]
