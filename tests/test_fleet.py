"""Fleet serving (gatekeeper_tpu/fleet/, docs/fleet.md, ISSUE 7).

Covers the single-role App contract (a webhook-only replica runs no
audit manager, no snapshot writer, no status controllers — the ISSUE's
acceptance assertion), the stdlib front door (round-robin and
least-inflight choice, dead-backend failover, explicit 502 when every
backend is down, /fleetz stats), the load-adaptive micro-batcher's
controller (equilibrium target, deadline, idle reset, dormancy without a
calibration, exported gauges), the aux-server idempotent starts, and
replica-identity stamping across spans / metrics / SLO payloads.
"""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gatekeeper_tpu import operations as ops_mod
from gatekeeper_tpu.fleet import FrontDoor
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.util import replica_id, set_replica_id
from gatekeeper_tpu.webhook import MicroBatcher


@pytest.fixture(autouse=True)
def _clear_replica_id():
    yield
    set_replica_id("")


# ---- operations role helpers ------------------------------------------------


class TestOperationsRoles:
    def test_default_is_every_operation(self):
        ops = ops_mod.Operations()
        assert ops.assigned_set() == set(ops_mod.ALL_OPERATIONS)
        assert not ops.explicitly_assigned()
        assert not ops.is_only(ops_mod.WEBHOOK)

    def test_single_role(self):
        ops = ops_mod.Operations([ops_mod.WEBHOOK])
        assert ops.assigned_set() == {ops_mod.WEBHOOK}
        assert ops.explicitly_assigned()
        assert ops.is_only(ops_mod.WEBHOOK)
        assert not ops.is_only(ops_mod.AUDIT)

    def test_multi_role_is_not_only(self):
        ops = ops_mod.Operations([ops_mod.WEBHOOK, ops_mod.AUDIT])
        assert not ops.is_only(ops_mod.WEBHOOK)
        assert ops.is_assigned(ops_mod.WEBHOOK)


# ---- single-role App wiring (the fleet replica's contract) ------------------


def _make_app(tmp_path, *ops):
    from gatekeeper_tpu.main import App, build_parser

    flags = [
        "--driver", "interp",
        "--port", "0",
        "--prometheus-port", "0",
        "--health-addr", ":0",
        "--disable-cert-rotation",
        "--snapshot-dir", str(tmp_path / "snap"),
    ]
    for op in ops:
        flags += ["--operation", op]
    return App(build_parser().parse_args(flags), kube=InMemoryKube())


class TestSingleRoleApp:
    def test_webhook_only_runs_no_audit_no_snapshotter_no_status(
        self, tmp_path,
    ):
        """The ISSUE 7 acceptance assertion: a webhook-only replica must
        not run an audit manager, must not ARM the snapshot writer (it is
        a read-mostly consumer of the shared dir), and must not run the
        status writers."""
        app = _make_app(tmp_path, ops_mod.WEBHOOK)
        app.start()
        try:
            assert app.audit_manager is None
            assert app.snapshotter is None
            assert not hasattr(app.manager, "constraint_status")
            assert not hasattr(app.manager, "template_status")
            assert app.micro_batcher is not None
            assert app.webhook_server is not None
        finally:
            app.stop()

    def test_audit_only_arms_snapshotter_and_no_webhook(self, tmp_path):
        app = _make_app(tmp_path, ops_mod.AUDIT)
        app.start()
        try:
            assert app.audit_manager is not None
            assert app.snapshotter is not None
            assert app.micro_batcher is None
            assert app.webhook_server is None
        finally:
            app.stop()


# ---- front door -------------------------------------------------------------


class _StubBackend:
    """Tiny HTTP backend that echoes its name (and can be made slow)."""

    def __init__(self, name: str, delay_s: float = 0.0):
        self.name = name
        self.delay_s = delay_s
        self.served = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if outer.delay_s:
                    time.sleep(outer.delay_s)
                outer.served += 1
                body = json.dumps({"backend": outer.name}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def _post_door(door, body=b"{}"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{door.port}/v1/admit", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        # resp.headers is case-insensitive (email.message.Message)
        return resp.status, resp.headers, resp.read()


class TestFrontDoor:
    def test_round_robin_rotates(self):
        a, b = _StubBackend("a"), _StubBackend("b")
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": a.port, "replica_id": "a"},
             {"host": "127.0.0.1", "port": b.port, "replica_id": "b"}],
            policy="round_robin",
        ).start()
        try:
            replicas = []
            for _ in range(6):
                _st, hd, data = _post_door(door)
                assert json.loads(data)["backend"] in ("a", "b")
                replicas.append(hd["X-GK-Replica"])
            assert replicas.count("a") == 3
            assert replicas.count("b") == 3
        finally:
            door.stop()
            a.stop()
            b.stop()

    def test_least_inflight_prefers_idle_backend(self):
        slow, fast = _StubBackend("slow", delay_s=0.25), _StubBackend("fast")
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": slow.port, "replica_id": "slow"},
             {"host": "127.0.0.1", "port": fast.port, "replica_id": "fast"}],
            policy="least_inflight",
        ).start()
        try:
            out = []
            lock = threading.Lock()

            def one():
                _st, hd, _d = _post_door(door)
                with lock:
                    out.append(hd["X-GK-Replica"])

            threads = [threading.Thread(target=one) for _ in range(10)]
            for t in threads:
                t.start()
                time.sleep(0.02)  # arrivals overlap the slow service time
            for t in threads:
                t.join()
            # while the slow backend holds a request in flight, new
            # arrivals must land on the idle one
            assert out.count("fast") > out.count("slow")
        finally:
            door.stop()
            slow.stop()
            fast.stop()

    def test_dead_backend_fails_over(self):
        dead, live = _StubBackend("dead"), _StubBackend("live")
        dead.stop()  # port is now refused
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": dead.port, "replica_id": "dead"},
             {"host": "127.0.0.1", "port": live.port, "replica_id": "live"}],
            policy="round_robin",
        ).start()
        try:
            for _ in range(4):
                st, hd, data = _post_door(door)
                assert st == 200
                assert hd["X-GK-Replica"] == "live"
            stats = {
                b["replica_id"]: b for b in door.stats()["backends"]
            }
            assert stats["dead"]["errors"] >= 1
            assert stats["live"]["served"] == 4
        finally:
            door.stop()
            live.stop()

    def test_healthz_liveness_is_recent_not_sticky(self):
        """A backend that once served but now fails every request is
        dead: /healthz must go 503 once every backend's error streak
        passes LIVE_ERROR_STREAK — a sticky served counter would keep
        answering 200 while every POST returns 502."""
        b = _StubBackend("b0")
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": b.port, "replica_id": "b0"}],
        ).start()
        try:
            st, _hd, _data = _post_door(door)
            assert st == 200  # served > 0: the old sticky predicate
            b.stop()  # backend dies after serving
            for _ in range(FrontDoor.LIVE_ERROR_STREAK):
                with pytest.raises(urllib.error.HTTPError):
                    _post_door(door)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{door.port}/healthz", timeout=10
                )
            assert ei.value.code == 503
        finally:
            door.stop()

    def test_all_backends_down_is_an_explicit_502(self):
        gone = _StubBackend("gone")
        gone.stop()
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": gone.port, "replica_id": "gone"}],
        ).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{door.port}/v1/admit", data=b"{}",
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            # 502, never a fabricated AdmissionReview verdict
            assert ei.value.code == 502
        finally:
            door.stop()

    def test_fleetz_and_unknown_path(self):
        a = _StubBackend("a")
        door = FrontDoor([("127.0.0.1", a.port)]).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{door.port}/fleetz", timeout=10
            ) as resp:
                stats = json.loads(resp.read())
            assert stats["policy"] == "least_inflight"
            assert len(stats["backends"]) == 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{door.port}/nope", timeout=10
                )
            assert ei.value.code == 404
        finally:
            door.stop()
            a.stop()

    def test_rejects_unknown_policy_and_empty_backends(self):
        with pytest.raises(ValueError):
            FrontDoor([("127.0.0.1", 1)], policy="weighted")
        with pytest.raises(ValueError):
            FrontDoor([])


# ---- load-adaptive micro-batcher -------------------------------------------


class _ModelDriver:
    """Affine service model: T(B) = floor + B*per_review (ms)."""

    def __init__(self, floor_ms=0.2, per_review_ms=0.05):
        self.floor_ms = floor_ms
        self.per_review_ms = per_review_ms
        self.loads = []

    def predicted_batch_ms(self, n):
        return self.floor_ms + n * self.per_review_ms

    def set_offered_load(self, rps):
        self.loads.append(rps)


class _ModelClient:
    def __init__(self, driver=None):
        self.driver = driver if driver is not None else _ModelDriver()

    def review_batch(self, objs, tracing=False):
        return [None] * len(objs)


def _equilibrium(driver, lam, max_batch=256):
    """The fixed point B = λ·T(B) the controller iterates toward."""
    lam_pms = lam / 1e3
    b = 1.0
    for _ in range(4):
        t = driver.predicted_batch_ms(max(int(b), 1))
        nb = min(max(lam_pms * t, 1.0), float(max_batch))
        if abs(nb - b) < 0.5:
            return nb
        b = nb
    return b


class TestAdaptiveBatcher:
    def _batcher(self, **kw):
        return MicroBatcher(_ModelClient(), window_s=0.002, **kw)

    def test_low_load_targets_immediate_dispatch(self):
        mb = self._batcher()
        try:
            with mb._rate_lock:
                mb._load_rps = 50.0  # sparse traffic
            target, deadline = mb._adapt()
            assert target == 1
            assert deadline == 0.0
        finally:
            mb.stop()

    def test_high_load_grows_target_and_sets_deadline(self):
        mb = self._batcher()
        drv = mb._client.driver
        try:
            with mb._rate_lock:
                mb._load_rps = 20000.0
            target, deadline = mb._adapt()
            want = _equilibrium(drv, 20000.0)
            assert target == max(int(round(want)), 1) and target > 1
            # deadline = time for λ to deliver the target, capped
            assert deadline == pytest.approx(
                min(target / 20000.0, mb.max_deadline_s)
            )
            # λ pushed to the driver so routing is load-aware
            assert drv.loads[-1] == 20000.0
        finally:
            mb.stop()

    def test_extreme_load_caps_at_max_batch_and_deadline(self):
        mb = self._batcher(max_deadline_s=0.010)
        try:
            with mb._rate_lock:
                mb._load_rps = 1e9
            target, deadline = mb._adapt()
            assert target == mb.max_batch
            assert deadline <= 0.010
        finally:
            mb.stop()

    def test_static_mode_never_adapts(self):
        mb = self._batcher(adaptive=False)
        try:
            with mb._rate_lock:
                mb._load_rps = 1e6
            assert mb._adapt() == (1, 0.0)
            assert mb._client.driver.loads == []
        finally:
            mb.stop()

    def test_no_calibration_stays_dormant(self):
        class _Bare:
            pass

        class _BareClient:
            driver = _Bare()

            def review_batch(self, objs, tracing=False):
                return [None] * len(objs)

        mb = MicroBatcher(_BareClient())
        try:
            with mb._rate_lock:
                mb._load_rps = 1e6
            assert mb._adapt() == (1, 0.0)
        finally:
            mb.stop()

    def test_model_failure_never_stalls_dispatch(self):
        class _Boom(_ModelDriver):
            def predicted_batch_ms(self, n):
                raise RuntimeError("model broke")

        mb = MicroBatcher(_ModelClient(_Boom()))
        try:
            with mb._rate_lock:
                mb._load_rps = 1e6
            assert mb._adapt() == (1, 0.0)
        finally:
            mb.stop()

    def test_idle_gap_resets_rate_outright(self):
        """A burst minutes ago must not tax today's lone request: one
        bucket roll across a long idle gap adopts the gap's (near-zero)
        rate instead of EWMA-halving the stale burst rate."""
        mb = self._batcher()
        try:
            with mb._rate_lock:
                mb._load_rps = 50000.0  # stale burst
                mb._arrivals = 1        # the lone request after the lull
                mb._rate_t0 = time.monotonic() - (mb.IDLE_RESET_S + 1.0)
            lam = mb.offered_load_rps()
            assert lam < 1.0
            target, deadline = mb._adapt()
            assert (target, deadline) == (1, 0.0)
        finally:
            mb.stop()

    def test_short_bucket_blends_ewma(self):
        mb = self._batcher()
        try:
            with mb._rate_lock:
                mb._load_rps = 1000.0
                mb._arrivals = 500
                mb._rate_t0 = time.monotonic() - 0.5  # ~1000 rps observed
            lam = mb.offered_load_rps()
            # blended, not replaced: stays in the same decade
            assert 900.0 < lam < 1100.0
        finally:
            mb.stop()

    def test_adaptive_window_clamped_to_member_deadline(self, monkeypatch):
        """A deadline-budgeted request must never be held past its own
        budget by the adaptive accumulation window and then refused: the
        window clamps to the earliest queued deadline minus a dispatch
        margin, so the request dispatches (and succeeds) in budget."""
        from gatekeeper_tpu import deadline as dl

        mb = self._batcher()
        try:
            # force a long adaptive window the single request can't fill
            monkeypatch.setattr(mb, "_adapt", lambda: (64, 10.0))
            token = dl.push(0.25)  # 250ms budget << the 10s window
            try:
                t0 = time.monotonic()
                mb.review({"kind": "Pod"})  # must NOT DeadlineExceeded
                waited = time.monotonic() - t0
            finally:
                dl.pop(token)
            # dispatched at the budget clamp, not the adaptive window
            assert waited < 1.0
        finally:
            mb.stop()

    def test_stop_clears_the_driver_load_hint(self):
        mb = self._batcher()
        drv = mb._client.driver
        with mb._rate_lock:
            mb._load_rps = 5000.0
        mb._adapt()
        mb.stop()
        assert drv.loads[-1] is None

    def test_dispatch_span_carries_adaptation_state(self, monkeypatch):
        """/debug/traces must show WHY a request waited: the batch span
        carries the target, deadline, and the load that set them."""
        from gatekeeper_tpu.obs import trace as obstrace
        from gatekeeper_tpu.webhook import server as websrv

        seen = {}
        real = obstrace.batch_span

        def capture(name, spans, **attrs):
            seen.update(attrs)
            return real(name, spans, **attrs)

        monkeypatch.setattr(websrv.obstrace, "batch_span", capture)

        class _SlowClient(_ModelClient):
            def review(self, obj, tracing=False):
                time.sleep(0.01)  # idle fast path: slow enough to queue
                return None

            def review_batch(self, objs, tracing=False):
                time.sleep(0.01)
                return [None] * len(objs)

        mb = MicroBatcher(_SlowClient(), window_s=0.05)
        try:
            done = threading.Barrier(5)

            def call():
                with obstrace.root_span("test.request"):
                    mb.review(object())
                done.wait(timeout=10)

            threads = [threading.Thread(target=call) for _ in range(4)]
            for t in threads:
                t.start()
            done.wait(timeout=10)
            for t in threads:
                t.join()
            assert "batch_target" in seen
            assert "batch_deadline_ms" in seen
            assert "offered_load_rps" in seen
            assert "batch_size" in seen
        finally:
            mb.stop()

    def test_batcher_state_exported_with_replica_id(self):
        from gatekeeper_tpu.metrics.catalog import record_batcher_state
        from gatekeeper_tpu.metrics.views import global_registry

        set_replica_id("r-test-7")
        record_batcher_state(17, 4.5, 1234.0)
        rows = global_registry().view_rows("webhook_batch_target_size")
        assert rows.get(("r-test-7",)) == 17.0
        rows = global_registry().view_rows("webhook_offered_load_rps")
        assert rows.get(("r-test-7",)) == 1234.0
        rows = global_registry().view_rows("webhook_batch_deadline_ms")
        assert rows.get(("r-test-7",)) == 4.5


# ---- aux server idempotent starts ------------------------------------------


class TestAuxServerIdempotentStart:
    def _double_start(self, server, probe_path):
        server.start()
        first_port = server.port
        try:
            server.port = 0
            server.start()  # replaces, never leaks
            assert server.port != 0
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{probe_path}", timeout=10
            ) as resp:
                assert resp.status == 200
            # the first port was released by the replacement
            import socket

            s = socket.socket()
            try:
                s.bind(("127.0.0.1", first_port))
            finally:
                s.close()
        finally:
            server.stop()

    def test_health_server_start_is_idempotent(self):
        from gatekeeper_tpu.main import HealthServer

        self._double_start(
            HealthServer(port=0, readiness_check=lambda: True), "/healthz"
        )

    def test_profile_server_start_is_idempotent(self):
        from gatekeeper_tpu.main import ProfileServer

        self._double_start(ProfileServer(port=0), "/debug/pprof/")


# ---- replica identity stamping ---------------------------------------------


class TestReplicaIdentity:
    def test_replica_id_on_root_spans(self):
        from gatekeeper_tpu.obs import trace as obstrace

        set_replica_id("r9")
        with obstrace.root_span("unit.test") as sp:
            pass
        assert sp.attrs.get("replica_id") == "r9"

    def test_no_replica_id_means_no_attr(self):
        from gatekeeper_tpu.obs import trace as obstrace

        set_replica_id("")
        with obstrace.root_span("unit.test") as sp:
            pass
        assert "replica_id" not in sp.attrs

    def test_replica_id_in_slo_payload(self):
        from gatekeeper_tpu.obs.slo import SLOEngine

        set_replica_id("r42")
        out = SLOEngine().evaluate()
        assert out["replica_id"] == "r42"
        set_replica_id("")
        out = SLOEngine().evaluate()
        assert "replica_id" not in out

    def test_replica_up_labelled(self):
        from gatekeeper_tpu.metrics.catalog import record_replica_up
        from gatekeeper_tpu.metrics.views import global_registry

        set_replica_id("r-up")
        record_replica_up()
        rows = global_registry().view_rows("replica_up")
        assert rows.get(("r-up",)) == 1.0

    def test_replica_id_env_fallback(self, monkeypatch):
        from gatekeeper_tpu import util as gkutil

        monkeypatch.setattr(gkutil, "_replica_id", None)
        monkeypatch.setenv("GK_REPLICA_ID", "env-r1")
        assert replica_id() == "env-r1"
