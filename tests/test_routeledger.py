"""Route-decision ledger (gatekeeper_tpu/obs/routeledger.py + the
driver's _route_eval/_review_batch_eval recording): decision entries
with priced tables and reasons, override reasons for breaker/compile
diverts, the per-shape tier-win table, bounded shapes, route flips into
the flight recorder, and the /debug/routez endpoint (ISSUE 13)."""

import json

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.obs import flightrec, routeledger
from gatekeeper_tpu.obs.routeledger import RouteLedger
from gatekeeper_tpu.ops.driver import TpuDriver


def make_client(n=3):
    from gatekeeper_tpu.util.synthetic import make_templates

    templates, constraints = make_templates(n)
    c = Client(driver=TpuDriver())
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
    return c


def review(i=0):
    return {
        "uid": f"u{i}",
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": f"pod-{i}", "namespace": "default",
        "operation": "CREATE",
        "object": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default",
                         "labels": {"i": str(i)}},
            "spec": {"containers": [{"name": "c",
                                     "image": f"img.io/x:{i}"}]},
        },
    }


class TestLedgerUnit:
    def test_record_builds_entry_and_wins(self):
        led = RouteLedger()
        led.record("np", "latency", cells=200, n_reviews=2, lam=100.0,
                   priced=[{"tier": "np", "floor_ms": 1.0,
                            "per_review_ms": 0.1, "predicted_ms": 1.2,
                            "mu_rps": 5000.0}])
        snap = led.snapshot()
        (entry,) = snap["decisions"]
        assert entry["tier"] == "np" and entry["reason"] == "latency"
        assert entry["per_review_cells"] == 100
        assert entry["lam"] == 100.0
        assert entry["priced"][0]["tier"] == "np"
        (row,) = snap["tier_wins"]
        assert row == {"per_review_cells": 100, "n_reviews": 2,
                       "cells": 200, "wins": {"np": 1}}
        assert snap["counts"] == {"np|latency": 1}

    def test_shapes_are_bounded_with_overflow_counted(self):
        led = RouteLedger()
        for i in range(routeledger.MAX_SHAPES + 10):
            led.record("interp", "latency", cells=i + 1, n_reviews=1,
                       lam=None)
        snap = led.snapshot()
        assert len(snap["tier_wins"]) == routeledger.MAX_SHAPES
        assert snap["tier_wins_overflow"] == 10

    def test_flip_feeds_flight_recorder(self):
        rec = flightrec.get_recorder()
        rec.clear()
        led = RouteLedger()
        led.record("device", "latency", 100, 1, None)
        led.record("device", "latency", 100, 1, None)
        assert led.flips == 0
        led.record("np", "breaker_open", 100, 1, None)
        assert led.flips == 1
        flips = [e for e in rec.events()
                 if e["type"] == flightrec.ROUTE_FLIP]
        assert flips and flips[-1]["from_tier"] == "device"
        assert flips[-1]["to_tier"] == "np"
        assert flips[-1]["reason"] == "breaker_open"
        rec.clear()

    def test_limit_zero_returns_no_decisions(self):
        led = RouteLedger()
        for i in range(3):
            led.record("np", "latency", 10 + i, 1, None)
        assert led.snapshot(limit=0)["decisions"] == []
        assert len(led.snapshot(limit=2)["decisions"]) == 2

    def test_disabled_ledger_records_nothing(self):
        led = RouteLedger()
        led.enabled = False
        led.record("np", "latency", 10, 1, None)
        assert led.snapshot()["decisions"] == []

    def test_route_decisions_counter_exported(self):
        from gatekeeper_tpu.metrics.views import global_registry

        led = RouteLedger()
        led.record("interp", "uncalibrated_prior", 5, 1, None)
        rows = global_registry().view_rows("route_decisions_total")
        assert any(
            key == ("interp", "uncalibrated_prior") for key in rows
        )


class TestDriverRecording:
    def test_route_eval_records_with_reason(self):
        c = make_client()
        d = c.driver
        d.route_ledger.clear()
        route = d._route_eval(10_000)
        snap = d.route_ledger.snapshot()
        assert snap["decisions"][-1]["tier"] == route
        assert snap["decisions"][-1]["reason"] == "uncalibrated_prior"

    def test_calibrated_decision_carries_priced_table(self):
        c = make_client()
        d = c.driver
        d._route_cal = {
            "rtt_ms": 5.0, "device_cells_per_ms": 100.0,
            "interp_cells_per_ms": 10.0,
            "np_floor_ms": 1.0, "np_cells_per_ms": 50.0,
        }
        d.route_ledger.clear()
        d._route_eval(1000, n_reviews=4)
        entry = d.route_ledger.snapshot()["decisions"][-1]
        assert entry["reason"] == "latency"
        tiers = {p["tier"] for p in entry["priced"]}
        assert tiers == {"interp", "device", "np"}
        for p in entry["priced"]:
            assert p["mu_rps"] > 0 and p["predicted_ms"] >= 0

    def test_brownout_pin_reason(self):
        c = make_client()
        d = c.driver
        d._route_cal = {
            "rtt_ms": 5.0, "device_cells_per_ms": 100.0,
            "interp_cells_per_ms": 10.0,
        }
        d.set_brownout_pin(True)
        d.route_ledger.clear()
        d._route_eval(100)
        assert (d.route_ledger.snapshot()["decisions"][-1]["reason"]
                == "brownout_pin")
        d.set_brownout_pin(False)

    def test_breaker_open_override_recorded(self):
        c = make_client()
        d = c.driver
        d.DEVICE_MIN_CELLS = 0  # price says device, always
        d.route_ledger.clear()
        d.breaker.trip()
        try:
            out = c.review(review(1))
            assert out is not None  # served host-side
            entry = d.route_ledger.snapshot()["decisions"][-1]
            assert entry["reason"] == "breaker_open"
            assert entry["tier"] in ("np", "interp")
        finally:
            d.breaker.record_success()  # close again

    def test_device_failure_records_amended_decision(self):
        from gatekeeper_tpu import faults
        from gatekeeper_tpu.faults import FaultRule

        c = make_client()
        d = c.driver
        d.DEVICE_MIN_CELLS = 0
        c.review(review(0))  # warm the device path
        d.route_ledger.clear()
        plane = faults.install(seed=3)
        plane.add(faults.TPU_DISPATCH,
                  FaultRule(mode=faults.ERROR, probability=1.0, count=1))
        try:
            out = c.review(review(2))
            assert out is not None
        finally:
            faults.uninstall()
        reasons = [e["reason"] for e in
                   d.route_ledger.snapshot()["decisions"]]
        assert "device_failed" in reasons

    def test_load_aware_reasons(self):
        c = make_client()
        d = c.driver
        d._route_cal = {
            "rtt_ms": 5.0, "device_cells_per_ms": 1000.0,
            "interp_cells_per_ms": 10.0,
            "np_floor_ms": 1.0, "np_cells_per_ms": 50.0,
        }
        d.route_ledger.clear()
        d.set_offered_load(100.0)  # modest: sustainable tiers exist
        d._route_eval(300, n_reviews=1)
        assert (d.route_ledger.snapshot()["decisions"][-1]["reason"]
                == "load_aware")
        d.set_offered_load(10_000_000.0)  # nothing sustains this
        d._route_eval(300, n_reviews=1)
        assert (d.route_ledger.snapshot()["decisions"][-1]["reason"]
                == "saturated")
        d.set_offered_load(None)


class TestRoutezEndpoint:
    def test_routez_serves_active_driver(self):
        from gatekeeper_tpu.obs.debug import get_router

        c = make_client()
        d = c.driver
        d.route_ledger.clear()
        d._route_eval(77)
        code, ctype, body = get_router().handle("/debug/routez", "limit=5")
        assert code == 200 and ctype == "application/json"
        payload = json.loads(body)
        assert payload["decisions"][-1]["cells"] == 77
        assert payload["calibration"] is None
        # calibration + curves appear once calibrated
        d._route_cal = {
            "rtt_ms": 5.0, "device_cells_per_ms": 100.0,
            "interp_cells_per_ms": 10.0,
        }
        payload = json.loads(get_router().handle("/debug/routez")[2])
        assert payload["calibration"]["rtt_ms"] == 5.0
        assert "curves_ms_per_review" in payload

    @pytest.mark.parametrize("query", ["limit=abc", "limit=-2",
                                       "limit=1.5"])
    def test_bad_params_are_json_400(self, query):
        from gatekeeper_tpu.obs.debug import get_router

        code, ctype, body = get_router().handle("/debug/routez", query)
        assert code == 400 and ctype == "application/json"
        assert "must be" in json.loads(body)["error"]
