"""State snapshot & warm resume (gatekeeper_tpu/snapshot/, ISSUE 3).

Covers the round trip (write -> restart -> restore -> first sweep equals
the cold sweep), the delta resync (only churned rows re-pack; deletions
tombstone; additions appear), every validation failure falling back to
the cold path with the outcome metric recorded, retention pruning, and
the malformed-constraint-spec tolerance satellite.
"""

import json
import os
import threading

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.metrics.views import global_registry
from gatekeeper_tpu.ops.auditpack import AuditPackCache
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.snapshot import SnapshotLoader, Snapshotter
from gatekeeper_tpu.snapshot import format as snapfmt

from .test_controllers import CONSTRAINT, TEMPLATE


def ns_obj(name, labeled):
    labels = {"team": name}
    if labeled:
        labels["gatekeeper"] = "yes"
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": name, "labels": labels},
    }


def build_cluster(n=12, labeled_every=2):
    """InMemoryKube with n Namespaces (RV-stamped), every `labeled_every`-th
    compliant."""
    kube = InMemoryKube()
    for i in range(n):
        kube.create(ns_obj(f"ns-{i:03d}", labeled=i % labeled_every == 0))
    return kube


def fresh_client(mesh_width=None):
    """TPU client pinned to a known sweep sharding: single-device by
    default so the basis round-trip is deterministic; pass mesh_width to
    exercise the sharded sweep (the conftest provisions 8 virtual CPU
    devices).  set_mesh also invalidates every topology-keyed cache, so
    each test starts from a clean placement."""
    client = Client(driver=TpuDriver())
    client.driver.set_mesh(mesh_width is not None, width=mesh_width)
    return client


def make_client(kube):
    client = fresh_client()
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    for obj in kube.list(("", "v1", "Namespace")):
        client.add_data(obj)
    return client


def make_client_mesh(kube, width):
    client = fresh_client(mesh_width=width)
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    for obj in kube.list(("", "v1", "Namespace")):
        client.add_data(obj)
    return client


def audit_sig(client):
    res, totals = client.audit_capped(20)
    sig = sorted(
        ((r.resource or {}).get("metadata", {}).get("name", ""), r.msg)
        for r in res.results()
    )
    return sig, totals


def outcome_counts():
    rows = global_registry().view_rows("snapshot_restore_outcome_total")
    return {k[0]: v for k, v in rows.items()}


@pytest.fixture()
def snap_dir(tmp_path):
    return str(tmp_path / "snapshots")


class TestRoundTrip:
    def test_warm_resume_equals_cold_and_skips_repack(self, snap_dir):
        kube = build_cluster(n=12)
        client1 = make_client(kube)
        cold_sig, _ = audit_sig(client1)
        assert cold_sig  # the corpus violates

        snapper = Snapshotter(client1, snap_dir, interval_s=0.0)
        path = snapper.write_once()
        assert path is not None and os.path.isdir(path)
        assert snapfmt.list_snapshots(snap_dir) == [os.path.basename(path)]
        # payload dirs are 0700 (seal trust model)
        assert os.stat(snap_dir).st_mode & 0o777 == 0o700

        # "restart": a fresh client restores and delta-resyncs
        client2 = fresh_client()
        loader = SnapshotLoader(snap_dir)
        packs, rebuilds = _instrument(client2.driver)
        outcome = loader.restore(client2, kube)
        assert outcome == "restored"
        assert loader.stats == {
            "matched": 12, "changed": 0, "added": 0, "deleted": 0,
        }
        assert loader.delta_restored is True
        warm_sig, _ = audit_sig(client2)
        assert warm_sig == cold_sig
        # the whole point: no full rebuild, no per-row re-pack, and with
        # zero churn the restored delta basis serves the sweep without
        # any full [C, R] device dispatch
        assert rebuilds() == 0
        assert packs() == 0
        assert client2.driver.last_sweep_stats.get("cached") == 1.0
        # lazily-adopted leaves still serve every store surface: frozen()
        # freezes them on first call (a later inventory-reading template
        # install), and hashing the result must not raise
        frozen = client2.driver.store.frozen()
        hash(frozen["cluster"]["v1"]["Namespace"]["ns-000"])
        ns = client2.driver.store.cached_namespace("ns-000")
        assert ns is None or isinstance(ns, dict)

    def test_delta_basis_dropped_on_mesh_width_drift(self, snap_dir):
        """A basis persisted under one sweep sharding layout must not
        serve a process whose mesh width differs: the restore keeps the
        pack (still 'restored') but drops the basis, and the first sweep
        is a full dispatch that rebases — with identical verdicts."""
        kube = build_cluster(n=12)
        client1 = make_client(kube)
        cold_sig, _ = audit_sig(client1)  # single-device basis (width 1)

        snapper = Snapshotter(client1, snap_dir, interval_s=0.0)
        assert snapper.write_once() is not None

        # same width restores the basis...
        same = fresh_client()
        loader = SnapshotLoader(snap_dir)
        assert loader.restore(same, kube) == "restored"
        assert loader.delta_restored is True

        # ...a width-4 mesh process drops it (width drift) but keeps the
        # restored pack and produces identical verdicts via a full sweep
        drifted = fresh_client(mesh_width=4)
        loader2 = SnapshotLoader(snap_dir)
        assert loader2.restore(drifted, kube) == "restored"
        assert loader2.delta_restored is False
        assert drifted.driver._delta_state is None
        warm_sig, _ = audit_sig(drifted)
        assert warm_sig == cold_sig
        assert drifted.driver.last_sweep_stats.get("cached") != 1.0

    def test_delta_basis_roundtrips_under_same_mesh_width(self, snap_dir):
        """Writer persists the mesh layout: a width-4 process's basis
        restores into another width-4 process and the first sweep serves
        from it (no full dispatch)."""
        kube = build_cluster(n=12)
        client1 = make_client_mesh(kube, width=4)
        cold_sig, _ = audit_sig(client1)

        snapper = Snapshotter(client1, snap_dir, interval_s=0.0)
        assert snapper.write_once() is not None

        client2 = fresh_client(mesh_width=4)
        loader = SnapshotLoader(snap_dir)
        assert loader.restore(client2, kube) == "restored"
        assert loader.delta_restored is True
        warm_sig, _ = audit_sig(client2)
        assert warm_sig == cold_sig
        assert client2.driver.last_sweep_stats.get("cached") == 1.0
        # churn after the restore rides the O(churn) delta path AGAINST
        # the restored (now mesh-committed) base mask — one dirty row
        # dispatched, not a full [C, R] resweep
        flipped = kube.get(("", "v1", "Namespace"), "ns-000")
        flipped["metadata"]["labels"].pop("gatekeeper", None)
        kube.update(flipped)
        client2.add_data(kube.get(("", "v1", "Namespace"), "ns-000"))
        churn_sig, _ = audit_sig(client2)
        assert client2.driver.last_sweep_stats.get("delta_rows") == 1.0
        ref = make_client_mesh(kube, width=4)
        ref_sig, _ = audit_sig(ref)
        assert churn_sig == ref_sig

    def test_delta_resync_packs_only_churn(self, snap_dir):
        kube = build_cluster(n=10)
        client1 = make_client(kube)
        audit_sig(client1)
        assert Snapshotter(client1, snap_dir).write_once() is not None

        # churn while "down": flip one compliant ns to violating, delete
        # one violating ns, add one new violating ns
        gvk = ("", "v1", "Namespace")
        flipped = kube.get(gvk, "ns-000")
        del flipped["metadata"]["labels"]["gatekeeper"]
        kube.update(flipped)
        kube.delete(gvk, "ns-001")
        kube.create(ns_obj("ns-new", labeled=False))

        client2 = fresh_client()
        loader = SnapshotLoader(snap_dir)
        packs, rebuilds = _instrument(client2.driver)
        assert loader.restore(client2, kube) == "restored"
        assert loader.stats == {
            "matched": 8, "changed": 1, "added": 1, "deleted": 1,
        }
        assert loader.delta_restored is True
        warm_sig, _ = audit_sig(client2)
        # the churned rows went through the O(churn) delta dispatch, not
        # a full sweep (changed + added + tombstoned = 3 dirty rows)
        assert client2.driver.last_sweep_stats.get("delta_rows") == 3.0
        # equal to a from-scratch evaluation of the churned cluster
        oracle = make_client(kube)
        cold_sig, _ = audit_sig(oracle)
        assert warm_sig == cold_sig
        names = [n for n, _ in warm_sig]
        assert "ns-000" in names and "ns-new" in names
        assert "ns-001" not in names
        assert rebuilds() == 0
        assert packs() == 2  # the flipped + the added row only

    def test_writer_skips_when_store_ahead_of_pack(self, snap_dir):
        kube = build_cluster(n=4)
        client = make_client(kube)
        audit_sig(client)
        kube.create(ns_obj("ns-late", labeled=False))
        client.add_data(kube.get(("", "v1", "Namespace"), "ns-late"))
        snapper = Snapshotter(client, snap_dir, capture_delta=False)
        assert snapper.write_once() is None
        assert "ahead of pack" in (snapper.last_error or "")
        audit_sig(client)  # sweep re-syncs the pack
        assert snapper.write_once() is not None

    def test_retention_prunes_old_snapshots(self, snap_dir):
        kube = build_cluster(n=3)
        client = make_client(kube)
        audit_sig(client)
        snapper = Snapshotter(client, snap_dir, retain=2,
                              capture_delta=False)
        paths = []
        for _ in range(4):
            snapper._last_write = 0.0  # defeat the cadence for the test
            p = snapper.write_once()
            assert p is not None
            paths.append(os.path.basename(p))
        names = snapfmt.list_snapshots(snap_dir)
        assert len(names) == 2
        assert names[0] == paths[-1]

    def test_restore_spans_visible_in_debug_traces(self, snap_dir):
        from gatekeeper_tpu.obs import trace as obstrace

        kube = build_cluster(n=4)
        client1 = make_client(kube)
        audit_sig(client1)
        snapper = Snapshotter(client1, snap_dir, capture_delta=False)
        assert snapper.write_once() is not None
        client2 = fresh_client()
        assert SnapshotLoader(snap_dir).restore(client2, kube) == "restored"
        traces = json.loads(obstrace.traces_json())["traces"]
        restore = [t for t in traces if t.get("root") == "snapshot.restore"]
        assert restore, "snapshot.restore trace missing from /debug/traces"
        names = {s.get("name") for s in restore[0].get("spans", [])}
        assert {"snapshot.load", "snapshot.install",
                "snapshot.resync"} <= names

    def test_no_snapshot_means_cold_outcome_none(self, snap_dir):
        kube = build_cluster(n=2)
        client = fresh_client()
        before = outcome_counts().get("none", 0)
        assert SnapshotLoader(snap_dir).restore(client, kube) == "none"
        assert outcome_counts().get("none", 0) == before + 1


def _instrument(driver):
    """Counters for per-row re-packs and full rebuilds on a driver's
    audit pack (class-level methods wrapped per-instance)."""
    state = {"packs": 0, "rebuilds": 0}
    ap = driver._audit_pack
    orig_pack = AuditPackCache._pack_row
    orig_rebuild = AuditPackCache._rebuild

    def pack_row(self, *a, **k):
        if self is driver._audit_pack:
            state["packs"] += 1
        return orig_pack(self, *a, **k)

    def rebuild(self, *a, **k):
        if self is driver._audit_pack:
            state["rebuilds"] += 1
        return orig_rebuild(self, *a, **k)

    ap.__class__._pack_row = pack_row
    ap.__class__._rebuild = rebuild
    return (lambda: state["packs"]), (lambda: state["rebuilds"])


@pytest.fixture(autouse=True)
def _restore_auditpack_methods():
    orig_pack = AuditPackCache._pack_row
    orig_rebuild = AuditPackCache._rebuild
    yield
    AuditPackCache._pack_row = orig_pack
    AuditPackCache._rebuild = orig_rebuild


class TestValidationFallback:
    def _snapshot(self, snap_dir, n=6):
        kube = build_cluster(n=n)
        client = make_client(kube)
        sig, _ = audit_sig(client)
        snapper = Snapshotter(client, snap_dir, capture_delta=False)
        assert snapper.write_once() is not None
        return kube, sig

    def _assert_fallback_then_cold_ok(self, snap_dir, kube, cold_sig):
        before = outcome_counts().get("fallback", 0)
        client = fresh_client()
        outcome = SnapshotLoader(snap_dir).restore(client, kube)
        assert outcome == "fallback"
        assert outcome_counts().get("fallback", 0) == before + 1
        # the cold path still serves correct results
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
        for obj in kube.list(("", "v1", "Namespace")):
            client.add_data(obj)
        sig, _ = audit_sig(client)
        assert sig == cold_sig

    def test_corrupt_manifest_falls_back(self, snap_dir):
        kube, sig = self._snapshot(snap_dir)
        snap = os.path.join(snap_dir, snapfmt.list_snapshots(snap_dir)[0])
        mpath = os.path.join(snap, snapfmt.MANIFEST)
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["schema"] = 999  # content change breaks the hmac too
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        self._assert_fallback_then_cold_ok(snap_dir, kube, sig)

    def test_wrong_hmac_falls_back(self, snap_dir):
        kube, sig = self._snapshot(snap_dir)
        snap = os.path.join(snap_dir, snapfmt.list_snapshots(snap_dir)[0])
        mpath = os.path.join(snap, snapfmt.MANIFEST)
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["hmac"] = "0" * 64
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        self._assert_fallback_then_cold_ok(snap_dir, kube, sig)

    def test_truncated_array_falls_back(self, snap_dir):
        kube, sig = self._snapshot(snap_dir)
        snap = os.path.join(snap_dir, snapfmt.list_snapshots(snap_dir)[0])
        apath = os.path.join(snap, snapfmt.ARRAYS)
        blob = open(apath, "rb").read()
        with open(apath, "wb") as f:
            f.write(blob[: len(blob) // 2])
        self._assert_fallback_then_cold_ok(snap_dir, kube, sig)

    def test_tampered_payload_fails_checksum(self, snap_dir):
        kube, sig = self._snapshot(snap_dir)
        snap = os.path.join(snap_dir, snapfmt.list_snapshots(snap_dir)[0])
        ipath = os.path.join(snap, snapfmt.INTERNER)
        strings = json.load(open(ipath))
        with open(ipath, "w") as f:
            json.dump(strings + ["evil"], f)
        self._assert_fallback_then_cold_ok(snap_dir, kube, sig)

    def test_fully_stale_resource_versions_fall_back(self, snap_dir):
        kube, _sig = self._snapshot(snap_dir)
        # every object re-written while down: all recorded RVs stale
        gvk = ("", "v1", "Namespace")
        for obj in kube.list(gvk):
            obj["metadata"]["labels"]["touched"] = "yes"
            kube.update(obj)
        before = outcome_counts().get("fallback", 0)
        client = fresh_client()
        loader = SnapshotLoader(snap_dir)
        outcome = loader.restore(client, kube)
        assert outcome == "fallback"
        assert loader.stats["matched"] == 0
        assert outcome_counts().get("fallback", 0) == before + 1
        # safe degradation: every row re-packs and the sweep is correct
        warm_sig, _ = audit_sig(client)
        oracle = make_client(kube)
        cold_sig, _ = audit_sig(oracle)
        assert warm_sig == cold_sig

    def test_older_snapshot_used_when_newest_corrupt(self, snap_dir):
        kube, sig = self._snapshot(snap_dir)
        client1 = make_client(kube)
        audit_sig(client1)
        snapper = Snapshotter(client1, snap_dir, capture_delta=False)
        snapper._last_write = 0.0
        newest = snapper.write_once()
        assert newest is not None
        # corrupt only the newest; the older one must restore
        with open(os.path.join(newest, snapfmt.ARRAYS), "ab") as f:
            f.write(b"garbage")
        client2 = fresh_client()
        outcome = SnapshotLoader(snap_dir).restore(client2, kube)
        assert outcome == "restored"
        warm_sig, _ = audit_sig(client2)
        assert warm_sig == sig


class TestStoreDeltaSemantics:
    def test_put_dedups_same_resource_version(self):
        client = fresh_client()
        store = client.driver.store
        obj = ns_obj("ns-a", labeled=True)
        obj["metadata"]["resourceVersion"] = "41"
        client.add_data(obj)
        epoch = store.epoch
        client.add_data(json.loads(json.dumps(obj)))  # replayed list entry
        assert store.epoch == epoch  # no change-log spam
        obj2 = json.loads(json.dumps(obj))
        obj2["metadata"]["resourceVersion"] = "42"
        client.add_data(obj2)
        assert store.epoch == epoch + 1

    def test_put_dedups_equal_content_without_rv(self):
        client = fresh_client()
        store = client.driver.store
        obj = ns_obj("ns-b", labeled=False)
        client.add_data(obj)
        epoch = store.epoch
        client.add_data(json.loads(json.dumps(obj)))
        assert store.epoch == epoch
        changed = ns_obj("ns-b", labeled=True)
        client.add_data(changed)
        assert store.epoch == epoch + 1


class TestMalformedConstraintSpec:
    """Satellite: non-dict spec tolerance across review/audit paths
    (mirrors target/match.py _get): one malformed constraint must not
    break every interp-path review."""

    REVIEW = {
        "uid": "u1",
        "kind": {"group": "", "version": "v1", "kind": "Namespace"},
        "name": "ns-x",
        "namespace": "",
        "operation": "CREATE",
        "userInfo": {"username": "t"},
        "object": ns_obj("ns-x", labeled=False),
    }

    @pytest.mark.parametrize("bad_spec", ["junk", ["junk"], 7, None])
    def test_review_survives_malformed_spec(self, bad_spec):
        client = fresh_client()
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
        bad = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": "malformed"},
            "spec": bad_spec,
        }
        # bypass CRD validation, as a raw store write would
        client.driver.put_constraint("K8sRequiredLabels", "malformed", bad)
        res = client.review(dict(self.REVIEW))
        # the healthy constraint still evaluated and still denies
        names = {
            (r.constraint.get("metadata") or {}).get("name")
            for r in res.results()
        }
        assert "ns-must-have-gk" in names

    @pytest.mark.parametrize("bad_spec", ["junk", ["junk"]])
    def test_audit_survives_malformed_spec(self, bad_spec):
        kube = build_cluster(n=4)
        client = make_client(kube)
        bad = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": "malformed"},
            "spec": bad_spec,
        }
        client.driver.put_constraint("K8sRequiredLabels", "malformed", bad)
        sig, _ = audit_sig(client)
        assert sig  # healthy constraint still reports violations


class TestWebhookIdempotentStart:
    def test_double_start_does_not_leak_gc_sweeper(self):
        from gatekeeper_tpu.webhook import NamespaceLabelHandler
        from gatekeeper_tpu.webhook.server import WebhookServer

        def handler(_req):  # never invoked
            raise AssertionError

        def sweepers():
            return [
                t for t in threading.enumerate()
                if t.name == "webhook-gc" and t.is_alive()
            ]

        srv = WebhookServer(
            handler, NamespaceLabelHandler([]), port=0,
            certfile=None, keyfile=None,
        )
        baseline = len(sweepers())
        srv.start()
        first_server = srv._server
        try:
            first = [t for t in sweepers()]
            assert len(first) == baseline + 1
            srv.start()  # double start: old sweeper + listener replaced
            assert srv._server is not first_server
            for t in first:
                t.join(timeout=10.0)
            assert len(sweepers()) == baseline + 1
        finally:
            srv.stop()
            for t in sweepers():
                t.join(timeout=10.0)
            assert len(sweepers()) == baseline
