"""Cost-attribution ledger (gatekeeper_tpu/obs/costs.py): apportioning,
decaying windows, cardinality caps, concurrent recording, metric export,
and the driver feed (ISSUE 5)."""

import threading

import pytest

from gatekeeper_tpu.metrics import catalog
from gatekeeper_tpu.metrics.views import Registry
from gatekeeper_tpu.obs import costs as obscosts
from gatekeeper_tpu.obs.costs import OTHER, CostLedger


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_dispatch_apportioned_by_cells():
    ledger = CostLedger(clock=FakeClock())
    # T1 has 3 constraints, T2 has 1 -> 4 cells/row; 10 rows, 8ms device
    ledger.record_dispatch({"T1": 3, "T2": 1}, 0.008, 10)
    totals = ledger.totals_by_template()
    assert totals["T1"]["device_ms"] == pytest.approx(6.0)
    assert totals["T2"]["device_ms"] == pytest.approx(2.0)
    assert totals["T1"]["cells"] == 30
    assert totals["T2"]["cells"] == 10


def test_render_apportioned_and_tier_mix():
    ledger = CostLedger(clock=FakeClock())
    ledger.record_render(
        [
            ("T1", "c1", 3, "static", 2, 1),
            ("T2", "c2", 1, "interp", 0, 0),
        ],
        plan_s=0.002, interp_s=0.002,
    )
    totals = ledger.totals_by_template()
    assert totals["T1"]["render_ms"] == pytest.approx(3.0)
    assert totals["T2"]["render_ms"] == pytest.approx(1.0)
    assert totals["T1"]["tier_mix"] == {"static": 3, "slots": 0, "interp": 0}
    assert totals["T2"]["tier_mix"] == {"static": 0, "slots": 0, "interp": 1}
    assert totals["T1"]["violations"] == 2
    assert totals["T1"]["memo_hits"] == 1


def test_window_decays_but_totals_persist():
    clock = FakeClock()
    ledger = CostLedger(window_s=300.0, bucket_s=30.0, clock=clock)
    ledger.record_dispatch({"T1": 1}, 0.004, 10)
    snap = ledger.snapshot()
    assert snap["templates"][0]["device_ms"] == pytest.approx(4.0)
    clock.advance(400.0)  # past the 5m window
    snap = ledger.snapshot()
    assert snap["templates"] == []  # window drained
    assert snap["totals"]["device_ms"] == pytest.approx(4.0)  # cumulative
    # fresh traffic repopulates the window
    ledger.record_dispatch({"T1": 1}, 0.002, 5)
    snap = ledger.snapshot()
    assert snap["templates"][0]["device_ms"] == pytest.approx(2.0)
    assert snap["totals"]["device_ms"] == pytest.approx(6.0)


def test_top_k_and_other_rollup():
    ledger = CostLedger(top_k=2, clock=FakeClock())
    # descending cost so the ranking is deterministic
    for i, ms in enumerate((0.008, 0.006, 0.004, 0.002)):
        ledger.record_dispatch({f"T{i}": 1}, ms, 10)
    snap = ledger.snapshot()  # default top = top_k = 2
    assert [t["template"] for t in snap["templates"]] == ["T0", "T1"]
    assert snap["other"]["device_ms"] == pytest.approx(6.0)  # T2 + T3
    assert snap["other"]["cells"] == 20
    # explicit ?top= widens the head
    snap = ledger.snapshot(top=3)
    assert [t["template"] for t in snap["templates"]] == ["T0", "T1", "T2"]
    assert snap["other"]["device_ms"] == pytest.approx(2.0)


def test_max_tracked_folds_into_other():
    ledger = CostLedger(top_k=2, max_tracked=3, clock=FakeClock())
    for i in range(10):
        ledger.record_dispatch({f"T{i}": 1}, 0.001, 1)
    totals = ledger.totals_by_template()
    # 3 tracked keys + the other bucket; cost is conserved
    assert len(totals) == 4 and OTHER in totals
    assert sum(t["device_ms"] for t in totals.values()) == pytest.approx(10.0)
    assert ledger.snapshot()["dropped_keys"] == 7


def test_concurrent_records_conserve_cost():
    """Thread-pounding: N threads recording dispatch+render concurrently
    must neither crash nor lose cost."""
    ledger = CostLedger(clock=FakeClock())
    threads, per_thread = 8, 200
    errors = []

    def pound(tid):
        try:
            for i in range(per_thread):
                ledger.record_dispatch({f"T{tid}": 2, "shared": 1}, 0.003, 4)
                ledger.record_render(
                    [(f"T{tid}", "c", 2, "slots", 1, 0)], 0.001, 0.0
                )
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    ts = [
        threading.Thread(target=pound, args=(t,)) for t in range(threads)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    totals = ledger.totals_by_template()
    total_device = sum(t["device_ms"] for t in totals.values())
    total_render = sum(t["render_ms"] for t in totals.values())
    assert total_device == pytest.approx(threads * per_thread * 3.0, rel=1e-6)
    assert total_render == pytest.approx(threads * per_thread * 1.0, rel=1e-6)
    assert totals["shared"]["cells"] == threads * per_thread * 4
    total_v = sum(t["violations"] for t in totals.values())
    assert total_v == threads * per_thread


def test_collect_exports_capped_gauges_and_retracts():
    ledger = CostLedger(top_k=2, clock=FakeClock())
    for i, ms in enumerate((0.008, 0.006, 0.004)):
        ledger.record_dispatch({f"T{i}": 1}, ms, 10)
    reg = Registry()
    ledger.collect(reg)
    rows = reg.view_rows("cost_device_ms")
    assert set(rows) == {("T0",), ("T1",), (OTHER,)}
    assert rows[("T0",)] == pytest.approx(8.0)
    assert rows[(OTHER,)] == pytest.approx(4.0)
    # tier-mix rows carry both labels
    rc = reg.view_rows("cost_render_cells")
    assert ("T0", "static") in rc
    # a template leaving the export set is retracted to 0, not left stale
    ledger.clear()
    ledger.record_dispatch({"TX": 1}, 0.002, 10)
    ledger.collect(reg)
    rows = reg.view_rows("cost_device_ms")
    assert rows[("TX",)] == pytest.approx(2.0)
    assert rows[("T0",)] == 0.0 and rows[("T1",)] == 0.0


def test_disabled_ledger_records_nothing():
    ledger = CostLedger(clock=FakeClock())
    ledger.enabled = False
    ledger.record_dispatch({"T1": 1}, 0.004, 10)
    ledger.record_render([("T1", "c", 1, "static", 1, 0)], 0.001, 0.0)
    assert ledger.totals_by_template() == {}


def test_driver_feeds_ledger_end_to_end():
    """A violating review through the TPU driver lands attributed
    device-ms, cells, tier mix and violations in the global ledger."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    from .test_controllers import CONSTRAINT, TEMPLATE

    ledger = obscosts.get_ledger()
    was_enabled = ledger.enabled
    ledger.clear()
    ledger.enabled = True
    try:
        driver = TpuDriver()
        driver.DEVICE_MIN_CELLS = 0  # force the device path
        driver.mesh_enabled = False
        c = Client(driver=driver)
        c.add_template(TEMPLATE)
        c.add_constraint(CONSTRAINT)
        review = {
            "uid": "u1",
            "kind": {"group": "", "version": "v1", "kind": "Namespace"},
            "name": "bad", "namespace": "", "operation": "CREATE",
            "userInfo": {"username": "alice"},
            "object": {"apiVersion": "v1", "kind": "Namespace",
                       "metadata": {"name": "bad", "labels": {}}},
        }
        resp = c.review(review)
        assert len(resp.results()) == 1
        totals = ledger.totals_by_template()
        row = totals["K8sRequiredLabels"]
        assert row["device_ms"] > 0.0
        assert row["cells"] >= 1
        assert row["render_cells"] >= 1
        assert row["violations"] >= 1
        assert sum(row["tier_mix"].values()) == row["render_cells"]
        # the capped audit sweep (the AuditManager's default path)
        # attributes dispatch and render too
        c.add_data({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "bad-ns", "labels": {}},
        })
        ledger.clear()
        responses, totals_by_key = c.audit_capped(20)
        assert totals_by_key
        totals = ledger.totals_by_template()
        row = totals["K8sRequiredLabels"]
        assert row["device_ms"] > 0.0
        assert row["violations"] >= 1
    finally:
        ledger.clear()
        ledger.enabled = was_enabled


def test_catalog_declares_cost_views_capped():
    for name in catalog.CAPPED_CARDINALITY_VIEWS:
        assert any(v.name == name for v in catalog.catalog_views())
    for v in catalog.catalog_views():
        if {"template", "constraint"} & set(v.tag_keys):
            assert v.name in catalog.CAPPED_CARDINALITY_VIEWS
