"""Tier-1 wiring for tools/check_fleet_parity.py: three replica
processes restore one sealed snapshot; identical requests must produce
byte-identical AdmissionReview bodies on every replica (and through the
front door), with verdicts AND rendered violation text matching the
interpreter oracle.  Skips cleanly where subprocess spawn is
unavailable."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import check_fleet_parity as chk  # noqa: E402

from .test_snapshot_concurrent import spawn_available


@spawn_available
def test_repo_fleet_is_conformant():
    # edge="both" drives the corpus through the threaded door AND the
    # ISSUE 19 event-loop door against one spawned fleet: byte parity,
    # attribution, and oracle conformance must hold on both edges
    assert chk.run_checks() == []


def test_detector_flags_replica_divergence():
    """A replica whose restore drifted must be detected."""
    good = b'{"response": {"uid": "u", "allowed": true}}'
    drifted = b'{"response": {"uid": "u", "allowed": false, ' \
              b'"status": {"message": "[denied by x] nope", "code": 403}}}'
    problems = chk.diff_verdicts(
        {"solo": [good], "r0": [good], "r1": [drifted]},
        [(True, [])],
    )
    assert problems and "diverge" in problems[0]


def test_detector_flags_oracle_divergence():
    allow = b'{"response": {"uid": "u", "allowed": true}}'
    problems = chk.diff_verdicts(
        {"solo": [allow], "r0": [allow]},
        [(False, ["one", "two"])],  # the oracle denies with 2 violations
    )
    assert problems and "oracle" in problems[0]


def test_detector_flags_message_content_drift():
    """Right verdict, right count, WRONG rendered text: count-only
    parity would pass this; content parity must not."""
    deny = b'{"response": {"uid": "u", "allowed": false, ' \
           b'"status": {"message": "[denied by a] garbled", "code": 403}}}'
    problems = chk.diff_verdicts(
        {"solo": [deny], "r0": [deny]},
        [(False, ["one"])],
    )
    assert problems and "rendered" in problems[0]


def test_detector_accepts_prefix_stripped_match():
    deny = b'{"response": {"uid": "u", "allowed": false, ' \
           b'"status": {"message": "[denied by a] one\\n' \
           b'[denied by b] two", "code": 403}}}'
    assert chk.diff_verdicts(
        {"solo": [deny], "r0": [deny]},
        [(False, ["one", "two"])],
    ) == []
