"""Device-side capped-audit compaction (VERDICT r2 #1).

The capped audit's per-constraint reduction happens on-device: only [C]
violation-candidate counts + [C, K] first-K candidate row indices cross back
to the host per sweep (reference cap contract pkg/audit/manager.go:49), with
a per-constraint fallback row fetch when the prefetched candidates render
short of the cap.  Steady-state host<->device traffic must be KBs, not the
full [C, R] mask.
"""

import numpy as np
import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


def _loaded(driver, n_templates=6, n_pods=120, violation_rate=0.5, seed=7):
    templates, constraints = make_templates(n_templates)
    c = Client(driver=driver)
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    for p in make_pods(n_pods, seed=seed, violation_rate=violation_rate):
        c.add_data(p)
    return c


def _keys(results):
    return sorted(
        (r.constraint["kind"], r.constraint["metadata"]["name"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in results
    )


def _per_constraint(results):
    per = {}
    for r in results:
        kk = (r.constraint["kind"], r.constraint["metadata"]["name"])
        per[kk] = per.get(kk, 0) + 1
    return per


def test_sweep_fetch_is_small():
    """The per-sweep device->host transfer must be the packed [C, 1+K]
    int32 array, not the [C, R] mask."""
    ct = _loaded(TpuDriver(), n_pods=300)
    cap = 5
    ct.audit_capped(cap)
    stats = ct.driver.last_sweep_stats
    K = ct.driver._audit_topk(cap)
    n_c = len(ct.driver._ordered_constraints())
    # C axis may be bucketed; the fetch is at most bucket(C) * (1+K) * 4B
    assert 0 < stats["fetch_bytes"] <= 2 * n_c * (1 + K) * 4
    assert stats["fallback_rows"] == 0, (
        "synthetic corpus candidates are tight; no fallback expected"
    )


def test_steady_state_sweep_is_cached():
    ct = _loaded(TpuDriver())
    ct.audit_capped(5)
    first = dict(ct.driver.last_sweep_stats)
    assert "cached" not in first
    ct.audit_capped(5)
    assert ct.driver.last_sweep_stats.get("cached") == 1.0


def test_count_exact_totals_past_cap():
    """For count-exact programs (single non-iterating exact clause, no
    label selectors) the capped total must equal the interpreter's exact
    violation count, reported as "exact" even past the cap."""
    ct = _loaded(TpuDriver(), n_templates=1, n_pods=200)  # labelreq family
    ci = _loaded(InterpDriver(), n_templates=1, n_pods=200)
    exact_per = _per_constraint(ci.audit().results())
    assert exact_per, "workload must violate"
    (kk, n_exact), = exact_per.items()
    assert n_exact > 3
    _res, totals = ct.audit_capped(3)
    n, how = totals[kk]
    assert how == "exact" and n == n_exact, (totals, exact_per)


def test_fallback_row_fetch_when_program_missing():
    """A template with no vectorized program gets an all-true candidate
    column; when the cap is not reached from the prefetched candidates the
    walk must fall back to that ONE constraint's full row and still produce
    exact results."""
    ct = _loaded(TpuDriver(), n_templates=1, n_pods=200, violation_rate=0.1)
    ci = _loaded(InterpDriver(), n_templates=1, n_pods=200,
                 violation_rate=0.1)
    drv = ct.driver
    kind = next(iter(drv.templates))
    with drv._lock:
        drv.programs[kind] = None  # simulate an unvectorizable template
        drv._cs_epoch += 1
    # cap chosen so it is never reached (~8 violations at rate 0.1) while
    # K = 2*cap = 128 < the 200 all-true candidates: the walk must page in
    # the rest of the row to prove the cap is unreachable
    cap = 50
    assert ct.driver._audit_topk(cap) < 200
    res, totals = ct.audit_capped(cap)
    res_i, totals_i = ci.audit_capped(cap)
    assert _keys(res.results()) == _keys(res_i.results())
    assert totals == totals_i
    stats = drv.last_sweep_stats
    # all-true column: far more candidates than the prefetched K
    assert stats["fallback_rows"] == 1
    assert stats["fallback_bytes"] > 0


def test_fallback_capped_totals_are_resources():
    """Same no-program setup but with the cap hit mid-walk: totals must be
    flagged "resources" (candidate cells, not violations) and the kept
    results must match the interpreter's count per constraint."""
    ct = _loaded(TpuDriver(), n_templates=1, n_pods=200, violation_rate=0.9)
    drv = ct.driver
    kind = next(iter(drv.templates))
    with drv._lock:
        drv.programs[kind] = None
        drv._cs_epoch += 1
    res, totals = ct.audit_capped(2)
    (kk, (n, how)), = totals.items()
    assert how == "resources"
    assert n >= 200  # every row is a candidate under the all-true column
    per = _per_constraint(res.results())
    assert all(v <= 2 + 1 for v in per.values())


def test_incremental_scatter_matches_full_upload():
    """Steady-state device-input updates go through the jitted dirty-row
    scatter; the resulting masks must be bit-identical to a fresh full
    upload of the same pack."""
    ct = _loaded(TpuDriver(), n_pods=150)
    drv = ct.driver
    drv.mesh_enabled = False
    drv._mesh_cache = None
    drv.delta_enabled = False  # force the full-dispatch scatter path
    ct.audit_capped(5)
    # mutate: one new violating pod, one changed pod, one delete
    pods = make_pods(150, seed=7, violation_rate=0.5)
    newp = make_pods(1, seed=99, violation_rate=1.0)[0]
    newp["metadata"]["name"] = "delta-new"
    ct.add_data(newp)
    changed = dict(pods[3])
    changed["metadata"] = dict(changed["metadata"])
    changed["metadata"]["labels"] = {}  # now violates labelreq
    ct.add_data(changed)
    ct.remove_data(pods[5])
    _res, _totals = ct.audit_capped(5)  # scatter path
    scattered = np.asarray(drv._audit_cache[1][2].get())  # base mask
    counts_s = drv._audit_cache[1][3].copy()
    # force a full re-upload of the identical pack and re-dispatch
    drv._audit_dev = None
    drv._audit_cache = None
    _res2, _totals2 = ct.audit_capped(5)
    fresh = np.asarray(drv._audit_cache[1][2].get())
    counts_f = drv._audit_cache[1][3]
    assert (scattered == fresh).all()
    assert (counts_s == counts_f).all()


def test_uncapped_audit_reuses_sweep_and_matches_interp():
    """audit() fetches the full mask from the device-resident sweep output
    (once per epoch) and must agree with the interpreter."""
    ct = _loaded(TpuDriver())
    ci = _loaded(InterpDriver())
    ct.audit_capped(5)  # populates the sweep cache
    a_t = sorted((r.constraint["metadata"]["name"], r.msg)
                 for r in ct.audit().results())
    a_i = sorted((r.constraint["metadata"]["name"], r.msg)
                 for r in ci.audit().results())
    assert a_t == a_i
    # the uncapped path must NOT have re-dispatched
    assert ct.driver.last_sweep_stats.get("cached") == 1.0


@pytest.mark.parametrize("mesh", [False, True])
def test_counts_and_topk_parity_across_mesh(mesh):
    """The on-device reduction (counts + first-K indices) must be
    bit-identical on the single-device and 8-virtual-device paths."""
    import jax

    if mesh and len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    ct = _loaded(TpuDriver(), n_pods=100)
    ct.driver.mesh_enabled = mesh
    ct.driver._mesh_cache = None
    ct.audit_capped(5)
    sweep = ct.driver._audit_cache[1]
    counts, topk = sweep[3], sweep[4]
    if not hasattr(test_counts_and_topk_parity_across_mesh, "_ref"):
        test_counts_and_topk_parity_across_mesh._ref = (counts, topk)
    else:
        rc, rt = test_counts_and_topk_parity_across_mesh._ref
        assert (counts == rc).all()
        assert (topk == rt).all()
