"""Chaos suite: webhook + audit + watch driven under seeded fault
schedules (gatekeeper_tpu/faults/), asserting the degradation ladder of
docs/failure-modes.md:

  - the TPU circuit breaker trips after N injected dispatch failures,
    serves interpreter-identical verdicts while open, and returns to the
    device after recovery probes succeed
  - no admission request exceeds its deadline budget by more than one
    batch window under injected hangs — exhaustion is an explicit
    fail-open/closed decision, never a socket timeout
  - the audit loop survives a full kube outage (every HTTP send fails)
    and resumes, with the failure streak visible in metrics
  - the watch pump survives injected delivery faults

Everything is deterministic: fixed seeds, probability-1/count-limited
schedules, and bounded waits (hangs are plane-released).  The suite runs
inside the tier-1 `-m 'not slow'` selection; the conftest leak fixture
fails any test that leaves the plane enabled.
"""

import json
import queue
import threading
import time

import pytest

from gatekeeper_tpu import deadline, faults
from gatekeeper_tpu.audit import AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.deadline import DeadlineExceeded
from gatekeeper_tpu.faults import FaultError, FaultPlane, FaultRule
from gatekeeper_tpu.kube.apiserver import KubeApiServer
from gatekeeper_tpu.kube.http_client import HttpKube
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.metrics import Reporters
from gatekeeper_tpu.metrics.views import Registry
from gatekeeper_tpu.ops.breaker import CLOSED, OPEN
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.target.target import AugmentedReview
from gatekeeper_tpu.watch.manager import WatchManager
from gatekeeper_tpu.webhook import BatcherStopped, MicroBatcher

from .test_controllers import CONSTRAINT, TEMPLATE

pytestmark = pytest.mark.chaos

SEED = 1234
PROBE_NAME = "gk-breaker-probe"


@pytest.fixture()
def fault_plane():
    plane = faults.install(seed=SEED)
    yield plane
    faults.uninstall()


def ns_review(name, labels=None):
    return {
        "uid": f"uid-{name}",
        "kind": {"group": "", "version": "v1", "kind": "Namespace"},
        "name": name,
        "namespace": "",
        "operation": "CREATE",
        "userInfo": {"username": "alice"},
        "object": {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": name, "labels": labels or {}},
        },
    }


def review_sig(responses):
    return sorted((r.msg, r.enforcement_action) for r in responses.results())


def tpu_client(threshold=3, cooldown=0.05):
    driver = TpuDriver(
        breaker_threshold=threshold, breaker_cooldown_s=cooldown
    )
    driver.DEVICE_MIN_CELLS = 0  # force the device path for unique content
    client = Client(driver=driver)
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    return client, driver


def interp_client():
    client = Client()
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    return client


def wait_until(cond, timeout_s=5.0, step_s=0.01):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(step_s)
    return cond()


class TestCircuitBreaker:
    def test_trips_serves_interp_identical_and_recovers(self, fault_plane):
        client, driver = tpu_client(threshold=3, cooldown=0.05)
        oracle = interp_client()

        dispatched = []  # review names per compute_masks call
        orig = driver.compute_masks

        def counting(reviews):
            dispatched.extend(r.get("name", "?") for r in reviews)
            return orig(reviews)

        driver.compute_masks = counting

        def traffic_dispatches():
            return [n for n in dispatched if n != PROBE_NAME]

        # healthy: device path serves and the breaker stays closed
        req = ns_review("warm")
        got = client.review(AugmentedReview(admission_request=req))
        want = oracle.review(AugmentedReview(admission_request=req))
        assert review_sig(got) == review_sig(want)
        assert driver.breaker.state == CLOSED
        assert traffic_dispatches(), "healthy review must hit the device"

        fault_plane.add(faults.TPU_DISPATCH, FaultRule(mode="error"))

        # N consecutive injected dispatch failures trip the breaker; each
        # failed batch STILL answers correctly (interpreter fallback)
        for i in range(3):
            req = ns_review(f"fail-{i}")
            got = client.review(AugmentedReview(admission_request=req))
            want = oracle.review(AugmentedReview(admission_request=req))
            assert review_sig(got) == review_sig(want)
        st = driver.breaker.status()
        assert st["state"] != "closed"
        assert st["trips"] >= 1

        # while degraded: traffic never reaches the device (background
        # probes may; they carry the probe review name) and every verdict
        # is interpreter-identical — deny and allow cases both
        n_before = len(traffic_dispatches())
        for i in range(4):
            labels = {"gatekeeper": "on"} if i % 2 else None
            req = ns_review(f"degraded-{i}", labels=labels)
            got = client.review(AugmentedReview(admission_request=req))
            want = oracle.review(AugmentedReview(admission_request=req))
            assert review_sig(got) == review_sig(want)
            if labels:
                assert review_sig(got) == []
            else:
                assert len(review_sig(got)) == 1
        assert len(traffic_dispatches()) == n_before, (
            "open breaker must keep admission traffic off the device"
        )

        # recovery: clear the schedule; the background half-open probe
        # closes the breaker without any real traffic
        fault_plane.clear(faults.TPU_DISPATCH)
        assert wait_until(lambda: driver.breaker.state == CLOSED), (
            f"breaker did not recover: {driver.breaker.status()}"
        )
        assert dispatched.count(PROBE_NAME) >= 1, "recovery must be probe-driven"

        # traffic returns to the TPU
        req = ns_review("recovered")
        got = client.review(AugmentedReview(admission_request=req))
        assert review_sig(got) == review_sig(
            oracle.review(AugmentedReview(admission_request=req))
        )
        assert len(traffic_dispatches()) > n_before, (
            "closed breaker must route traffic back to the device"
        )
        assert driver.breaker_status()["consecutive_failures"] == 0

    def test_breaker_transitions_land_in_metrics(self, fault_plane):
        from gatekeeper_tpu.metrics.views import global_registry

        client, driver = tpu_client(threshold=2, cooldown=30.0)
        fault_plane.add(faults.TPU_DISPATCH, FaultRule(mode="error"))
        for i in range(2):
            client.review(
                AugmentedReview(admission_request=ns_review(f"m-{i}"))
            )
        assert driver.breaker.state == OPEN
        rows = global_registry().view_rows("tpu_breaker_state")
        assert rows.get(()) == 2.0  # open
        trips = global_registry().view_rows("tpu_breaker_trips")
        assert trips.get(()) >= 1.0
        fault_plane.clear(faults.TPU_DISPATCH)
        driver.breaker.probe_now()
        assert driver.breaker.state == CLOSED
        rows = global_registry().view_rows("tpu_breaker_state")
        assert rows.get(()) == 0.0  # closed again

    def test_degraded_seconds_span_failed_trials(self):
        """A failed half-open trial restarts the cooldown clock but must
        NOT zero the degraded-time metric: degraded_seconds spans the
        whole outage, not just the last cooldown interval."""
        from gatekeeper_tpu.ops.breaker import CircuitBreaker

        t = [0.0]
        cb = CircuitBreaker(
            failure_threshold=1, cooldown_s=5.0, clock=lambda: t[0]
        )
        cb.record_failure()  # trips at t=0
        t[0] = 10.0
        assert cb.allow()  # lazy half-open trial
        cb.record_failure()  # failed trial: re-open
        t[0] = 20.0
        assert cb.status()["degraded_seconds"] == 20.0
        assert cb.allow()
        cb.record_success()
        assert cb.state == CLOSED
        assert cb.status()["degraded_seconds"] == 20.0  # frozen on close

    def test_breaker_state_visible_on_health_endpoints(self):
        import urllib.request

        from gatekeeper_tpu.webhook import ValidationHandler, WebhookServer

        client, driver = tpu_client()
        handler = ValidationHandler(client, kube=InMemoryKube())
        srv = WebhookServer(
            handler, port=0,
            health_status=lambda: {"tpu_breaker": driver.breaker_status()},
        )
        srv.start()
        try:
            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=5
                ) as r:
                    return r.status, r.read()

            code, body = get("/healthz")
            assert (code, body) == (200, b"ok")
            driver.breaker.trip()
            code, body = get("/healthz")
            # degraded-but-serving: still 200 (no restart), marker visible
            assert (code, body) == (200, b"ok (degraded)")
            code, body = get("/statusz")
            st = json.loads(body)["tpu_breaker"]
            assert code == 200
            assert st["state"] == "open" and st["trips"] == 1
            driver.breaker.record_success()
            code, body = get("/healthz")
            assert (code, body) == (200, b"ok")
        finally:
            srv.stop()

    def test_degraded_audit_matches_interpreter(self):
        client, driver = tpu_client()
        oracle = interp_client()
        for c in (client, oracle):
            for i in range(3):
                c.add_data({"apiVersion": "v1", "kind": "Namespace",
                            "metadata": {"name": f"bad-{i}", "labels": {}}})
            c.add_data({"apiVersion": "v1", "kind": "Namespace",
                        "metadata": {"name": "good",
                                     "labels": {"gatekeeper": "on"}}})
        want_resp, want_totals = oracle.audit_capped(20)

        driver.breaker.trip()

        def no_device(*a, **k):
            raise AssertionError("device sweep ran while the breaker is open")

        driver._audit_sweep = no_device
        got_resp, got_totals = client.audit_capped(20)
        assert got_totals == want_totals
        assert sorted(r.msg for r in got_resp.results()) == sorted(
            r.msg for r in want_resp.results()
        )
        driver.breaker.record_success()  # close it again


class TestDeadlineBudget:
    def test_no_request_overshoots_budget_under_injected_hangs(
        self, fault_plane
    ):
        client, driver = tpu_client()
        window = 0.01
        budget = 0.15
        mb = MicroBatcher(client, window_s=window)
        fault_plane.add(
            faults.TPU_DISPATCH,
            FaultRule(mode="hang", hang_s=2.0),
        )
        try:
            for i in range(3):
                with deadline.budget(budget):
                    t0 = time.monotonic()
                    with pytest.raises(DeadlineExceeded):
                        mb.review(AugmentedReview(
                            admission_request=ns_review(f"hang-{i}")
                        ))
                    dur = time.monotonic() - t0
                # acceptance bound: budget + one batch window (plus
                # scheduler slack far below the 2s injected hang)
                assert dur <= budget + window + 0.1, (
                    f"request {i} took {dur:.3f}s against a "
                    f"{budget:.3f}s budget"
                )
        finally:
            fault_plane.release_hangs()
            mb.stop()

    def test_expired_budget_refused_before_enqueue(self):
        client, driver = tpu_client()
        mb = MicroBatcher(client, window_s=0.01)
        try:
            token = deadline.push(-1.0)  # already expired
            try:
                with pytest.raises(DeadlineExceeded):
                    mb.review(AugmentedReview(
                        admission_request=ns_review("expired")
                    ))
            finally:
                deadline.pop(token)
        finally:
            mb.stop()

    def test_server_answers_within_budget_not_socket_timeout(
        self, fault_plane
    ):
        """End-to-end: a hung dispatch yields a well-formed 504 deny
        AdmissionReview inside budget + window, not a hung socket."""
        import urllib.request

        from gatekeeper_tpu.webhook import ValidationHandler, WebhookServer

        client, driver = tpu_client()
        mb = MicroBatcher(client, window_s=0.01)
        handler = ValidationHandler(mb, kube=InMemoryKube())
        srv = WebhookServer(handler, port=0, deadline_budget_s=0.15)
        srv.start()
        fault_plane.add(
            faults.TPU_DISPATCH, FaultRule(mode="hang", hang_s=2.0)
        )
        try:
            body = json.dumps({
                "apiVersion": "admission.k8s.io/v1beta1",
                "kind": "AdmissionReview",
                "request": ns_review("e2e-hang"),
            }).encode()
            r = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/admit", data=body,
                headers={"Content-Type": "application/json"},
            )
            t0 = time.monotonic()
            with urllib.request.urlopen(r, timeout=5) as resp:
                out = json.loads(resp.read())
            dur = time.monotonic() - t0
            assert dur < 1.0, f"response took {dur:.3f}s (hang leaked)"
            assert out["response"]["allowed"] is False
            assert out["response"]["status"]["code"] == 504
            assert out["response"]["status"]["message"] == (
                "admission deadline budget exhausted"
            )
        finally:
            fault_plane.release_hangs()
            srv.stop()
            mb.stop()


class TestAuditOutage:
    def test_audit_survives_full_kube_outage_and_resumes(self, fault_plane):
        srv = KubeApiServer()
        srv.start()
        try:
            kube = HttpKube(srv.url, discovery_retry_s=1.0)
            client = interp_client()
            # register the synthesized constraint CRD so the status-write
            # path can list (finding no constraint objects is fine)
            kube.create(client.add_template(TEMPLATE))
            for i in range(2):
                kube.create({"apiVersion": "v1", "kind": "Namespace",
                             "metadata": {"name": f"bad-{i}", "labels": {}}})
            reporter = Reporters(Registry())
            am = AuditManager(kube, client, reporter=reporter,
                              interval_s=3600.0)

            assert am.run_once_guarded() is True
            assert am.last_run_status == "ok"
            assert am.consecutive_failures == 0

            # full outage: every kube HTTP send fails
            fault_plane.add(faults.KUBE_SEND, FaultRule(mode="error"))
            assert am.run_once_guarded() is False
            assert am.run_once_guarded() is False
            assert am.consecutive_failures == 2
            assert am.last_run_status == "error"
            rows = reporter.registry.view_rows("audit_consecutive_failures")
            assert rows.get(()) == 2.0
            assert reporter.registry.view_rows(
                "audit_last_run_status"
            ).get(()) == 0.0

            # recovery: the very next sweep succeeds and finds violations
            fault_plane.clear(faults.KUBE_SEND)
            assert am.run_once_guarded() is True
            assert am.consecutive_failures == 0
            assert am.last_run_status == "ok"
            assert reporter.registry.view_rows(
                "audit_last_run_status"
            ).get(()) == 1.0
            update_lists = am.audit_once()
            assert update_lists, "post-outage sweep must find violations"
            (violations,) = update_lists.values()
            assert {v.name for v in violations} == {"bad-0", "bad-1"}
        finally:
            srv.stop()


class TestWatchFaults:
    def test_pump_survives_injected_delivery_drops(self, fault_plane):
        kube = InMemoryKube()
        wm = WatchManager(kube)
        reg = wm.new_registrar("chaos")
        ns_gvk = ("", "v1", "Namespace")
        reg.add_watch(ns_gvk)
        assert wait_until(lambda: wm.replays_active() == 0)
        try:
            # exactly the first two deliveries drop; the pump survives
            fault_plane.add(
                faults.WATCH_DELIVER, FaultRule(mode="error", count=2)
            )
            for i in range(5):
                kube.create({"apiVersion": "v1", "kind": "Namespace",
                             "metadata": {"name": f"ns-{i}"}})
            got = []
            end = time.monotonic() + 5.0
            while len(got) < 3 and time.monotonic() < end:
                try:
                    got.append(reg.events.get(timeout=0.2))
                except queue.Empty:
                    pass
            names = [ev.object["metadata"]["name"] for _gvk, ev in got]
            assert names == ["ns-2", "ns-3", "ns-4"]
            # schedule spent: later events flow normally
            kube.create({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": "ns-after"}})
            _gvk, ev = reg.events.get(timeout=2.0)
            assert ev.object["metadata"]["name"] == "ns-after"
        finally:
            wm.stop()


class TestBatcherShutdown:
    def test_stop_drains_pending_and_rejects_new_enqueues(self):
        client = interp_client()
        entered = threading.Event()
        gate = threading.Event()
        orig_batch = client.review_batch

        def blocking_batch(objs, tracing=False):
            entered.set()
            gate.wait(5.0)
            return orig_batch(objs, tracing=tracing)

        client.review_batch = blocking_batch
        mb = MicroBatcher(client, window_s=0.01)
        results = {}

        def call(key, name):
            try:
                results[key] = mb.review(
                    AugmentedReview(admission_request=ns_review(name))
                )
            except Exception as e:
                results[key] = e

        # occupy the batch loop with a genuinely in-flight batch
        mb._busy = True  # steer the first request into the queue
        t1 = threading.Thread(target=call, args=("t1", "first"))
        t1.start()
        assert entered.wait(5.0), "batch loop never picked up the request"
        # now enqueue a second request behind the in-flight batch
        t2 = threading.Thread(target=call, args=("t2", "second"))
        t2.start()
        assert wait_until(lambda: len(mb._pending) == 1)

        # stop() while a request is pending: it must get a shutdown error
        # (the old code left it waiting on its event forever)
        stopper = threading.Thread(target=mb.stop)
        stopper.start()
        t2.join(timeout=5.0)
        assert not t2.is_alive()
        assert isinstance(results["t2"], BatcherStopped)

        # enqueues after stop() fail fast
        with pytest.raises(BatcherStopped):
            mb.review(AugmentedReview(admission_request=ns_review("third")))

        # release the in-flight batch: its caller still gets its answer
        gate.set()
        t1.join(timeout=5.0)
        stopper.join(timeout=5.0)
        assert not t1.is_alive() and not stopper.is_alive()
        assert not isinstance(results["t1"], Exception)
        assert len(results["t1"].results()) == 1


class TestReconnectBackoff:
    """Bounds of the watch reconnect schedule (syncutil.Backoff, used by
    HttpWatcher._pump): capped exponential with downward jitter — the cap
    is HARD (no interval ever exceeds it, jittered or not) and the jitter
    desynchronizes a fleet of reconnecting watchers without shrinking any
    interval below half its nominal value.  (Lives here rather than
    test_http_kube.py because that module needs `cryptography` to
    collect.)"""

    def test_schedule_bounds_and_hard_cap(self):
        import random as _random

        from gatekeeper_tpu.syncutil import Backoff

        b = Backoff(base=0.05, factor=2.0, cap=2.0, jitter=0.5,
                    rng=_random.Random(7))
        nominal = 0.05
        for _ in range(16):
            v = b.next()
            hi = min(nominal, 2.0)
            assert hi * 0.5 - 1e-9 <= v <= hi + 1e-9
            assert v <= 2.0  # hard cap survives jitter
            nominal = min(nominal * 2.0, 2.0)

    def test_no_jitter_is_the_exact_ladder(self):
        from gatekeeper_tpu.syncutil import Backoff

        b = Backoff(base=0.05, factor=2.0, cap=2.0, jitter=0.0)
        got = [round(b.next(), 4) for _ in range(8)]
        assert got == [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 2.0, 2.0]
        b.reset()
        assert b.next() == 0.05

    def test_seeded_schedules_deterministic_and_desynchronized(self):
        import random as _random

        from gatekeeper_tpu.syncutil import Backoff

        def schedule(seed):
            b = Backoff(rng=_random.Random(seed))
            return [b.next() for _ in range(10)]

        assert schedule(1) == schedule(1)
        assert schedule(1) != schedule(2)  # the anti-storm property

    def test_watcher_pump_uses_jittered_capped_schedule(self):
        from gatekeeper_tpu.kube.http_client import HttpWatcher

        assert HttpWatcher.RECONNECT_BASE_S == 0.05
        assert HttpWatcher.RECONNECT_CAP_S == 2.0
        assert 0.0 < HttpWatcher.RECONNECT_JITTER < 1.0


class TestFaultPlane:
    def test_inert_by_default(self):
        assert faults.ENABLED is False
        faults.fire(faults.TPU_DISPATCH)  # no plane installed: a no-op
        # call sites gated on the flag inject nothing anywhere
        client, driver = tpu_client()
        got = client.review(
            AugmentedReview(admission_request=ns_review("inert"))
        )
        assert len(got.results()) == 1
        assert driver.breaker.state == CLOSED

    def test_seeded_schedules_are_deterministic(self):
        def decisions(seed):
            plane = FaultPlane(seed=seed)
            plane.add("pt", FaultRule(mode="error", probability=0.5))
            out = []
            for _ in range(64):
                try:
                    plane.fire("pt")
                    out.append(0)
                except FaultError:
                    out.append(1)
            return out

        a, b, c = decisions(5), decisions(5), decisions(6)
        assert a == b
        assert a != c
        assert 10 < sum(a) < 54  # probability actually applied

    def test_count_after_and_latency_semantics(self):
        plane = FaultPlane(seed=0)
        rule = plane.add("pt", FaultRule(mode="error", count=2, after=1))
        outcomes = []
        for _ in range(5):
            try:
                plane.fire("pt")
                outcomes.append("ok")
            except FaultError:
                outcomes.append("err")
        assert outcomes == ["ok", "err", "err", "ok", "ok"]
        assert rule.fires == 2
        lat = plane.add("lat", FaultRule(mode="latency", latency_s=0.05))
        t0 = time.monotonic()
        plane.fire("lat")
        assert time.monotonic() - t0 >= 0.04

    def test_hang_is_bounded_and_releasable(self):
        plane = FaultPlane(seed=0)
        plane.add("h", FaultRule(mode="hang", hang_s=10.0))
        done = threading.Event()

        def hang_call():
            plane.fire("h")
            done.set()

        t = threading.Thread(target=hang_call, daemon=True)
        t.start()
        assert not done.wait(0.1), "hang returned immediately"
        plane.release_hangs()
        assert done.wait(2.0), "release did not unblock the hang"


# ---- snapshot / warm-resume chaos (ISSUE 3 satellite) -----------------------
# Every corruption or injected fault must degrade to the COLD path — no
# crash, no partial state — with snapshot_restore_outcome_total{outcome=
# "fallback"} incremented and the next audit sweep still correct.

import os

from gatekeeper_tpu.metrics.views import global_registry
from gatekeeper_tpu.snapshot import SnapshotLoader, Snapshotter
from gatekeeper_tpu.snapshot import format as snapfmt

from .test_snapshot import (
    TEMPLATE as SNAP_TEMPLATE,
    CONSTRAINT as SNAP_CONSTRAINT,
    audit_sig,
    build_cluster,
    fresh_client,
    make_client,
    outcome_counts,
)


class TestSnapshotChaos:
    def _written(self, snap_dir, n=6):
        kube = build_cluster(n=n)
        client = make_client(kube)
        sig, _ = audit_sig(client)
        snapper = Snapshotter(
            client, str(snap_dir), capture_delta=False
        )
        assert snapper.write_once() is not None
        return kube, sig

    def _restore_expect_fallback(self, snap_dir, kube, cold_sig):
        before = outcome_counts().get("fallback", 0)
        client = fresh_client()
        outcome = SnapshotLoader(str(snap_dir)).restore(client, kube)
        assert outcome == "fallback"
        assert outcome_counts().get("fallback", 0) == before + 1
        # the cold path still produces the oracle's verdicts
        client.add_template(SNAP_TEMPLATE)
        client.add_constraint(SNAP_CONSTRAINT)
        for obj in kube.list(("", "v1", "Namespace")):
            client.add_data(obj)
        sig, _ = audit_sig(client)
        assert sig == cold_sig

    def _corrupt(self, snap_dir, fname, mutate):
        snap = os.path.join(
            str(snap_dir), snapfmt.list_snapshots(str(snap_dir))[0]
        )
        path = os.path.join(snap, fname)
        mutate(path)

    def test_corrupt_manifest_falls_back_clean(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            blob = open(path).read()
            open(path, "w").write(blob.replace('"schema": 1', '"schema": 9'))

        self._corrupt(tmp_path, snapfmt.MANIFEST, mutate)
        self._restore_expect_fallback(tmp_path, kube, sig)

    def test_truncated_array_falls_back_clean(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            blob = open(path, "rb").read()
            open(path, "wb").write(blob[: max(1, len(blob) // 3)])

        self._corrupt(tmp_path, snapfmt.ARRAYS, mutate)
        self._restore_expect_fallback(tmp_path, kube, sig)

    def test_wrong_hmac_falls_back_clean(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            manifest = json.load(open(path))
            manifest["hmac"] = "f" * 64
            json.dump(manifest, open(path, "w"))

        self._corrupt(tmp_path, snapfmt.MANIFEST, mutate)
        self._restore_expect_fallback(tmp_path, kube, sig)

    def test_stale_resource_versions_fall_back_clean(self, tmp_path):
        kube, _sig = self._written(tmp_path)
        gvk = ("", "v1", "Namespace")
        for obj in kube.list(gvk):  # every RV moves while "down"
            obj["metadata"]["labels"]["churn"] = "y"
            kube.update(obj)
        before = outcome_counts().get("fallback", 0)
        client = fresh_client()
        outcome = SnapshotLoader(str(tmp_path)).restore(client, kube)
        assert outcome == "fallback"
        assert outcome_counts().get("fallback", 0) == before + 1
        warm_sig, _ = audit_sig(client)  # safe: everything re-packs
        oracle = make_client(kube)
        cold_sig, _ = audit_sig(oracle)
        assert warm_sig == cold_sig

    def test_injected_load_fault_falls_back(self, tmp_path, fault_plane):
        kube, sig = self._written(tmp_path)
        fault_plane.add(faults.SNAPSHOT_LOAD, FaultRule(mode="error"))
        self._restore_expect_fallback(tmp_path, kube, sig)

    def test_injected_resync_fault_wipes_to_cold(self, tmp_path, fault_plane):
        kube, sig = self._written(tmp_path)
        fault_plane.add(faults.SNAPSHOT_RESYNC, FaultRule(mode="error"))
        before = outcome_counts().get("fallback", 0)
        client = fresh_client()
        outcome = SnapshotLoader(str(tmp_path)).restore(client, kube)
        assert outcome == "fallback"
        assert outcome_counts().get("fallback", 0) == before + 1
        # mid-restore failure wiped the partial state: the store is empty
        # and the cold path rebuilds to the oracle verdicts
        assert client.driver._audit_pack.rp is None
        client.add_template(SNAP_TEMPLATE)
        client.add_constraint(SNAP_CONSTRAINT)
        for obj in kube.list(("", "v1", "Namespace")):
            client.add_data(obj)
        cold_sig, _ = audit_sig(client)
        assert cold_sig == sig

    def test_injected_write_fault_leaves_no_partial_snapshot(
        self, tmp_path, fault_plane
    ):
        kube = build_cluster(n=4)
        client = make_client(kube)
        audit_sig(client)
        fault_plane.add(
            faults.SNAPSHOT_WRITE, FaultRule(mode="error", count=1)
        )
        snapper = Snapshotter(client, str(tmp_path), capture_delta=False)
        assert snapper.write_once() is None
        assert snapper.last_error
        # no partial or temp dirs survive a failed write
        leftovers = [
            n for n in os.listdir(str(tmp_path))
            if n.startswith(snapfmt.TMP_PREFIX)
        ]
        assert leftovers == []
        assert snapfmt.list_snapshots(str(tmp_path)) == []
        # the audit loop is unaffected by persistence failures
        mgr = AuditManager(
            kube, client, from_cache=True, snapshotter=snapper,
        )
        assert mgr.run_once_guarded() is True
        # and the retry (fault exhausted) succeeds
        snapper._last_write = 0.0
        assert snapper.write_once() is not None


class TestGracefulDrain:
    """ISSUE 8: the drain protocol's two halves — the micro-batcher
    flush bounded by its deadline budget, and the server-side intake
    stop (docs/fleet.md)."""

    def test_drain_under_deadline_budget_never_exceeds_it(
        self, fault_plane
    ):
        """ISSUE 8 satellite: a graceful drain with a 10ms deadline
        budget returns within it (plus scheduler slack) even when the
        in-flight batch is wedged on a 2s injected hang — the drain
        reports `overran`, it never waits the hang out."""
        client, driver = tpu_client()
        mb = MicroBatcher(client, window_s=0.01)
        fault_plane.add(
            faults.TPU_DISPATCH, FaultRule(mode="hang", hang_s=2.0)
        )
        result = {}

        def call():
            # a deadline-carrying request always takes the QUEUED path
            # (the inline fast path is uninterruptible), so the batch
            # loop — not this thread — owns the wedged dispatch.  The
            # budget is generous: only the wedge bounds this test.
            token = deadline.push(30.0)
            try:
                result["r"] = mb.review(AugmentedReview(
                    admission_request=ns_review("drain-hang")
                ))
            except Exception as e:
                result["r"] = e
            finally:
                deadline.pop(token)

        try:
            t = threading.Thread(target=call)
            t.start()
            # the batch loop picks it up and wedges inside the dispatch
            # (observing the 1-element queue in between would race the
            # loop's sub-ms grab — this state is the stable one)
            assert wait_until(
                lambda: mb._busy and not mb._pending, timeout_s=5.0
            ), "batch loop never picked up the wedged request"
            t0 = time.monotonic()
            stats = mb.drain(0.010)
            dur = time.monotonic() - t0
            assert dur <= 0.010 + 0.1, (
                f"drain took {dur:.3f}s against a 10ms budget"
            )
            assert stats["overran"] is True
            assert stats["drained"] is False
        finally:
            fault_plane.release_hangs()
            t.join(timeout=5.0)
            mb.stop()

    def test_drain_of_idle_batcher_returns_immediately(self):
        client, driver = tpu_client()
        mb = MicroBatcher(client, window_s=0.01)
        try:
            stats = mb.drain(0.010)
            assert stats == {
                "pending_start": 0, "drained": True, "overran": False,
                "drain_ms": stats["drain_ms"],
            }
            assert stats["drain_ms"] <= 10.0
        finally:
            mb.stop()

    def test_draining_server_refuses_new_admissions_explicitly(self):
        """The drain protocol's intake side: a draining server answers
        503 (the front door fails over), /readyz goes not-ready, and
        /healthz stays 200 — then drain(False) restores service."""
        import urllib.error
        import urllib.request

        from gatekeeper_tpu.webhook import ValidationHandler, WebhookServer

        client = interp_client()
        handler = ValidationHandler(client, kube=InMemoryKube())
        srv = WebhookServer(handler, port=0)
        srv.start()
        try:
            body = json.dumps({"request": ns_review("pre-drain")}).encode()

            def post():
                r = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/v1/admit", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(r, timeout=5) as resp:
                    return resp.status, json.loads(resp.read())

            def get(path):
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}", timeout=5
                    ) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            assert post()[0] == 200
            srv.drain()
            code, _ = get("/readyz")
            assert code == 503
            assert get("/healthz")[0] == 200
            with pytest.raises(urllib.error.HTTPError) as ei:
                post()
            assert ei.value.code == 503
            assert b"draining" in ei.value.read()
            srv.drain(False)
            assert post()[0] == 200
            assert get("/readyz")[0] == 200
        finally:
            srv.stop()


class TestMeshDispatchStall:
    """ISSUE 8: a wedged mesh collective must not hold the sweep (or the
    dispatch gate) forever — the watchdog abandons it, trips the breaker
    (interpreter-identical verdicts meanwhile), and re-shards the sweep
    one step narrower; the rebasing full sweep at the new width stays
    byte-parity with the oracle."""

    def _populate(self, *clients, n=6):
        for c in clients:
            for i in range(n):
                labels = {"gatekeeper": "on"} if i % 2 else {}
                c.add_data({"apiVersion": "v1", "kind": "Namespace",
                            "metadata": {"name": f"m-{i}",
                                         "labels": labels}})

    def _audit_sig(self, client):
        resp, totals = client.audit_capped(20)
        return sorted(r.msg for r in resp.results()), totals

    def test_stall_trips_breaker_and_narrows_mesh(self, fault_plane):
        from gatekeeper_tpu.parallel.mesh import DISPATCH_LOCK

        driver = TpuDriver(
            breaker_threshold=3, breaker_cooldown_s=30.0,
            mesh_watchdog_s=0.25,
        )
        driver.DEVICE_MIN_CELLS = 0
        client = Client(driver=driver)
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
        oracle = interp_client()
        self._populate(client, oracle)
        driver.set_mesh(True, width=4)
        want = self._audit_sig(oracle)

        revocations_before = DISPATCH_LOCK.revocations
        # the collective wedges (bounded, releasable) INSIDE the gate —
        # exactly what a stuck AllReduce rendezvous looks like
        fault_plane.add(
            faults.MESH_DISPATCH_STALL,
            FaultRule(mode="hang", hang_s=10.0, count=1),
        )
        got = self._audit_sig(client)
        assert got == want, "stalled sweep must still answer (interp tier)"
        assert driver.breaker.state == OPEN, driver.breaker.status()
        assert driver.mesh_layout() == 2, (
            "stall must re-shard the sweep one step narrower"
        )
        assert DISPATCH_LOCK.revocations == revocations_before + 1
        from gatekeeper_tpu.metrics.views import global_registry

        assert global_registry().view_rows(
            "mesh_dispatch_stalls_total"
        ).get(()) >= 1.0
        assert global_registry().view_rows(
            "mesh_sweep_width"
        ).get(()) == 2.0

        # while degraded every sweep is interpreter-identical
        assert self._audit_sig(client) == want

        # unwedge the abandoned dispatch and let it finish ALONE before
        # any new device work (enqueue-order discipline)
        fault_plane.release_hangs()
        time.sleep(0.3)
        fault_plane.clear(faults.MESH_DISPATCH_STALL)
        # the first width-2 dispatch pays the SPMD trace+compile INSIDE
        # the guarded region (this jax cannot pre-populate the jit cache
        # from lower().compile()), so the recovery phase needs a budget
        # that covers a cold compile — exactly why the production
        # default is 30s, not sub-second
        driver.mesh_watchdog_s = 60.0
        assert driver.breaker.probe_now(), driver.breaker.status()
        # the next device sweep runs at the narrower width and rebases
        # via one full dispatch — parity preserved
        assert self._audit_sig(client) == want
        stats = driver.last_sweep_stats
        assert stats.get("shards") == 2.0, stats
        assert not stats.get("cached")

    def test_second_stall_degrades_to_single_device(self, fault_plane):
        driver = TpuDriver(
            breaker_threshold=3, breaker_cooldown_s=30.0,
            mesh_watchdog_s=0.25,
        )
        driver.DEVICE_MIN_CELLS = 0
        client = Client(driver=driver)
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
        oracle = interp_client()
        self._populate(client, oracle)
        want = self._audit_sig(oracle)
        driver.set_mesh(True, width=2)
        fault_plane.add(
            faults.MESH_DISPATCH_STALL,
            FaultRule(mode="hang", hang_s=10.0, count=1),
        )
        assert self._audit_sig(client) == want
        assert driver.mesh_layout() == 1, (
            "width 2 degrades to the single-device path"
        )
        fault_plane.release_hangs()
        time.sleep(0.3)
        fault_plane.clear(faults.MESH_DISPATCH_STALL)
        assert driver.breaker.probe_now()
        assert self._audit_sig(client) == want
        assert driver.last_sweep_stats.get("shards") == 1.0

    def test_watchdog_disabled_by_default(self):
        driver = TpuDriver()
        assert driver.mesh_watchdog_s == 0.0


class TestSnapshotQuarantine:
    """ISSUE 8 satellite: a snapshot that fails validation is moved
    aside into .quarantine/ EXACTLY once (with the outcome counter
    incremented), the cold path proceeds, and the next restart never
    re-validates it.  Read-mostly consumers (resync=False) never touch
    the shared dir."""

    def _written(self, snap_dir, n=6):
        kube = build_cluster(n=n)
        client = make_client(kube)
        sig, _ = audit_sig(client)
        assert Snapshotter(
            client, str(snap_dir), capture_delta=False
        ).write_once() is not None
        return kube, sig

    def _corrupt(self, snap_dir, fname, mutate):
        snap = os.path.join(
            str(snap_dir), snapfmt.list_snapshots(str(snap_dir))[0]
        )
        mutate(os.path.join(snap, fname))

    def _assert_quarantined_once(self, snap_dir, kube, cold_sig):
        qdir = os.path.join(str(snap_dir), snapfmt.QUARANTINE_DIR)
        before_q = outcome_counts().get("quarantined", 0)
        client = fresh_client()
        outcome = SnapshotLoader(str(snap_dir)).restore(client, kube)
        assert outcome == "fallback"
        assert outcome_counts().get("quarantined", 0) == before_q + 1
        # moved aside: the snapshot root holds no snap-* dirs anymore,
        # the quarantine dir holds exactly one
        assert snapfmt.list_snapshots(str(snap_dir)) == []
        assert len(os.listdir(qdir)) == 1
        # cold start proceeds to the oracle's verdicts
        client.add_template(SNAP_TEMPLATE)
        client.add_constraint(SNAP_CONSTRAINT)
        for obj in kube.list(("", "v1", "Namespace")):
            client.add_data(obj)
        sig, _ = audit_sig(client)
        assert sig == cold_sig
        # exactly once: the NEXT restore sees a clean (empty) root —
        # outcome none, no second quarantine sample
        second = SnapshotLoader(str(snap_dir)).restore(
            fresh_client(), kube
        )
        assert second == "none"
        assert outcome_counts().get("quarantined", 0) == before_q + 1
        assert len(os.listdir(qdir)) == 1

    def test_corrupt_manifest_is_quarantined_once(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            blob = open(path).read()
            open(path, "w").write(blob.replace('"schema": 1', '"schema": 9'))

        self._corrupt(tmp_path, snapfmt.MANIFEST, mutate)
        self._assert_quarantined_once(tmp_path, kube, sig)

    def test_truncated_arrays_are_quarantined_once(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            blob = open(path, "rb").read()
            open(path, "wb").write(blob[: max(1, len(blob) // 3)])

        self._corrupt(tmp_path, snapfmt.ARRAYS, mutate)
        self._assert_quarantined_once(tmp_path, kube, sig)

    def test_wrong_hmac_key_is_quarantined_once(self, tmp_path):
        kube, sig = self._written(tmp_path)

        def mutate(path):
            manifest = json.load(open(path))
            manifest["hmac"] = "f" * 64
            json.dump(manifest, open(path, "w"))

        self._corrupt(tmp_path, snapfmt.MANIFEST, mutate)
        self._assert_quarantined_once(tmp_path, kube, sig)

    def test_injected_corruption_point_quarantines(
        self, tmp_path, fault_plane
    ):
        """The seeded snapshot.corrupt fault point: post-seal payload
        validation fails -> the quarantine path, deterministically."""
        kube, sig = self._written(tmp_path)
        fault_plane.add(
            faults.SNAPSHOT_CORRUPT, FaultRule(mode="error", count=1)
        )
        self._assert_quarantined_once(tmp_path, kube, sig)

    def test_readmostly_consumer_never_quarantines(self, tmp_path):
        """A fleet replica adopting a SHARED dir (resync=False) must not
        move other processes' warmth aside, however corrupt — the dir's
        owner (the audit role) does that."""
        kube, _sig = self._written(tmp_path)

        def mutate(path):
            open(path, "w").write("{not json")

        self._corrupt(tmp_path, snapfmt.MANIFEST, mutate)
        listing = sorted(os.listdir(str(tmp_path)))
        before_q = outcome_counts().get("quarantined", 0)
        outcome = SnapshotLoader(str(tmp_path)).restore(
            fresh_client(), InMemoryKube(), resync=False
        )
        assert outcome == "fallback"
        assert sorted(os.listdir(str(tmp_path))) == listing
        assert outcome_counts().get("quarantined", 0) == before_q

    def test_older_snapshot_still_restores_after_quarantine(self, tmp_path):
        """Corrupt NEWEST + valid older: the owner quarantines the bad
        one and warm-restores from the older — quarantine never costs
        warmth that exists."""
        kube = build_cluster(n=6)
        client = make_client(kube)
        audit_sig(client)
        snapper = Snapshotter(client, str(tmp_path), capture_delta=False)
        first = snapper.write_once()
        snapper._last_write = 0.0
        second = snapper.write_once()
        assert first and second and first != second
        with open(os.path.join(second, snapfmt.MANIFEST), "w") as f:
            f.write("{not json")
        before_q = outcome_counts().get("quarantined", 0)
        outcome = SnapshotLoader(str(tmp_path)).restore(
            fresh_client(), kube
        )
        assert outcome == "restored"
        assert outcome_counts().get("quarantined", 0) == before_q + 1
        assert len(snapfmt.list_snapshots(str(tmp_path))) == 1


class TestDispatchGate:
    """The revocable mesh dispatch gate (parallel/mesh.py): revoke()
    unblocks the fleet from a wedged holder, and a waiter that was
    already parked on the revoked generation MIGRATES to the current one
    instead of dispatching under the abandoned lock (which would
    unserialize it against new-generation holders)."""

    def test_revoke_frees_new_acquirers_while_holder_wedged(self):
        from gatekeeper_tpu.parallel.mesh import DispatchGate

        gate = DispatchGate()
        held = gate.acquire()
        assert held is not None
        assert gate.acquire(timeout=0.05) is None  # busy
        gate.revoke()
        fresh = gate.acquire(timeout=1.0)
        assert fresh is not None, "revoked gate must admit new holders"
        gate.release(fresh)
        gate.release(held)  # the abandoned holder's late release: no-op

    def test_pre_revoke_waiter_migrates_to_current_generation(self):
        from gatekeeper_tpu.parallel.mesh import DispatchGate

        gate = DispatchGate()
        wedged = gate.acquire()
        order = []
        waiter_in = threading.Event()

        def old_gen_waiter():
            waiter_in.set()
            tok = gate.acquire()  # parks on the soon-revoked generation
            order.append("waiter")
            gate.release(tok)

        t = threading.Thread(target=old_gen_waiter, daemon=True)
        t.start()
        assert waiter_in.wait(2.0)
        time.sleep(0.05)  # let it block on the old lock
        gate.revoke()
        new_holder = gate.acquire(timeout=1.0)
        assert new_holder is not None
        # the wedged holder unsticks and releases the OLD lock: the
        # waiter wakes, must NOT proceed (stale generation) while the
        # new generation is held
        gate.release(wedged)
        time.sleep(0.15)
        assert order == [], (
            "waiter ran under the abandoned generation, unserialized "
            "against the new-generation holder"
        )
        order.append("new-holder-done")
        gate.release(new_holder)
        t.join(timeout=2.0)
        assert not t.is_alive()
        assert order == ["new-holder-done", "waiter"]


class TestOverloadStorm:
    """ISSUE 12: shedding under storm load never corrupts verdicts —
    accepted requests match the interpreter oracle exactly while the
    overload plane refuses the excess, and the new fault points drive
    the storm deterministically."""

    def test_zero_verdict_divergence_while_shedding(self, fault_plane):
        """Saturate a REAL evaluation pipeline (slow dispatch via an
        injected latency, bounded pending queue): every shed is an
        OverloadShed, every accepted verdict is byte-identical to the
        interpreter oracle — shedding must drop requests, never
        accuracy."""
        client, driver = tpu_client()
        oracle = interp_client()
        mb = MicroBatcher(client, window_s=0.005, max_pending=2,
                          adaptive=False)
        fault_plane.add(
            faults.TPU_DISPATCH,
            FaultRule(mode="latency", latency_s=0.15),
        )
        reqs = [
            ns_review(f"storm-{i}",
                      labels={"gatekeeper": "on"} if i % 3 else None)
            for i in range(12)
        ]
        want = {
            r["name"]: review_sig(oracle.review(
                AugmentedReview(admission_request=r)))
            for r in reqs
        }
        got: dict = {}
        sheds: list = []
        lock = threading.Lock()

        def call(req):
            try:
                resp = mb.review(AugmentedReview(admission_request=req))
            except deadline.OverloadShed:
                with lock:
                    sheds.append(req["name"])
                return
            with lock:
                got[req["name"]] = review_sig(resp)

        threads = [threading.Thread(target=call, args=(r,)) for r in reqs]
        try:
            for t in threads:
                t.start()
                time.sleep(0.01)
            for t in threads:
                t.join(timeout=30)
            assert sheds, "the storm never forced a shed — not a storm"
            assert got, "everything shed — no accepted verdicts to check"
            divergences = [
                name for name, sig in got.items() if sig != want[name]
            ]
            assert divergences == [], (
                f"accepted verdicts diverged under shedding: {divergences}"
            )
        finally:
            mb.stop()

    def test_overload_storm_point_drives_door_sheds(self, fault_plane):
        """The fleet.overload_storm seam: a latency rule holds proxied
        attempts with their inflight slot taken, so the door's
        accept-time shed engages — 429s answer FAST while the slow
        requests complete correctly."""
        from http.server import BaseHTTPRequestHandler
        from http.server import ThreadingHTTPServer as _TS

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b'{"served": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        backend = _TS(("127.0.0.1", 0), H)
        bport = backend.server_address[1]
        threading.Thread(target=backend.serve_forever,
                         daemon=True).start()
        from gatekeeper_tpu.fleet.frontdoor import FrontDoor

        fault_plane.add(
            faults.OVERLOAD_STORM,
            FaultRule(mode="latency", latency_s=0.4),
        )
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": bport, "replica_id": "b"}],
            probe_interval_s=3600.0, max_inflight=1,
        ).start()
        body = json.dumps({"request": ns_review("storm")}).encode()
        results: list = []
        lock = threading.Lock()

        def post():
            import http.client as hc

            t0 = time.perf_counter()
            conn = hc.HTTPConnection("127.0.0.1", door.port, timeout=10)
            try:
                conn.request(
                    "POST", "/v1/admit", body=body,
                    headers={"Content-Type": "application/json"},
                )
                r = conn.getresponse()
                data = r.read()
                with lock:
                    results.append(
                        (r.status, time.perf_counter() - t0, data))
            finally:
                conn.close()

        threads = [threading.Thread(target=post) for _ in range(6)]
        try:
            for t in threads:
                t.start()
                time.sleep(0.02)
            for t in threads:
                t.join(timeout=30)
            codes = [c for c, _d, _b in results]
            assert 200 in codes, "the storm starved every request"
            shed = [(c, d, b) for c, d, b in results if c == 429]
            assert shed, "inflight bound never shed under the storm"
            for _c, dur, data in shed:
                assert dur < 0.2, f"shed took {dur:.3f}s"
                out = json.loads(data)["response"]
                assert out["allowed"] is False
                assert out["status"]["code"] == 429
        finally:
            door.stop()
            backend.shutdown()
            backend.server_close()

    def test_slow_client_point_fires_in_read_body(self, fault_plane):
        """The frontdoor.slow_client seam: a latency rule stretches the
        request's read_body stage (an accept thread held by a trickling
        client) without corrupting the response."""
        from http.server import BaseHTTPRequestHandler
        from http.server import ThreadingHTTPServer as _TS

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b'{"served": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        backend = _TS(("127.0.0.1", 0), H)
        bport = backend.server_address[1]
        threading.Thread(target=backend.serve_forever,
                         daemon=True).start()
        from gatekeeper_tpu.fleet.frontdoor import FrontDoor

        fault_plane.add(
            faults.SLOW_CLIENT,
            FaultRule(mode="latency", latency_s=0.25, count=1),
        )
        door = FrontDoor(
            [{"host": "127.0.0.1", "port": bport, "replica_id": "b"}],
            probe_interval_s=3600.0,
        ).start()
        try:
            import http.client as hc

            t0 = time.perf_counter()
            conn = hc.HTTPConnection("127.0.0.1", door.port, timeout=10)
            conn.request("POST", "/v1/admit", body=b"{}",
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            data = r.read()
            dur = time.perf_counter() - t0
            conn.close()
            assert r.status == 200 and b"served" in data
            assert dur >= 0.25, "the slow-client latency never applied"
        finally:
            door.stop()
            backend.shutdown()
            backend.server_close()
