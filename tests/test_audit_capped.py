"""Cap-aware audit: cap-bounded host render vs the exact interpreter path.

The status write-back keeps at most --constraint-violations-limit violations
per constraint (reference pkg/audit/manager.go:49), so the TPU driver walks
the device candidate mask per constraint in row order and stops rendering at
the cap, with device-counted "resources" totals for capped constraints
(VERDICT r1 #3)."""


from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


def _loaded(driver, n_templates=6, n_pods=60, violation_rate=0.5):
    templates, constraints = make_templates(n_templates)
    c = Client(driver=driver)
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    for p in make_pods(n_pods, seed=7, violation_rate=violation_rate):
        c.add_data(p)
    return c


def _result_keys(results):
    return sorted(
        (r.constraint["kind"], r.constraint["metadata"]["name"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in results
    )


def test_capped_matches_exact_when_under_cap():
    """cap larger than any per-constraint violation count: capped results
    and totals must equal the exact audit on both drivers."""
    ct = _loaded(TpuDriver())
    ci = _loaded(InterpDriver())
    exact = ci.audit().results()
    res_t, totals_t = ct.audit_capped(10_000)
    res_i, totals_i = ci.audit_capped(10_000)
    assert _result_keys(res_t.results()) == _result_keys(exact)
    assert _result_keys(res_i.results()) == _result_keys(exact)
    assert totals_t == totals_i
    assert all(how == "exact" for _n, how in totals_t.values())
    # totals agree with a direct per-constraint count of the exact audit
    per = {}
    for r in exact:
        kk = (r.constraint["kind"], r.constraint["metadata"]["name"])
        per[kk] = per.get(kk, 0) + 1
    for kk, (n, _how) in totals_t.items():
        assert per.get(kk, 0) == n


def test_cap_bounds_results_per_constraint():
    cap = 3
    ct = _loaded(TpuDriver())
    res, totals = ct.audit_capped(cap)
    per = {}
    for r in res.results():
        kk = (r.constraint["kind"], r.constraint["metadata"]["name"])
        per[kk] = per.get(kk, 0) + 1
    assert per, "workload must produce violations"
    # a single cell can render several violations, so the bound is
    # cap + (max violations per cell - 1); for this corpus a cell yields
    # at most 2 (two missing labels)
    assert all(n <= cap + 1 for n in per.values()), per
    # capped constraints report "resources" totals >= the kept results
    interp = _loaded(InterpDriver())
    exact_per = {}
    for r in interp.audit().results():
        kk = (r.constraint["kind"], r.constraint["metadata"]["name"])
        exact_per[kk] = exact_per.get(kk, 0) + 1
    for kk, (n, how) in totals.items():
        if how == "exact":
            assert exact_per.get(kk, 0) == n, kk
        else:
            assert n >= per.get(kk, 0)


def test_capped_results_are_subset_of_exact():
    cap = 2
    ct = _loaded(TpuDriver())
    interp = _loaded(InterpDriver())
    capped_keys = set(_result_keys(ct.audit_capped(cap)[0].results()))
    exact_keys = set(_result_keys(interp.audit().results()))
    assert capped_keys <= exact_keys


def test_capped_on_mesh_matches_single_device():
    ct = _loaded(TpuDriver())
    ct.driver.mesh_enabled = True
    assert ct.driver._mesh() is not None
    res_mesh, totals_mesh = ct.audit_capped(4)

    ct2 = _loaded(TpuDriver())
    ct2.driver.mesh_enabled = False
    res_single, totals_single = ct2.audit_capped(4)
    assert totals_mesh == totals_single
    assert _result_keys(res_mesh.results()) == _result_keys(res_single.results())


def test_capped_resources_totals_match_device_counts():
    """With a cap far below the violating-cell count, capped constraints
    must report "resources" totals equal to the device mask's per-constraint
    cell counts.  Use a high violation rate so every constraint caps."""
    ct = _loaded(TpuDriver(), n_templates=3, n_pods=120, violation_rate=0.9)
    interp = _loaded(InterpDriver(), n_templates=3, n_pods=120, violation_rate=0.9)
    res, totals = ct.audit_capped(2)
    per = {}
    for r in res.results():
        kk = (r.constraint["kind"], r.constraint["metadata"]["name"])
        per[kk] = per.get(kk, 0) + 1
    exact_cells = {}
    _o, mask, _a = ct.driver.compute_masks(
        [ct.driver.target.make_audit_review(o, a, k, n, ns)
         for o, a, k, n, ns in (
             (__import__("gatekeeper_tpu.engine.value", fromlist=["thaw"]).thaw(of), api, kn, nm, ns)
             for of, api, kn, nm, ns in ct.driver.store.iter_objects())]
    )
    for ci, (kind, name, _c) in enumerate(_o):
        exact_cells[(kind, name)] = int(mask[ci].sum())
    for kk, (n, how) in totals.items():
        if how == "resources":
            assert n == exact_cells[kk], (kk, n, exact_cells[kk])


def test_manager_totals_key_matches_status_key_with_namespace():
    """A constraint carrying metadata.namespace must have its driver-exact
    total land under the same status key _add_results uses, not a
    cluster-scoped 'Kind//name' variant."""
    from gatekeeper_tpu.audit.manager import AuditManager
    from gatekeeper_tpu.kube.inmem import InMemoryKube

    kube = InMemoryKube()
    templates, constraints = make_templates(2)
    driver = TpuDriver()
    c = Client(driver=driver)
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        cons = dict(cons)
        cons["metadata"] = dict(cons["metadata"], namespace="weird-ns")
        c.add_constraint(cons)
        kube.create(dict(cons))
    for p in make_pods(30, seed=3, violation_rate=0.9):
        c.add_data(p)
    mgr = AuditManager(kube=kube, client=c, from_cache=True,
                       violations_limit=2, interval_s=1e9)
    mgr.audit_once()
    wrote = 0
    for gvk in mgr._constraint_kinds():
        for obj in kube.list(gvk):
            status = obj.get("status") or {}
            if "totalViolations" in status:
                wrote += 1
                assert status["totalViolations"] >= len(
                    status.get("violations") or [])
    assert wrote, "namespaced constraints must still receive status totals"


def test_manager_action_totals_counted_when_nothing_rendered():
    """violations_limit=0 keeps no results; per-action totals must still
    reflect the driver-exact counts (review r2 finding)."""
    from gatekeeper_tpu.audit.manager import AuditManager
    from gatekeeper_tpu.kube.inmem import InMemoryKube

    kube = InMemoryKube()
    ct = _loaded(TpuDriver(), n_templates=3, n_pods=30, violation_rate=0.9)
    _templates, constraints = make_templates(3)
    for cons in constraints:
        kube.create(dict(cons))

    seen = {}

    class Reporter:
        def report_audit_last_run(self, *a):
            pass

        def report_audit_duration(self, *a):
            pass

        def report_total_violations(self, action, n):
            seen[action] = n

    mgr = AuditManager(kube=kube, client=ct, from_cache=True,
                       violations_limit=0, interval_s=1e9,
                       reporter=Reporter())
    mgr.audit_once()
    assert sum(seen.values()) > 0, seen


def test_capped_empty_inventory_totals_contract():
    """Both drivers report (0, 'exact') for every registered constraint on
    an empty inventory."""
    templates, constraints = make_templates(3)
    for drv in (TpuDriver(), InterpDriver()):
        c = Client(driver=drv)
        for t in templates:
            c.add_template(t)
        for cons in constraints:
            c.add_constraint(cons)
        res, totals = c.audit_capped(5)
        assert res.results() == []
        assert len(totals) == len(constraints)
        assert all(v == (0, "exact") for v in totals.values())


def test_audit_manager_uses_capped_totals():
    """From-cache audit manager writes capped violation lists but
    driver-exact totals."""
    from gatekeeper_tpu.audit.manager import AuditManager
    from gatekeeper_tpu.kube.inmem import InMemoryKube

    kube = InMemoryKube()
    ct = _loaded(TpuDriver(), n_templates=4, n_pods=40, violation_rate=0.8)
    # register the constraints in the kube store so status writes land
    templates, constraints = make_templates(4)
    for cons in constraints:
        cons = dict(cons)
        kube.create(dict(cons))
    mgr = AuditManager(
        kube=kube, client=ct, from_cache=True, violations_limit=3,
        interval_s=1e9,
    )
    update_lists = mgr.audit_once()
    assert update_lists
    for key, viols in update_lists.items():
        assert len(viols) <= 3
    # status got totals >= listed violations
    for gvk in mgr._constraint_kinds():
        for c in kube.list(gvk):
            status = c.get("status") or {}
            if "violations" in status:
                assert status["totalViolations"] >= len(status["violations"])


def test_status_carries_totals_exact_marker():
    """VERDICT r2 #9: the constraint status surfaces whether
    totalViolations is exact (violation semantics) or a device-candidate
    approximation past the cap."""
    from gatekeeper_tpu.audit.manager import AuditManager
    from gatekeeper_tpu.kube.inmem import InMemoryKube

    kube = InMemoryKube()
    ct = _loaded(TpuDriver(), n_templates=6, n_pods=60, violation_rate=0.8)
    templates, constraints = make_templates(6)
    for cons in constraints:
        kube.create(dict(cons))
    mgr = AuditManager(
        kube=kube, client=ct, from_cache=True, violations_limit=2,
        interval_s=1e9,
    )
    mgr.audit_once()
    markers = {}
    for gvk in mgr._constraint_kinds():
        for c in kube.list(gvk):
            status = c.get("status") or {}
            assert "totalViolationsExact" in status
            markers[c["metadata"]["name"]] = status["totalViolationsExact"]
    # the synthetic corpus has both count-exact (labelreq) and inexact
    # (privflag et al) families over the cap
    _res, totals = ct.audit_capped(2)
    want = {f"c-{k[0].lower()}": how == "exact"
            for k, (n, how) in totals.items()}
    for name, exact in markers.items():
        assert exact == want[name], (name, exact)
