"""Tier-1 wiring for tools/check_overload.py (ISSUE 12): a 2-replica
fleet behind the overload-armed front door survives a saturation burst
with fast explicit sheds, preserved goodput, and zero verdict
divergence among accepted requests.  Skips cleanly where subprocess
spawn is unavailable (same contract as test_self_heal_tool); the
classification and verdict helpers are covered unconditionally."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import check_overload as chk  # noqa: E402

from .test_snapshot_concurrent import spawn_available


@spawn_available
def test_fleet_sheds_fast_and_keeps_verdicts_under_saturation():
    """Both serving edges hold the overload contract: the threaded door
    and the ISSUE 19 selectors-based door + batched wire listeners must
    shed/expire/serve under the identical saturation burst (one shared
    replica fleet — the taxonomy is a property of the doors)."""
    assert chk.run_checks(edge="both") == []


def test_classify_taxonomy():
    ok = b'{"response": {"allowed": true}}'
    assert chk.classify(200, ok)[0] == "accepted"
    shed_door = (b'{"response": {"allowed": false, '
                 b'"status": {"message": "shed", "code": 429}}}')
    assert chk.classify(429, shed_door)[0] == "shed"
    shed_replica = (b'{"response": {"allowed": false, '
                    b'"status": {"message": "shed", "code": 429}}}')
    assert chk.classify(200, shed_replica)[0] == "shed"
    expired = (b'{"response": {"allowed": false, '
               b'"status": {"message": "late", "code": 504}}}')
    assert chk.classify(200, expired)[0] == "expired"
    assert chk.classify(502, b"no backend")[0] == "problem"
    assert chk.classify(200, b"not-json")[0] == "problem"
    # a refusal WITHOUT an explicit verdict is a contract violation
    assert chk.classify(429, b'{"response": {}}')[0] == "problem"


def test_verdict_matcher():
    deny = {"allowed": False,
            "status": {"message": "[denied by a] broken pod",
                       "code": 403}}
    assert chk._verdict_matches(deny, (False, ["broken pod"]))
    assert not chk._verdict_matches(deny, (False, ["other"]))
    assert not chk._verdict_matches(deny, (True, []))
    assert chk._verdict_matches({"allowed": True}, (True, []))
