"""Incremental O(changes) audit sweep (ops/deltasweep.py): steady-state
capped audits evaluate only dirty rows on-device and fold the before/after
candidate columns into host-side counts/candidate state, falling back to a
full sweep only when the known candidate horizon runs out.
"""

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


def _pair(n_templates=8, n_pods=150, violation_rate=0.3, seed=21):
    """(tpu client on single device, interp oracle) on the same workload."""
    out = []
    for driver in (TpuDriver(), InterpDriver()):
        c = Client(driver=driver)
        if isinstance(driver, TpuDriver):
            driver.mesh_enabled = False
            driver._mesh_cache = None
        templates, constraints = make_templates(n_templates)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        for p in make_pods(n_pods, seed=seed, violation_rate=violation_rate):
            c.add_data(p)
        out.append(c)
    return out


def _audit_keys(c):
    return sorted((r.constraint["metadata"]["name"], r.msg)
                  for r in c.audit().results())


def _totals_vs_oracle(totals, oracle_totals):
    for k, (n, how) in totals.items():
        if how == "exact":
            assert n == oracle_totals[k][0], (k, n, oracle_totals[k])


def test_delta_path_used_and_matches_oracle_over_many_mutations():
    ct, ci = _pair()
    ct.audit_capped(5)  # cold full sweep bases the state
    pods = make_pods(150, seed=21, violation_rate=0.3)
    delta_sweeps = 0
    for i in range(6):
        # mix of add / modify / delete per sweep
        newp = make_pods(1, seed=500 + i, violation_rate=1.0)[0]
        newp["metadata"]["name"] = f"delta-add-{i}"
        ct.add_data(newp)
        ci.add_data(dict(newp))
        mod = dict(pods[i])
        mod["metadata"] = dict(mod["metadata"])
        mod["metadata"]["labels"] = {} if i % 2 else {"owner": "x"}
        ct.add_data(mod)
        ci.add_data(dict(mod))
        if i % 3 == 2:
            ct.remove_data(pods[10 + i])
            ci.remove_data(pods[10 + i])
        res_t, tot_t = ct.audit_capped(5)
        res_i, tot_i = ci.audit_capped(5)
        if "delta_rows" in ct.driver.last_sweep_stats:
            delta_sweeps += 1
        # per-constraint rendered counts agree where both are uncapped
        per_t, per_i = {}, {}
        for r in res_t.results():
            per_t[r.constraint["metadata"]["name"]] = per_t.get(
                r.constraint["metadata"]["name"], 0) + 1
        for r in res_i.results():
            per_i[r.constraint["metadata"]["name"]] = per_i.get(
                r.constraint["metadata"]["name"], 0) + 1
        for k, (n, how) in tot_t.items():
            if how == "exact":
                # on failure, capture which sweep path produced the count
                # and the incremental state (rare-flake diagnostics)
                st = ct.driver._delta_state
                assert n == tot_i[k][0], (
                    i, k, n, tot_i[k], ct.driver.last_sweep_stats,
                    None if st is None else {
                        "counts": st.counts.tolist(),
                        "row_cols": sorted(st.row_cols),
                        "store_epoch": st.store_epoch,
                        "cs_epoch": st.cs_epoch,
                    },
                )
        # full uncapped parity (forces a fresh full sweep for audit())
        assert _audit_keys(ct) == _audit_keys(ci), f"sweep {i}"
    assert delta_sweeps >= 4, f"delta path unused ({delta_sweeps} sweeps)"


def test_delta_counts_match_full_recompute():
    ct, _ = _pair(n_templates=6, n_pods=120)
    ct.audit_capped(4)
    for i in range(3):
        p = make_pods(1, seed=900 + i, violation_rate=1.0)[0]
        p["metadata"]["name"] = f"probe-{i}"
        ct.add_data(p)
        ct.audit_capped(4)
    st = ct.driver._delta_state
    delta_counts = st.counts.copy()
    # force a full resweep of the identical store and compare
    ct.driver._delta_state = None
    ct.driver._audit_cache = None
    ct.audit_capped(4)
    full_counts = ct.driver._delta_state.counts
    assert (delta_counts == full_counts).all()


def test_needs_full_sweep_escalation():
    """Exhausting the known horizon after deltas must transparently rebase
    with a full sweep, not miss candidates."""
    ct, ci = _pair(n_templates=1, n_pods=500, violation_rate=0.9)
    drv = ct.driver
    cap = 30  # K = 64 < labelreq candidates (~0.9*0.4*500): finite horizon
    ct.audit_capped(cap)
    st = drv._delta_state
    # make the state stale (delta applied) then chop its known candidates
    p = make_pods(1, seed=777, violation_rate=1.0)[0]
    p["metadata"]["name"] = "stale-maker"
    ct.add_data(p)
    ci.add_data(dict(p))
    ct.audit_capped(cap)
    st = drv._delta_state
    ci_res, ci_tot = ci.audit_capped(cap)
    if all(h is None for h in st.horizon):
        pytest.skip("workload produced complete knowledge; no horizon")
    # artificially shrink a horizon-limited candidate list to force the
    # escalation branch on the next render
    target = next(i for i, h in enumerate(st.horizon) if h is not None)
    st.cand[target] = st.cand[target][:2]
    res, totals = ct.audit_capped(cap)
    _totals_vs_oracle(totals, ci_tot)
    assert drv._delta_state is not st, "state must have been rebased"
    assert _audit_keys(ct) == _audit_keys(ci)


def test_many_dirty_rows_fall_back_to_full_sweep():
    ct, _ = _pair(n_templates=4, n_pods=80)
    ct.audit_capped(5)
    drv = ct.driver
    drv.DELTA_MAX_ROWS = 4
    for i in range(10):  # 10 dirty rows > 4
        p = make_pods(1, seed=1200 + i, violation_rate=0.5)[0]
        p["metadata"]["name"] = f"bulk-{i}"
        ct.add_data(p)
    ct.audit_capped(5)
    assert "delta_rows" not in drv.last_sweep_stats
    # and the state was rebased by the full sweep
    assert drv._delta_state.store_epoch == drv.store.epoch


def test_delta_disabled_env_forces_full_sweeps():
    ct, _ = _pair(n_templates=4, n_pods=60)
    ct.driver.delta_enabled = False
    ct.audit_capped(5)
    p = make_pods(1, seed=1500, violation_rate=1.0)[0]
    p["metadata"]["name"] = "nodelta"
    ct.add_data(p)
    ct.audit_capped(5)
    assert "delta_rows" not in ct.driver.last_sweep_stats


def test_render_cache_respects_cap_changes():
    """Re-auditing an unchanged cluster with a different cap must re-render
    (the per-constraint render cache keys on the cap)."""
    ct, ci = _pair(n_templates=4, n_pods=200, violation_rate=0.9)
    r5, t5 = ct.audit_capped(5)
    r50, t50 = ct.audit_capped(50)
    i5, it5 = ci.audit_capped(5)
    i50, it50 = ci.audit_capped(50)
    per = {}
    for r in r50.results():
        k = r.constraint["metadata"]["name"]
        per[k] = per.get(k, 0) + 1
    per_i = {}
    for r in i50.results():
        k = r.constraint["metadata"]["name"]
        per_i[k] = per_i.get(k, 0) + 1
    assert per == per_i, (per, per_i)
    assert len(r50.results()) > len(r5.results())
    # shrinking the cap must bound results again
    r2, _t2 = ct.audit_capped(2)
    per2 = {}
    for r in r2.results():
        k = r.constraint["metadata"]["name"]
        per2[k] = per2.get(k, 0) + 1
    assert all(v <= 2 + 1 for v in per2.values()), per2


def test_uncapped_audit_incremental_after_churn():
    """audit() (the --audit-exact-totals path) must stay correct and
    incremental under churn: the base mask is fetched once, then changed
    columns are patched host-side."""
    ct, ci = _pair(n_templates=6, n_pods=120)
    ct.audit_capped(5)  # base full sweep
    for i in range(4):
        p = make_pods(1, seed=2500 + i, violation_rate=1.0)[0]
        p["metadata"]["name"] = f"ua-{i}"
        ct.add_data(p)
        ci.add_data(dict(p))
        if i == 2:
            pods = make_pods(120, seed=21, violation_rate=0.3)
            ct.remove_data(pods[7])
            ci.remove_data(pods[7])
        assert _audit_keys(ct) == _audit_keys(ci), f"churn step {i}"
    st = ct.driver._delta_state
    assert st is not None and st.host_mask is not None
    # the host mask equals a fresh full fetch of the same store
    ct.driver._delta_state = None
    ct.driver._audit_cache = None
    _r, _o, fresh = ct.driver._audit_masks()
    assert (st.host_mask == fresh).all()
