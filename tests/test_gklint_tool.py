"""tools/gklint.py wired into tier-1 (the check_observability pattern):
the repo itself must lint clean — zero unsuppressed findings over
gatekeeper_tpu/ — and the CLI contract (exit codes, JSON format, rule
listing) must hold, so a regression that re-introduces a deadlock shape
or a silent swallow fails the suite, not a future incident review."""

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
TOOL = REPO / "tools" / "gklint.py"
FIXTURES = REPO / "tests" / "gklint_fixtures"


def _run(*args):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # gklint never imports jax,
    # but keep the child hermetic anyway
    return subprocess.run(
        [sys.executable, str(TOOL), *args],
        capture_output=True, text=True, cwd=str(REPO), env=env,
        timeout=120,
    )


def test_repo_lints_clean():
    """The acceptance bar: `python tools/gklint.py gatekeeper_tpu/`
    exits 0 with zero unsuppressed findings."""
    r = _run("gatekeeper_tpu/")
    assert r.returncode == 0, f"gklint found problems:\n{r.stderr}"
    assert "gklint: ok" in r.stdout


def test_tools_and_bench_lint_clean():
    """The auxiliary surfaces stay clean too (make lint covers them via
    the default path; pin them here so a regression is attributable)."""
    r = _run("tools/", "bench.py")
    assert r.returncode == 0, f"gklint found problems:\n{r.stderr}"


def test_fixture_seeds_fail_with_json_details():
    r = _run(str(FIXTURES), "--no-baseline", "--format=json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    rules = {f["rule"] for f in payload["findings"]}
    # the four incident-derived must-flag classes from the issue
    assert "lock-order-cycle" in rules
    assert "cv-held-lock" in rules
    assert "tracer-truthiness" in rules
    assert "swallowed-exception" in rules
    assert payload["count"] == len(payload["findings"]) > 0
    for f in payload["findings"]:
        assert f["path"].startswith("tests/gklint_fixtures/")
        assert f["line"] >= 1 and f["message"]


def test_list_rules():
    r = _run("--list-rules")
    assert r.returncode == 0
    for rule in ("lock-order-cycle", "blocking-under-lock", "cv-held-lock",
                 "tracer-truthiness", "jit-in-loop", "impure-in-jit",
                 "swallowed-exception", "thread-leak", "bare-join",
                 "listener-close", "start-guard", "unknown-fault-point",
                 "undocumented-fault-point", "undocumented-metric",
                 "suppression-reason"):
        assert rule in r.stdout, rule


def test_unknown_select_is_usage_error():
    r = _run("--select", "no-such-rule")
    assert r.returncode == 2


def test_baseline_absorbs_fixture_findings(tmp_path):
    from gatekeeper_tpu import analysis

    baseline = tmp_path / "b.json"
    findings = analysis.lint(str(REPO), [str(FIXTURES)])
    analysis.write_baseline(str(baseline), findings)
    r = _run(str(FIXTURES), "--baseline", str(baseline))
    assert r.returncode == 0, r.stderr
    # and --no-baseline surfaces them again
    r = _run(str(FIXTURES), "--baseline", str(baseline), "--no-baseline")
    assert r.returncode == 1


def test_write_baseline_refuses_narrowed_runs(tmp_path):
    """A baseline written from a subset would silently drop every
    accepted finding outside it — the CLI must refuse."""
    baseline = tmp_path / "b.json"
    r = _run(str(FIXTURES), "--baseline", str(baseline), "--write-baseline")
    assert r.returncode == 2
    assert not baseline.exists()
    r = _run("--select", "bare-join", "--baseline", str(baseline),
             "--write-baseline")
    assert r.returncode == 2
    assert not baseline.exists()


def test_committed_baseline_is_empty():
    """The repo's committed baseline must stay at zero entries: new
    findings are fixed or inline-suppressed with reasons, not silently
    banked (regenerating with --write-baseline on a dirty tree would
    show up here)."""
    with open(REPO / ".gklint-baseline.json") as f:
        data = json.load(f)
    assert data["findings"] == []
