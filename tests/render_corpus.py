"""Inline render-parity corpus: PSP- and agilebank-family templates with
original Rego (the reference fixture tree under /root/reference is absent
in this container, so the corpus is self-contained), plus adversarial
resources — unicode everywhere, missing fields, malformed shapes.

Used by tests/test_render_parity.py and tools/check_render_parity.py: the
corpus deliberately spans all three render-plan classes
(static / slots / interp) so both the compiled pipeline and the
interpreter fallback are exercised.

Every entry: (name, template dict, constraint dict, expected plan tier or
None when unasserted).
"""

from gatekeeper_tpu.ops.renderplan import INTERP, SLOTS, STATIC


def _template(kind: str, rego: str) -> dict:
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [
                {"target": "admission.k8s.gatekeeper.sh", "rego": rego}
            ],
        },
    }


def _constraint(kind: str, params: dict, name=None) -> dict:
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": name or f"c-{kind.lower()}"},
        "spec": {
            "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
            "parameters": params,
        },
    }


# ---- psp family -------------------------------------------------------------

_PSP_PRIVILEGED = """
package k8spspprivileged

violation[{"msg": msg, "details": {}}] {
  c := input_containers[_]
  c.securityContext.privileged
  msg := sprintf("Privileged container is not allowed: %v, securityContext: %v", [c.name, c.securityContext])
}

input_containers[c] {
  c := input.review.object.spec.containers[_]
}

input_containers[c] {
  c := input.review.object.spec.initContainers[_]
}
"""

_PSP_HOST_NAMESPACE = """
package k8spsphostnamespace

violation[{"msg": msg, "details": {}}] {
  input_share_hostnamespace(input.review.object)
  msg := sprintf("Sharing the host namespace is not allowed: %v", [input.review.object.metadata.name])
}

input_share_hostnamespace(o) {
  o.spec.hostPID
}

input_share_hostnamespace(o) {
  o.spec.hostIPC
}
"""

_PSP_HOST_NETWORK = """
package k8spsphostnetworkingports

violation[{"msg": msg, "details": {}}] {
  input.review.object.spec.hostNetwork
  msg := sprintf("The specified hostNetwork and hostPort are not allowed, pod: %v", [input.review.object.metadata.name])
}

violation[{"msg": msg, "details": {}}] {
  c := input_containers[_]
  p := c.ports[_].hostPort
  p < input.parameters.min
  msg := sprintf("The specified hostNetwork and hostPort are not allowed, pod: %v", [input.review.object.metadata.name])
}

violation[{"msg": msg, "details": {}}] {
  c := input_containers[_]
  p := c.ports[_].hostPort
  p > input.parameters.max
  msg := sprintf("The specified hostNetwork and hostPort are not allowed, pod: %v", [input.review.object.metadata.name])
}

input_containers[c] {
  c := input.review.object.spec.containers[_]
}
"""

# ---- agilebank family -------------------------------------------------------

_REQUIRED_LABELS = """
package k8srequiredlabels

violation[{"msg": msg, "details": {"missing_labels": missing}}] {
  provided := {label | input.review.object.metadata.labels[label]}
  required := {label | label := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("you must provide labels: %v", [missing])
}
"""

_ALLOWED_REPOS = """
package k8sallowedrepos

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  satisfied := [good | repo = input.parameters.repos[_]; good = startswith(c.image, repo)]
  not any(satisfied)
  msg := sprintf("container <%v> has an invalid image repo <%v>, allowed repos are %v", [c.name, c.image, input.parameters.repos])
}
"""

_VOLUME_TYPES = """
package k8spspvolumetypes

violation[{"msg": msg, "details": {}}] {
  fields := {f | input.review.object.spec.volumes[_][f]; f != "name"}
  not input_volume_type_allowed(fields)
  msg := sprintf("The volume types %v are not allowed", [fields])
}

input_volume_type_allowed(fields) {
  input.parameters.volumes[_] == "*"
}

input_volume_type_allowed(fields) {
  allowed := {t | t = input.parameters.volumes[_]}
  extra := fields - allowed
  count(extra) == 0
}
"""

# static-message family: the message reads only parameters
_DENY_ALL = """
package k8sdenyall

violation[{"msg": msg}] {
  input.review.object.spec.hostPID
  msg := sprintf("hostPID is forbidden by policy %v", [input.parameters.policy])
}
"""

_DISALLOWED_TAGS = """
package k8sdisallowedtags

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  matched := [hit | tag = input.parameters.tags[_]; hit = endswith(c.image, tag)]
  any(matched)
  msg := sprintf("container <%v> uses a disallowed tag <%v>; disallowed tags are %v", [c.name, c.image, input.parameters.tags])
}
"""

_HOST_FILESYSTEM = """
package k8spsphostfilesystem

violation[{"msg": msg, "details": {}}] {
  v := input.review.object.spec.volumes[_]
  v.hostPath
  msg := sprintf("HostPath volume %v is not allowed, pod: %v", [v, input.review.object.metadata.name])
}
"""

_IMAGE_DIGESTS = """
package k8simagedigests

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  not re_match("@sha256:[a-f0-9]+$", c.image)
  msg := sprintf("container <%v> image <%v> uses a tag, not a digest", [c.name, c.image])
}
"""

_DENY_NAME = """
package k8sdenyname

violation[{"msg": msg}] {
  input.review.object.metadata.name == input.parameters.name
  msg := sprintf("objects named %v are denied", [input.parameters.name])
}
"""

# dynamic family: message built through an unrecognized call chain ->
# interpreter class; ALSO semantically out of the vectorized fragment
_DYNAMIC_MSG = """
package k8sdynamicmsg

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  c.securityContext.privileged
  parts := split(c.image, ":")
  msg := sprintf("privileged image %v", [parts[0]])
}
"""


def corpus():
    return [
        ("psp-privileged", _template("K8sPSPPrivileged", _PSP_PRIVILEGED),
         _constraint("K8sPSPPrivileged", {}), SLOTS),
        ("psp-host-namespace",
         _template("K8sPSPHostNamespace", _PSP_HOST_NAMESPACE),
         _constraint("K8sPSPHostNamespace", {}), SLOTS),
        # nested per-entity array iteration (ports under containers) is
        # outside the vectorized fragment: a REALISTIC interpreter-tier
        # template, exercising the fallback path end to end
        ("psp-host-network",
         _template("K8sPSPHostNetwork", _PSP_HOST_NETWORK),
         _constraint("K8sPSPHostNetwork", {"min": 80, "max": 9000}),
         INTERP),
        ("disallowed-tags",
         _template("K8sDisallowedTags", _DISALLOWED_TAGS),
         _constraint("K8sDisallowedTags", {"tags": [":latest", ":dev"]}),
         SLOTS),
        ("host-filesystem",
         _template("K8sPSPHostFilesystem", _HOST_FILESYSTEM),
         _constraint("K8sPSPHostFilesystem", {}), SLOTS),
        ("image-digests", _template("K8sImageDigests", _IMAGE_DIGESTS),
         _constraint("K8sImageDigests", {}), SLOTS),
        ("deny-name", _template("K8sDenyName", _DENY_NAME),
         _constraint("K8sDenyName", {"name": "bad-pod"}), STATIC),
        ("required-labels",
         _template("K8sRequiredLabels", _REQUIRED_LABELS),
         _constraint("K8sRequiredLabels",
                     {"labels": ["owner", "billing", "ütf-läbel"]}), SLOTS),
        ("allowed-repos", _template("K8sAllowedRepos", _ALLOWED_REPOS),
         _constraint("K8sAllowedRepos",
                     {"repos": ["safe.io/", "registry.corp/"]}), SLOTS),
        ("volume-types", _template("K8sPSPVolumeTypes", _VOLUME_TYPES),
         _constraint("K8sPSPVolumeTypes",
                     {"volumes": ["configMap", "emptyDir"]}), SLOTS),
        ("deny-all-static", _template("K8sDenyAll", _DENY_ALL),
         _constraint("K8sDenyAll", {"policy": "no-host-pid"}), STATIC),
        ("dynamic-msg", _template("K8sDynamicMsg", _DYNAMIC_MSG),
         _constraint("K8sDynamicMsg", {}), INTERP),
        # missing-parameter edge: required param absent -> the msg ref is
        # undefined, so the clause must never fire (both tiers)
        ("allowed-repos-no-params",
         _template("K8sAllowedRepos2", _ALLOWED_REPOS.replace(
             "k8sallowedrepos", "k8sallowedrepos2")),
         _constraint("K8sAllowedRepos2", {}), None),
        ("required-labels-no-params",
         _template("K8sRequiredLabels2", _REQUIRED_LABELS.replace(
             "k8srequiredlabels", "k8srequiredlabels2")),
         _constraint("K8sRequiredLabels2", {}), None),
    ]


def resources():
    """Adversarial resource set: unicode, missing fields, empty lists,
    type confusion, multi-slot duplicates."""
    return [
        # ordinary violating pod
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "bad-pod", "namespace": "default",
                      "labels": {"owner": "me"}},
         "spec": {"hostPID": True, "hostNetwork": True,
                  "containers": [
                      {"name": "nginx", "image": "evil.io/nginx:latest",
                       "securityContext": {"privileged": True},
                       "ports": [{"hostPort": 31337}]},
                      {"name": "side", "image": "safe.io/side:1"},
                  ],
                  "volumes": [{"name": "v", "hostPath": {"path": "/"}}]}},
        # unicode names / labels / images
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "pöd-ünicode-🚀",
                      "namespace": "défault",
                      "labels": {"ütf-läbel": "präsent", "owner": "陈"}},
         "spec": {"hostIPC": True,
                  "containers": [
                      {"name": "contäiner-ß",
                       "image": "ünsafe.io/рус:v1",
                       "securityContext": {"privileged": True}}]}},
        # missing fields everywhere
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "minimal"},
         "spec": {}},
        # containers without names/images; securityContext without the flag
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "partial", "labels": {}},
         "spec": {"containers": [
             {"securityContext": {"privileged": True}},
             {"name": "x", "securityContext": {}},
             {"name": "y", "image": "evil.io/y",
              "ports": [{"containerPort": 80}]},
         ]}},
        # duplicate containers (identical msg dedup), empty label VALUES,
        # false-valued label (excluded from the provided-keys set)
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "dup",
                      "labels": {"owner": "", "billing": False}},
         "spec": {"containers": [
             {"name": "same", "image": "evil.io/same",
              "securityContext": {"privileged": True}},
             {"name": "same", "image": "evil.io/same",
              "securityContext": {"privileged": True}},
         ],
             "initContainers": [
             {"name": "same", "image": "evil.io/same",
              "securityContext": {"privileged": True}}]}},
        # type confusion: hostPort as string, privileged as string
        # (truthy!), volumes entry with extra keys
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "confused", "labels": {"owner": "o",
                                                     "billing": "b"}},
         "spec": {"containers": [
             {"name": "c1", "image": "registry.corp/ok:1",
              "securityContext": {"privileged": "yes"},
              "ports": [{"hostPort": "8080"}]}],
             "volumes": [
             {"name": "v0", "emptyDir": {}, "nfs": {"server": "s"}}]}},
        # compliant pod (no violations anywhere)
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "good",
                      "labels": {"owner": "o", "billing": "b",
                                 "ütf-läbel": "x"}},
         "spec": {"containers": [
             {"name": "ok", "image": "safe.io/app:2",
              "ports": [{"hostPort": 443}]}],
             "volumes": [{"name": "v0", "emptyDir": {}}]}},
    ]


def review_of(obj, namespace=None):
    r = {
        "kind": {"group": "", "version": "v1",
                 "kind": obj.get("kind", "Pod")},
        "name": obj.get("metadata", {}).get("name", ""),
        "operation": "CREATE",
        "object": obj,
    }
    ns = namespace or obj.get("metadata", {}).get("namespace")
    if ns:
        r["namespace"] = ns
    return r


# ---- referential (cross-resource join) scenarios ---------------------------
# Rendered through the interpreter with a real inventory (join plans
# produce the mask; rendering is the oracle by construction), so the
# parity suite drives these end-to-end driver-vs-oracle instead of
# plan.apply().  Each entry: (name, template, constraint, objects) where
# `objects` is the inventory the scenario audits.

_JOIN_UNIQUE_HOST = """
package k8suniqueingresshost

violation[{"msg": msg}] {
  host := input.review.object.spec.rules[_].host
  other := data.inventory.namespace[_][_]["Ingress"][_]
  otherhost := other.spec.rules[_].host
  host == otherhost
  not identical(other, input.review)
  msg := sprintf("duplicate ingress host: %v", [host])
}

identical(obj, review) {
  obj.metadata.namespace == review.object.metadata.namespace
  obj.metadata.name == review.object.metadata.name
}
"""

_JOIN_REQUIRED_CLASS = """
package k8srequiredstorageclass

violation[{"msg": msg}] {
  class := input.review.object.spec.storageClassName
  not class_exists(class)
  msg := sprintf("storage class %v does not exist", [class])
}

class_exists(name) {
  sc := data.inventory.cluster[_]["StorageClass"][_]
  sc.metadata.name == name
}
"""

_JOIN_TEAM_QUOTA = """
package k8steamquota

violation[{"msg": msg}] {
  team := input.review.object.metadata.labels.team
  n := count({[ns, ident] | p := data.inventory.namespace[ns][_]["Pod"][ident]; p.metadata.labels.team == team})
  n > input.parameters.limit
  msg := sprintf("team %v has %v pods (limit %v)", [team, n, input.parameters.limit])
}
"""


def _ingress(name, ns, hosts):
    return {
        "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"rules": [{"host": h} for h in hosts]},
    }


def _join_match(kind_name, groups):
    return {"kinds": [{"apiGroups": groups, "kinds": [kind_name]}]}


def join_corpus():
    """Referential scenarios for the parity suite: unicode hosts,
    duplicate slots within one object (self never duplicates itself),
    dangling and present references, and int-vs-str quota keys (the
    interned-key normalization satellite)."""
    unique_objs = [
        _ingress("ing-a", "ns-1", ["app.corp.io", "dup-🌍.corp.io"]),
        _ingress("ing-b", "ns-2", ["dup-🌍.corp.io"]),
        _ingress("ing-c", "ns-1", ["solo.corp.io", "solo.corp.io"]),
        _ingress("ing-d", "défault", ["ünïque.corp.io"]),
    ]
    class_objs = [
        {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
         "metadata": {"name": "standard"}},
        {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
         "metadata": {"name": "ok", "namespace": "ns-1"},
         "spec": {"storageClassName": "standard"}},
        {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
         "metadata": {"name": "dangling-ütf", "namespace": "ns-1"},
         "spec": {"storageClassName": "missing-klässe"}},
        {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
         "metadata": {"name": "no-field", "namespace": "ns-1"},
         "spec": {}},
    ]
    quota_objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": f"q-{i}", "namespace": "ns-1",
                      "labels": {"team": team}},
         "spec": {}}
        for i, team in enumerate([5, 5, 5, "5", "tëam-ü", "tëam-ü"])
    ]
    unique_t = _template("K8sUniqueIngressHost", _JOIN_UNIQUE_HOST)
    unique_c = _constraint("K8sUniqueIngressHost", {})
    unique_c["spec"]["match"] = _join_match(
        "Ingress", ["networking.k8s.io"]
    )
    class_t = _template("K8sRequiredStorageClass", _JOIN_REQUIRED_CLASS)
    class_c = _constraint("K8sRequiredStorageClass", {})
    class_c["spec"]["match"] = _join_match(
        "PersistentVolumeClaim", ["*"]
    )
    quota_t = _template("K8sTeamQuota", _JOIN_TEAM_QUOTA)
    quota_c = _constraint("K8sTeamQuota", {"limit": 2})
    quota_c["spec"]["match"] = _join_match("Pod", [""])
    return [
        ("join-unique-host", unique_t, unique_c, unique_objs),
        ("join-required-class", class_t, class_c, class_objs),
        ("join-team-quota", quota_t, quota_c, quota_objs),
    ]
