"""Routing-model regression + load-aware route choice (ISSUE 7).

The r05 curve misrouted N=50 to the interpreter (6.28ms measured vs
np's 2.11ms) because the calibration priced the interpreter with min()
over samples that included cold parser/freeze caches.  The fix measures
warm samples and takes the median; the regression test here pins the
route choice for every point of the RECORDED r05 curve against the
RECORDED r05 calibration — the model must pick the tier that actually
measured fastest, at every N.

The load-aware extension (docs/fleet.md): with a fresh offered-load
hint from the micro-batcher, tiers that cannot SUSTAIN the offered rate
are excluded even when they win single-batch latency, and when nothing
sustains it the highest-throughput tier drains the queue.
"""

import time

import pytest

from gatekeeper_tpu.ops.driver import TpuDriver

# BENCH_r05.json routing_calibration — the recorded model
R05_CAL = {
    "rtt_ms": 192.724,
    "device_cells_per_ms": 2.185,
    "interp_cells_per_ms": 9.78,
    "np_floor_ms": 1.615,
    "np_cells_per_ms": 19.323,
}

# BENCH_r05.json curve_*_p50_ms — what each tier actually measured, and
# therefore the tier the router should have picked
R05_MEASURED = {
    #  N: (interp_ms, np_ms, device_ms)
    5: (0.608, 1.087, 159.41),
    10: (1.192, 1.535, 141.531),
    50: (6.28, 2.108, 134.664),      # the r05 misroute: was "interp"
    100: (12.48, 1.929, 124.846),
    200: (22.938, 2.704, 133.507),
    1000: (118.841, 2.08, 125.88),
    2000: (243.156, 3.026, 201.429),
}


def _driver_with(cal):
    drv = TpuDriver()
    drv._route_cal = dict(cal) if cal else None
    assert drv.DEVICE_MIN_CELLS != 0, "route tests need the real prior"
    return drv


class TestR05CurveRegression:
    def test_route_matches_the_measured_winner_at_every_n(self):
        drv = _driver_with(R05_CAL)
        for n, (interp_ms, np_ms, device_ms) in R05_MEASURED.items():
            want = min(
                [(interp_ms, "interp"), (np_ms, "np"),
                 (device_ms, "device")]
            )[1]
            assert drv._route_eval(n) == want, (
                f"N={n}: route {drv._route_eval(n)!r}, "
                f"measured winner {want!r}"
            )

    def test_n50_is_np_not_interp(self):
        """The specific r05 defect, pinned on its own."""
        drv = _driver_with(R05_CAL)
        assert drv._route_eval(50) == "np"


LOAD_CAL = {
    # per-review service with 10 cells/review:
    #   interp: 10ms/review        -> mu @ B=256 =  100 rps
    #   np:     0.5 + 1ms/review   -> mu @ B=256 ~  998 rps
    #   device: 5 + 0.1ms/review   -> mu @ B=256 ~ 8366 rps
    # single-review latency: np 1.5ms < device 5.1ms < interp 10ms
    "rtt_ms": 5.0,
    "device_cells_per_ms": 100.0,
    "interp_cells_per_ms": 1.0,
    "np_floor_ms": 0.5,
    "np_cells_per_ms": 10.0,
}
CELLS = 10  # one review x 10 constraints


class TestLoadAwareRouting:
    def test_no_hint_routes_by_latency(self):
        drv = _driver_with(LOAD_CAL)
        assert drv._route_eval(CELLS) == "np"

    def test_moderate_load_excludes_the_unsustainable_interpreter(self):
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(100.0)  # interp mu=100 < 100*1.25
        assert drv._route_eval(CELLS, n_reviews=1) == "np"

    def test_high_load_overrides_latency_for_throughput(self):
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(2000.0)  # np mu ~998 < 2500: excluded
        assert drv._route_eval(CELLS, n_reviews=1) == "device"

    def test_saturation_everywhere_picks_max_throughput(self):
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(20000.0)  # above every tier's mu
        assert drv._route_eval(CELLS, n_reviews=1) == "device"

    def test_stale_hint_expires(self):
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(2000.0)
        rps, _t = drv._offered_load
        drv._offered_load = (
            rps, time.monotonic() - drv.LOAD_HINT_TTL_S - 1.0
        )
        # hint expired: back to latency routing
        assert drv._route_eval(CELLS, n_reviews=1) == "np"

    def test_clearing_the_hint_restores_latency_routing(self):
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(2000.0)
        assert drv._route_eval(CELLS, n_reviews=1) == "device"
        drv.set_offered_load(None)
        assert drv._route_eval(CELLS, n_reviews=1) == "np"
        drv.set_offered_load(0.0)  # zero load == no hint
        assert drv._offered_load is None

    def test_batch_size_scales_per_review_cells(self):
        """The load model prices PER-REVIEW service: a 64-review batch
        of the same corpus must not look 64x heavier per review."""
        drv = _driver_with(LOAD_CAL)
        drv.set_offered_load(2000.0)
        assert drv._route_eval(CELLS * 64, n_reviews=64) == "device"


class TestPredictedBatchMs:
    def test_none_without_calibration(self):
        drv = _driver_with(None)
        assert drv.predicted_batch_ms(8) is None

    def test_cheapest_tier_minimum(self):
        drv = _driver_with(LOAD_CAL)
        # empty constraint registry -> 1 cell/review; affine minimum over
        # tiers at B=1 and B=256 (np floor wins small, slope rules large)
        t1 = drv.predicted_batch_ms(1)
        t256 = drv.predicted_batch_ms(256)
        assert t1 is not None and t256 is not None
        assert t1 < t256
        models = drv._tier_models(1)
        assert t1 == pytest.approx(
            min(floor + 1 * per for _t, floor, per in models)
        )

    def test_monotone_in_batch_size(self):
        drv = _driver_with(LOAD_CAL)
        xs = [drv.predicted_batch_ms(n) for n in (1, 4, 16, 64, 256)]
        assert xs == sorted(xs)
