"""Foundation-layer tests: util, apis/config, apis/status, operations,
process excluder, logging (reference parity: pkg/util, apis/, pkg/operations,
pkg/controller/config/process)."""

import io
import json

import pytest

from gatekeeper_tpu import operations, util
from gatekeeper_tpu import logging as gklog
from gatekeeper_tpu.apis import status as status_api
from gatekeeper_tpu.apis.config import parse_config
from gatekeeper_tpu.process.excluder import Excluder


class TestEnforcementAction:
    def test_default_deny(self):
        assert util.get_enforcement_action({"spec": {}}) == "deny"
        assert util.get_enforcement_action({}) == "deny"

    def test_dryrun(self):
        assert util.get_enforcement_action({"spec": {"enforcementAction": "dryrun"}}) == "dryrun"

    def test_unrecognized(self):
        # reference enforcement_action.go:40-43: unsupported -> unrecognized
        assert (
            util.get_enforcement_action({"spec": {"enforcementAction": "warn"}})
            == "unrecognized"
        )

    def test_validate_rejects(self):
        with pytest.raises(util.EnforcementActionError):
            util.validate_enforcement_action("unrecognized")
        util.validate_enforcement_action("deny")


class TestRequestPacking:
    def test_roundtrip(self):
        gvk = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")
        packed, ns = util.pack_request(gvk, "my-constraint", "")
        got_gvk, name, namespace = util.unpack_request(packed, ns)
        assert got_gvk == gvk
        assert name == "my-constraint"
        assert namespace == ""

    def test_empty_version_defaults_v1(self):
        packed, _ = util.pack_request(("", "", "Namespace"), "ns1")
        gvk, name, _ = util.unpack_request(packed)
        assert gvk == ("", "v1", "Namespace")

    def test_name_with_colons(self):
        packed, _ = util.pack_request(("g", "v1", "K"), "a:b:c")
        _, name, _ = util.unpack_request(packed)
        assert name == "a:b:c"

    def test_invalid(self):
        with pytest.raises(ValueError):
            util.unpack_request("notgvk:x:y")


class TestDashPacking:
    def test_roundtrip(self):
        packed = status_api.dash_pack("pod-1", "k8srequiredlabels", "ns-must-have-gk")
        assert status_api.dash_unpack(packed) == [
            "pod-1",
            "k8srequiredlabels",
            "ns-must-have-gk",
        ]

    def test_escaping(self):
        # util.go:55-91 semantics: '-' doubles inside tokens
        assert status_api.dash_pack("a-b", "c") == "a--b-c"
        assert status_api.dash_unpack("a--b-c") == ["a-b", "c"]

    def test_rejects_empty_and_edge_dash(self):
        with pytest.raises(status_api.KeyError_):
            status_api.dash_pack("")
        with pytest.raises(status_api.KeyError_):
            status_api.dash_pack("-leading")
        with pytest.raises(status_api.KeyError_):
            status_api.dash_pack("trailing-")

    def test_key_for_constraint(self):
        c = {"kind": "K8sRequiredLabels", "metadata": {"name": "must-have"}}
        key = status_api.key_for_constraint("pod-abc", c)
        assert status_api.dash_unpack(key) == ["pod-abc", "k8srequiredlabels", "must-have"]


class TestStatusObjects:
    def test_constraint_status(self):
        c = {
            "kind": "K8sRequiredLabels",
            "metadata": {"name": "must-have", "uid": "u1", "generation": 3},
        }
        obj = status_api.new_constraint_status_for_pod("pod-1", "gatekeeper-system", c, ["audit"])
        assert obj["metadata"]["labels"][status_api.CONSTRAINT_KIND_LABEL] == "K8sRequiredLabels"
        assert obj["metadata"]["labels"][status_api.POD_LABEL] == "pod-1"
        assert obj["status"]["constraintUID"] == "u1"
        assert obj["status"]["observedGeneration"] == 3

    def test_template_status(self):
        t = {"metadata": {"name": "k8srequiredlabels", "uid": "u2"}}
        obj = status_api.new_template_status_for_pod("pod-1", "gatekeeper-system", t, ["audit", "webhook"])
        assert obj["metadata"]["name"] == status_api.key_for_template("pod-1", "k8srequiredlabels")
        assert obj["status"]["templateUID"] == "u2"


class TestConfigParsing:
    def test_full(self):
        cfg = parse_config(
            {
                "spec": {
                    "sync": {"syncOnly": [{"group": "", "version": "v1", "kind": "Pod"}]},
                    "validation": {
                        "traces": [
                            {
                                "user": "alice",
                                "kind": {"group": "", "version": "v1", "kind": "Namespace"},
                                "dump": "All",
                            }
                        ]
                    },
                    "match": [
                        {"excludedNamespaces": ["kube-system"], "processes": ["*"]}
                    ],
                    "readiness": {"statsEnabled": True},
                }
            }
        )
        assert cfg.sync_only[0].gvk() == ("", "v1", "Pod")
        assert cfg.traces[0].user == "alice"
        assert cfg.traces[0].dump == "All"
        assert cfg.match[0].excluded_namespaces == ["kube-system"]
        assert cfg.readiness_stats_enabled

    def test_empty(self):
        cfg = parse_config(None)
        assert cfg.sync_only == [] and cfg.traces == [] and cfg.match == []


class TestOperations:
    def test_default_all(self):
        ops = operations.Operations()
        for op in operations.ALL_OPERATIONS:
            assert ops.is_assigned(op)
        assert ops.assigned_string_list() == ["audit", "status", "webhook"]

    def test_subset(self):
        ops = operations.Operations(["audit"])
        assert ops.is_assigned("audit")
        assert not ops.is_assigned("webhook")
        assert ops.assigned_string_list() == ["audit"]

    def test_unknown_rejected(self):
        with pytest.raises(operations.OperationError):
            operations.Operations(["bogus"])


class TestExcluder:
    def _entries(self, raw):
        from gatekeeper_tpu.apis.config import parse_config

        return parse_config({"spec": {"match": raw}}).match

    def test_star_expands(self):
        ex = Excluder()
        ex.add(self._entries([{"excludedNamespaces": ["kube-system"], "processes": ["*"]}]))
        for p in ("audit", "webhook", "sync"):
            assert ex.is_namespace_excluded(p, "kube-system")
        assert not ex.is_namespace_excluded("audit", "default")

    def test_per_process(self):
        ex = Excluder()
        ex.add(self._entries([{"excludedNamespaces": ["payments"], "processes": ["audit"]}]))
        assert ex.is_namespace_excluded("audit", "payments")
        assert not ex.is_namespace_excluded("webhook", "payments")

    def test_replace_and_equals(self):
        a, b = Excluder(), Excluder()
        b.add(self._entries([{"excludedNamespaces": ["x"], "processes": ["sync"]}]))
        assert not a.equals(b)
        a.replace(b)
        assert a.equals(b)
        assert a.is_namespace_excluded("sync", "x")


class TestLogging:
    def test_json_lines_with_stable_keys(self):
        buf = io.StringIO()
        import logging as pylog

        logger = pylog.getLogger("gatekeeper.test")
        logger.setLevel("INFO")
        h = pylog.StreamHandler(buf)
        h.setFormatter(gklog.JsonFormatter())
        logger.addHandler(h)
        logger.propagate = False
        try:
            gklog.log_event(
                logger,
                "denied admission",
                **{
                    gklog.PROCESS: "admission",
                    gklog.EVENT_TYPE: "violation",
                    gklog.CONSTRAINT_KIND: "K8sRequiredLabels",
                    gklog.RESOURCE_NAME: "ns1",
                },
            )
        finally:
            logger.removeHandler(h)
        line = json.loads(buf.getvalue())
        assert line["msg"] == "denied admission"
        assert line["process"] == "admission"
        assert line["constraint_kind"] == "K8sRequiredLabels"


class TestIncrementalFrozenSpine:
    """store.frozen() rebuilds only the spine along changed paths; the
    result must always deep-equal a from-scratch freeze."""

    def _check(self, store):
        from gatekeeper_tpu.client.drivers import freeze_spine

        assert store.frozen() == freeze_spine(store.tree)

    def test_incremental_matches_full(self):
        from gatekeeper_tpu.client.drivers import InventoryStore

        s = InventoryStore()
        s.put(("cluster", "v1", "Namespace", "a"), {"x": 1})
        base = s.frozen()
        self._check(s)
        s.put(("namespace", "ns1", "v1", "Pod", "p1"), {"y": [1, 2]})
        s.put(("cluster", "v1", "Namespace", "b"), {"x": 2})
        self._check(s)
        # update in place
        s.put(("cluster", "v1", "Namespace", "a"), {"x": 9})
        self._check(s)
        assert s.frozen()["cluster"]["v1"]["Namespace"]["a"]["x"] == 9
        # delete a leaf and an implied-empty parent path
        s.delete(("namespace", "ns1", "v1", "Pod", "p1"))
        self._check(s)
        # wipe falls back to full rebuild
        s.delete(())
        self._check(s)
        assert len(s.frozen()) == 0
        del base

    def test_sharing_across_epochs(self):
        from gatekeeper_tpu.client.drivers import InventoryStore

        s = InventoryStore()
        for i in range(50):
            s.put(("namespace", f"ns{i % 5}", "v1", "Pod", f"p{i}"), {"i": i})
        f1 = s.frozen()
        s.put(("namespace", "ns0", "v1", "Pod", "p0"), {"i": 999})
        f2 = s.frozen()
        # untouched namespace subtrees are the same objects
        assert f1["namespace"]["ns1"] is f2["namespace"]["ns1"]
        assert f2["namespace"]["ns0"]["v1"]["Pod"]["p0"]["i"] == 999
        # old spine unchanged (immutability)
        assert f1["namespace"]["ns0"]["v1"]["Pod"]["p0"]["i"] == 0

    def test_flapping_objects_stay_incremental(self):
        """Many log entries for few paths must not force a full re-freeze
        (entries dedupe before the RESPINE_MAX check)."""
        from gatekeeper_tpu.client.drivers import InventoryStore, freeze_spine

        s = InventoryStore()
        s.RESPINE_MAX = 16
        for i in range(40):
            s.put(("namespace", f"ns{i}", "v1", "Pod", f"p{i}"), {"i": i})
        f1 = s.frozen()
        for _flap in range(100):  # 100 entries, 2 unique paths
            s.put(("namespace", "ns0", "v1", "Pod", "p0"), {"i": _flap})
            s.put(("namespace", "ns1", "v1", "Pod", "p1"), {"i": -_flap})
        f2 = s.frozen()
        assert f2 == freeze_spine(s.tree)
        # untouched subtree shared => the incremental path ran
        assert f1["namespace"]["ns5"] is f2["namespace"]["ns5"]
