"""Metrics subsystem: views, catalog, reporter facade, Prometheus rendering.

Covers the behavior the reference's stats_reporter tests assert (recorded
row values and tags; e.g. pkg/webhook/stats_reporter_test.go) plus the
exposition endpoint."""

import urllib.request

from gatekeeper_tpu.metrics import (
    MetricsExporter,
    Reporters,
    render_prometheus,
)
from gatekeeper_tpu.metrics.views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    Measure,
    Registry,
    View,
)


def fresh_reporters():
    return Reporters(Registry())


def test_count_and_distribution_aggregation():
    reg = Registry()
    m = Measure("latency", "latency", "s")
    reg.register(
        View("req_count", m, AGG_COUNT, tag_keys=("status",)),
        View("req_hist", m, AGG_DISTRIBUTION, tag_keys=("status",),
             buckets=(0.01, 0.1, 1.0)),
    )
    for v in (0.005, 0.05, 0.5, 5.0):
        reg.record(m, v, {"status": "allow"})
    reg.record(m, 0.05, {"status": "deny"})

    assert reg.view_rows("req_count")[("allow",)] == 4
    assert reg.view_rows("req_count")[("deny",)] == 1
    dist = reg.view_rows("req_hist")[("allow",)]
    assert dist.bucket_counts == [1, 1, 1, 1]
    assert dist.count == 4
    assert abs(dist.sum - 5.555) < 1e-9


def test_last_value_overwrites():
    reg = Registry()
    m = Measure("g", "gauge")
    reg.register(View("g", m, AGG_LAST_VALUE))
    reg.record(m, 3)
    reg.record(m, 7)
    assert reg.view_rows("g")[()] == 7.0


def test_reporter_facade_records_catalog_rows():
    r = fresh_reporters()
    r.report_request("allow", 0.004)
    r.report_request("deny", 0.02)
    r.report_constraints({("deny", "active"): 5, ("dryrun", "error"): 1})
    r.report_ingestion("active", 0.03)
    r.report_total_violations("deny", 12)
    r.report_audit_duration(0.8)
    r.report_sync({("", "v1", "Pod"): 10}, 0.001)
    r.report_gvk_count(3, 4)

    reg = r.registry
    assert reg.view_rows("request_count")[("allow",)] == 1
    assert reg.view_rows("constraints")[("deny", "active")] == 5.0
    assert reg.view_rows("violations")[("deny",)] == 12.0
    assert reg.view_rows("sync")[("Pod", "active")] == 10.0
    assert reg.view_rows("watch_manager_watched_gvk")[()] == 3.0
    dist = reg.view_rows("request_duration_seconds")[("deny",)]
    assert dist.count == 1


def test_prometheus_rendering():
    r = fresh_reporters()
    r.report_request("allow", 0.004)
    r.report_audit_duration(2.5)
    r.report_total_violations("deny", 3)
    text = render_prometheus(r.registry)
    assert '# TYPE gatekeeper_request_duration_seconds histogram' in text
    assert 'gatekeeper_request_count{admission_status="allow"} 1' in text
    assert 'gatekeeper_violations{enforcement_action="deny"} 3' in text
    assert 'gatekeeper_audit_duration_seconds_bucket{le="+Inf"} 1' in text
    # cumulative bucket counts: 2.5 falls in the le=3 bucket
    assert 'gatekeeper_audit_duration_seconds_bucket{le="3"} 1' in text
    assert 'gatekeeper_audit_duration_seconds_bucket{le="2"} 0' in text


def test_exporter_http_endpoint():
    r = fresh_reporters()
    r.report_request("allow", 0.002)
    exp = MetricsExporter(port=0, registry=r.registry)
    exp.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exp.port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert "gatekeeper_request_count" in body
    finally:
        exp.stop()
