"""gklint analyzer unit tests: every seeded fixture violation is
flagged (the PR 6 ABBA deadlock shape, the PR 7 cv-held-lock stall,
tracer truthiness, swallowed admission exceptions, resource hygiene),
every clean twin is silent, and the suppression + baseline mechanics
behave as documented in docs/static-analysis.md."""

import json
import os
import pathlib
import textwrap

from gatekeeper_tpu import analysis

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "gklint_fixtures"


def _lint(*names):
    paths = [str(FIXTURES / n) for n in names]
    return analysis.lint(str(REPO), paths)


def _rules_by_file(findings):
    out = {}
    for f in findings:
        out.setdefault(os.path.basename(f.path), set()).add(f.rule)
    return out


# ---- must-flag seeds --------------------------------------------------------


def test_lockorder_abba_flagged_both_sites():
    findings = [
        f for f in _lint("lockorder_bad.py") if f.rule == "lock-order-cycle"
    ]
    # one finding per conflicting edge: the gate->driver site AND the
    # driver->gate site, each naming the full cycle
    assert len(findings) == 2, findings
    assert {f.context for f in findings} == {"warm_path", "sweep_path"}
    for f in findings:
        assert "DISPATCH_LOCK" in f.message and "DRIVER_LOCK" in f.message
        assert "deadlock cycle" in f.message


def test_lockorder_clean_twin_silent():
    assert _lint("lockorder_clean.py") == []


def test_cvhold_flagged_as_cv_held_lock_and_blocking():
    rules = _rules_by_file(_lint("cvhold_bad.py"))["cvhold_bad.py"]
    assert "cv-held-lock" in rules  # the PR 7 _adapt-under-cv shape
    assert "blocking-under-lock" in rules  # readline under the cv
    cv = [f for f in _lint("cvhold_bad.py") if f.rule == "cv-held-lock"]
    assert cv[0].context == "Batcher.run_once"
    assert "_driver_lock" in cv[0].message and "_cv" in cv[0].message


def test_cvhold_clean_twin_silent():
    assert _lint("cvhold_clean.py") == []


def test_tracer_seeds_flagged():
    findings = _lint("tracer_bad.py")
    rules = {f.rule for f in findings}
    assert rules == {"tracer-truthiness", "jit-in-loop", "impure-in-jit"}
    truthy = [f for f in findings if f.rule == "tracer-truthiness"]
    # the `if x > limit` branch AND the float(x) coercion
    assert len(truthy) == 2
    assert all("bad_kernel" in f.message for f in truthy)


def test_tracer_clean_twin_silent():
    # jnp.where, shape-space branches, module-scope jit: all legal
    assert _lint("tracer_clean.py") == []


def test_swallowed_admission_exception_flagged():
    findings = _lint("swallow_bad.py")
    assert {f.rule for f in findings} == {"swallowed-exception"}
    assert {f.context for f in findings} == {"handle_admission", "audit_sweep"}


def test_swallow_clean_twin_silent():
    assert _lint("swallow_clean.py") == []


def test_hygiene_seeds_flagged():
    rules = _rules_by_file(_lint("hygiene_bad.py"))["hygiene_bad.py"]
    assert rules == {"thread-leak", "start-guard", "listener-close"}


def test_hygiene_clean_twin_silent():
    assert _lint("hygiene_clean.py") == []


def test_bare_join_flagged():
    findings = _lint("barejoin_bad.py")
    assert [f.rule for f in findings] == ["bare-join"]
    assert findings[0].context == "Supervisor.stop"


# ---- suppression mechanics --------------------------------------------------


def test_reasoned_suppression_honored_and_unreasoned_reported():
    findings = _lint("suppression_demo.py")
    rules = {f.rule for f in findings}
    # the reasoned disable silences its swallow entirely; the unreasoned
    # one still suppresses but earns a suppression-reason finding; the
    # typo'd rule id earns unknown-rule
    assert "swallowed-exception" not in rules
    assert "suppression-reason" in rules
    assert "unknown-rule" in rules


def test_disable_file_suppresses_everywhere(tmp_path):
    mod = tmp_path / "gen.py"
    mod.write_text(textwrap.dedent("""\
        # gklint: disable-file=swallowed-exception -- generated fixture
        def a(run):
            try:
                return run()
            except Exception:
                pass
        def b(run):
            try:
                return run()
            except Exception:
                pass
    """))
    findings = analysis.lint(str(tmp_path), [str(mod)])
    assert [f.rule for f in findings] == []


def test_suppression_comment_block_above_statement(tmp_path):
    mod = tmp_path / "block.py"
    mod.write_text(textwrap.dedent("""\
        def a(run):
            try:
                return run()
            # a multi-line justification whose disable sits at the top
            # gklint: disable=swallowed-exception -- documented contract
            # with trailing commentary lines after the disable
            except Exception:
                pass
    """))
    assert analysis.lint(str(tmp_path), [str(mod)]) == []


# ---- baseline mechanics -----------------------------------------------------


def test_baseline_roundtrip_absorbs_then_surfaces_new(tmp_path):
    findings = _lint("swallow_bad.py")
    assert findings
    baseline_path = tmp_path / "baseline.json"
    analysis.write_baseline(str(baseline_path), findings)
    data = json.loads(baseline_path.read_text())
    assert data["findings"]  # keyed entries present
    baseline = analysis.load_baseline(str(baseline_path))
    # identical findings are fully absorbed
    assert analysis.apply_baseline(findings, baseline) == []
    # a NEW finding (different context) still surfaces
    extra = analysis.Finding(
        "swallowed-exception", findings[0].path, 99, "new", "new_fn"
    )
    assert analysis.apply_baseline(findings + [extra], baseline) == [extra]


def test_baseline_is_count_capped(tmp_path):
    findings = _lint("swallow_bad.py")
    one = [findings[0]]
    baseline_path = tmp_path / "baseline.json"
    analysis.write_baseline(str(baseline_path), one)
    baseline = analysis.load_baseline(str(baseline_path))
    # two findings under a count-1 key: one absorbed, one surfaces
    dup = analysis.Finding(
        findings[0].rule, findings[0].path, findings[0].line + 1,
        findings[0].message, findings[0].context,
    )
    left = analysis.apply_baseline([findings[0], dup], baseline)
    assert len(left) == 1


# ---- registry cross-checks --------------------------------------------------


def _registry_repo(tmp_path, fire_point="faults.KNOWN", doc_points=("a.b",),
                   view_name="documented_metric", doc_metrics=("documented_metric",)):
    root = tmp_path
    (root / "gatekeeper_tpu" / "faults").mkdir(parents=True)
    (root / "gatekeeper_tpu" / "metrics").mkdir(parents=True)
    (root / "docs").mkdir()
    (root / "gatekeeper_tpu" / "faults" / "__init__.py").write_text(
        'KNOWN = "a.b"\nALL_POINTS = (KNOWN,)\n'
    )
    (root / "gatekeeper_tpu" / "metrics" / "catalog.py").write_text(
        f'View = object\nv = View\ndef catalog_views():\n'
        f'    return [View("{view_name}")]\n'
        if False else
        f'def catalog_views():\n    return [View("{view_name}")]\n'
    )
    (root / "gatekeeper_tpu" / "caller.py").write_text(
        "from . import faults\n"
        f"def go():\n    faults.fire({fire_point})\n"
    )
    (root / "docs" / "failure-modes.md").write_text(
        "\n".join(f"`{p}`" for p in doc_points) + "\n"
    )
    (root / "docs" / "metrics.md").write_text(
        "\n".join(f"`{m}`" for m in doc_metrics) + "\n"
    )
    return root


def test_unknown_fault_point_literal_flagged(tmp_path):
    root = _registry_repo(tmp_path, fire_point='"not.registered"')
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    assert any(f.rule == "unknown-fault-point" for f in findings)


def test_registered_fault_point_clean(tmp_path):
    root = _registry_repo(tmp_path)
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    assert [f for f in findings if f.rule == "unknown-fault-point"] == []


def test_undocumented_fault_point_flagged(tmp_path):
    root = _registry_repo(tmp_path, doc_points=("something.else",))
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    assert any(f.rule == "undocumented-fault-point" for f in findings)


def test_undocumented_metric_flagged(tmp_path):
    root = _registry_repo(tmp_path, doc_metrics=("other_metric",))
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    assert any(f.rule == "undocumented-metric" for f in findings)


# ---- misc ergonomics --------------------------------------------------------


def test_syntax_error_is_reported_not_crashed(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def nope(:\n")
    findings = analysis.lint(str(tmp_path), [str(bad)])
    assert findings and "does not parse" in findings[0].message


def test_select_restricts_rules():
    findings = analysis.lint(
        str(REPO), [str(FIXTURES / "tracer_bad.py")],
        select={"jit-in-loop"},
    )
    assert {f.rule for f in findings} == {"jit-in-loop"}


def test_every_registered_rule_documented_in_catalog():
    # self-check: every rule id carries a description for --list-rules
    for rule, doc in analysis.RULES.items():
        assert doc and len(doc) > 10, rule


# ---- unbounded-queue (ISSUE 12) --------------------------------------------


def test_unbounded_queue_seeds_flagged():
    findings = _lint("queuebound_bad.py")
    assert {f.rule for f in findings} == {"unbounded-queue"}
    # the bare Queue(), the maxsize=0, and the SimpleQueue
    assert len(findings) == 3, findings
    assert {f.context for f in findings} == {"Intake.__init__"}
    assert any("SimpleQueue" in f.message for f in findings)


def test_unbounded_queue_clean_twin_silent():
    assert _lint("queuebound_clean.py") == []


def test_pending_list_flagged_on_serving_paths_only(tmp_path):
    src = textwrap.dedent(
        """
        class Batcher:
            def __init__(self):
                self._pending = []
        """
    )
    root = tmp_path / "repo"
    serving = root / "gatekeeper_tpu" / "webhook"
    serving.mkdir(parents=True)
    (serving / "srv.py").write_text(src)
    elsewhere = root / "gatekeeper_tpu" / "audit"
    elsewhere.mkdir(parents=True)
    (elsewhere / "pack.py").write_text(src)
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    by_path = {f.path for f in findings
               if f.rule == "unbounded-queue"}
    # the serving-path copy is flagged; the audit-side scratch list is
    # out of the rule's blast radius by design
    assert by_path == {"gatekeeper_tpu/webhook/srv.py"}, findings


def test_pending_list_with_len_bound_is_clean(tmp_path):
    src = textwrap.dedent(
        """
        class Batcher:
            MAX_PENDING = 64

            def __init__(self):
                self._pending = []

            def push(self, item):
                if len(self._pending) >= self.MAX_PENDING:
                    raise RuntimeError("shed")
                self._pending.append(item)
        """
    )
    root = tmp_path / "repo"
    serving = root / "gatekeeper_tpu" / "fleet"
    serving.mkdir(parents=True)
    (serving / "door.py").write_text(src)
    findings = analysis.lint(str(root), [str(root / "gatekeeper_tpu")])
    assert [f for f in findings if f.rule == "unbounded-queue"] == []


def test_unbounded_queue_suppressible_with_reason(tmp_path):
    src = textwrap.dedent(
        """
        import queue

        # gklint: disable=unbounded-queue -- bounded by protocol: one
        # reply per command
        REPLIES = queue.Queue()
        """
    )
    f = tmp_path / "mod.py"
    f.write_text(src)
    findings = analysis.lint(str(tmp_path), [str(f)])
    assert findings == []
