"""Incremental host-serving constraint side (ops/npside.py).

The np path must be mask-identical to compute_masks (same VExpr IR, same
match algebra) and stay correct under INCREMENTAL maintenance: adds,
updates, removes, template re-puts, vocabulary growth between serves,
and change-log overrun.  The reference analogue is the admission-time
matching_constraints scan + per-template Rego eval
(target_template_source.go:27-44); here it is one numpy mask pass.
"""

import numpy as np
import pytest

from gatekeeper_tpu.client import Client
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates

from .test_client_conformance import (
    PARAM_REGO,
    make_constraint,
    make_object,
    make_template,
)


def pod_req(pod, i):
    return {
        "uid": str(i),
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": "default",
        "operation": "CREATE",
        "object": pod,
    }


def masks_equal(driver, reviews):
    """Assert np serve and compute_masks agree cell-for-cell."""
    with driver._lock:
        ordered_d, mask_d, rej_d = driver.compute_masks(reviews)
        driver._np_side.sync(driver)
        got = driver._np_side.serve(driver, reviews)
        assert got is not None
        ordered_n, mask_n, rej_n = got
    assert [o[:2] for o in ordered_d] == [o[:2] for o in ordered_n]
    R = mask_n.shape[1]
    np.testing.assert_array_equal(mask_d[:, :R], mask_n)
    np.testing.assert_array_equal(rej_d[:, :R], rej_n)


@pytest.fixture
def driver():
    d = TpuDriver()
    d.DEVICE_MIN_CELLS = 10**9  # route reviews to the host side
    d.NP_MIN_CELLS = 0  # even 1-constraint scenarios serve from npside
    return d


class TestMaskParity:
    def test_synthetic_corpus(self, driver):
        templates, constraints = make_templates(60)
        c = Client(driver=driver)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        for i, p in enumerate(make_pods(12, seed=3)):
            masks_equal(driver, [pod_req(p, i)])

    def test_vocab_growth_between_serves(self, driver):
        """New strings interned by later reviews must land in the
        predicate mats before the gather (the r5 refresh-order bug:
        extract_columns, not pack_reviews, interns program columns)."""
        templates, constraints = make_templates(24)
        c = Client(driver=driver)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        with driver._lock:
            driver._np_side.sync(driver)
        for i, p in enumerate(make_pods(10, seed=11, violation_rate=0.0)):
            r = pod_req(p, i)
            out, _trace = driver.review(r)
            # compliant pods must draw ZERO violations; a stale predicate
            # table shows up as mass imageprefix false-renders
            assert out == []

    def test_batch_of_multiple_reviews(self, driver):
        templates, constraints = make_templates(12)
        c = Client(driver=driver)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        pods = make_pods(6, seed=5)
        masks_equal(driver, [pod_req(p, i) for i, p in enumerate(pods)])


class TestIncrementalSync:
    def test_constraint_update_changes_params(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template(rego=PARAM_REGO))
        c.add_constraint(make_constraint(params={"name": "alpha"}))
        assert len(c.review(make_object("alpha")).results()) == 1
        # update the SAME constraint to a different parameter
        c.add_constraint(make_constraint(params={"name": "beta"}))
        assert c.review(make_object("alpha")).results() == []
        assert len(c.review(make_object("beta")).results()) == 1

    def test_constraint_remove(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(name="a", params={"name": "x"}))
        c.add_constraint(make_constraint(name="b", params={"name": "x"}))
        assert len(c.review(make_object("x")).results()) == 2
        c.remove_constraint(make_constraint(name="a"))
        out = c.review(make_object("x")).results()
        assert len(out) == 1
        assert out[0].constraint["metadata"]["name"] == "b"

    def test_template_reput_changes_program(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(params={"name": "x"}))
        assert len(c.review(make_object("x")).results()) == 1
        # re-put the template with an inverted rule: violation when the
        # name does NOT equal the parameter
        inverted = """
package foo
violation[{"msg": msg}] {
  input.review.object.metadata.name != input.parameters.name
  msg := "name mismatch"
}
"""
        c.add_template(make_template(rego=inverted))
        assert c.review(make_object("x")).results() == []
        assert len(c.review(make_object("y")).results()) == 1

    def test_template_remove_then_constraint_orphan(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(params={"name": "x"}))
        c.remove_template(make_template())
        # constraint gone with the template (client cascade); np side
        # must not serve stale rows
        assert c.review(make_object("x")).results() == []

    def test_delete_template_purges_caches(self, driver):
        """delete_template cascades constraints away; the incremental
        ordered/memoable caches must drop them too (advisor r5: stale
        entries kept evaluating deleted constraints and permanently
        disabled the request memo)."""
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            params={"name": "x"},
            match={"namespaceSelector": {"matchLabels": {"env": "prod"}}},
        ))
        assert len(c.review(make_object("x")).results()) >= 1
        c.remove_template(make_template())
        assert c.review(make_object("x")).results() == []
        assert driver._ordered_constraints() == []
        assert not driver._memoable_false
        with driver._lock:
            assert driver._memoable_synced() is True

    def test_explicit_null_kinds_is_wildcard(self, driver):
        """match: {kinds: null} means wildcard (oracle _get semantics);
        the GVK prefilter must not skip such constraints (advisor r5)."""
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            params={"name": "x"}, match={"kinds": None},
        ))
        out = c.review(make_object("x")).results()
        from gatekeeper_tpu.client.drivers import InterpDriver

        ci = Client(driver=InterpDriver())
        ci.add_template(make_template())
        ci.add_constraint(make_constraint(
            params={"name": "x"}, match={"kinds": None},
        ))
        want = ci.review(make_object("x")).results()
        assert [r.msg for r in out] == [r.msg for r in want]
        assert len(out) == 1
        # and through the forced interp walk too — FRESH content so the
        # request memo can't replay the np-served verdict
        driver.np_serve_enabled = False
        assert [r.msg for r in c.review(make_object("zzz")).results()] == \
            ["DENIED"]  # deny-all template: the walk DID visit it
        assert len(driver._gvk_walk_list(
            {"kind": {"group": "", "kind": "ConfigMap"}}
        )) == 1  # null kinds == wildcard: visited for every GVK
        driver.np_serve_enabled = True

    def test_change_log_overrun_rebuilds(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(params={"name": "x"}))
        c.review(make_object("x"))
        # simulate a long-disconnected side: force the floor past it
        with driver._lock:
            driver._cs_log_floor = driver._cs_epoch + 100
            driver._cs_epoch += 100
        out = c.review(make_object("x")).results()
        assert len(out) == 1


class TestSelectors:
    def test_label_selector_still_exact(self, driver):
        """The host fast path skips selector algebra only when every row's
        selector is empty; a real selector must still evaluate."""
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            params={"name": "x"},
            match={"labelSelector": {"matchLabels": {"team": "a"}}},
        ))
        hit = make_object("x", labels={"team": "a"})
        miss = make_object("x", labels={"team": "b"})
        assert len(c.review(hit).results()) == 1
        assert c.review(miss).results() == []

    def test_namespace_selector_autoreject(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            params={"name": "zzz"},
            match={"namespaceSelector": {"matchLabels": {"env": "prod"}}},
        ))
        out = c.review(make_object("anything")).results()
        # pin against the oracle: identical messages in identical order
        from gatekeeper_tpu.client.drivers import InterpDriver

        ci = Client(driver=InterpDriver())
        ci.add_template(make_template())
        ci.add_constraint(make_constraint(
            params={"name": "zzz"},
            match={"namespaceSelector": {"matchLabels": {"env": "prod"}}},
        ))
        want = ci.review(make_object("anything")).results()
        assert [r.msg for r in out] == [r.msg for r in want]
        assert "Namespace is not cached in OPA." in [r.msg for r in out]


class TestStorm:
    def test_interleaved_unique_reviews_stay_correct(self, driver):
        """Mid-storm serves (every add bumps the epoch) must match a
        fresh full evaluation at the end."""
        templates, constraints = make_templates(40)
        c = Client(driver=driver)
        pods = make_pods(40, seed=13)
        seen = []
        for i, (t, k) in enumerate(zip(templates, constraints)):
            c.add_template(t)
            c.add_constraint(k)
            out, _ = driver.review(pod_req(pods[i], i))
            seen.append(sorted(
                (r.constraint["kind"], r.constraint["metadata"]["name"],
                 r.msg)
                for r in out
            ))
        # replay the same pods against the settled side via the oracle
        from gatekeeper_tpu.client.drivers import InterpDriver

        oracle = InterpDriver()
        for kind, tmpl in driver.templates.items():
            oracle.put_template(kind, tmpl)
        for kind, by_name in driver.constraints.items():
            for name, cs in by_name.items():
                oracle.put_constraint(kind, name, cs)
        for i, p in enumerate(pods):
            want = sorted(
                (r.constraint["kind"], r.constraint["metadata"]["name"],
                 r.msg)
                for r in oracle.review(pod_req(p, i))[0]
            )
            # mid-storm review i only saw templates 0..i installed;
            # filter the oracle's answer down to those
            installed = {t["spec"]["crd"]["spec"]["names"]["kind"]
                         for t in templates[: i + 1]}
            want = [w for w in want if w[0] in installed]
            assert seen[i] == want


class TestGvkPrefilter:
    def test_walk_list_prunes_unrelated_kinds(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            params={"name": "x"},
            match={"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
        ))
        pod_review = {"uid": "1", "kind": {"group": "", "kind": "Pod"},
                      "name": "p", "operation": "CREATE",
                      "object": {"kind": "Pod", "metadata": {"name": "p"}}}
        cm_review = {"uid": "2", "kind": {"group": "", "kind": "ConfigMap"},
                     "name": "m", "operation": "CREATE",
                     "object": {"kind": "ConfigMap",
                                "metadata": {"name": "m"}}}
        assert len(driver._gvk_walk_list(pod_review)) == 1
        assert driver._gvk_walk_list(cm_review) == []

    def test_wildcards_and_nssel_kept(self, driver):
        c = Client(driver=driver)
        c.add_template(make_template())
        c.add_constraint(make_constraint(
            name="wild", params={"name": "x"},
            match={"kinds": [{"apiGroups": ["*"], "kinds": ["*"]}]},
        ))
        c.add_constraint(make_constraint(
            name="nssel", params={"name": "x"},
            match={"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                   "namespaceSelector": {"matchLabels": {"a": "b"}}},
        ))
        cm_review = {"uid": "1", "kind": {"group": "apps",
                                          "kind": "Deployment"},
                     "name": "d", "operation": "CREATE",
                     "object": {"kind": "Deployment",
                                "metadata": {"name": "d"}}}
        names = [n for _k, n, _c in driver._gvk_walk_list(cm_review)]
        # wildcard matches everything; nssel rides along for autoreject
        assert names == ["nssel", "wild"]

    def test_interp_walk_matches_oracle_with_prefilter(self, driver):
        """Force the interp walk (np off) and pin it against the oracle
        across mixed-kind reviews."""
        driver.np_serve_enabled = False
        c = Client(driver=driver)
        templates, constraints = make_templates(18)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        from gatekeeper_tpu.client.drivers import InterpDriver

        oracle = InterpDriver()
        for kind, tmpl in driver.templates.items():
            oracle.put_template(kind, tmpl)
        for kind, by_name in driver.constraints.items():
            for name, cs in by_name.items():
                oracle.put_constraint(kind, name, cs)
        for i, p in enumerate(make_pods(8, seed=17)):
            r = pod_req(p, i)
            got = [(x.constraint["metadata"]["name"], x.msg)
                   for x in driver.review(r)[0]]
            want = [(x.constraint["metadata"]["name"], x.msg)
                    for x in oracle.review(r)[0]]
            assert got == want
