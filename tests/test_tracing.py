"""End-to-end tracing and per-stage telemetry (ISSUE 2 tentpole).

Covers: traceparent round-trip through the webhook server, batch
span <-> request span linkage through the micro-batcher, tier/breaker
attributes under a tripped breaker, /debug/traces filtering and
/debug/stacks, the slow-trace sampler, trace_id injection into deny log
lines, the stage-sum accounting contract (spans sum to ~the recorded
request_duration_seconds sample), and Prometheus exposition for every
new histogram/counter."""

import io
import json
import logging
import threading
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu import logging as gklog
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.metrics import Reporters, render_prometheus
from gatekeeper_tpu.metrics.views import Registry
from gatekeeper_tpu.obs import trace as obs
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.webhook import (
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
)

from .test_controllers import CONSTRAINT, TEMPLATE

TRACEPARENT = "00-" + "1234567890abcdef" * 2 + "-aabbccddeeff0011-01"


@pytest.fixture(autouse=True)
def _fresh_tracer():
    obs.configure(buffer_size=256, slow_threshold_s=0.25, sample_rate=1.0)
    obs.get_tracer().clear()
    yield
    obs.get_tracer().clear()


def ns_request(name="demo", labels=None):
    return {
        "uid": f"uid-{name}",
        "kind": {"group": "", "version": "v1", "kind": "Namespace"},
        "name": name,
        "namespace": "",
        "operation": "CREATE",
        "userInfo": {"username": "alice"},
        "object": {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": name, "labels": labels or {}},
        },
    }


def post(port, request, headers=None, path="/v1/admit"):
    body = json.dumps({"request": request}).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, headers=hdrs
    )
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read())


def get_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


class TestSpanPrimitive:
    def test_traceparent_parse_format_round_trip(self):
        tid, sid = obs.parse_traceparent(TRACEPARENT)
        assert tid == "1234567890abcdef" * 2
        assert sid == "aabbccddeeff0011"
        assert obs.parse_traceparent(obs.format_traceparent(tid, sid)) == (
            tid, sid
        )

    @pytest.mark.parametrize("bad", [
        None, "", "00", "00-short-aabbccddeeff0011-01",
        "00-" + "0" * 32 + "-aabbccddeeff0011-01",       # all-zero trace
        "00-" + "1234567890abcdef" * 2 + "-" + "0" * 16 + "-01",
        "00-" + "zz" * 16 + "-aabbccddeeff0011-01",      # non-hex
        "ff-" + "12" * 16 + "-aabbccddeeff0011-01",      # forbidden version
        "zz-" + "12" * 16 + "-aabbccddeeff0011-01",      # non-hex version
        "0-" + "12" * 16 + "-aabbccddeeff0011-01",       # short version
        "00-" + "AB" * 16 + "-aabbccddeeff0011-01",      # uppercase hex
    ])
    def test_traceparent_malformed_rejected(self, bad):
        assert obs.parse_traceparent(bad) is None

    def test_span_without_context_is_discarded(self):
        with obs.span("orphan", stage=obs.PACK):
            pass
        assert obs.get_tracer().traces() == []

    def test_nested_spans_and_completion(self):
        with obs.root_span("admission", traceparent=TRACEPARENT) as root:
            assert obs.current_trace_id() == "1234567890abcdef" * 2
            with obs.span("tpu.pack", stage=obs.PACK):
                pass
        traces = obs.get_tracer().traces()
        assert len(traces) == 1
        t = traces[0]
        assert t["trace_id"] == "1234567890abcdef" * 2
        assert t["remote_parent"] == "aabbccddeeff0011"
        assert t["root"] == "admission"
        pack = [s for s in t["spans"] if s["name"] == "tpu.pack"][0]
        assert pack["parent_id"] == root.span_id
        assert pack["attrs"]["stage"] == "pack"

    def test_ring_buffer_bounded_and_filtered(self):
        obs.configure(buffer_size=4)
        for i in range(10):
            with obs.root_span(f"r{i}"):
                pass
        traces = obs.get_tracer().traces()
        assert len(traces) == 4
        assert traces[0]["root"] == "r9"  # newest first
        assert obs.get_tracer().traces(min_ms=1e9) == []
        assert len(obs.get_tracer().traces(limit=2)) == 2

    def test_slow_trace_sampler_logs_breakdown(self, caplog):
        obs.configure(slow_threshold_s=0.0001)
        with caplog.at_level(logging.WARNING, logger="gatekeeper.obs"):
            with obs.root_span("slowpoke"):
                with obs.span("work", stage=obs.RENDER):
                    import time

                    time.sleep(0.002)
        recs = [r for r in caplog.records if "slow trace" in r.getMessage()]
        assert recs
        kv = recs[0].kv
        assert kv["event_type"] == "slow_trace"
        assert "render" in kv["stages"]

    def test_fault_plane_event_lands_on_span(self):
        from gatekeeper_tpu import faults

        plane = faults.install(seed=7)
        try:
            plane.add(
                faults.TPU_DISPATCH,
                faults.FaultRule(mode=faults.LATENCY, latency_s=0.0),
            )
            with obs.root_span("req"):
                with obs.span("tpu.dispatch", stage=obs.DISPATCH):
                    faults.fire(faults.TPU_DISPATCH)
        finally:
            faults.uninstall()
        t = obs.get_tracer().traces()[0]
        disp = [s for s in t["spans"] if s["name"] == "tpu.dispatch"][0]
        ev = disp["events"][0]
        assert ev["name"] == "fault_injected"
        assert ev["point"] == faults.TPU_DISPATCH
        assert ev["mode"] == faults.LATENCY


def make_server(log_denies=False, registry=None, batch_window_s=0.002):
    driver = TpuDriver()
    driver.DEVICE_MIN_CELLS = 0  # force the device path: full stage set
    client = Client(driver=driver)
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    reporters = Reporters(registry or Registry())
    mb = MicroBatcher(client, window_s=batch_window_s)
    handler = ValidationHandler(
        mb, kube=InMemoryKube(), reporter=reporters, log_denies=log_denies
    )
    srv = WebhookServer(handler, NamespaceLabelHandler(), port=0)
    srv.start()
    return srv, mb, reporters


class TestWebhookTracing:
    def test_traceparent_round_trip_and_deny_log_trace_id(self):
        srv, mb, _rep = make_server(log_denies=True)
        buf = io.StringIO()
        lg = logging.getLogger("gatekeeper.webhook")
        old_level, old_prop = lg.level, lg.propagate
        h = logging.StreamHandler(buf)
        h.setFormatter(gklog.JsonFormatter())
        lg.addHandler(h)
        lg.setLevel(logging.INFO)
        lg.propagate = False
        try:
            post(srv.port, ns_request("warm"))  # compile outside the assert
            obs.get_tracer().clear()
            out = post(srv.port, ns_request("traced"),
                       headers={"traceparent": TRACEPARENT})
            assert out["response"]["allowed"] is False  # CONSTRAINT denies
            traces = obs.get_tracer().traces()
            assert len(traces) == 1
            t = traces[0]
            # the upstream trace id was adopted end to end
            assert t["trace_id"] == "1234567890abcdef" * 2
            assert t["remote_parent"] == "aabbccddeeff0011"
            root = [s for s in t["spans"] if s["name"] == "admission"][0]
            assert root["attrs"]["admission_status"] == "deny"
            # the deny log line carries the same trace id
            denies = [
                json.loads(line) for line in buf.getvalue().splitlines()
                if '"violation"' in line
            ]
            assert denies, buf.getvalue()
            assert denies[-1]["trace_id"] == "1234567890abcdef" * 2
        finally:
            lg.removeHandler(h)
            lg.setLevel(old_level)
            lg.propagate = old_prop
            srv.stop()
            mb.stop()

    def test_stage_spans_sum_to_request_duration(self):
        """Acceptance: a single admission served through the micro-batcher
        yields a retrievable trace whose stage spans sum to within 10% of
        the recorded request_duration_seconds sample."""
        registry = Registry()
        srv, mb, _rep = make_server(registry=registry)
        try:
            for i in range(5):  # warm every shape/cache outside the assert
                post(srv.port, ns_request(f"warm-{i}"))
            # timing measurement: a one-off scheduler/GC pause landing in
            # the un-spanned handler slices can dent one sample, so take
            # the best accounting ratio over a few requests
            best = (None, None, float("inf"))
            for attempt in range(5):
                registry.clear()
                obs.get_tracer().clear()
                post(srv.port, ns_request(f"unique-measured-{attempt}"))
                t = obs.get_tracer().traces()[0]
                stages = obs.stage_breakdown(t)
                # the full stage set of a device-path evaluation
                for stage in (obs.CACHE_LOOKUP, obs.PACK, obs.DISPATCH,
                              obs.RENDER):
                    assert stage in stages, stages
                rows = registry.view_rows("request_duration_seconds")
                assert rows
                dur_ms = sum(d.sum for d in rows.values()) * 1000.0
                ratio = sum(stages.values()) / dur_ms
                if abs(ratio - 1.0) < abs(best[2] - 1.0):
                    best = (stages, dur_ms, ratio)
                if 0.9 <= ratio <= 1.1:
                    break
            stages, dur_ms, ratio = best
            assert 0.9 <= ratio <= 1.1, (stages, dur_ms, ratio)
        finally:
            srv.stop()
            mb.stop()

    def test_batch_span_links_concurrent_request_spans(self):
        srv, mb, _rep = make_server(batch_window_s=0.02)
        try:
            post(srv.port, ns_request("warm"))
            obs.get_tracer().clear()
            errors = []

            def worker(i):
                try:
                    post(srv.port, ns_request(f"burst-{i}"))
                except Exception as e:  # pragma: no cover - assert below
                    errors.append(e)

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(6)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert not errors
            traces = obs.get_tracer().traces()
            assert len(traces) == 6
            # at least one trace went through the queued/batched path and
            # carries the mirrored batch span (the first request may take
            # the idle inline path)
            linked = []
            for t in traces:
                for s in t["spans"]:
                    if s["name"] == "webhook.batch":
                        linked.append((t, s))
            assert linked, [
                [s["name"] for s in t["spans"]] for t in traces
            ]
            for t, batch_rec in linked:
                # the batch span lives in its own trace...
                assert batch_rec["trace_id"] != t["trace_id"]
                # ...and links back to this trace's request span
                root = [s for s in t["spans"] if s["name"] == "admission"][0]
                link_ids = {l["span_id"] for l in batch_rec["links"]}
                assert root["span_id"] in link_ids
                # queue-wait was recorded for batched members
                names = [s["name"] for s in t["spans"]]
                assert "webhook.queue_wait" in names
        finally:
            srv.stop()
            mb.stop()

    def test_tier_and_breaker_attrs_under_tripped_breaker(self):
        srv, mb, _rep = make_server()
        try:
            post(srv.port, ns_request("warm"))
            driver = mb._client.driver
            driver.breaker.trip()
            obs.get_tracer().clear()
            out = post(srv.port, ns_request("degraded-unique"))
            assert out["response"]["allowed"] is False
            t = obs.get_tracer().traces()[0]
            evals = [
                s for s in t["spans"]
                if "breaker" in (s.get("attrs") or {})
            ]
            assert evals, [s["name"] for s in t["spans"]]
            assert all(s["attrs"]["breaker"] == "open" for s in evals)
            assert all(
                s["attrs"]["tier"] in ("interp", "numpy") for s in evals
            )
            # no device-tier span served this degraded request
            assert not [
                s for s in t["spans"]
                if (s.get("attrs") or {}).get("tier") == "tpu"
            ]
        finally:
            driver.breaker.record_success()  # close for clean teardown
            srv.stop()
            mb.stop()

    def test_debug_traces_filtering_and_stacks(self):
        srv, mb, _rep = make_server()
        try:
            post(srv.port, ns_request("warm"))
            obs.get_tracer().clear()
            post(srv.port, ns_request("a-unique"))
            post(srv.port, ns_request("b-unique"))
            out = get_json(srv.port, "/debug/traces")
            assert len(out["traces"]) == 2
            assert out["traces"][0]["root"] == "admission"
            # min_ms filters, limit caps
            assert get_json(
                srv.port, "/debug/traces?min_ms=1000000"
            )["traces"] == []
            assert len(get_json(
                srv.port, "/debug/traces?limit=1"
            )["traces"]) == 1
            with pytest.raises(urllib.error.HTTPError) as exc:
                get_json(srv.port, "/debug/traces?min_ms=bogus")
            assert exc.value.code == 400
            stacks = get_json(srv.port, "/debug/stacks")
            assert stacks["thread_count"] >= 1
            names = {t["name"] for t in stacks["threads"]}
            assert "microbatcher" in names
            assert any(
                t["stack"] for t in stacks["threads"]
            )
        finally:
            srv.stop()
            mb.stop()

    def test_unknown_debug_path_is_json_404(self):
        srv, mb, _rep = make_server()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                get_json(srv.port, "/debug/nothing-here")
            assert exc.value.code == 404
            body = json.loads(exc.value.read())
            assert body["error"] == "unknown debug path"
            assert "/debug/traces" in body["available"]
        finally:
            srv.stop()
            mb.stop()


class TestStageMetricsExposition:
    def test_prometheus_output_for_every_new_metric(self):
        """Drive real traffic, then assert the Prometheus text output
        carries every new histogram/counter (the exporter serves the
        global registry the hot paths record into)."""
        srv, mb, _rep = make_server(batch_window_s=0.02)
        try:
            post(srv.port, ns_request("warm"))
            errors = []

            def worker(i):
                try:
                    post(srv.port, ns_request(f"m-{i}"))
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [
                threading.Thread(target=worker, args=(i,)) for i in range(4)
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert not errors
        finally:
            srv.stop()
            mb.stop()
        out = render_prometheus()  # global registry
        for needle in (
            "# TYPE gatekeeper_webhook_batch_queue_seconds histogram",
            "# TYPE gatekeeper_webhook_batch_size histogram",
            "# TYPE gatekeeper_tpu_pack_seconds histogram",
            "# TYPE gatekeeper_tpu_compile_seconds histogram",
            "# TYPE gatekeeper_tpu_dispatch_seconds histogram",
            "# TYPE gatekeeper_cache_requests_total counter",
        ):
            assert needle in out
        # real samples landed from the traffic above
        assert 'gatekeeper_tpu_pack_seconds_bucket{path="review"' in out
        assert ('gatekeeper_tpu_dispatch_seconds_bucket{path="review",'
                'tier="tpu"') in out
        assert 'cache_requests_total{cache="request_memo",outcome="miss"}' \
            in out
        assert "gatekeeper_webhook_batch_queue_seconds_count" in out
        assert "gatekeeper_webhook_batch_size_count" in out

    def test_histogram_sum_renders_like_other_samples(self):
        """Satellite: integral sums must not render as '40.0' (the old
        repr(val.sum) path)."""
        from gatekeeper_tpu.metrics.views import (
            AGG_DISTRIBUTION, Measure, View,
        )

        reg = Registry()
        m = Measure("x_seconds", "x", "s")
        reg.register(View("x_seconds", m, AGG_DISTRIBUTION,
                          buckets=(10.0, 100.0)))
        for v in (15.0, 25.0):  # sum = 40, integral
            reg.record(m, v)
        out = render_prometheus(reg)
        line = [
            ln for ln in out.splitlines()
            if ln.startswith("gatekeeper_x_seconds_sum")
        ][0]
        assert line == "gatekeeper_x_seconds_sum 40"


class TestAuditTracing:
    def test_audit_trace_has_sweep_stages(self):
        from gatekeeper_tpu.audit.manager import AuditManager

        driver = TpuDriver()
        driver.DEVICE_MIN_CELLS = 0
        # the container jax lacks jax.shard_map: the 8-virtual-device mesh
        # path would fail and degrade to the interpreter tier
        driver.mesh_enabled = False
        client = Client(driver=driver)
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
        kube = InMemoryKube()
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "audited", "labels": {}}})
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": "audited", "labels": {}}})
        mgr = AuditManager(kube, client, from_cache=True)
        obs.get_tracer().clear()
        mgr.audit_once()
        traces = [
            t for t in obs.get_tracer().traces() if t["root"] == "audit"
        ]
        assert traces
        t = traces[0]
        root = [s for s in t["spans"] if s["name"] == "audit"][0]
        assert root["attrs"]["mode"] == "from-cache"
        stages = obs.stage_breakdown(t)
        for stage in (obs.PACK, obs.DISPATCH, obs.FETCH, obs.RENDER,
                      obs.STATUS_WRITE):
            assert stage in stages, stages
        disp = [s for s in t["spans"] if s["name"] == "audit.dispatch"][0]
        assert disp["attrs"]["tier"] == "tpu"
        assert disp["attrs"]["shards"] >= 1


class TestActiveSpansConcurrency:
    """The cross-thread active_spans registry (profiler stage tagging,
    PR 11) under concurrent activate/deactivate churn: snapshots must
    stay iterable while N request threads mutate the registry, nesting
    must restore the outer span exactly, and finished threads must leave
    no entry behind (ISSUE 13 satellite)."""

    N_THREADS = 8
    ITERS = 300

    def test_churn_vs_snapshot_reader(self):
        stop = threading.Event()
        errors = []

        def reader():
            # the sampler's view: iterate snapshots continuously while
            # workers churn — a live-dict iteration would RuntimeError
            while not stop.is_set():
                try:
                    for ident, span in obs.active_spans().items():
                        assert isinstance(ident, int)
                        assert span.name  # a Span, never a torn entry
                except Exception as e:  # pragma: no cover - failure path
                    errors.append(e)
                    return

        def worker(idx):
            ident = threading.get_ident()
            try:
                for i in range(self.ITERS):
                    tr = obs.Trace(export=False)
                    outer = obs.Span(f"outer-{idx}", tr)
                    state = obs.activate(outer)
                    assert obs.active_spans()[ident] is outer
                    # nested context-manager activation (the _SpanCtx /
                    # _UseCtx path every traced request takes)
                    with obs.use_span(obs.Span(f"inner-{idx}", tr)) as sp:
                        assert obs.active_spans()[ident] is sp
                    # the nested exit restored the OUTER span
                    assert obs.active_spans()[ident] is outer
                    obs.deactivate(state)
                    assert ident not in obs.active_spans()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(self.N_THREADS)
        ]
        sampler = threading.Thread(target=reader, daemon=True)
        sampler.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
            assert not t.is_alive(), "worker wedged"
        stop.set()
        sampler.join(timeout=10.0)
        assert not sampler.is_alive(), "sampler reader wedged"
        assert errors == []
        # no finished worker left a registry entry behind
        live = {t.ident for t in threads}
        assert not live & set(obs.active_spans())

    def test_deactivate_out_of_order_restores_previous(self):
        tr = obs.Trace(export=False)
        ident = threading.get_ident()
        a, b = obs.Span("a", tr), obs.Span("b", tr)
        sa = obs.activate(a)
        sb = obs.activate(b)
        assert obs.active_spans()[ident] is b
        obs.deactivate(sb)
        assert obs.active_spans()[ident] is a
        obs.deactivate(sa)
        assert ident not in obs.active_spans()
