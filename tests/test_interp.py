"""Interpreter (oracle) semantics tests: corpus templates against the
reference's own good/bad fixtures, plus targeted Rego-semantics cases
(undefined propagation, negation, functions, comprehensions, set algebra)."""

import glob

import pytest
import yaml

from gatekeeper_tpu.engine.interp import TemplatePolicy
from gatekeeper_tpu.rego import RegoCompileError

from .corpus import REF, load_yaml, make_review, template_rego


def compile_template(relpath: str) -> TemplatePolicy:
    tmpl = load_yaml(relpath)
    rego, libs = template_rego(tmpl)
    return TemplatePolicy.compile(rego, libs)


class TestRequiredLabels:
    def test_bad_ns_violates(self):
        pol = compile_template("demo/basic/templates/k8srequiredlabels_template.yaml")
        obj = load_yaml("demo/basic/bad/bad_ns.yaml")
        v = pol.eval_violations(make_review(obj), {"labels": ["gatekeeper"]}, {})
        assert len(v) == 1
        assert v[0]["msg"] == 'you must provide labels: {"gatekeeper"}'
        assert v[0]["details"] == {"missing_labels": ["gatekeeper"]}

    def test_good_ns_passes(self):
        pol = compile_template("demo/basic/templates/k8srequiredlabels_template.yaml")
        obj = load_yaml("demo/basic/good/good_ns.yaml")
        assert pol.eval_violations(make_review(obj), {"labels": ["gatekeeper"]}, {}) == []


class TestPSP:
    """Each psp-pods fixture violates exactly its own template
    (reference pkg/webhook/testdata/psp-all-violations)."""

    BASE = "pkg/webhook/testdata/psp-all-violations"

    @pytest.fixture(scope="class")
    def setup(self):
        pols, params, pods = {}, {}, []
        for tf in sorted(glob.glob(str(REF / self.BASE / "psp-templates/*.yaml"))):
            t = yaml.safe_load(open(tf))
            kind = t["spec"]["crd"]["spec"]["names"]["kind"]
            rego, libs = template_rego(t)
            pols[kind] = TemplatePolicy.compile(rego, libs)
            params[kind] = {}
        for cf in glob.glob(str(REF / self.BASE / "psp-constraints/*.yaml")):
            c = yaml.safe_load(open(cf))
            if c["kind"] in params:
                params[c["kind"]] = c["spec"].get("parameters") or {}
        for pf in sorted(glob.glob(str(REF / self.BASE / "psp-pods/*.yaml"))):
            pods.append(yaml.safe_load(open(pf)))
        return pols, params, pods

    EXPECT = {
        "K8sPSPHostFilesystem": {"nginx-host-filesystem", "nginx-volume-types"},
        "K8sPSPHostNamespace": {"nginx-host-namespace"},
        "K8sPSPHostNetworkingPorts": {"nginx-host-networking-ports"},
        "K8sPSPPrivilegedContainer": {"nginx-privileged"},
        "K8sPSPVolumeTypes": {"nginx-host-filesystem", "nginx-volume-types"},
    }

    def test_violation_matrix(self, setup):
        pols, params, pods = setup
        for kind, pol in pols.items():
            violators = set()
            for pod in pods:
                review = make_review(pod, namespace="default")
                if pol.eval_violations(review, params[kind], {}):
                    violators.add(pod["metadata"]["name"])
            assert violators == self.EXPECT[kind], kind


class TestContainerLimits:
    """Function clauses, negation, arbitrary-precision literals, re_match."""

    @pytest.fixture(scope="class")
    def pol(self):
        return compile_template("demo/agilebank/templates/k8scontainterlimits_template.yaml")

    PARAMS = {"cpu": "200m", "memory": "1Gi"}

    def test_good(self, pol):
        obj = load_yaml("demo/agilebank/good_resources/opa.yaml")
        assert pol.eval_violations(make_review(obj), self.PARAMS, {}) == []

    def test_no_limits(self, pol):
        obj = load_yaml("demo/agilebank/bad_resources/opa_no_limits.yaml")
        msgs = [v["msg"] for v in pol.eval_violations(make_review(obj), self.PARAMS, {})]
        assert msgs == ["container <opa> has no resource limits"]

    def test_limits_too_high(self, pol):
        obj = load_yaml("demo/agilebank/bad_resources/opa_limits_too_high.yaml")
        msgs = sorted(v["msg"] for v in pol.eval_violations(make_review(obj), self.PARAMS, {}))
        assert msgs == [
            "container <opa> cpu limit <300m> is higher than the maximum allowed of <200m>",
            "container <opa> memory limit <4000Mi> is higher than the maximum allowed of <1Gi>",
        ]


class TestInventoryTemplates:
    def test_unique_label_duplicate(self):
        pol = compile_template("demo/basic/templates/k8suniquelabel_template.yaml")
        ns1 = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": "ns1", "labels": {"gatekeeper": "true"}}}
        ns2 = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": "ns2", "labels": {"gatekeeper": "true"}}}
        inv = {"cluster": {"v1": {"Namespace": {"ns1": ns1}}}}
        v = pol.eval_violations(make_review(ns2), {"label": "gatekeeper"}, inv)
        assert [x["msg"] for x in v] == ["label gatekeeper has duplicate value true"]

    def test_unique_label_self_excluded(self):
        pol = compile_template("demo/basic/templates/k8suniquelabel_template.yaml")
        ns1 = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": "ns1", "labels": {"gatekeeper": "true"}}}
        inv = {"cluster": {"v1": {"Namespace": {"ns1": ns1}}}}
        # reviewing ns1 itself: its cached copy must not count as a duplicate
        assert pol.eval_violations(make_review(ns1), {"label": "gatekeeper"}, inv) == []

    def test_unique_ingress_host(self):
        pol = compile_template("demo/agilebank/dryrun/k8suniqueingresshost_template.yaml")
        ing = lambda name, ns, host: {
            "apiVersion": "extensions/v1beta1", "kind": "Ingress",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"rules": [{"host": host}]},
        }
        other = ing("existing", "ns-a", "example.com")
        inv = {"namespace": {"ns-a": {"extensions/v1beta1": {"Ingress": {"existing": other}}}}}
        dup = ing("dup", "ns-b", "example.com")
        v = pol.eval_violations(make_review(dup), {}, inv)
        assert [x["msg"] for x in v] == [
            "ingress host conflicts with an existing ingress <example.com>"
        ]
        ok = ing("ok", "ns-b", "other.com")
        assert pol.eval_violations(make_review(ok), {}, inv) == []


class TestAllowedRepos:
    def test_wrong_repo(self):
        pol = compile_template("demo/agilebank/templates/k8sallowedrepos_template.yaml")
        obj = load_yaml("demo/agilebank/bad_resources/opa_wrong_repo.yaml")
        v = pol.eval_violations(make_review(obj), {"repos": ["openpolicyagent"]}, {})
        assert len(v) == 1 and "invalid image repo" in v[0]["msg"]

    def test_good_repo(self):
        pol = compile_template("demo/agilebank/templates/k8sallowedrepos_template.yaml")
        obj = load_yaml("demo/agilebank/good_resources/opa.yaml")
        assert pol.eval_violations(make_review(obj), {"repos": ["openpolicyagent"]}, {}) == []


class TestSemantics:
    """Targeted Rego-subset semantics."""

    def run(self, rego, input_value=None, inventory=None):
        pol = TemplatePolicy.compile(rego)
        return pol.eval_violations(
            (input_value or {}).get("review", {}),
            (input_value or {}).get("parameters", {}),
            inventory or {},
        )

    def test_undefined_vs_false_negation(self):
        v = self.run(
            """
package p
violation[{"msg": "undef"}] { not input.review.object.missing }
violation[{"msg": "false"}] { not input.review.object.flag }
violation[{"msg": "present"}] { input.review.object.present }
""",
            {"review": {"object": {"flag": False, "present": 1}}},
        )
        assert sorted(x["msg"] for x in v) == ["false", "present", "undef"]

    def test_else_unsupported(self):
        with pytest.raises(Exception):
            TemplatePolicy.compile(
                "package p\nviolation[{\"msg\": \"x\"}] { true } else = true { true }"
            )

    def test_recursion_rejected(self):
        with pytest.raises(RegoCompileError, match="recursion"):
            TemplatePolicy.compile(
                """
package p
violation[{"msg": "x"}] { f(1) > 0 }
f(x) = y { y := g(x) }
g(x) = y { y := f(x) }
"""
            )

    def test_data_ref_restriction(self):
        with pytest.raises(RegoCompileError, match="restricted"):
            TemplatePolicy.compile(
                'package p\nviolation[{"msg": "x"}] { data.secrets.key == "boo" }'
            )

    def test_lib_package_required(self):
        with pytest.raises(RegoCompileError, match="lib"):
            TemplatePolicy.compile(
                'package p\nviolation[{"msg": "x"}] { true }',
                ("package notlib\nhelper = 1 { true }",),
            )

    def test_lib_call(self):
        pol = TemplatePolicy.compile(
            """
package p
violation[{"msg": msg}] {
  data.lib.helpers.is_big(input.review.object.size)
  msg := sprintf("big: %v", [data.lib.helpers.limit])
}
""",
            (
                """
package lib.helpers
limit = 10 { true }
is_big(x) { x > limit }
""",
            ),
        )
        assert pol.eval_violations({"object": {"size": 11}}, {}, {}) == [{"msg": "big: 10"}]
        assert pol.eval_violations({"object": {"size": 9}}, {}, {}) == []

    def test_set_algebra_and_comprehensions(self):
        v = self.run(
            """
package p
violation[{"msg": msg}] {
  a := {x | x := input.review.object.xs[_]}
  b := {x | x := input.review.object.ys[_]}
  inter := a & b
  uni := a | b
  diff := a - b
  count(inter) == 1
  count(uni) == 3
  count(diff) == 1
  msg := sprintf("%v/%v/%v", [inter, uni, diff])
}
""",
            {"review": {"object": {"xs": ["p", "q"], "ys": ["q", "r"]}}},
        )
        assert v == [{"msg": '{"q"}/{"p", "q", "r"}/{"p"}'}]

    def test_object_pattern_membership(self):
        v = self.run(
            """
package p
pairs[{"k": k, "tag": "even"}] { k := input.review.object.ns[_]; k % 2 == 0 }
pairs[{"k": k, "tag": "odd"}] { k := input.review.object.ns[_]; k % 2 == 1 }
violation[{"msg": msg}] {
  pairs[{"k": k, "tag": "even"}]
  msg := sprintf("even %v", [k])
}
""",
            {"review": {"object": {"ns": [1, 2, 3, 4]}}},
        )
        assert sorted(x["msg"] for x in v) == ["even 2", "even 4"]

    def test_arbitrary_precision(self):
        v = self.run(
            """
package p
violation[{"msg": msg}] {
  x := 1152921504606846976000 * 2
  msg := sprintf("%v", [x])
}
"""
        )
        assert v == [{"msg": "2305843009213693952000"}]

    def test_division_and_mod_undefined_on_zero(self):
        assert (
            self.run('package p\nviolation[{"msg": "x"}] { y := 1 / 0; y == y }') == []
        )

    def test_string_builtins(self):
        v = self.run(
            """
package p
violation[{"msg": msg}] {
  s := "registry.example.com/app:latest"
  parts := split(s, ":")
  tag := parts[count(parts) - 1]
  startswith(s, "registry")
  endswith(tag, "est")
  contains(s, "/app")
  t := trim("  x  ", " ")
  r := replace(s, "latest", "stable")
  msg := concat("|", [tag, t, substring(r, 0, 8)])
}
"""
        )
        assert v == [{"msg": "latest|x|registry"}]

    def test_destructuring_assignment(self):
        v = self.run(
            """
package p
make_group_version(api_version) = [group, version] {
  contains(api_version, "/")
  [group, version] := split(api_version, "/")
}
make_group_version(api_version) = [group, version] {
  not contains(api_version, "/")
  group := ""
  version := api_version
}
violation[{"msg": msg}] {
  [g1, v1] := make_group_version("apps/v1")
  [g2, v2] := make_group_version("v1")
  msg := sprintf("%v,%v,%v,%v", [g1, v1, g2, v2])
}
"""
        )
        assert v == [{"msg": "apps,v1,,v1"}]


class TestImportsAndElse:
    """Import aliasing + else chains (OPA v0.21 semantics: vendored
    opa/ast resolves imports at compile time; else is ordered choice)."""

    def test_bats_containerlimits_template_uses_import(self):
        # test/bats/tests/templates/k8scontainterlimits_template.yaml:131
        # `import data.lib.helpers` + `helpers.canonify_cpu(...)` calls.
        pol = compile_template("test/bats/tests/templates/k8scontainterlimits_template.yaml")
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "default"},
            "spec": {"containers": [{
                "name": "big", "image": "x",
                "resources": {"limits": {"cpu": "4", "memory": "8Gi"}}}]},
        }
        msgs = sorted(
            v["msg"] for v in pol.eval_violations(
                make_review(pod), {"cpu": "200m", "memory": "1Gi"}, {})
        )
        assert msgs == [
            "container <big> cpu limit <4> is higher than the maximum allowed of <200m>",
            "container <big> memory limit <8Gi> is higher than the maximum allowed of <1Gi>",
        ]

    def _pol(self, rego):
        return TemplatePolicy.compile(rego)

    def test_else_complete_rule_ordering(self):
        pol = self._pol(
            """
package p

x = "first" { input.review.a } else = "second" { input.review.b } else = "third" { true }

violation[{"msg": x}] { true }
"""
        )
        def msg(review):
            return pol.eval_violations(review, {}, {})[0]["msg"]
        assert msg({"a": True, "b": True}) == "first"
        assert msg({"b": True}) == "second"
        assert msg({}) == "third"

    def test_else_function(self):
        pol = self._pol(
            """
package p

grade(s) = "pass" { s >= 50 } else = "fail" { true }

violation[{"msg": m}] { m := grade(input.review.score) }
"""
        )
        assert pol.eval_violations({"score": 60}, {}, {})[0]["msg"] == "pass"
        assert pol.eval_violations({"score": 10}, {}, {})[0]["msg"] == "fail"

    def test_else_valueless_clause_yields_true(self):
        pol = self._pol(
            """
package p

ok { input.review.a } else { input.review.b }

violation[{"msg": "y"}] { ok }
"""
        )
        assert pol.eval_violations({"b": True}, {}, {}) == [{"msg": "y"}]
        assert pol.eval_violations({}, {}, {}) == []

    def test_else_undefined_falls_to_default(self):
        pol = self._pol(
            """
package p

default x = "dflt"

x = "set" { input.review.a } else = "els" { input.review.b }

violation[{"msg": x}] { true }
"""
        )
        assert pol.eval_violations({}, {}, {})[0]["msg"] == "dflt"


class TestWithModifiers:
    """`with` modifiers, OPA v0.21 scope: input[...] and base documents
    (data.inventory[...] here).  Values bind in the outer context; the
    modified literal evaluates under patched documents with rule caches
    isolated."""

    def _pol(self, rego):
        return TemplatePolicy.compile(rego)

    def test_with_whole_input(self):
        pol = self._pol(
            """
package p

flagged { input.review.object.bad == true }

violation[{"msg": "synthetic"}] {
  flagged with input as {"review": {"object": {"bad": true}}}
}

violation[{"msg": "real"}] { flagged }
"""
        )
        # real input is clean; only the with-patched evaluation fires
        assert pol.eval_violations({"object": {"bad": False}}, {}, {}) == [
            {"msg": "synthetic"}
        ]

    def test_with_input_path_override_and_insert(self):
        pol = self._pol(
            """
package p

violation[{"msg": m}] {
  x := input.review.object.replicas with input.review.object.replicas as 9
  y := input.review.extra with input.review.extra as "new"
  m := sprintf("%v/%v", [x, y])
}
"""
        )
        out = pol.eval_violations({"object": {"replicas": 2}}, {}, {})
        assert out == [{"msg": "9/new"}]

    def test_with_scopes_only_the_literal(self):
        pol = self._pol(
            """
package p

violation[{"msg": m}] {
  a := input.review.n with input.review.n as 7
  b := input.review.n
  m := sprintf("%v:%v", [a, b])
}
"""
        )
        assert pol.eval_violations({"n": 1}, {}, {}) == [{"msg": "7:1"}]

    def test_with_applies_to_negation(self):
        pol = self._pol(
            """
package p

present { input.review.flag }

violation[{"msg": "gone"}] {
  not present with input.review as {}
}
"""
        )
        assert pol.eval_violations({"flag": True}, {}, {}) == [{"msg": "gone"}]

    def test_with_data_inventory(self):
        pol = self._pol(
            """
package p

count_ns = n { n := count(data.inventory.cluster["v1"]["Namespace"]) }

violation[{"msg": m}] {
  real := count_ns
  mocked := count_ns with data.inventory.cluster as {"v1": {"Namespace": {"a": {}, "b": {}}}}
  m := sprintf("%v->%v", [real, mocked])
}
"""
        )
        inv = {"cluster": {"v1": {"Namespace": {"x": {}}}}}
        assert pol.eval_violations({}, {}, inv) == [{"msg": "1->2"}]

    def test_with_value_binds_in_outer_context(self):
        pol = self._pol(
            """
package p

violation[{"msg": m}] {
  v := input.review.seed
  m := input.review.out with input.review.out as v
}
"""
        )
        assert pol.eval_violations({"seed": "s1"}, {}, {}) == [{"msg": "s1"}]

    def test_with_disallowed_target_rejected(self):
        from gatekeeper_tpu.rego import RegoError
        with pytest.raises(RegoError):
            self._pol("package p\n\nviolation[{\"msg\": \"x\"}] { true with data.lib.q as 1 }\n")

    def test_with_policy_not_memo_safe(self):
        pol = self._pol(
            """
package p

violation[{"msg": "x"}] { input.review.a with input.review.a as true }
"""
        )
        assert pol.memo_safe is False

    def test_with_target_through_input_alias(self):
        # OPA resolves import aliases in with targets during rewriting
        pol = self._pol(
            """
package p
import input.review as rev

violation[{"msg": m}] {
  m := rev.tag with rev.tag as "mocked"
}
"""
        )
        assert pol.eval_violations({"tag": "real"}, {}, {}) == [{"msg": "mocked"}]

    def test_with_inside_comprehension_not_vectorized_exact(self):
        # a with-modifier inside a comprehension body must disable the
        # exact vectorized path (the patch is interpreter-only)
        rego = """
package p

violation[{"msg": "missing"}] {
  provided := {l | input.review.object.metadata.labels[l] with input.review.object.metadata.labels as {"mock": "1"}}
  required := {l | l := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
}
"""
        pol = TemplatePolicy.compile(rego)
        from gatekeeper_tpu.ops.vectorizer import Vectorizer
        prog = Vectorizer(pol).compile()
        assert prog is None or not prog.exact
        # and the interpreter applies the patch: "mock" is provided
        assert pol.eval_violations(
            {"object": {"metadata": {"labels": {}}}},
            {"labels": ["mock"]}, {},
        ) == []
        assert pol.eval_violations(
            {"object": {"metadata": {"labels": {}}}},
            {"labels": ["other"]}, {},
        ) == [{"msg": "missing"}]


class TestConflictErrors:
    """OPA eval_conflict_error semantics: first-wins is not OPA — multiple
    defined outputs with different values are evaluation errors."""

    def _pol(self, rego):
        return TemplatePolicy.compile(rego)

    def test_complete_rule_conflict_raises(self):
        from gatekeeper_tpu.engine.interp import RegoEvalError
        pol = self._pol(
            """
package p

x = 1 { input.review.a }
x = 2 { input.review.b }

violation[{"msg": "v"}] { x > 0 }
"""
        )
        # only one clause defined: fine, either way
        assert pol.eval_violations({"a": True}, {}, {}) == [{"msg": "v"}]
        assert pol.eval_violations({"b": True}, {}, {}) == [{"msg": "v"}]
        with pytest.raises(RegoEvalError, match="multiple outputs"):
            pol.eval_violations({"a": True, "b": True}, {}, {})

    def test_complete_rule_same_value_no_conflict(self):
        pol = self._pol(
            """
package p

x = 7 { input.review.a }
x = 7 { input.review.b }

violation[{"msg": "v"}] { x == 7 }
"""
        )
        assert pol.eval_violations({"a": True, "b": True}, {}, {}) == [{"msg": "v"}]

    def test_function_conflict_raises(self):
        from gatekeeper_tpu.engine.interp import RegoEvalError
        pol = self._pol(
            """
package p

f(x) = 1 { x > 0 }
f(x) = 2 { x > 10 }

violation[{"msg": "v"}] { f(input.review.n) == 1 }
"""
        )
        assert pol.eval_violations({"n": 5}, {}, {}) == [{"msg": "v"}]
        with pytest.raises(RegoEvalError, match="multiple outputs"):
            pol.eval_violations({"n": 20}, {}, {})

    def test_partial_object_key_conflict_raises(self):
        from gatekeeper_tpu.engine.interp import RegoEvalError
        pol = self._pol(
            """
package p

m["k"] = v { v := input.review.a }
m["k"] = v { v := input.review.b }

violation[{"msg": "v"}] { m["k"] }
"""
        )
        assert pol.eval_violations({"a": True}, {}, {}) == [{"msg": "v"}]
        with pytest.raises(RegoEvalError, match="keys must be unique"):
            pol.eval_violations({"a": 1, "b": 2}, {}, {})
        # same value on both clauses: no conflict
        assert pol.eval_violations({"a": 3, "b": 3}, {}, {}) == [{"msg": "v"}]

    def test_intra_clause_multiple_outputs_conflict(self):
        from gatekeeper_tpu.engine.interp import RegoEvalError
        pol = self._pol(
            """
package p

x = v { v := input.review.items[_] }

violation[{"msg": "v"}] { x > 0 }
"""
        )
        assert pol.eval_violations({"items": [1]}, {}, {}) == [{"msg": "v"}]
        assert pol.eval_violations({"items": [2, 2]}, {}, {}) == [{"msg": "v"}]
        with pytest.raises(RegoEvalError, match="multiple outputs"):
            pol.eval_violations({"items": [1, 2]}, {}, {})

    def test_intra_clause_function_conflict(self):
        from gatekeeper_tpu.engine.interp import RegoEvalError
        pol = self._pol(
            """
package p

f(a) = v { v := a[_] }

violation[{"msg": "v"}] { f(input.review.items) == 1 }
"""
        )
        assert pol.eval_violations({"items": [1, 1]}, {}, {}) == [{"msg": "v"}]
        with pytest.raises(RegoEvalError, match="multiple outputs"):
            pol.eval_violations({"items": [1, 2]}, {}, {})
