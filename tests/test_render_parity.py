"""Render-parity suite: the compiled render pipeline (ops/renderplan.py)
must be BYTE-IDENTICAL to the interpreter for every violating cell of the
corpus — messages, details, ordering, dedup — including unicode and
missing-field edge cases.  Also pins the plan classification: >= 90% of
corpus template cells compile to the static/slots tiers (the interpreter
tail is the exception, not the rule)."""

import pytest

from gatekeeper_tpu.engine.interp import TemplatePolicy
from gatekeeper_tpu.engine.value import freeze
from gatekeeper_tpu.ops import renderplan as rp
from gatekeeper_tpu.ops.vectorizer import vectorize

from .render_corpus import corpus, resources, review_of


def _policy(template):
    tgt = template["spec"]["targets"][0]
    return TemplatePolicy.compile(tgt["rego"], tuple(tgt.get("libs") or ()))


def _cells():
    for name, template, constraint, tier in corpus():
        pol = _policy(template)
        prog = vectorize(pol)
        plan = rp.bind(prog, pol, constraint)
        yield name, pol, constraint, plan, tier


@pytest.mark.parametrize(
    "name", [c[0] for c in corpus()], ids=[c[0] for c in corpus()]
)
def test_plan_matches_interpreter_byte_identical(name):
    entry = next(c for c in _cells() if c[0] == name)
    _name, pol, constraint, plan, _tier = entry
    params = freeze(constraint["spec"].get("parameters", {}))
    inv = freeze({})
    checked = 0
    for obj in resources():
        review = review_of(obj)
        want = pol.eval_violations(freeze(review), params, inv)
        if plan is None:
            continue  # interp tier: the fallback IS the interpreter
        got = plan.apply(rp.RowView(review))
        assert got == want, (
            f"{name} diverged on {obj['metadata']['name']}:\n"
            f"  plan:   {got}\n  interp: {want}"
        )
        # strict byte identity for messages, not just value equality
        assert [v["msg"] for v in got] == [v["msg"] for v in want]
        checked += 1
    if plan is not None:
        assert checked == len(resources())


def test_every_violating_cell_is_covered():
    """The corpus must actually produce violations (a vacuous parity
    suite would pass on a broken renderer)."""
    total = 0
    for _name, pol, constraint, plan, _tier in _cells():
        params = freeze(constraint["spec"].get("parameters", {}))
        for obj in resources():
            total += len(
                pol.eval_violations(
                    freeze(review_of(obj)), params, freeze({})
                )
            )
    assert total >= 25


def test_plan_classification_expected_tiers():
    for name, _pol, _constraint, plan, tier in _cells():
        if tier is None:
            continue
        got = rp.INTERP if plan is None else plan.tier
        assert got == tier, f"{name}: expected {tier}, classified {got}"


def test_corpus_classification_coverage():
    """Acceptance: >= 90% of corpus template cells classify static/slot.

    The parity corpus above is deliberately adversarial (it includes two
    fallback-exercising templates), so the acceptance ratio is measured
    over the FULL corpus: parity fixtures + the synthetic bench families
    (the population BENCH_r05's ingest_violating metric measures).  The
    synthetic families must classify 100%; combined coverage must clear
    90%."""
    from gatekeeper_tpu.util.synthetic import make_templates

    plans = [plan for _n, _p, _c, plan, _t in _cells()]
    planned = sum(1 for p in plans if p is not None)
    # the adversarial parity fixtures on their own: interp stays a small
    # minority even here
    assert planned / len(plans) >= 0.8

    templates, constraints = make_templates(60)
    syn_total = syn_planned = 0
    for t, c in zip(templates, constraints):
        pol = _policy(t)
        plan = rp.bind(vectorize(pol), pol, c)
        syn_total += 1
        syn_planned += plan is not None
    assert syn_planned == syn_total  # every bench family compiles a plan
    combined = (planned + syn_planned) / (len(plans) + syn_total)
    assert combined >= 0.9


def test_driver_end_to_end_parity_and_counts():
    """Full-stack check: TpuDriver (compiled render, all routes) vs
    InterpDriver over the corpus, and the per-tier cell counters show the
    plan tiers actually served."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver
    from gatekeeper_tpu.ops.driver import TpuDriver

    def mk(driver):
        c = Client(driver=driver)
        for _n, t, k, _tier in corpus():
            c.add_template(t)
            c.add_constraint(k)
        return c

    tpu, oracle = mk(TpuDriver()), mk(InterpDriver())
    tpu.driver.DEVICE_MIN_CELLS = 0  # force the device path
    tiers = {"static": 0, "slots": 0, "interp": 0}
    orig = tpu.driver._flush_render_counts

    def capture():
        for k in tiers:
            tiers[k] += tpu.driver._tier_counts[k]
        orig()

    tpu.driver._flush_render_counts = capture
    for obj in resources():
        review = review_of(obj)
        a = tpu.review(dict(review)).results()
        b = oracle.review(dict(review)).results()
        assert [
            (r.msg, r.metadata, r.constraint["metadata"]["name"],
             r.enforcement_action) for r in a
        ] == [
            (r.msg, r.metadata, r.constraint["metadata"]["name"],
             r.enforcement_action) for r in b
        ], obj["metadata"]["name"]
    served = sum(tiers.values())
    assert served > 0
    # adversarial corpus: the two fallback templates over-flag (their
    # widened device masks are exactly what the interp tier filters), so
    # the threshold here is looser than the full-corpus 90% acceptance
    # asserted in test_corpus_classification_coverage
    assert (tiers["static"] + tiers["slots"]) / served >= 0.7


def test_driver_audit_parity():
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver
    from gatekeeper_tpu.ops.driver import TpuDriver

    def mk(driver):
        c = Client(driver=driver)
        for _n, t, k, _tier in corpus():
            c.add_template(t)
            c.add_constraint(k)
        for obj in resources():
            c.add_data(obj)
        return c

    tpu, oracle = mk(TpuDriver()), mk(InterpDriver())
    tpu.driver.mesh_enabled = False  # container jax lacks shard_map
    a = sorted(
        (r.constraint["metadata"]["name"], r.msg, str(r.metadata))
        for r in tpu.audit().results()
    )
    b = sorted(
        (r.constraint["metadata"]["name"], r.msg, str(r.metadata))
        for r in oracle.audit().results()
    )
    assert a == b and a


def test_plan_disabled_kill_switch():
    """GK_RENDER_PLAN=0 routes every cell to the interpreter with
    identical output (the escape hatch must stay byte-equivalent)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    def mk():
        c = Client(driver=TpuDriver())
        for _n, t, k, _tier in corpus():
            c.add_template(t)
            c.add_constraint(k)
        c.driver.DEVICE_MIN_CELLS = 0
        return c

    on, off = mk(), mk()
    off.driver.render_plan_enabled = False
    for obj in resources():
        review = review_of(obj)
        a = on.review(dict(review)).results()
        b = off.review(dict(review)).results()
        assert [(r.msg, r.metadata) for r in a] == [
            (r.msg, r.metadata) for r in b
        ]
    assert off.driver._tier_counts == {"static": 0, "slots": 0, "interp": 0}


class TestJoinScenarios:
    """Referential (cross-resource) corpus entries: join plans produce
    the mask, the interpreter renders with the inventory — the parity
    bar is full-stack driver-vs-oracle byte identity per scenario, under
    the armed divergence assertion."""

    @pytest.mark.parametrize(
        "name", [e[0] for e in __import__(
            "tests.render_corpus", fromlist=["join_corpus"]
        ).join_corpus()],
    )
    def test_join_scenario_audit_byte_parity(self, name, monkeypatch):
        monkeypatch.setenv("GK_JOIN_ASSERT", "1")
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.client.drivers import InterpDriver
        from gatekeeper_tpu.ops.driver import TpuDriver
        from gatekeeper_tpu.util.synthetic import audit_result_sig

        from .render_corpus import join_corpus

        _n, template, constraint, objects = next(
            e for e in join_corpus() if e[0] == name
        )
        # the scenario must classify into a join plan, not interp fallback
        pol = _policy(template)
        prog = vectorize(pol)
        assert prog is not None and prog.join_plans and prog.exact

        def load(driver):
            c = Client(driver=driver)
            c.add_template(template)
            c.add_constraint(constraint)
            for o in objects:
                c.add_data(dict(o))
            return c

        tpu, oracle = load(TpuDriver()), load(InterpDriver())
        res, totals, _ = tpu.driver.audit_capped(4096)
        ores, ototals, _ = oracle.driver.audit_capped(4096)
        assert audit_result_sig(res) == audit_result_sig(ores)
        assert totals == ototals

    def test_join_scenarios_produce_violations(self):
        """Vacuity guard: every scenario must violate somewhere."""
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.client.drivers import InterpDriver

        from .render_corpus import join_corpus

        for name, template, constraint, objects in join_corpus():
            c = Client(driver=InterpDriver())
            c.add_template(template)
            c.add_constraint(constraint)
            for o in objects:
                c.add_data(dict(o))
            res, _t, _ = c.driver.audit_capped(4096)
            assert res, f"{name} produced no violations"
