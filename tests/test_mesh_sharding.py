"""Sharded mesh audit in production (ISSUE 6): shard-boundary padding,
O(churn) delta sweeps under the mesh, and the set_mesh topology API.

Every parity assertion here is against the interpreter oracle
(InterpDriver.audit_capped on the same driver state) — byte-identical
verdicts AND rendered messages, the cross-layer-verification discipline
that gates every mesh width's throughput claim."""

import numpy as np
import pytest

from gatekeeper_tpu.util.synthetic import (
    audit_result_sig as _sig,
    build_driver,
    build_oracle,
    make_pods,
)

CAP = 100  # above every per-constraint count: totals exact on all tiers


def _pair(n_templates, n_resources, seed=0):
    """(TPU client, interpreter-oracle client) loaded with the SAME
    synthetic corpus (util/synthetic.build_oracle — see its docstring for
    why the oracle must be its own InterpDriver instance)."""
    return (
        build_driver(n_templates, n_resources, seed),
        build_oracle(n_templates, n_resources, seed),
    )


def _sweep_with_oracle(pair, cap=CAP):
    """One device sweep + interpreter-oracle sweep over identical state:
    byte-parity of verdicts, rendered messages and totals.  Returns the
    device results and the device sweep's stats (captured BEFORE the
    oracle run so `cached` reads reflect the device sweep)."""
    tpu, oracle = pair
    got_r, got_t, _ = tpu.driver.audit_capped(cap)
    stats = dict(tpu.driver.last_sweep_stats)
    want_r, want_t, _ = oracle.driver.audit_capped(cap)
    assert _sig(got_r) == _sig(want_r)
    assert got_t == want_t
    return got_r, stats


def _churn_both(pair, start, n, tag="churned"):
    import json

    pods = make_pods(start + n)[start: start + n]
    for p in pods:
        p["metadata"].setdefault("labels", {})[tag] = "yes"
        for client in pair:
            client.add_data(json.loads(json.dumps(p)))
    return pods


class TestShardBoundaryPadding:
    def test_width_not_dividing_rows(self):
        """Width 3 never divides the power-of-two row bucket: every
        sweep exercises the padded tail slab end to end."""
        pair = _pair(6, 20)
        pair[0].driver.set_mesh(True, width=3)
        _r, stats = _sweep_with_oracle(pair)
        assert stats.get("shards") == 3.0

    def test_rows_smaller_than_width(self):
        """3 live rows across an 8-wide mesh: most shards hold ONLY
        padding (valid=False rows) and must contribute nothing."""
        pair = _pair(6, 3)
        pair[0].driver.set_mesh(True, width=8)
        _r, stats = _sweep_with_oracle(pair)
        assert stats.get("shards") == 8.0

    def test_churn_row_lands_in_padded_tail(self):
        """A new object allocates a row in the padded tail (n_rows <
        capacity); the next sweep must evaluate it on its owning shard
        with byte-parity."""
        pair = _pair(6, 9)  # capacity buckets to 16: tail rows 9..15
        driver = pair[0].driver
        driver.set_mesh(True, width=4)
        driver.audit_capped(CAP)
        ap = driver._audit_pack
        assert ap.n_rows < ap.capacity
        _churn_both(pair, 9, 2, tag="tail")  # new rows 9, 10: the tail
        _sweep_with_oracle(pair)
        # the pack synced during the sweep: the new rows landed in the
        # formerly-padded tail without growing the capacity bucket
        assert ap.n_rows == 11 and ap.capacity == 16

    def test_tombstone_in_padded_region_stays_dead(self):
        """Deleting an object tombstones its row (valid=False); padded
        and tombstoned rows must both stay invisible to every shard."""
        pair = _pair(6, 9)
        driver = pair[0].driver
        driver.set_mesh(True, width=4)
        driver.audit_capped(CAP)
        seg = next(
            p for p in driver._audit_pack.row_path if p is not None
        )
        driver.delete_data(seg)
        pair[1].driver.delete_data(seg)
        _sweep_with_oracle(pair)


class TestDeltaSweepUnderMesh:
    def test_churn_dispatches_only_dirty_rows(self):
        """The acceptance criterion: churn of d rows repacks/dispatches
        d rows (O(churn)), not the cluster, with the mesh enabled — and
        the owning-shard count shows the slab locality."""
        pair = _pair(8, 256)
        driver = pair[0].driver
        driver.set_mesh(True, width=4)
        driver.audit_capped(CAP)  # full sweep rebases the delta basis
        # in-place churn of 5 existing objects (content change, same rows)
        _churn_both(pair, 10, 5)
        _r, st = _sweep_with_oracle(pair)
        assert st.get("delta_rows") == 5.0
        assert st.get("shards") == 4.0
        assert st.get("rows") == 256.0  # cluster size, NOT re-dispatched
        assert st.get("delta_shards", 0) <= 2.0  # slab-local churn

    def test_churn_across_slabs_reports_owning_shards(self):
        client = build_driver(8, 256)
        driver = client.driver
        driver.set_mesh(True, width=4)
        driver.audit_capped(CAP)
        ap = driver._audit_pack
        # pick one LIVE ROW per 64-row slab by row index (row order is
        # pack order, not pod-name order) and churn its object in place
        from gatekeeper_tpu.engine.value import thaw

        for r in (1, 65, 129, 193):
            seg = ap.row_path[r]
            obj = thaw(driver.store.get(seg))
            obj["metadata"].setdefault("labels", {})["c"] = "y"
            client.add_data(obj)
        driver.audit_capped(CAP)
        st = driver.last_sweep_stats
        assert st.get("delta_rows") == 4.0
        assert st.get("delta_shards") == 4.0


class TestSetMeshApi:
    def test_width_change_invalidates_and_stays_correct(self):
        pair = _pair(6, 24)
        driver = pair[0].driver
        driver.set_mesh(True, width=2)
        r2, stats2 = _sweep_with_oracle(pair)
        assert stats2.get("shards") == 2.0
        driver.set_mesh(True, width=4)
        assert driver._audit_dev_mesh is None
        assert driver._delta_state is None
        assert driver._audit_cache is None
        r4, stats4 = _sweep_with_oracle(pair)
        assert stats4.get("shards") == 4.0
        assert _sig(r2) == _sig(r4)

    def test_disable_returns_to_single_device(self):
        pair = _pair(6, 24)
        driver = pair[0].driver
        driver.set_mesh(True, width=4)
        _sweep_with_oracle(pair)
        driver.set_mesh(False)
        assert driver._mesh() is None
        _r, stats = _sweep_with_oracle(pair)
        assert stats.get("shards") == 1.0

    def test_width_one_is_single_device(self):
        client = build_driver(4, 8)
        client.driver.set_mesh(True, width=1)
        assert client.driver._mesh() is None

    def test_width_beyond_devices_rejected(self):
        import jax

        client = build_driver(4, 8)
        with pytest.raises(ValueError):
            client.driver.set_mesh(True, width=len(jax.devices()) + 1)


class TestShardTelemetry:
    def test_full_placement_records_shard_histograms(self):
        from gatekeeper_tpu.metrics.views import global_registry

        client = build_driver(6, 24)
        client.driver.set_mesh(True, width=4)
        client.driver.audit_capped(CAP)
        rows = global_registry().view_rows("audit_shard_rows")
        audit_rows = {k: v for k, v in rows.items() if "audit" in k}
        assert audit_rows, "no audit_shard_rows samples recorded"
        # one sample per shard per full placement: count divisible by 4
        dist = next(iter(audit_rows.values()))
        assert dist.count >= 4
