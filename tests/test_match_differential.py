"""Differential test: native match semantics vs the reference's own Rego.

Extracts the constraint-matching library straight out of the reference
(pkg/target/target_template_source.go), substitutes the template roots, and
evaluates `matching_constraints` / `autoreject_review` with the
gatekeeper_tpu interpreter.  The native implementation
(gatekeeper_tpu.target.match) must agree on every generated
(match-spec x review) combination — including the original's
undefined-propagation quirks.
"""

import itertools
import random
import re

import pytest

from gatekeeper_tpu.engine.interp import TemplatePolicy
from gatekeeper_tpu.target.match import constraint_matches, needs_autoreject

from .corpus import REF

GO_SOURCE = REF / "pkg/target/target_template_source.go"


def load_matching_library() -> TemplatePolicy:
    src = GO_SOURCE.read_text()
    m = re.search(r"const templSrc = `(.*)`", src, re.DOTALL)
    assert m, "could not extract templSrc"
    rego = m.group(1)
    rego = rego.replace("{{.ConstraintsRoot}}", "data.inventory.constraints")
    rego = rego.replace("{{.DataRoot}}", "data.inventory.external")
    # Drop the audit cross-product rules (they use `with`, and their
    # semantics are exercised via the audit path tests instead).
    rego = re.sub(
        r"# Namespace-scoped objects\n.*?# Cluster-scoped objects\n.*?\n}\n",
        "",
        rego,
        flags=re.DOTALL,
    )
    assert "with input" not in rego
    return TemplatePolicy.compile(rego, entry="matching_constraints")


@pytest.fixture(scope="module")
def lib():
    return load_matching_library()


NS_OBJECTS = {
    "cached-a": {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": "cached-a", "labels": {"team": "a", "env": "prod"}},
    },
    "cached-plain": {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": "cached-plain"},
    },
}


MATCH_SPECS = [
    {},
    {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
    {"kinds": [{"apiGroups": ["*"], "kinds": ["*"]}]},
    {"kinds": [{"apiGroups": ["apps"], "kinds": ["Deployment", "Pod"]}]},
    {"kinds": [{"apiGroups": [""], "kinds": ["Namespace"]}]},
    {"namespaces": ["cached-a", "other"]},
    {"namespaces": []},
    {"excludedNamespaces": ["cached-a"]},
    {"scope": "Cluster"},
    {"scope": "Namespaced"},
    {"scope": "*"},
    {"labelSelector": {"matchLabels": {"app": "web"}}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "In", "values": ["web", "api"]}]}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "NotIn", "values": ["db"]}]}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "Exists"}]}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "DoesNotExist"}]}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "Bogus", "values": ["x"]}]}},
    {"labelSelector": {"matchExpressions": [{"key": "app", "operator": "In", "values": []}]}},
    {"namespaceSelector": {"matchLabels": {"team": "a"}}},
    {"namespaceSelector": {"matchExpressions": [{"key": "team", "operator": "Exists"}]}},
    {"namespaceSelector": {}},
    {"namespaces": ["cached-a"], "excludedNamespaces": ["cached-a"]},
    {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
     "namespaceSelector": {"matchLabels": {"team": "a"}},
     "labelSelector": {"matchLabels": {"app": "web"}}},
    {"scope": "Namespaced", "namespaces": ["cached-plain"]},
    None,  # no match field at all
    # null-valued fields: has_field treats null as PRESENT, get_default as
    # missing — the library mixes both (code-review finding, now pinned).
    {"scope": None},
    {"namespaces": None},
    {"excludedNamespaces": None},
    {"namespaceSelector": None},
    {"labelSelector": None},
    {"labelSelector": {"matchLabels": {"app": None}}},
    {"labelSelector": {"matchExpressions": None}},
    {"kinds": None},
]


def make_reviews():
    reviews = []
    kinds = [
        {"group": "", "version": "v1", "kind": "Pod"},
        {"group": "apps", "version": "v1", "kind": "Deployment"},
        {"group": "", "version": "v1", "kind": "Namespace"},
    ]
    namespaces = [None, "", "cached-a", "cached-plain", "uncached"]
    labelsets = [None, {}, {"app": "web"}, {"app": "db", "team": "a"}]
    for kind, ns, labels in itertools.product(kinds, namespaces, labelsets):
        meta = {"name": "obj-1"}
        if labels is not None:
            meta["labels"] = labels
        obj = {"metadata": meta}
        review = {"kind": kind, "name": "obj-1", "object": obj}
        if ns is not None:
            review["namespace"] = ns
        reviews.append(review)
    # oldObject-only (DELETE-ish) and both-objects reviews
    reviews.append(
        {"kind": kinds[0], "name": "obj-1", "namespace": "cached-a",
         "oldObject": {"metadata": {"name": "obj-1", "labels": {"app": "web"}}}}
    )
    reviews.append(
        {"kind": kinds[0], "name": "obj-1", "namespace": "cached-a",
         "object": {"metadata": {"name": "obj-1", "labels": {"app": "db"}}},
         "oldObject": {"metadata": {"name": "obj-1", "labels": {"app": "web"}}}}
    )
    # side-loaded namespace
    reviews.append(
        {"kind": kinds[0], "name": "obj-1", "namespace": "uncached",
         "object": {"metadata": {"name": "obj-1"}},
         "_unstable": {"namespace": NS_OBJECTS["cached-a"]}}
    )
    # null-valued fields exercise get_default's null handling
    reviews.append(
        {"kind": kinds[0], "name": "obj-1", "namespace": "cached-a",
         "object": {"metadata": {"name": "obj-1", "labels": None}}}
    )
    # null-valued label key: has_field treats it as present (Exists matches)
    reviews.append(
        {"kind": kinds[0], "name": "obj-1", "namespace": "cached-a",
         "object": {"metadata": {"name": "obj-1", "labels": {"app": None}}}}
    )
    return reviews


def rego_verdicts(lib: TemplatePolicy, constraint: dict, review: dict):
    inventory = {
        "constraints": {constraint["kind"]: {constraint["metadata"]["name"]: constraint}},
        "external": {"cluster": {"v1": {"Namespace": NS_OBJECTS}}},
    }
    matched = lib.eval_rule("matching_constraints", {"review": review}, inventory)
    rejected = lib.eval_rule("autoreject_review", {"review": review}, inventory)
    return bool(matched), bool(rejected)


def native_verdicts(constraint: dict, review: dict):
    cached = lambda name: NS_OBJECTS.get(name)
    return (
        constraint_matches(constraint, review, cached),
        needs_autoreject(constraint, review, cached),
    )


def test_differential_native_vs_rego(lib):
    rng = random.Random(7)
    reviews = make_reviews()
    mismatches = []
    total = 0
    for spec in MATCH_SPECS:
        constraint = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "Foo",
            "metadata": {"name": "c1"},
            "spec": ({"match": spec} if spec is not None else {}),
        }
        # sample reviews to keep runtime bounded while covering every spec
        for review in rng.sample(reviews, min(len(reviews), 30)):
            total += 1
            want = rego_verdicts(lib, constraint, review)
            got = native_verdicts(constraint, review)
            if want != got:
                mismatches.append((spec, review, want, got))
    assert total > 500
    assert not mismatches, f"{len(mismatches)}/{total} divergences; first: {mismatches[0]}"
