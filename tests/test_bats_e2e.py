"""bats-parity e2e lane: the reference's in-cluster battery
(test/bats/test.bats, 17 @test cases) replayed against the full App with
the reference's own bats fixtures (test/bats/tests/).  kind+kubectl are
replaced by the in-memory API store; "kubectl apply" is modeled as
webhook review -> create-if-allowed, which is exactly what the apiserver
does with the validating webhook registered.

Tests run in definition order and share one App, mirroring the bats
file's stateful flow (the dryrun switch feeds the audit and event
cases)."""

import json
import os
import ssl
import time
import urllib.request

import pytest
import yaml

from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.main import App, build_parser

BATS = "/root/reference/test/bats/tests"

# the battery replays the reference's own bats fixtures against the real
# HTTPS webhook listener: it needs both the reference checkout and the
# `cryptography` package (cert generation).  Without either, skip the
# module — the shared class-scoped App cannot even come up meaningfully.
if not os.path.isdir(BATS):
    pytest.skip(
        "reference bats fixtures absent (/root/reference)",
        allow_module_level=True,
    )
try:
    import cryptography  # noqa: F401
except ImportError:
    pytest.skip(
        "bats battery drives the HTTPS listener; requires 'cryptography'",
        allow_module_level=True,
    )

RL_GVK = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")
EVENTS_GVK = ("", "v1", "Event")


def load(relpath):
    with open(f"{BATS}/{relpath}") as fh:
        return yaml.safe_load(fh)


def admission_request(obj, operation="CREATE", namespace=None, old=None):
    api = obj.get("apiVersion", "v1")
    group, _, version = api.rpartition("/")
    req = {
        "uid": "e2e",
        "kind": {"group": group, "version": version, "kind": obj.get("kind", "")},
        "name": (obj.get("metadata") or {}).get("name", ""),
        "operation": operation,
        "object": obj,
        "userInfo": {"username": "bats"},
    }
    ns = namespace or (obj.get("metadata") or {}).get("namespace")
    if ns:
        req["namespace"] = ns
    if old is not None:
        req["oldObject"] = old
    return req


@pytest.fixture(scope="class")
def cluster():
    kube = InMemoryKube()
    # the namespaces a kind cluster starts with (the audit counts them)
    for ns in ("default", "kube-system", "kube-public", "kube-node-lease",
               "gatekeeper-system"):
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": ns}})
    app = App(build_parser().parse_args([
        "--driver", "interp",
        "--port", "0", "--prometheus-port", "0", "--health-addr", ":0",
        "--audit-interval", "0.2",
        "--cert-dir", "/tmp/gk-bats-certs",
        "--exempt-namespace", "gatekeeper-system",
        "--emit-admission-events", "--emit-audit-events",
        "--log-denies",
    ]), kube=kube)
    app.start()
    state = {"app": app, "kube": kube}
    try:
        yield state
    finally:
        app.stop()


class Ctx:
    def __init__(self, state):
        self.app = state["app"]
        self.kube = state["kube"]

    def _post(self, path, request):
        body = json.dumps({"request": request}).encode()
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        r = urllib.request.Request(
            f"https://127.0.0.1:{self.app.webhook_server.port}{path}", data=body
        )
        with urllib.request.urlopen(r, context=ctx, timeout=10) as resp:
            return json.loads(resp.read())["response"]

    def admit(self, request):
        return self._post("/v1/admit", request)

    def admitlabel(self, request):
        return self._post("/v1/admitlabel", request)

    def apply(self, obj, namespace=None):
        """kubectl apply: review through the webhook, create when allowed."""
        if namespace:
            obj = json.loads(json.dumps(obj))
            obj.setdefault("metadata", {})["namespace"] = namespace
        resp = self.admit(admission_request(obj, namespace=namespace))
        if resp["allowed"]:
            self.kube.apply(obj)
        return resp

    def drain(self):
        assert self.app.manager.drain()

    def wait_for(self, pred, timeout=15.0, msg="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = pred()
            if got:
                return got
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.usefixtures("cluster")
class TestBatsBattery:
    # "gatekeeper-controller-manager is running" / "gatekeeper-audit is
    # running" / "waiting for validating webhook"
    def test_processes_running(self, cluster):
        c = Ctx(cluster)
        # health endpoints ride the webhook listener when the webhook role
        # is assigned (reference main.go:193-196 registers them on the
        # manager's server)
        port = c.app.webhook_server.port
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        for path in ("/healthz", "/readyz"):
            with urllib.request.urlopen(
                f"https://127.0.0.1:{port}{path}", context=ctx, timeout=5
            ) as resp:
                assert resp.status == 200

    # "namespace label webhook is serving"
    def test_namespace_label_webhook_serving(self, cluster):
        c = Ctx(cluster)
        ok = c.admitlabel(admission_request(
            {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "probe"}}))
        assert ok["allowed"] is True

    # "applying sync config"
    def test_applying_sync_config(self, cluster):
        c = Ctx(cluster)
        c.kube.create(load("sync.yaml"))
        c.drain()
        watched = c.app.manager.watch_manager.watched_gvks()
        assert watched.contains(("", "v1", "Namespace"))
        assert watched.contains(("", "v1", "Pod"))

    # "required labels dryrun test" part 1 + "constrainttemplates crd is
    # established"
    def test_template_and_crd_established(self, cluster):
        c = Ctx(cluster)
        c.kube.create(load("templates/k8srequiredlabels_template.yaml"))
        c.drain()
        crd = c.kube.get(
            ("apiextensions.k8s.io", "v1", "CustomResourceDefinition"),
            "k8srequiredlabels.constraints.gatekeeper.sh",
        )
        conds = (crd.get("status") or {}).get("conditions") or []
        assert any(
            x.get("type") == "Established" and x.get("status") == "True"
            for x in conds
        )

    # "no ignore label unless namespace is exempt test"
    def test_no_ignore_label_unless_exempt(self, cluster):
        c = Ctx(cluster)
        resp = c.admitlabel(admission_request(load("bad/ignore_label_ns.yaml")))
        assert resp["allowed"] is False
        assert (
            "Only exempt namespace can have the admission.gatekeeper.sh/ignore label"
            in resp["status"]["message"]
        )

    # "gatekeeper-system ignore label can be patched"
    def test_exempt_namespace_ignore_label_allowed(self, cluster):
        c = Ctx(cluster)
        patched = {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "gatekeeper-system",
                         "labels": {"admission.gatekeeper.sh/ignore":
                                    "ignore-label-test-passed"}},
        }
        resp = c.admitlabel(admission_request(patched, operation="UPDATE"))
        assert resp["allowed"] is True

    # "required labels dryrun test" parts 2-4
    def test_required_labels_deny_then_dryrun(self, cluster):
        c = Ctx(cluster)
        c.kube.create(load("constraints/all_ns_must_have_gatekeeper.yaml"))
        c.drain()
        good = c.apply(load("good/good_ns.yaml"))
        assert good["allowed"] is True
        bad = c.apply(load("bad/bad_ns.yaml"))
        assert bad["allowed"] is False
        assert "denied" in bad["status"]["message"]
        # switch the same constraint to enforcementAction: dryrun
        c.kube.apply(load("constraints/all_ns_must_have_gatekeeper-dryrun.yaml"))
        c.drain()
        spec = c.kube.get(RL_GVK, "ns-must-have-gk")["spec"]
        assert spec.get("enforcementAction") == "dryrun"
        bad2 = c.apply(load("bad/bad_ns.yaml"))
        assert bad2["allowed"] is True  # dryrun violations never block

    # "create namespace for unique labels test" + "unique labels test"
    def test_unique_labels(self, cluster):
        c = Ctx(cluster)
        c.kube.create(load("templates/k8suniquelabel_template.yaml"))
        c.drain()
        c.kube.create(load("constraints/all_ns_gatekeeper_label_unique.yaml"))
        c.drain()
        first = c.apply(load("good/no_dupe_ns.yaml"))
        assert first["allowed"] is True
        c.drain()  # sync the namespace into the inventory
        dupe = c.apply(load("bad/no_dupe_ns_2.yaml"))
        assert dupe["allowed"] is False

    # "container limits test"
    def test_container_limits(self, cluster):
        c = Ctx(cluster)
        c.kube.create(load("templates/k8scontainterlimits_template.yaml"))
        c.drain()
        c.kube.create(load("constraints/containers_must_be_limited.yaml"))
        c.drain()
        no_limits = c.apply(load("bad/opa_no_limits.yaml"), namespace="good-ns")
        assert no_limits["allowed"] is False
        good = c.apply(load("good/opa.yaml"))
        assert good["allowed"] is True

    # "deployment test": the deployment itself is admitted (no Deployment
    # match); the pod it stamps out is denied, which in a live cluster
    # surfaces as unavailableReplicas
    def test_deployment_pods_denied(self, cluster):
        c = Ctx(cluster)
        deploy = load("bad/bad_deployment.yaml")
        resp = c.apply(deploy)
        assert resp["allowed"] is True
        pod_template = deploy["spec"]["template"]
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": "opa-test-deployment-0",
                "namespace": "default",
                "labels": (pod_template.get("metadata") or {}).get("labels") or {},
            },
            "spec": pod_template["spec"],
        }
        denied = c.admit(admission_request(pod))
        assert denied["allowed"] is False

    # "waiting for namespaces to be synced using metrics endpoint"
    def test_sync_metric_matches_namespace_count(self, cluster):
        c = Ctx(cluster)
        n_ns = len(c.kube.list(("", "v1", "Namespace")))

        def metric_ok():
            port = c.app.metrics_exporter.port
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            for line in body.splitlines():
                if line.startswith("gatekeeper_sync{") and 'kind="Namespace"' in line \
                        and 'status="active"' in line:
                    return float(line.rsplit(" ", 1)[1]) == n_ns
            return False

        c.wait_for(metric_ok, msg="gatekeeper_sync Namespace metric")

    # "required labels audit test"
    def test_required_labels_audit(self, cluster):
        c = Ctx(cluster)

        def audited():
            st = (c.kube.get(RL_GVK, "ns-must-have-gk").get("status") or {})
            return st if st.get("violations") else None

        st = c.wait_for(audited, msg="audit violations on ns-must-have-gk")
        names = {v["name"] for v in st["violations"]}
        # every unlabeled namespace violates, including the dryrun'd bad-ns
        assert "bad-ns" in names and "default" in names
        assert st["totalViolations"] == len(st["violations"])
        assert st["totalViolationsExact"] is True
        assert all(v["enforcementAction"] == "dryrun" for v in st["violations"])

    # "emit events test"
    def test_emit_events(self, cluster):
        c = Ctx(cluster)

        def events_of(reason):
            return [
                e for e in c.kube.list(EVENTS_GVK)
                if e.get("reason") == reason
                and (e["metadata"].get("annotations") or {}).get(
                    "constraint_kind") == "K8sRequiredLabels"
            ]

        assert len(events_of("FailedAdmission")) == 1
        assert len(events_of("DryrunViolation")) == 1
        c.wait_for(lambda: len(events_of("AuditViolation")) >= 6,
                   msg="audit violation events")

    # "config namespace exclusion test"
    def test_config_namespace_exclusion(self, cluster):
        c = Ctx(cluster)
        c.kube.create({"apiVersion": "v1", "kind": "Namespace",
                       "metadata": {"name": "excluded-namespace"}})
        resp = c.apply(load("bad/opa_no_limits.yaml"),
                       namespace="excluded-namespace")
        assert resp["allowed"] is True  # sync.yaml excludes it for "*"
