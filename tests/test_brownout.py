"""Brownout ladder unit tests (ISSUE 12, obs/brownout.py): hysteresis in
BOTH directions over a fake clock, the shed-rate signal's decay, action
callbacks on every transition, the background-deferral predicate, and
the module-global wiring record_shed feeds."""

import pytest

from gatekeeper_tpu.obs import brownout
from gatekeeper_tpu.obs.brownout import MAX_LEVEL, BrownoutController


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture()
def ctl():
    clock = _Clock()
    c = BrownoutController(clock=clock)
    c.clock = clock  # test handle
    return c


def drive(c, seconds, step=0.25, queue_frac=0.0, slo=False):
    """Advance the fake clock through `seconds` of ticks with the given
    signal providers pinned."""
    c.set_providers(queue_frac=lambda: queue_frac,
                    slo_degraded=lambda: slo)
    end = c.clock.t + seconds
    while c.clock.t < end:
        c.clock.t += step
        c.tick(now=c.clock.t)


class TestLadderUp:
    def test_sustained_queue_pressure_steps_up_one_rung_per_window(
        self, ctl
    ):
        drive(ctl, 0.9, queue_frac=1.0)
        assert ctl.level == 0  # not sustained long enough yet
        drive(ctl, 0.5, queue_frac=1.0)
        assert ctl.level == 1
        # each further rung needs its own sustained window
        drive(ctl, ctl.UP_AFTER_S + 0.3, queue_frac=1.0)
        assert ctl.level == 2
        drive(ctl, ctl.UP_AFTER_S + 0.3, queue_frac=1.0)
        assert ctl.level == 3

    def test_caps_at_max_level(self, ctl):
        drive(ctl, 10 * ctl.UP_AFTER_S, queue_frac=1.0)
        assert ctl.level == MAX_LEVEL

    def test_slo_burn_alone_is_an_overload_signal(self, ctl):
        drive(ctl, ctl.UP_AFTER_S + 0.5, slo=True)
        assert ctl.level >= 1

    def test_shed_rate_alone_is_an_overload_signal(self, ctl):
        end = ctl.clock.t + ctl.UP_AFTER_S + 0.6
        while ctl.clock.t < end:
            ctl.note_shed(5)  # 5 sheds per 0.25s tick = 20/s
            ctl.clock.t += 0.25
            ctl.tick(now=ctl.clock.t)
        assert ctl.level >= 1

    def test_transient_blip_does_not_step(self, ctl):
        drive(ctl, 0.5, queue_frac=1.0)   # brief spike
        drive(ctl, 5.0, queue_frac=0.0)   # clear
        assert ctl.level == 0


class TestLadderDown:
    def test_recovery_steps_down_with_its_own_hysteresis(self, ctl):
        drive(ctl, 3 * (ctl.UP_AFTER_S + 0.5), queue_frac=1.0)
        assert ctl.level == 3
        # clear, but not for long enough: holds
        drive(ctl, ctl.DOWN_AFTER_S - 1.0, queue_frac=0.0)
        assert ctl.level == 3
        drive(ctl, 1.5, queue_frac=0.0)
        assert ctl.level == 2
        # all the way down
        drive(ctl, 3 * (ctl.DOWN_AFTER_S + 0.5), queue_frac=0.0)
        assert ctl.level == 0

    def test_between_the_bars_holds_the_rung(self, ctl):
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.level == 1
        # mid-band pressure (above QUEUE_LOW, below QUEUE_HIGH): the
        # ladder must neither climb nor recover — that's the hysteresis
        mid = (ctl.QUEUE_LOW + ctl.QUEUE_HIGH) / 2
        drive(ctl, 4 * ctl.DOWN_AFTER_S, queue_frac=mid)
        assert ctl.level == 1

    def test_oscillation_across_the_low_bar_never_recovers(self, ctl):
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.level == 1
        # alternate clear / mid-band faster than DOWN_AFTER_S: the clear
        # streak resets every time, so the rung holds
        for _ in range(10):
            drive(ctl, ctl.DOWN_AFTER_S / 2, queue_frac=0.0)
            drive(ctl, 0.5, queue_frac=0.5)
        assert ctl.level == 1


class TestActionsAndStatus:
    def test_actions_fire_on_every_transition_with_old_and_new(self, ctl):
        seen = []
        ctl.on_change(lambda old, new: seen.append((old, new)))
        drive(ctl, 2 * (ctl.UP_AFTER_S + 0.5), queue_frac=1.0)
        drive(ctl, 3 * (ctl.DOWN_AFTER_S + 0.5), queue_frac=0.0)
        assert (0, 1) in seen and (1, 2) in seen
        assert (2, 1) in seen and (1, 0) in seen
        assert ctl.transitions == len(seen)

    def test_action_failure_does_not_break_the_ladder(self, ctl):
        def boom(old, new):
            raise RuntimeError("action defect")

        ctl.on_change(boom)
        drive(ctl, 2 * (ctl.UP_AFTER_S + 0.5), queue_frac=1.0)
        assert ctl.level == 2  # the ladder kept stepping

    def test_deferral_predicates_by_level(self, ctl):
        assert not ctl.defer_background()
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.defer_background()
        assert not ctl.reduce_telemetry()
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.reduce_telemetry()
        assert not ctl.pin_routing()
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.pin_routing()

    def test_status_payload(self, ctl):
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        st = ctl.status()
        assert st["level"] == 1
        assert st["level_name"] == "defer-audit"
        assert st["transitions"] >= 1
        assert st["signals"]["queue_frac"] == 1.0

    def test_provider_failure_reads_as_not_overloaded(self, ctl):
        def broken():
            raise RuntimeError("provider died")

        ctl.set_providers(queue_frac=broken, slo_degraded=broken)
        ctl.clock.t += 10.0
        ctl.tick(now=ctl.clock.t)
        assert ctl.level == 0

    def test_reset_returns_to_normal(self, ctl):
        drive(ctl, ctl.UP_AFTER_S + 0.5, queue_frac=1.0)
        assert ctl.level == 1
        ctl.reset()
        assert ctl.level == 0
        assert not ctl.defer_background()


class TestShedRateDecay:
    def test_burst_decays_instead_of_pinning_the_ladder(self, ctl):
        ctl.note_shed(100)
        ctl.clock.t += 0.25
        ctl.tick(now=ctl.clock.t)
        assert ctl.shed_rate() > ctl.SHED_HIGH
        # a long quiet stretch decays the rate below the low bar
        drive(ctl, 30.0, queue_frac=0.0)
        assert ctl.shed_rate() < ctl.SHED_LOW


class TestModuleGlobalWiring:
    def test_record_shed_feeds_the_global_controller(self):
        from gatekeeper_tpu.metrics.catalog import record_shed

        ctl = brownout.get_controller()
        ctl.reset()
        before = ctl._shed_count
        record_shed("queue_full")
        assert ctl._shed_count == before + 1
        ctl.reset()

    def test_defer_background_module_helper(self):
        ctl = brownout.get_controller()
        ctl.reset()
        assert brownout.defer_background() is False
        ctl.level = 1
        try:
            assert brownout.defer_background() is True
        finally:
            ctl.reset()

    def test_sampler_start_stop_idempotent(self):
        ctl = BrownoutController()
        ctl.start()
        ctl.start()  # idempotent: no second thread
        import threading

        names = [t.name for t in threading.enumerate()]
        assert names.count("gk-brownout") == 1
        ctl.stop()
        ctl.stop()
        names = [t.name for t in threading.enumerate()]
        assert "gk-brownout" not in names
