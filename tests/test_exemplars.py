"""Exemplars + exposition formats (ISSUE 5): bounded per-bucket exemplar
capture, byte-checked OpenMetrics and classic renderings, HTTP content
negotiation, the exporter's debug surface, idempotent start, and the
port-in-use contract."""

import json
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu.metrics.exporter import (
    CONTENT_TYPE_OPENMETRICS,
    CONTENT_TYPE_TEXT,
    MetricsExporter,
    render_openmetrics,
    render_prometheus,
)
from gatekeeper_tpu.metrics.views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    Measure,
    Registry,
    View,
)

TRACE_ID = "ab" * 16


def small_registry() -> Registry:
    reg = Registry()
    m_count = Measure("demo_total", "demo counter")
    m_gauge = Measure("demo_gauge", "demo gauge")
    m_hist = Measure("demo_seconds", "demo histogram", "s")
    reg.register(
        View("demo_total", m_count, AGG_COUNT, tag_keys=("outcome",)),
        View("demo_gauge", m_gauge, AGG_LAST_VALUE),
        View("demo_seconds", m_hist, AGG_DISTRIBUTION,
             buckets=(0.01, 0.1)),
    )
    reg.record(m_count, 1.0, {"outcome": "hit"}, count=3)
    reg.record(m_gauge, 2.5)
    reg.record(m_hist, 0.05, exemplar_trace_id=TRACE_ID)
    reg.record(m_hist, 0.5, exemplar_trace_id=TRACE_ID)
    # pin the (wall-anchored) exemplar timestamps so the rendering is
    # byte-checkable
    dist = reg._views["demo_seconds"].rows[()]
    dist.exemplars = {
        i: type(ex)(value=ex.value, trace_id=ex.trace_id, ts=1700000000.0)
        for i, ex in dist.exemplars.items()
    }
    return reg


def test_exemplar_capture_is_bounded_per_bucket():
    reg = small_registry()
    m_hist = Measure("demo_seconds", "demo histogram", "s")
    for _ in range(50):  # hammer one bucket: newest exemplar wins
        reg.record(m_hist, 0.02, exemplar_trace_id="cd" * 16)
    dist = reg._views["demo_seconds"].rows[()]
    assert set(dist.exemplars) == {1, 2}  # never more than one per bucket
    assert dist.exemplars[1].trace_id == "cd" * 16
    # records without an active trace attach nothing
    reg.record(m_hist, 0.02)
    assert dist.exemplars[1].trace_id == "cd" * 16


def test_openmetrics_rendering_byte_exact():
    expected = (
        "# HELP gatekeeper_demo_gauge demo gauge\n"
        "# TYPE gatekeeper_demo_gauge gauge\n"
        "gatekeeper_demo_gauge 2.5\n"
        "# HELP gatekeeper_demo_seconds demo histogram\n"
        "# TYPE gatekeeper_demo_seconds histogram\n"
        'gatekeeper_demo_seconds_bucket{le="0.01"} 0\n'
        'gatekeeper_demo_seconds_bucket{le="0.1"} 1 '
        f'# {{trace_id="{TRACE_ID}"}} 0.05 1700000000.000\n'
        'gatekeeper_demo_seconds_bucket{le="+Inf"} 2 '
        f'# {{trace_id="{TRACE_ID}"}} 0.5 1700000000.000\n'
        "gatekeeper_demo_seconds_sum 0.55\n"
        "gatekeeper_demo_seconds_count 2\n"
        "# HELP gatekeeper_demo demo counter\n"
        "# TYPE gatekeeper_demo counter\n"
        'gatekeeper_demo_total{outcome="hit"} 3\n'
        "# EOF\n"
    )
    assert render_openmetrics(small_registry()) == expected


def test_classic_rendering_byte_exact_no_exemplars():
    expected = (
        "# HELP gatekeeper_demo_gauge demo gauge\n"
        "# TYPE gatekeeper_demo_gauge gauge\n"
        "gatekeeper_demo_gauge 2.5\n"
        "# HELP gatekeeper_demo_seconds demo histogram\n"
        "# TYPE gatekeeper_demo_seconds histogram\n"
        'gatekeeper_demo_seconds_bucket{le="0.01"} 0\n'
        'gatekeeper_demo_seconds_bucket{le="0.1"} 1\n'
        'gatekeeper_demo_seconds_bucket{le="+Inf"} 2\n'
        "gatekeeper_demo_seconds_sum 0.55\n"
        "gatekeeper_demo_seconds_count 2\n"
        "# HELP gatekeeper_demo_total demo counter\n"
        "# TYPE gatekeeper_demo_total counter\n"
        'gatekeeper_demo_total{outcome="hit"} 3\n'
    )
    assert render_prometheus(small_registry()) == expected


def test_stage_records_capture_trace_exemplars():
    """record_stage inside an active span attaches the span's trace id;
    outside one it attaches nothing."""
    from gatekeeper_tpu.metrics import catalog
    from gatekeeper_tpu.obs import trace as obstrace

    reg = catalog.register_catalog(Registry())
    import gatekeeper_tpu.metrics.catalog as cat

    old_ready, old_global = cat._GLOBAL_READY, None
    # route the module-global recorder at our registry for the test
    import gatekeeper_tpu.metrics.views as views_mod

    old_global = views_mod._global
    views_mod._global = reg
    cat._GLOBAL_READY = False
    try:
        with obstrace.root_span("t") as sp:
            cat.record_stage(catalog.PACK_M, 0.001, {"path": "review"})
            tid = sp.trace.trace_id
        rows = reg.view_rows("tpu_pack_seconds")
        dist = rows[("review",)]
        assert len(dist.exemplars) == 1
        ex = next(iter(dist.exemplars.values()))
        assert ex.trace_id == tid and ex.value == pytest.approx(0.001)
        cat.record_stage(catalog.PACK_M, 0.001, {"path": "review"})
        assert len(dist.exemplars) == 1  # no trace, no new exemplar...
    finally:
        views_mod._global = old_global
        cat._GLOBAL_READY = old_ready


def content_type_of(url, accept=None):
    req = urllib.request.Request(url)
    if accept:
        req.add_header("Accept", accept)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.headers.get("Content-Type"), resp.read().decode()


class TestExporterServer:
    def test_content_negotiation_and_debug_surface(self):
        exp = MetricsExporter(port=0, registry=small_registry())
        exp.start()
        try:
            base = f"http://127.0.0.1:{exp.port}"
            ctype, body = content_type_of(f"{base}/metrics")
            assert ctype == CONTENT_TYPE_TEXT
            assert "# EOF" not in body and " # {" not in body
            ctype, body = content_type_of(
                f"{base}/metrics", accept=CONTENT_TYPE_OPENMETRICS
            )
            assert ctype == CONTENT_TYPE_OPENMETRICS
            assert body.endswith("# EOF\n")
            assert f'# {{trace_id="{TRACE_ID}"}}' in body
            # audit-only deployments get the debug surface from this
            # listener: traces, costs, slo
            for path in ("/debug/traces", "/debug/costs", "/debug/slo"):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    assert r.status == 200
                    json.loads(r.read())
            # hardened params: JSON 400, never a 500 traceback
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    base + "/debug/costs?top=banana", timeout=10
                )
            assert ei.value.code == 400
            assert json.loads(ei.value.read())["error"] == (
                "top must be numeric"
            )
        finally:
            exp.stop()

    def test_collect_hooks_refresh_before_scrape(self):
        calls = []
        reg = small_registry()
        exp = MetricsExporter(
            port=0, registry=reg, collect_hooks=[lambda r: calls.append(r)]
        )
        exp.start()
        try:
            content_type_of(f"http://127.0.0.1:{exp.port}/metrics")
            assert calls == [reg]
        finally:
            exp.stop()

    def test_start_is_idempotent(self):
        exp = MetricsExporter(port=0, registry=small_registry())
        exp.start()
        first_port = exp.port
        try:
            # double start replaces the listener instead of leaking it;
            # the replacement binds and serves
            exp.port = 0
            exp.start()
            assert exp.port != 0
            ctype, _ = content_type_of(f"http://127.0.0.1:{exp.port}/metrics")
            assert ctype == CONTENT_TYPE_TEXT
            # the first port was released by the replacement
            exp2 = MetricsExporter(
                port=first_port, registry=small_registry(),
                host="127.0.0.1",
            )
            exp2.start()
            exp2.stop()
        finally:
            exp.stop()

    def test_port_in_use_is_a_clear_error(self):
        exp = MetricsExporter(port=0, registry=small_registry(),
                              host="127.0.0.1")
        exp.start()
        try:
            clash = MetricsExporter(
                port=exp.port, registry=small_registry(), host="127.0.0.1"
            )
            with pytest.raises(RuntimeError) as ei:
                clash.start()
            msg = str(ei.value)
            assert str(exp.port) in msg
            assert "--prometheus-port" in msg
        finally:
            exp.stop()
