"""SLO burn-rate engine (gatekeeper_tpu/obs/slo.py): burn-rate math
against hand-computed windows, decay, multi-window alerts, audit
freshness, metric export, and the webhook/audit feeds (ISSUE 5)."""

import pytest

from gatekeeper_tpu.metrics.views import Registry
from gatekeeper_tpu.obs import slo as obsslo
from gatekeeper_tpu.obs.slo import (
    ADMISSION_LATENCY,
    AUDIT_FRESHNESS,
    FAIL_CLOSED_ERRORS,
    SLOEngine,
)


class FakeClock:
    def __init__(self, t=10_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def engine_with(name="x", target=0.999):
    clock = FakeClock()
    eng = SLOEngine(clock=clock)
    eng.add_objective(name, target)
    return eng, clock


def test_burn_rate_hand_computed():
    eng, _clock = engine_with(target=0.999)  # budget 0.001
    eng.record("x", True, n=990)
    eng.record("x", False, n=10)
    rates = eng.burn_rates("x")
    # bad fraction 10/1000 = 0.01; 0.01 / 0.001 = 10x burn in EVERY
    # window that contains the events
    for window in ("5m", "30m", "1h", "6h"):
        assert rates[window] == pytest.approx(10.0)


def test_burn_rate_windows_decay_independently():
    eng, clock = engine_with(target=0.99)  # budget 0.01
    eng.record("x", False, n=5)
    eng.record("x", True, n=5)  # bad frac 0.5 -> burn 50
    clock.advance(400.0)  # out of 5m, inside 30m/1h/6h
    rates = eng.burn_rates("x")
    assert rates["5m"] == 0.0
    assert rates["30m"] == pytest.approx(50.0)
    clock.advance(3600.0)  # out of 30m and 1h, inside 6h
    rates = eng.burn_rates("x")
    assert rates["30m"] == 0.0 and rates["1h"] == 0.0
    assert rates["6h"] == pytest.approx(50.0)
    clock.advance(22_000.0)  # out of every window
    assert eng.burn_rates("x")["6h"] == 0.0


def test_zero_traffic_burns_zero():
    eng, _clock = engine_with()
    assert eng.burn_rates("x") == {
        "5m": 0.0, "30m": 0.0, "1h": 0.0, "6h": 0.0
    }


def test_mixed_buckets_sum_across_window():
    eng, clock = engine_with(target=0.9)  # budget 0.1
    # spread events across 3 one-minute buckets inside the 5m window:
    # 30 bad / 300 total = 0.1 bad frac -> burn 1.0
    for _ in range(3):
        eng.record("x", True, n=90)
        eng.record("x", False, n=10)
        clock.advance(60.0)
    assert eng.burn_rates("x")["5m"] == pytest.approx(1.0)


def test_multiwindow_alert_fires_and_clears():
    eng, clock = engine_with(target=0.9)  # budget 0.1
    fired = []
    eng.on_alert(lambda name, pair: fired.append((name, pair)))
    # 100% bad -> burn 10: below fast (14.4), above slow (6.0)
    eng.record("x", False, n=50)
    st = eng.evaluate()
    assert st["objectives"]["x"]["alerts"] == {"fast": False, "slow": True}
    assert fired == [("x", "slow")]
    assert eng.degraded()
    # edge-triggered: an unchanged state must not re-fire
    eng.evaluate()
    assert fired == [("x", "slow")]
    # events age out of 30m -> the alert clears
    clock.advance(2000.0)
    st = eng.evaluate()
    assert st["objectives"]["x"]["alerts"]["slow"] is False
    assert not eng.degraded()


def test_alert_volume_floor():
    """1 bad event out of 2 must not page anyone even at infinite burn."""
    eng, _clock = engine_with(target=0.999)
    eng.record("x", False, n=2)  # burn 1000x but only 2 events
    st = eng.evaluate()
    assert st["objectives"]["x"]["alerts"] == {"fast": False, "slow": False}
    eng.record("x", False, n=eng.min_alert_events)
    st = eng.evaluate()
    assert st["objectives"]["x"]["alerts"] == {"fast": True, "slow": True}


def test_audit_freshness_probe_and_age():
    clock = FakeClock()
    eng = SLOEngine(clock=clock)
    eng.audit_max_age_s = 100.0
    eng.add_objective(
        AUDIT_FRESHNESS, 0.9,
        probe=lambda: eng.audit_age_s() <= eng.audit_max_age_s,
    )
    # never ran: age counts from engine start
    clock.advance(50.0)
    assert eng.audit_age_s() == pytest.approx(50.0)
    eng.evaluate()  # good sample (50 <= 100)
    clock.advance(100.0)
    eng.evaluate()  # bad sample (150 > 100)
    with eng._lock:
        good, bad = eng._counts(AUDIT_FRESHNESS, 21600.0)
    assert (good, bad) == (1, 1)
    eng.observe_audit_run()
    assert eng.audit_age_s() == 0.0
    st = eng.evaluate()
    assert st["audit_last_run_age_s"] == 0.0


def test_budget_remaining():
    eng, _clock = engine_with(target=0.9)  # budget 0.1
    eng.record("x", True, n=95)
    eng.record("x", False, n=5)  # consumed: 0.05/0.1 = 50%
    st = eng.evaluate()
    assert st["objectives"]["x"]["budget_remaining"] == pytest.approx(0.5)


def test_collect_exports_gauges():
    clock = FakeClock()
    eng = SLOEngine(clock=clock)
    eng.add_objective("x", 0.999)
    eng.record("x", False, n=1)
    eng.record("x", True, n=99)
    reg = Registry()
    eng.collect(reg)
    rows = reg.view_rows("slo_burn_rate")
    assert rows[("x", "5m")] == pytest.approx(10.0)
    assert ("x", "6h") in rows
    assert reg.view_rows("slo_error_budget_remaining")[("x",)] < 1.0
    assert reg.view_rows("audit_last_run_age_s")[()] >= 0.0


def test_observe_admission_feeds_global_engine():
    eng = obsslo.get_engine()
    eng.clear()
    try:
        obsslo.observe_admission("allow", 0.001)          # fast + ok
        obsslo.observe_admission("error", eng.admission_threshold_s + 1.0)
        with eng._lock:
            lat = eng._counts(ADMISSION_LATENCY, 300.0)
            err = eng._counts(FAIL_CLOSED_ERRORS, 300.0)
        assert lat == (1, 1)  # one within threshold, one over
        assert err == (1, 1)  # one non-error, one error
    finally:
        eng.clear()


def test_validation_handler_feeds_slo(monkeypatch):
    """handle() feeds the global engine through its existing finally
    block — the same outcome the request metric records."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    eng = obsslo.get_engine()
    eng.clear()
    try:
        handler = ValidationHandler(Client())
        resp = handler.handle({
            "uid": "u", "namespace": "",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "operation": "CREATE",
            "object": {"kind": "Pod", "metadata": {"name": "p"}},
            "userInfo": {"username": "alice"},
        })
        assert resp.allowed
        with eng._lock:
            good, bad = eng._counts(FAIL_CLOSED_ERRORS, 300.0)
        assert good == 1 and bad == 0
    finally:
        eng.clear()


def test_configure_rejects_out_of_range_targets():
    """A --slo-*-target typo (1.0, or 99.9 meaning percent) must fail
    loudly at configure time, not zero the budget and crash every later
    evaluate()."""
    eng = obsslo.get_engine()
    before = None
    with eng._lock:
        before = eng._objectives[ADMISSION_LATENCY].target
    try:
        for bad in (1.0, 0.0, 99.9, -0.1):
            with pytest.raises(ValueError):
                obsslo.configure(admission_target=bad)
        with eng._lock:
            assert eng._objectives[ADMISSION_LATENCY].target == before
        obsslo.configure(admission_target=0.95)
        with eng._lock:
            assert eng._objectives[ADMISSION_LATENCY].target == 0.95
        eng.evaluate()  # still healthy
    finally:
        obsslo.configure(admission_target=before)
        eng.clear()


def test_webhook_only_pod_is_not_stale():
    """audit_expected=False (no audit operation assigned): the freshness
    probe always reports good and the age gauge is withheld, so a
    webhook-only pod never latches the degraded marker."""
    clock = FakeClock()
    eng = SLOEngine(clock=clock)
    eng.audit_max_age_s = 10.0
    eng.audit_expected = False
    eng.min_alert_events = 1
    eng.add_objective(
        AUDIT_FRESHNESS, 0.999,
        probe=lambda: (
            not eng.audit_expected
            or eng.audit_age_s() <= eng.audit_max_age_s
        ),
    )
    clock.advance(10_000.0)  # far past any max age
    for _ in range(5):
        st = eng.evaluate()
    assert st["objectives"][AUDIT_FRESHNESS]["burn_rates"]["5m"] == 0.0
    assert not eng.degraded()
    reg = Registry()
    eng.collect(reg)
    assert reg.view_rows("audit_last_run_age_s") == {}
    # the same engine WITH audit expected does go stale
    eng.audit_expected = True
    for _ in range(5):
        st = eng.evaluate()
    assert st["objectives"][AUDIT_FRESHNESS]["alerts"]["fast"] is True


def test_audit_manager_moves_freshness_anchor():
    from gatekeeper_tpu.audit import AuditManager
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube

    eng = obsslo.get_engine()
    eng.clear()
    try:
        mgr = AuditManager(InMemoryKube(), Client(), from_cache=True)
        before = eng.audit_age_s()
        assert mgr.run_once_guarded()
        assert eng.audit_age_s() <= before
        assert eng.audit_age_s() < 1.0
    finally:
        eng.clear()
