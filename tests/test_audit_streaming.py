"""Streamed discovery-mode audit (VERDICT r2 #5; reference
manager.go:342-396): the per-GVK list is consumed one limit+continue page at
a time through the kube surface, so audit host memory is bounded by
--audit-chunk-size, not cluster size — proven over the wire against the
envtest-analogue HTTPS API server."""

import json

from gatekeeper_tpu.audit import AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.kube.apiserver import KubeApiServer
from gatekeeper_tpu.kube.http_client import HttpKube
from gatekeeper_tpu.kube.inmem import InMemoryKube

from .test_controllers import CONSTRAINT, TEMPLATE

CGVK = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")
N_BAD, N_GOOD = 7, 13


def _constraint_crd():
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "k8srequiredlabels.constraints.gatekeeper.sh"},
        "spec": {
            "group": "constraints.gatekeeper.sh",
            "names": {"kind": "K8sRequiredLabels",
                      "plural": "k8srequiredlabels"},
            "scope": "Cluster",
            "versions": [{"name": "v1beta1", "served": True,
                          "storage": True,
                          "subresources": {"status": {}}}],
        },
    }


def _world(kube, with_crd=False):
    client = Client()
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    if with_crd:
        kube.create(_constraint_crd())
    kube.create(json.loads(json.dumps(CONSTRAINT)))
    for i in range(N_BAD):
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"bad-{i:03d}", "labels": {}}})
    for i in range(N_GOOD):
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"good-{i:03d}",
                                  "labels": {"gatekeeper": "on"}}})
    return client


class PageCountingKube(InMemoryKube):
    def __init__(self):
        super().__init__()
        self.page_sizes = []
        self.full_lists = []

    def list_pages(self, gvk, namespace=None, limit=500):
        for page in super().list_pages(gvk, namespace, limit):
            self.page_sizes.append(len(page))
            yield page

    def list(self, gvk, namespace=None):
        self.full_lists.append(gvk)
        return super().list(gvk, namespace)


def test_streamed_pages_bound_page_size_inmem():
    kube = PageCountingKube()
    client = _world(kube)
    mgr = AuditManager(kube, client, chunk_size=5)
    update_lists = mgr.audit_once()
    key = "K8sRequiredLabels//ns-must-have-gk"
    assert len(update_lists[key]) == N_BAD
    # every audited page respected the chunk bound; N_BAD+N_GOOD namespaces
    # forced several pages
    audit_pages = [s for s in kube.page_sizes]
    assert audit_pages and max(audit_pages) <= 5
    assert len([s for s in audit_pages]) >= (N_BAD + N_GOOD) // 5
    # list_pages is internally built on list() for the in-memory kube, so a
    # full-list call happens inside pagination — the streaming contract to
    # check here is the page-bounded consumption above


def test_streamed_audit_over_the_wire_matches_unchunked():
    """Same audit through the HTTPS API server with chunk 4 vs unchunked:
    identical violations/status, and the wire requests actually paginate
    (continue tokens issued)."""
    results = {}
    for chunk in (4, 0):
        srv = KubeApiServer()
        srv.start()
        try:
            kube = HttpKube(srv.url, discovery_retry_s=1.0)
            client = _world(kube, with_crd=True)
            mgr = AuditManager(kube, client, chunk_size=chunk)
            update_lists = mgr.audit_once()
            status = kube.get(CGVK, "ns-must-have-gk").get("status", {})
            results[chunk] = (
                {k: sorted(v.to_dict()["name"] for v in vs)
                 for k, vs in update_lists.items()},
                status.get("totalViolations"),
            )
        finally:
            srv.stop()
    assert results[4] == results[0]
    assert results[4][1] == N_BAD


def test_wire_pagination_issues_continue_tokens():
    srv = KubeApiServer()
    srv.start()
    try:
        kube = HttpKube(srv.url, discovery_retry_s=1.0)
        _world(kube, with_crd=True)
        pages = list(kube.list_pages(("", "v1", "Namespace"), limit=6))
        assert len(pages) >= (N_BAD + N_GOOD) // 6
        assert all(len(p) <= 6 for p in pages)
        flat = [o["metadata"]["name"] for p in pages for o in p]
        assert len(flat) == N_BAD + N_GOOD
        assert len(set(flat)) == len(flat), "pages must not overlap"
        # every page item is usable as a full object (apiVersion restored)
        assert all(o.get("apiVersion") == "v1"
                   for p in pages for o in p)
    finally:
        srv.stop()
