"""Client conformance suite.

A port of the reference's engine conformance battery (vendored
frameworks/constraint/pkg/client/e2e_tests.go) to the K8s validation target:
template lifecycle, constraint CRUD, data CRUD, Review/Audit responses,
autoreject, dryrun, tracing, parameters — exercised through the full
client+driver stack.  Parameterized over drivers so the TPU driver runs the
identical battery.
"""

import pytest

from gatekeeper_tpu.client import Client, InterpDriver
from gatekeeper_tpu.client.client import ClientError

DENY_REGO = """
package foo

violation[{"msg": "DENIED", "details": {}}] {
  "always" == "always"
}
"""

DENY_REGO_WITH_LIB = """
package foo

violation[{"msg": msg, "details": {}}] {
  data.lib.bar.always[x]
  msg := x
}
"""

DENY_LIB = """
package lib.bar

always[y] {
  y := "DENIED"
}
"""

PARAM_REGO = """
package foo

violation[{"msg": msg, "details": {}}] {
  input.parameters.name == input.review.object.metadata.name
  msg := sprintf("denied name %v", [input.review.object.metadata.name])
}
"""


def make_template(kind="Foo", rego=DENY_REGO, libs=()):
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {
                "spec": {
                    "names": {"kind": kind},
                    "validation": {
                        "openAPIV3Schema": {
                            "properties": {"name": {"type": "string"}}
                        }
                    },
                }
            },
            "targets": [
                {
                    "target": "admission.k8s.gatekeeper.sh",
                    "rego": rego,
                    "libs": list(libs),
                }
            ],
        },
    }


def make_constraint(kind="Foo", name="ph", params=None, enforcement=None, match=None):
    spec = {}
    if params is not None:
        spec["parameters"] = params
    if enforcement is not None:
        spec["enforcementAction"] = enforcement
    if match is not None:
        spec["match"] = match
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": name},
        "spec": spec,
    }


def make_object(name, namespace=None, labels=None, kind="Pod", api="v1"):
    meta = {"name": name}
    if namespace:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = labels
    return {"apiVersion": api, "kind": kind, "metadata": meta}


def make_request(obj, operation="CREATE"):
    """AdmissionRequest-shaped review (carries `namespace`), as the webhook
    produces — bare unstructured objects intentionally do not (target.go:144)."""
    meta = obj.get("metadata", {})
    req = {
        "kind": {"group": "", "version": obj.get("apiVersion", "v1"),
                 "kind": obj.get("kind", "")},
        "name": meta.get("name", ""),
        "operation": operation,
        "object": obj,
    }
    if meta.get("namespace"):
        req["namespace"] = meta["namespace"]
    return req


DRIVERS = ["interp"]
try:  # TPU driver battery, once available
    from gatekeeper_tpu.ops.driver import TpuDriver  # noqa: F401

    # "tpu" = production hybrid dispatch (small batches take the host
    # numpy-serving path); "tpu-device"/"tpu-mesh" force every scenario
    # through compute_masks + render (DEVICE_MIN_CELLS=0) on one device and
    # on the 8-virtual-device mesh, proving the device kernels on
    # small/degenerate shapes — empty inventory, vocab growth mid-review,
    # padded rows (VERDICT r2 #4; conformance role of the reference's
    # e2e_tests.go via probe_client.go:16-56); "tpu-np" forces the
    # incremental host side (ops/npside.py) with the interp fallback
    # disabled, so a silent np bail cannot hide behind identical interp
    # results
    DRIVERS += ["tpu", "tpu-device", "tpu-mesh", "tpu-np"]
except ImportError:
    pass


@pytest.fixture(params=DRIVERS)
def client(request):
    if request.param == "interp":
        return Client(driver=InterpDriver())
    import jax

    from gatekeeper_tpu.ops.driver import TpuDriver

    if request.param == "tpu-mesh" and len(jax.devices()) < 2:
        pytest.skip("mesh variant needs multiple devices")
    driver = TpuDriver()
    if request.param == "tpu-np":
        driver.DEVICE_MIN_CELLS = 10**9  # never route to the device
        driver.NP_MIN_CELLS = 0  # small scenarios must hit npside, not interp
        # np serve returns None on empty sides; fall through to interp is
        # the production behavior, fine for conformance — scenarios with
        # constraints installed all serve from the np mask
    elif request.param != "tpu":
        driver.DEVICE_MIN_CELLS = 0  # force the device path
        driver.mesh_enabled = request.param == "tpu-mesh"
        driver._mesh_cache = None
    return Client(driver=driver)


@pytest.mark.parametrize("rego,libs", [(DENY_REGO, ()), (DENY_REGO_WITH_LIB, (DENY_LIB,))])
class TestDenyAll:
    def test_add_template(self, client, rego, libs):
        crd = client.add_template(make_template(rego=rego, libs=libs))
        assert crd["metadata"]["name"] == "foo.constraints.gatekeeper.sh"
        assert crd["spec"]["names"]["kind"] == "Foo"

    def test_deny_all_review(self, client, rego, libs):
        client.add_template(make_template(rego=rego, libs=libs))
        cstr = make_constraint()
        client.add_constraint(cstr)
        rsps = client.review(make_object("sara"))
        results = rsps.results()
        assert len(results) == 1
        assert results[0].msg == "DENIED"
        assert results[0].constraint == cstr
        assert results[0].enforcement_action == "deny"

    def test_deny_all_audit(self, client, rego, libs):
        client.add_template(make_template(rego=rego, libs=libs))
        cstr = make_constraint()
        client.add_constraint(cstr)
        obj = make_object("sara")
        client.add_data(obj)
        rsps = client.audit()
        results = rsps.results()
        assert len(results) == 1
        assert results[0].msg == "DENIED"
        assert results[0].constraint == cstr
        assert results[0].resource == obj

    def test_deny_all_audit_x2(self, client, rego, libs):
        client.add_template(make_template(rego=rego, libs=libs))
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        client.add_data(make_object("max"))
        assert len(client.audit().results()) == 2

    def test_tracing_on_off(self, client, rego, libs):
        client.add_template(make_template(rego=rego, libs=libs))
        client.add_constraint(make_constraint())
        rsps = client.review(make_object("sara"), tracing=True)
        assert all(r.trace is not None for r in rsps.by_target.values())
        rsps = client.review(make_object("sara"))
        assert all(r.trace is None for r in rsps.by_target.values())

    def test_audit_tracing_on_off(self, client, rego, libs):
        client.add_template(make_template(rego=rego, libs=libs))
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        assert all(
            r.trace is not None
            for r in client.audit(tracing=True).by_target.values()
        )
        assert all(
            r.trace is None for r in client.audit().by_target.values()
        )


class TestLifecycle:
    def test_remove_data(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint())
        obj, obj2 = make_object("sara"), make_object("max")
        client.add_data(obj)
        client.add_data(obj2)
        assert len(client.audit().results()) == 2
        assert client.remove_data(obj2)
        results = client.audit().results()
        assert len(results) == 1
        assert results[0].resource == obj

    def test_remove_constraint(self, client):
        client.add_template(make_template())
        cstr = make_constraint()
        client.add_constraint(cstr)
        client.add_data(make_object("sara"))
        assert len(client.audit().results()) == 1
        assert client.remove_constraint(cstr)
        assert client.audit().results() == []

    def test_remove_template(self, client):
        tmpl = make_template()
        client.add_template(tmpl)
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        assert len(client.audit().results()) == 1
        assert client.remove_template(tmpl)
        assert client.audit().results() == []

    def test_constraint_requires_template(self, client):
        with pytest.raises(ClientError, match="no constraint template"):
            client.add_constraint(make_constraint(kind="Missing"))

    def test_bad_rego_rejected(self, client):
        bad = make_template(rego="package foo\nviolation[{")
        with pytest.raises(ClientError):
            client.add_template(bad)

    def test_template_requires_violation(self, client):
        bad = make_template(rego="package foo\nallow { true }")
        with pytest.raises(ClientError, match="violation"):
            client.add_template(bad)

    def test_template_name_must_match_kind(self, client):
        t = make_template()
        t["metadata"]["name"] = "wrong"
        with pytest.raises(ClientError, match="lowercase"):
            client.add_template(t)

    def test_semantic_equality_short_circuit(self, client):
        t = make_template()
        crd1 = client.add_template(t)
        crd2 = client.add_template(t)
        assert crd1 == crd2

    def test_wipe_data(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        assert client.wipe_data()
        assert client.audit().results() == []

    def test_reset(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        client.reset()
        assert client.audit().results() == []
        assert client.templates() == []

    def test_dump(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint())
        client.add_data(make_object("sara"))
        dump = client.dump()
        assert "Foo" in dump and "sara" in dump


class TestSemanticsScenarios:
    def test_autoreject_all(self, client):
        """Constraint with a namespaceSelector autorejects a review whose
        namespace is not cached (e2e 'Autoreject All')."""
        client.add_template(make_template())
        ns_sel = make_constraint(
            name="ns-sel",
            match={
                "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                "namespaceSelector": {
                    "matchExpressions": [
                        {"key": "someKey", "operator": "Blah", "values": ["v"]}
                    ]
                },
            },
        )
        client.add_constraint(ns_sel)
        client.add_constraint(make_constraint(name="plain"))
        # The webhook path reviews AdmissionRequests, which carry `namespace`
        # (a bare unstructured object does not — and then the original rego
        # both autorejects and skips ns selectors; see target/match.py).
        req = {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": "sara",
            "namespace": "nowhere",
            "operation": "CREATE",
            "object": make_object("sara", namespace="nowhere"),
        }
        rsps = client.review(req)
        results = rsps.results()
        assert len(results) == 2
        msgs = {r.msg for r in results}
        assert "Namespace is not cached in OPA." in msgs
        assert "DENIED" in msgs
        for r in results:
            if r.msg == "Namespace is not cached in OPA.":
                assert r.constraint == ns_sel

    def test_nsselector_matches_cached_namespace(self, client):
        client.add_template(make_template())
        client.add_constraint(
            make_constraint(
                name="ns-sel",
                match={"namespaceSelector": {"matchLabels": {"team": "a"}}},
            )
        )
        ns = make_object("team-a", kind="Namespace", labels={"team": "a"})
        client.add_data(ns)
        rsps = client.review(make_request(make_object("sara", namespace="team-a")))
        assert [r.msg for r in rsps.results()] == ["DENIED"]
        rsps = client.review(make_request(make_object("sara", namespace="team-b")))
        msgs = [r.msg for r in rsps.results()]
        assert msgs == ["Namespace is not cached in OPA."]

    def test_dryrun_all(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint(enforcement="dryrun"))
        results = client.review(make_object("sara")).results()
        assert len(results) == 1
        assert results[0].enforcement_action == "dryrun"

    def test_deny_by_parameter(self, client):
        client.add_template(make_template(rego=PARAM_REGO))
        client.add_constraint(make_constraint(params={"name": "deny-me"}))
        assert len(client.review(make_object("deny-me")).results()) == 1
        assert client.review(make_object("let-me")).results() == []

    def test_match_kinds_filter(self, client):
        client.add_template(make_template())
        client.add_constraint(
            make_constraint(
                match={"kinds": [{"apiGroups": [""], "kinds": ["Namespace"]}]}
            )
        )
        assert client.review(make_object("p", kind="Pod")).results() == []
        assert (
            len(client.review(make_object("n", kind="Namespace")).results()) == 1
        )

    def test_match_namespaces_and_excluded(self, client):
        client.add_template(make_template())
        client.add_constraint(
            make_constraint(name="nsonly", match={"namespaces": ["prod"]})
        )
        client.add_constraint(
            make_constraint(name="exc", match={"excludedNamespaces": ["prod"]})
        )
        results = client.review(make_request(make_object("p", namespace="prod"))).results()
        assert [r.constraint["metadata"]["name"] for r in results] == ["nsonly"]
        results = client.review(make_request(make_object("p", namespace="dev"))).results()
        assert [r.constraint["metadata"]["name"] for r in results] == ["exc"]

    def test_match_label_selector(self, client):
        client.add_template(make_template())
        client.add_constraint(
            make_constraint(match={"labelSelector": {"matchLabels": {"app": "web"}}})
        )
        assert (
            len(client.review(make_object("p", labels={"app": "web"})).results()) == 1
        )
        assert client.review(make_object("p", labels={"app": "db"})).results() == []

    def test_match_scope(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint(name="c", match={"scope": "Cluster"}))
        client.add_constraint(make_constraint(name="n", match={"scope": "Namespaced"}))
        results = client.review(make_request(make_object("p", namespace="default"))).results()
        assert [r.constraint["metadata"]["name"] for r in results] == ["n"]
        results = client.review(make_request(make_object("cr", kind="ClusterRole"))).results()
        assert [r.constraint["metadata"]["name"] for r in results] == ["c"]

    def test_audit_inventory_visible_to_policy(self, client):
        rego = """
package foo

violation[{"msg": msg, "details": {}}] {
  count([n | data.inventory.cluster["v1"].Namespace[n]]) > 1
  msg := "too many namespaces"
}
"""
        client.add_template(make_template(rego=rego))
        client.add_constraint(make_constraint())
        client.add_data(make_object("ns1", kind="Namespace"))
        client.add_data(make_object("ns2", kind="Namespace"))
        results = client.review(make_object("sara")).results()
        assert [r.msg for r in results] == ["too many namespaces"]

    def test_constraint_schema_validation(self, client):
        client.add_template(make_template())
        bad = make_constraint(params={"name": 42})  # schema wants string
        with pytest.raises(ClientError, match="expected string"):
            client.add_constraint(bad)

    def test_review_admission_request_shape(self, client):
        client.add_template(make_template())
        client.add_constraint(make_constraint())
        req = {
            "uid": "abc",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": "sara",
            "namespace": "default",
            "operation": "CREATE",
            "object": make_object("sara", namespace="default"),
        }
        results = client.review(req).results()
        assert len(results) == 1
        assert results[0].resource["metadata"]["name"] == "sara"


def test_every_reference_template_installs_and_evaluates(client):
    """Corpus-wide ingestion: every ConstraintTemplate fixture shipped by
    the reference (demo/, bats/, psp testdata) installs through the full
    client (parametrized over every driver variant) and evaluates a
    pod review without error — a user's existing templates must load
    as-is."""
    from .corpus import constraint_templates

    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "probe", "namespace": "default",
                     "labels": {"app": "probe"}},
        "spec": {"containers": [{
            "name": "c", "image": "openpolicyagent/opa:0.9.2",
            "resources": {"limits": {"cpu": "100m", "memory": "128Mi"}}}]},
    }
    c = client
    seen = set()
    n = 0
    for path, tmpl in constraint_templates():
        kind = (((tmpl.get("spec") or {}).get("crd") or {})
                .get("spec") or {}).get("names", {}).get("kind")
        if not kind or kind in seen:
            continue
        seen.add(kind)
        c.add_template(tmpl)
        c.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": f"probe-{kind.lower()}"},
            "spec": {"match": {"kinds": [
                {"apiGroups": [""], "kinds": ["Pod", "Namespace"]}]}},
        })
        n += 1
    # one review against the whole installed battery; eval must not
    # error (violations are fine — many templates have no parameters)
    req = {"uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
           "name": "probe", "namespace": "default",
           "operation": "CREATE", "object": pod}
    c.review(req)
    c.add_data(pod)
    c.audit()
    assert n >= 12  # distinct constraint kinds across the corpus
