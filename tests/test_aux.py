"""Auxiliary subsystems: syncutil, upgrade manager, cert rotation."""

import ssl
import threading
import time
import urllib.request

import pytest

# the battery exercises cert rotation end to end; without `cryptography`
# (gated import, see main.py) the module cannot even import — skip
# cleanly instead of erroring at collection
pytest.importorskip("cryptography")

from gatekeeper_tpu.certs import CertRotator
from gatekeeper_tpu.certs.rotator import SECRET_GVK, VWC_GVK, cert_expiry
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.syncutil import SingleRunner, SyncBool, retry_with_backoff
from gatekeeper_tpu.upgrade import UpgradeManager


class TestSyncUtil:
    def test_syncbool(self):
        b = SyncBool()
        assert not b.get()
        b.set(True)
        assert b.get()

    def test_single_runner_keys_are_single_use(self):
        runner = SingleRunner()
        ran = []

        def work(stop):
            ran.append(1)
            stop.wait(timeout=5)

        assert runner.schedule("k", work)
        assert not runner.schedule("k", work)  # silently ignored
        runner.cancel("k")
        runner.wait(timeout=2)
        assert ran == [1]

    def test_single_runner_cancel_unblocks(self):
        runner = SingleRunner()
        finished = threading.Event()

        def work(stop):
            stop.wait(timeout=30)
            finished.set()

        runner.schedule("x", work)
        t0 = time.monotonic()
        runner.cancel("x")
        assert finished.wait(timeout=2)
        assert time.monotonic() - t0 < 2

    def test_retry_with_backoff(self):
        attempts = []

        def fn():
            attempts.append(1)
            return len(attempts) >= 3

        assert retry_with_backoff(fn, initial=0.001)
        assert len(attempts) == 3
        attempts.clear()
        assert not retry_with_backoff(lambda: False, initial=0.001, steps=3)


class TestUpgradeManager:
    def test_migrates_v1alpha1(self):
        kube = InMemoryKube()
        kube.create({
            "apiVersion": "constraints.gatekeeper.sh/v1alpha1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": "old-one"},
            "spec": {"parameters": {"labels": ["a"]}},
        })
        kube.create({
            "apiVersion": "templates.gatekeeper.sh/v1alpha1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "old-template"},
            "spec": {},
        })
        n = UpgradeManager(kube).upgrade()
        assert n == 2
        old = kube.list(("constraints.gatekeeper.sh", "v1alpha1",
                         "K8sRequiredLabels"))
        assert old == []
        new = kube.get(("constraints.gatekeeper.sh", "v1beta1",
                        "K8sRequiredLabels"), "old-one")
        assert new["spec"]["parameters"] == {"labels": ["a"]}
        assert new["apiVersion"] == "constraints.gatekeeper.sh/v1beta1"

    def test_existing_new_version_wins(self):
        kube = InMemoryKube()
        kube.create({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K",
            "metadata": {"name": "x"},
            "spec": {"new": True},
        })
        kube.create({
            "apiVersion": "constraints.gatekeeper.sh/v1alpha1",
            "kind": "K",
            "metadata": {"name": "x"},
            "spec": {"old": True},
        })
        UpgradeManager(kube).upgrade()
        kept = kube.get(("constraints.gatekeeper.sh", "v1beta1", "K"), "x")
        assert kept["spec"] == {"new": True}


class TestCertRotator:
    def test_generates_secret_and_injects_bundle(self):
        kube = InMemoryKube()
        kube.create({
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata":
                {"name": "gatekeeper-validating-webhook-configuration"},
            "webhooks": [
                {"name": "validation.gatekeeper.sh", "clientConfig": {}},
                {"name": "check-ignore-label.gatekeeper.sh",
                 "clientConfig": {}},
            ],
        })
        rot = CertRotator(kube)
        assert not rot.is_ready.is_set()
        rot.ensure_certs()
        assert rot.is_ready.is_set()
        secret = kube.get(SECRET_GVK, rot.secret_name, rot.namespace)
        data = secret["stringData"]
        assert data["tls.crt"].startswith("-----BEGIN CERTIFICATE")
        vwc = kube.get(VWC_GVK, "gatekeeper-validating-webhook-configuration")
        assert all(w["clientConfig"]["caBundle"] for w in vwc["webhooks"])

    def test_valid_secret_not_regenerated(self):
        kube = InMemoryKube()
        rot = CertRotator(kube)
        s1 = rot.ensure_certs()
        s2 = rot.ensure_certs()
        assert s1["stringData"]["tls.crt"] == s2["stringData"]["tls.crt"]

    def test_expiring_cert_refreshed(self):
        kube = InMemoryKube()
        rot = CertRotator(kube)
        secret = rot.ensure_certs()
        # corrupt the cert: forces regeneration
        secret["stringData"]["tls.crt"] = "garbage"
        kube.update(secret)
        s2 = rot.ensure_certs()
        assert s2["stringData"]["tls.crt"].startswith("-----BEGIN CERTIFICATE")
        assert cert_expiry(s2["stringData"]["tls.crt"].encode())

    def test_tls_webhook_server(self, tmp_path):
        """End-to-end: rotator-issued certs serve real TLS."""
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.webhook import ValidationHandler, WebhookServer

        kube = InMemoryKube()
        rot = CertRotator(kube)
        certfile, keyfile = rot.write_cert_files(str(tmp_path))
        handler = ValidationHandler(Client(), kube=kube)
        srv = WebhookServer(handler, port=0, certfile=certfile, keyfile=keyfile)
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            with urllib.request.urlopen(
                f"https://127.0.0.1:{srv.port}/healthz", context=ctx, timeout=5
            ) as r:
                assert r.status == 200
        finally:
            srv.stop()

    def test_refresh_reuses_valid_ca(self):
        import datetime
        from gatekeeper_tpu.certs import rotator as rot_mod

        kube = InMemoryKube()
        rot = CertRotator(kube)
        s1 = rot.ensure_certs()
        ca1 = s1["stringData"]["ca.crt"]
        # hook installed after bootstrap, as App wires it
        refreshed = []
        rot.on_refresh = lambda s: refreshed.append(s)
        # expire only the serving cert by shrinking its validity window
        old_validity = rot_mod.CERT_VALIDITY
        try:
            # re-issue a serving cert that is inside the refresh margin
            rot_mod.CERT_VALIDITY = datetime.timedelta(days=1)
            tls_crt, tls_key = rot_mod.generate_server_cert(
                ca1.encode(), s1["stringData"]["ca.key"].encode(),
                rot.dns_names,
            )
            s1["stringData"]["tls.crt"] = tls_crt.decode()
            s1["stringData"]["tls.key"] = tls_key.decode()
            kube.update(s1)
        finally:
            rot_mod.CERT_VALIDITY = old_validity
        s2 = rot.ensure_certs()
        # serving cert re-signed, CA unchanged (caBundle stability)
        assert s2["stringData"]["ca.crt"] == ca1
        assert s2["stringData"]["tls.crt"] != s1["stringData"]["tls.crt"]
        assert len(refreshed) == 1

    def test_key_file_permissions(self, tmp_path):
        import os

        kube = InMemoryKube()
        rot = CertRotator(kube)
        certfile, keyfile = rot.write_cert_files(str(tmp_path / "certs"))
        assert oct(os.stat(keyfile).st_mode & 0o777) == "0o600"
        assert oct(os.stat(os.path.dirname(keyfile)).st_mode & 0o777) == "0o700"


class TestSmallPieces:
    def test_version(self):
        from gatekeeper_tpu import version

        assert version.VERSION
        assert "gatekeeper-tpu/" in version.user_agent()

    def test_retry_kube_retries_conflict(self):
        from gatekeeper_tpu.kube.clients import RetryKube

        kube = InMemoryKube()
        kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": "x"}})
        rk = RetryKube(kube, backoff_s=0.001)
        stale = rk.get(("", "v1", "ConfigMap"), "x")
        kube.update({"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": "x"}, "data": {"a": "1"}})
        import pytest as _pytest

        stale["data"] = {"b": "2"}
        with _pytest.raises(Exception):
            rk.update(stale, check_version=True)  # stays conflicted
        # non-versioned update goes through
        rk.update(stale)
        assert kube.get(("", "v1", "ConfigMap"), "x")["data"] == {"b": "2"}

    def test_noop_kube(self):
        from gatekeeper_tpu.kube.clients import NoopKube
        from gatekeeper_tpu.kube.inmem import NotFound

        nk = NoopKube()
        assert nk.list(("", "v1", "Pod")) == []
        assert nk.create({"x": 1}) == {"x": 1}
        import pytest as _pytest

        with _pytest.raises(NotFound):
            nk.get(("", "v1", "Pod"), "a")

    def test_profile_server(self):
        from gatekeeper_tpu.main import ProfileServer

        ps = ProfileServer(port=0)
        ps.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ps.port}/debug/pprof", timeout=5
            ) as r:
                body = r.read().decode()
            assert "thread MainThread" in body
        finally:
            ps.stop()


class TestJaxProfileServer:
    def test_flag_starts_profiler_server(self):
        """--jax-profile-port starts the jax.profiler server (the TPU
        analogue of --enable-pprof; TensorBoard attaches on demand)."""
        import socket

        from gatekeeper_tpu.main import App, build_parser
        from gatekeeper_tpu.kube.inmem import InMemoryKube

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        app = App(
            build_parser().parse_args(
                ["--jax-profile-port", str(port), "--disable-cert-rotation"]
            ),
            kube=InMemoryKube(),
        )
        try:
            app.start()
            # the profiler server listens (gRPC); a TCP connect suffices
            probe = socket.create_connection(("127.0.0.1", port), timeout=5)
            probe.close()
        finally:
            app.stop()


class TestFlagParityAdditions:
    """main.go:84-87 + controller.go:40 flags added for full surface parity."""

    def test_log_level_encoders(self):
        from gatekeeper_tpu.logging import LEVEL_ENCODERS
        assert LEVEL_ENCODERS["lower"]("INFO") == "info"
        assert LEVEL_ENCODERS["capital"]("info") == "INFO"
        assert "\x1b[" in LEVEL_ENCODERS["color"]("ERROR")
        assert "ERROR" in LEVEL_ENCODERS["capitalcolor"]("error").upper()

    def test_parser_accepts_new_flags(self):
        from gatekeeper_tpu.main import build_parser
        args = build_parser().parse_args([
            "--log-level-key", "severity", "--log-level-encoder", "capital",
            "--metrics-addr", ":0", "--debug-use-fake-pod",
        ])
        assert args.log_level_key == "severity"
        assert args.debug_use_fake_pod is True

    def test_debug_use_fake_pod_disables_ownership(self, monkeypatch):
        import os
        from gatekeeper_tpu.apis import status as status_api
        from gatekeeper_tpu.main import App
        monkeypatch.setattr(status_api, "_POD_OWNERSHIP", True)
        # App writes POD_NAME directly; register restoration so later tests
        # don't inherit the fake pod identity
        monkeypatch.setitem(os.environ, "POD_NAME", os.environ.get("POD_NAME", ""))
        app = App(["--debug-use-fake-pod", "--api-server", "inmem",
                   "--driver", "interp"])
        assert os.environ.get("POD_NAME") == "no-pod"
        assert status_api.pod_ownership_enabled() is False

    def test_status_crs_owner_reference_the_pod(self, monkeypatch):
        from gatekeeper_tpu.apis import status as status_api
        monkeypatch.setattr(status_api, "_POD_OWNERSHIP", True)
        pod = {"metadata": {"name": "gk-pod-1", "uid": "u-123"}}
        st = status_api.new_constraint_status_for_pod(
            "gk-pod-1", "gatekeeper-system",
            {"kind": "K8sFoo", "metadata": {"name": "c1"}}, ["audit"],
            owner_pod=pod,
        )
        refs = st["metadata"]["ownerReferences"]
        assert refs == [{"apiVersion": "v1", "kind": "Pod",
                         "name": "gk-pod-1", "uid": "u-123"}]
        # ownership disabled -> no owner refs (DisablePodOwnership analogue)
        monkeypatch.setattr(status_api, "_POD_OWNERSHIP", False)
        st2 = status_api.new_template_status_for_pod(
            "gk-pod-1", "gatekeeper-system",
            {"metadata": {"name": "t1"}}, ["audit"], owner_pod=pod,
        )
        assert "ownerReferences" not in st2["metadata"]

    def test_metrics_addr_rejects_malformed(self):
        from gatekeeper_tpu.main import App
        import pytest as _pytest
        for bad in ("localhost", "127.0.0.1:", ":", "localhost:http"):
            with _pytest.raises(SystemExit):
                app = App(["--api-server", "inmem", "--driver", "interp",
                           "--metrics-addr", bad, "--prometheus-port", "0",
                           "--port", "0", "--health-addr", ":0",
                           "--disable-cert-rotation"])
                app.start()
                app.stop()

    def test_stop_safe_after_failed_start(self):
        # a start() that dies before metrics-addr binding must still allow
        # cleanup via stop() without AttributeError
        from gatekeeper_tpu.main import App
        app = App(["--api-server", "inmem", "--driver", "interp"])
        app.stop()  # never started: every component is None

    def test_logging_resetup_applies_new_format(self):
        import io, json, logging
        from gatekeeper_tpu import logging as gklog
        root = logging.getLogger("gatekeeper")
        saved = root.handlers[:]
        try:
            root.handlers = []
            buf = io.StringIO()
            gklog.setup("INFO", stream=buf)
            gklog.setup("INFO", level_key="severity", level_encoder="capital")
            gklog.get("t").info("x")
            line = json.loads(buf.getvalue())
            assert line["severity"] == "INFO"
        finally:
            root.handlers = saved


class TestXlaCache:
    def test_enable_idempotent_and_functional(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from gatekeeper_tpu.ops import xlacache

        d = str(tmp_path / "cache")
        prior = jax.config.jax_compilation_cache_dir
        try:
            assert xlacache.enable(d) is True
            assert xlacache.enable(d) is True  # idempotent
            f = jax.jit(lambda x: (x * 2).sum())
            assert float(f(jnp.ones(64))) == 128.0
            import os
            assert os.path.isdir(d) and len(os.listdir(d)) >= 1
        finally:
            # undo the global config so later compiles don't write into a
            # pruned pytest tmp dir
            jax.config.update("jax_compilation_cache_dir", prior)
            xlacache._enabled_dir = None

    def test_flag_wires_cache(self, tmp_path, monkeypatch):
        from gatekeeper_tpu.main import build_parser
        args = build_parser().parse_args(["--xla-cache-dir", str(tmp_path)])
        assert args.xla_cache_dir == str(tmp_path)
