"""Async late-joiner watch replay (VERDICT r2 #6; reference
pkg/watch/replay.go:35-120): the snapshot list runs off the manager lock in
a cancellable per-(registrar, gvk) thread with retry/backoff, while live
fan-out keeps flowing and the no-stale-resurrection ordering holds.
"""

import queue
import threading
import time

from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.watch.manager import WatchManager

POD = ("", "v1", "Pod")
NS = ("", "v1", "Namespace")


def _obj(kind, name, ns=""):
    o = {"apiVersion": "v1", "kind": kind, "metadata": {"name": name}}
    if ns:
        o["metadata"]["namespace"] = ns
    return o


def _drain(r, n, timeout=5.0):
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        try:
            out.append(r.events.get(timeout=0.1))
        except queue.Empty:
            pass
    return out


class SlowListKube(InMemoryKube):
    """list() blocks until released — an envtest-scale list over HTTP."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.slow_gvks = set()
        self.list_calls = []

    def list(self, gvk, **kw):
        self.list_calls.append(gvk)
        if gvk in self.slow_gvks:
            assert self.gate.wait(10), "test never released the list gate"
        return super().list(gvk, **kw)


class FlakyListKube(InMemoryKube):
    def __init__(self, failures=2):
        super().__init__()
        self.failures = failures

    def list(self, gvk, **kw):
        if self.failures > 0:
            self.failures -= 1
            raise ConnectionError("transient list failure")
        return super().list(gvk, **kw)


def test_slow_replay_does_not_stall_live_fanout():
    """A second registrar joining with a slow snapshot list must not block
    live events for other registrars or other GVKs."""
    kube = SlowListKube()
    kube.apply(_obj("Pod", "pre-1", "default"))
    wm = WatchManager(kube)
    r1 = wm.new_registrar("first")
    r1.add_watch(POD)
    assert len(_drain(r1, 1)) == 1  # r1's own replay lands

    kube.slow_gvks.add(POD)
    r2 = wm.new_registrar("late")
    r2.add_watch(POD)  # replay now parked on the list gate

    # live fan-out to r1 keeps flowing while r2's replay is stuck
    kube.apply(_obj("Pod", "live-1", "default"))
    evs = _drain(r1, 1, timeout=2.0)
    assert [e.object["metadata"]["name"] for _g, e in evs] == ["live-1"]
    assert wm.replays_active() == 1

    # a different registrar on a different GVK is also unaffected
    r3 = wm.new_registrar("other")
    r3.add_watch(NS)
    kube.apply(_obj("Namespace", "ns-live"))
    assert [e.object["metadata"]["name"] for _g, e in _drain(r3, 1)] == ["ns-live"]

    kube.gate.set()
    deadline = time.monotonic() + 5
    while wm.replays_active() and time.monotonic() < deadline:
        time.sleep(0.01)
    # r2 sees the snapshot (pre-1) and then the buffered live event, in order
    got = _drain(r2, 3)
    names = [e.object["metadata"]["name"] for _g, e in got]
    assert names == ["pre-1", "live-1"] or names == ["pre-1", "live-1", "live-1"][:len(names)]
    assert names[0] == "pre-1" and "live-1" in names


def test_no_stale_resurrection_on_delete_during_replay():
    """An object deleted while the replay list is in flight must not be
    resurrected: its buffered DELETED wins over the snapshot ADDED."""
    kube = SlowListKube()
    doomed = _obj("Pod", "doomed", "default")
    kube.apply(doomed)
    kube.apply(_obj("Pod", "keeper", "default"))
    wm = WatchManager(kube)
    keeper_watch = wm.new_registrar("keeper-reg")
    keeper_watch.add_watch(POD)  # keeps the pump alive
    _drain(keeper_watch, 2)

    kube.slow_gvks.add(POD)
    late = wm.new_registrar("late")
    late.add_watch(POD)
    # delete while the replay's list is parked: the DELETED event lands in
    # the replay buffer
    deleter = threading.Thread(
        target=kube.delete, args=(POD, "doomed", "default"))
    deleter.start()
    deleter.join(5)
    time.sleep(0.1)  # let the pump fan the DELETED into the buffer
    kube.gate.set()
    deadline = time.monotonic() + 5
    while wm.replays_active() and time.monotonic() < deadline:
        time.sleep(0.01)
    got = _drain(late, 3, timeout=2.0)
    seq = [(e.type, e.object["metadata"]["name"]) for _g, e in got]
    # the replayed ADDED for "doomed" must be suppressed (fresher buffered
    # event exists); the DELETED follows the snapshot
    assert ("ADDED", "keeper") in seq
    added_doomed = [s for s in seq if s == ("ADDED", "doomed")]
    assert not added_doomed, seq
    assert ("DELETED", "doomed") in seq, seq


def test_teardown_during_replay_cancels_cleanly():
    """Removing the watch (or the registrar) mid-replay cancels the replay:
    no events are delivered afterwards and no thread leaks."""
    kube = SlowListKube()
    kube.apply(_obj("Pod", "p1", "default"))
    wm = WatchManager(kube)
    anchor = wm.new_registrar("anchor")
    anchor.add_watch(POD)
    _drain(anchor, 1)

    kube.slow_gvks.add(POD)
    r = wm.new_registrar("doomed-reg")
    r.add_watch(POD)
    assert wm.replays_active() == 1
    r.remove_watch(POD)  # teardown mid-replay
    assert wm.replays_active() == 0
    kube.gate.set()
    time.sleep(0.2)
    assert r.events.empty(), "cancelled replay must not deliver"


def test_replay_retries_list_errors_with_backoff():
    kube = FlakyListKube(failures=2)
    kube.apply(_obj("Pod", "p1", "default"))
    wm = WatchManager(kube)
    r = wm.new_registrar("r")
    r.add_watch(POD)
    got = _drain(r, 1, timeout=5.0)
    assert [e.object["metadata"]["name"] for _g, e in got] == ["p1"]


def test_manager_stop_cancels_replays():
    kube = SlowListKube()
    kube.apply(_obj("Pod", "p1", "default"))
    wm = WatchManager(kube)
    kube.slow_gvks.add(POD)
    r = wm.new_registrar("r")
    r.add_watch(POD)
    assert wm.replays_active() == 1
    wm.stop()
    assert wm.replays_active() == 0
    kube.gate.set()
    time.sleep(0.2)
    assert r.events.empty()
