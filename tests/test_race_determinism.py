"""Race / determinism lane (VERDICT r2 #7; SURVEY §5.2 asks this framework
to add what the reference lacks — its only concurrency assurance is
golangci-lint + code review).

(a) chaos: threads concurrently ingest templates/constraints, mutate data,
    and call review/audit against both drivers — no exception, no deadlock,
    and interp/TPU parity once quiesced;
(b) determinism: two identical sweeps produce bit-identical device masks and
    identical capped results, with GK_MESH on and off.
"""

import threading

import numpy as np
import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


def _mk_client(driver):
    return Client(driver=driver)


@pytest.mark.parametrize("driver_kind", ["interp", "tpu", "tpu-async"])
def test_chaos_concurrent_ingest_review_audit(driver_kind):
    if driver_kind == "interp":
        client = _mk_client(InterpDriver())
    else:
        client = _mk_client(TpuDriver(async_compile=driver_kind == "tpu-async"))
        client.driver.DEVICE_MIN_CELLS = 0
    templates, constraints = make_templates(12)
    pods = make_pods(40, seed=3, violation_rate=0.5)
    req_pod = pods[0]
    req = {
        "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": req_pod["metadata"]["name"],
        "namespace": req_pod["metadata"]["namespace"],
        "operation": "CREATE", "object": req_pod,
    }
    errors = []
    stop = threading.Event()

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)
        return run

    it = {"i": 0}

    def ingest():
        i = it["i"] = (it["i"] + 1) % len(templates)
        client.add_template(templates[i])
        client.add_constraint(constraints[i])

    di = {"i": 0}

    def mutate():
        i = di["i"] = (di["i"] + 1) % len(pods)
        p = dict(pods[i])
        client.add_data(p)
        if i % 5 == 0:
            client.remove_data(p)

    def review():
        client.review(req)

    def audit():
        client.audit_capped(3)

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (ingest, ingest, mutate, review, audit)]
    for t in threads:
        t.start()
    import time

    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker deadlocked"
    assert not errors, errors[:3]

    # quiesce: install the full set deterministically and check parity
    for t, c in zip(templates, constraints):
        client.add_template(t)
        client.add_constraint(c)
    client.wipe_data()
    for p in pods:
        client.add_data(p)
    if driver_kind == "tpu-async":
        client.driver.wait_ready(timeout=120.0)
    got = sorted((r.constraint["metadata"]["name"], r.msg)
                 for r in client.audit().results())
    oracle = _mk_client(InterpDriver())
    for t, c in zip(templates, constraints):
        oracle.add_template(t)
        oracle.add_constraint(c)
    for p in pods:
        oracle.add_data(p)
    want = sorted((r.constraint["metadata"]["name"], r.msg)
                  for r in oracle.audit().results())
    assert got == want
    if driver_kind == "tpu-async":
        client.driver._compiler.stop()


@pytest.mark.parametrize("mesh", [False, True])
def test_sweep_determinism_bit_identical(mesh):
    import jax

    if mesh and len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")

    def build():
        c = Client(driver=TpuDriver())
        c.driver.mesh_enabled = mesh
        c.driver._mesh_cache = None
        templates, constraints = make_templates(10)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        for p in make_pods(200, seed=11, violation_rate=0.3):
            c.add_data(p)
        return c

    outs = []
    for _ in range(2):
        c = build()
        res, totals = c.audit_capped(5)
        sweep = c.driver._audit_cache[1]
        mask = np.asarray(sweep[2].get())
        outs.append((
            mask.copy(), sweep[3].copy(), sweep[4].copy(),
            sorted((r.constraint["metadata"]["name"], r.msg)
                   for r in res.results()),
            dict(totals),
        ))
    a, b = outs
    assert (a[0] == b[0]).all(), "mask not bit-identical across runs"
    assert (a[1] == b[1]).all() and (a[2] == b[2]).all()
    assert a[3] == b[3] and a[4] == b[4]


def test_mesh_vs_single_device_masks_identical():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")

    def masks(mesh_on):
        c = Client(driver=TpuDriver())
        c.driver.mesh_enabled = mesh_on
        c.driver._mesh_cache = None
        templates, constraints = make_templates(8)
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        for p in make_pods(120, seed=13, violation_rate=0.3):
            c.add_data(p)
        c.audit_capped(5)
        sweep = c.driver._audit_cache[1]
        return np.asarray(sweep[2].get()), sweep[3], sweep[4]

    m1, c1, t1 = masks(False)
    m2, c2, t2 = masks(True)
    R = min(m1.shape[1], m2.shape[1])  # mesh pads rows to a device multiple
    assert (m1[:, :R] == m2[:, :R]).all()
    assert (m1[:, R:] == 0).all() and (m2[:, R:] == 0).all()
    assert (c1 == c2).all() and (t1 == t2).all()


def test_two_sweeps_same_store_are_cached_and_identical():
    c = Client(driver=TpuDriver())
    templates, constraints = make_templates(6)
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
    for p in make_pods(100, seed=17, violation_rate=0.4):
        c.add_data(p)
    r1, t1 = c.audit_capped(4)
    r2, t2 = c.audit_capped(4)
    k1 = sorted((r.constraint["metadata"]["name"], r.msg) for r in r1.results())
    k2 = sorted((r.constraint["metadata"]["name"], r.msg) for r in r2.results())
    assert k1 == k2 and t1 == t2
    assert c.driver.last_sweep_stats.get("cached") == 1.0


def test_microbatcher_stress_under_concurrent_ingest():
    """The batcher's idle fast path (inline lock), busy flag, and window
    logic under real contention: worker threads stream reviews through a
    MicroBatcher while templates/constraints keep ingesting.  Asserts
    no deadlock, no dropped request, and no cross-request object mixing
    (every result references the object actually submitted)."""
    import threading

    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver
    from gatekeeper_tpu.target.target import AugmentedReview
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates
    from gatekeeper_tpu.webhook import MicroBatcher

    client = Client(driver=InterpDriver())
    templates, constraints = make_templates(6, seed=21)
    client.add_template(templates[0])
    client.add_constraint(constraints[0])
    mb = MicroBatcher(client, window_s=0.001)

    pods = make_pods(40, seed=21, violation_rate=0.5)
    reqs = [
        {"uid": str(i),
         "kind": {"group": "", "version": "v1", "kind": "Pod"},
         "name": p["metadata"]["name"],
         "namespace": p["metadata"].get("namespace", "default"),
         "operation": "CREATE", "object": p}
        for i, p in enumerate(pods)
    ]
    errors = []
    done = threading.Event()

    def ingester():
        # continuous template churn while reviews stream; the rego is
        # perturbed each round so add_template's semantic-equality
        # short-circuit (client.py) cannot turn the churn into a no-op
        import copy as _copy
        import time as _t

        i = 1
        while not done.is_set():
            t = _copy.deepcopy(templates[i % len(templates)])
            tgt = t["spec"]["targets"][0]
            tgt["rego"] = tgt["rego"] + f"\n# churn {i}\n"
            k = constraints[i % len(constraints)]
            try:
                client.add_template(t)
                client.add_constraint(k)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return
            i += 1
            _t.sleep(0.001)

    def worker(wid):
        try:
            for j in range(30):
                req = reqs[(wid * 7 + j) % len(reqs)]
                resp = mb.review(AugmentedReview(admission_request=req))
                assert resp is not None
                for r in resp.results():
                    # verdicts reference the object actually submitted
                    assert r.review["object"]["metadata"]["name"] == req["name"]
        except Exception as e:
            errors.append(e)

    ing = threading.Thread(target=ingester, daemon=True)
    ing.start()
    workers = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(8)]
    try:
        for t in workers:
            t.start()
        for t in workers:
            t.join(timeout=60)
            assert not t.is_alive(), "worker deadlocked"
    finally:
        done.set()
        ing.join(timeout=10)
        mb.stop()
    assert not errors, errors[:3]
