"""Clean twin of swallow_bad: failures route through an explicit
decision and are logged/counted.  gklint must stay silent."""

import logging

log = logging.getLogger("fixture.swallow")


def handle_admission(request, evaluate, fail_open):
    try:
        return evaluate(request)
    except Exception:
        log.exception("evaluation failed; applying failure policy")
        return {"allowed": bool(fail_open), "status": "backend failure"}


def audit_sweep(inventory, evaluate):
    findings = []
    failures = 0
    for row in inventory:
        try:
            findings.extend(evaluate(row))
        except Exception:
            failures += 1
            log.warning("audit row failed", exc_info=True)
    return findings, failures
