"""Clean twin of lockorder_bad: both paths honor ONE global order
(gate before driver lock), so the held-while-acquiring graph is acyclic
and gklint must stay silent."""

import threading

DISPATCH_LOCK = threading.Lock()
DRIVER_LOCK = threading.Lock()


def warm_path(executable):
    with DISPATCH_LOCK:
        with DRIVER_LOCK:
            executable.warm()


def sweep_path(driver):
    with DISPATCH_LOCK:
        with DRIVER_LOCK:
            driver.dispatch()
