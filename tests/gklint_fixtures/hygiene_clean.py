"""Clean twin of hygiene_bad: daemon threads, guarded idempotent
start(), bounded join with a liveness check, listener torn down.
gklint must stay silent."""

import threading
from http.server import ThreadingHTTPServer


def fire_and_forget(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()
    return t


class Poller:
    def __init__(self):
        self._thread = None
        self._server = None
        self._stop = threading.Event()

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), None)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            if self._thread.is_alive():
                raise RuntimeError("poller loop wedged past its join")
            self._thread = None

    def _loop(self):
        while not self._stop.wait(timeout=0.05):
            pass
