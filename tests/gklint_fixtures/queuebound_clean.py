"""Clean twin of queuebound_bad: every queue carries a bound — a
literal maxsize, a positional bound, and a configured one.  gklint must
stay silent."""

import queue

DEPTH = 256


class Intake:
    def __init__(self, depth: int = 128):
        self.requests = queue.Queue(maxsize=DEPTH)  # configured bound
        self.events = queue.Queue(64)               # positional bound
        self.replies = queue.Queue(maxsize=depth)   # computed bound
