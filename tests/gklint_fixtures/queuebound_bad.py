"""MUST-FLAG fixture: unbounded queues (unbounded-queue, ISSUE 12) — a
bare queue.Queue(), an explicit maxsize=0 (infinite by queue's
semantics), and a SimpleQueue (unbounded by construction)."""

import queue


class Intake:
    def __init__(self):
        self.requests = queue.Queue()          # no bound at all
        self.events = queue.Queue(maxsize=0)   # 0 = explicitly infinite
        self.replies = queue.SimpleQueue()     # cannot be bounded
