"""Clean twin of cvhold_bad: the adaptation runs OUTSIDE the condition
variable (producers only need the cv to append and notify), so gklint
must stay silent."""

import threading


class Batcher:
    def __init__(self, driver):
        self._cv = threading.Condition()
        self._driver_lock = threading.Lock()
        self._driver = driver
        self._pending = []

    def _adapt(self):
        with self._driver_lock:
            return self._driver.predict()

    def run_once(self, command_pipe):
        with self._cv:
            while not self._pending:
                self._cv.wait(timeout=0.1)
        self._adapt()  # adapt with the cv RELEASED
        command_pipe.readline()  # blocking I/O with no lock held
        with self._cv:
            batch, self._pending = self._pending, []
        return batch
