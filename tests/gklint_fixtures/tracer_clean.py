"""Clean twin of tracer_bad: branches go through jnp.where, shape-space
reads (static under tracing) drive Python control flow, and the jit
wrapper is built once at module scope.  gklint must stay silent."""

import jax
import jax.numpy as jnp


@jax.jit
def good_kernel(x, limit):
    zeros = jnp.zeros_like(x)
    scaled = x * x.astype(jnp.float32)
    return jnp.where(x > limit, zeros, scaled)


@jax.jit
def shaped(x):
    if x.ndim > 1:  # shape space: static under tracing
        return x.sum(axis=-1)
    rows = x.shape[0]
    return x * rows


_eval_one = jax.jit(lambda v: v + 1)  # built once


def eval_shards(shards):
    return [_eval_one(shard) for shard in shards]
