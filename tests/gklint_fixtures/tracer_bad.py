"""MUST-FLAG fixture: JAX trace-safety violations.

tracer-truthiness: Python `if` and float() on traced arguments inside a
jitted body concretize the tracer (TracerBoolConversionError at best, a
silently baked-in branch at worst).
jit-in-loop: constructing the jit wrapper per iteration.
impure-in-jit: a wall-clock read frozen into the executable at trace
time."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def bad_kernel(x, limit):
    if x > limit:  # truthiness on a tracer
        return jnp.zeros_like(x)
    scale = float(x)  # scalar coercion on a tracer
    return x * scale


@jax.jit
def stamped(x):
    return x * time.time()  # frozen at trace time


def eval_shards(shards):
    out = []
    for shard in shards:
        fn = jax.jit(lambda v: v + 1)  # rebuilt every iteration
        out.append(fn(shard))
    return out
