"""MUST-FLAG fixture: the PR 6 deadlock shape (lock-order-cycle).

Two process-wide locks acquired in opposite orders on two paths — the
background warm path took the dispatch gate then the driver lock while a
foreground sweep held the driver lock and waited on the gate.  Each
thread holds one and waits on the other: the classic ABBA rendezvous
deadlock, exactly what DISPATCH_LOCK's ordering discipline exists to
prevent."""

import threading

DISPATCH_LOCK = threading.Lock()
DRIVER_LOCK = threading.Lock()


def warm_path(executable):
    # background warm: gate first, then driver state
    with DISPATCH_LOCK:
        with DRIVER_LOCK:
            executable.warm()


def sweep_path(driver):
    # foreground sweep: driver state first, then the gate
    with DRIVER_LOCK:
        with DISPATCH_LOCK:
            driver.dispatch()
