"""MUST-FLAG fixture: a swallowed admission exception.

A backend failure during evaluation silently becomes... nothing — an
implicit fail-open nobody chose.  PR 1 made this an explicit routed
decision (deadline.py fail-open/closed); a bare pass is the anti-
pattern."""


def handle_admission(request, evaluate):
    try:
        return evaluate(request)
    except Exception:
        pass  # BUG: implicit fail-open; the caller sees None


def audit_sweep(inventory, evaluate):
    findings = []
    for row in inventory:
        try:
            findings.extend(evaluate(row))
        except Exception:
            continue  # BUG: the sweep "succeeds" with missing violations
    return findings
