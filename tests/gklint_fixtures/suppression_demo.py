"""Suppression-syntax fixture: one properly reasoned disable (must be
honored silently), one disable missing its reason (must yield a
suppression-reason finding while still suppressing the original), and
one naming an unknown rule (unknown-rule finding)."""


def reasoned(evaluate):
    try:
        return evaluate()
    # gklint: disable=swallowed-exception -- fixture: demonstrates a
    # correctly reasoned suppression the analyzer must honor
    except Exception:
        pass


def unreasoned(evaluate):
    try:
        return evaluate()
    except Exception:  # gklint: disable=swallowed-exception
        pass


def unknown(evaluate):  # gklint: disable=no-such-rule -- typo'd rule id
    return evaluate()
