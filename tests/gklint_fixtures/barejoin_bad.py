"""MUST-FLAG fixture: bare-join — an unbounded thread join on the
shutdown path; a wedged worker hangs the supervisor forever (the PR 8
wedge chaos class)."""


class Supervisor:
    def __init__(self, worker):
        self._worker = worker

    def stop(self):
        self._worker.join()  # unbounded: a wedged worker hangs shutdown
