"""MUST-FLAG fixture: resource-hygiene violations — a non-daemon thread
nobody joins (thread-leak), a start() with no idempotence guard
(start-guard), and a listener this file never closes (listener-close)."""

import threading
from http.server import ThreadingHTTPServer


def fire_and_forget(work):
    t = threading.Thread(target=work)  # neither daemon nor joined
    t.start()
    return t


class Poller:
    def __init__(self):
        self._thread = None
        self._server = None

    def start(self):
        # no guard: a second start() leaks the first loop thread and
        # binds a second listener
        self._server = ThreadingHTTPServer(("127.0.0.1", 0), None)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            pass
