"""MUST-FLAG fixture: the PR 7 stall shape (cv-held-lock +
blocking-under-lock).

The batcher loop ran its adaptation step while holding the batcher
condition variable; the service model inside takes the driver lock (and
can block on real work).  During a long driver hold — an audit sweep, a
snapshot capture — every producer trying to enqueue stalls behind the
cv even though the queue itself is free.  The fix moved the adaptation
outside the cv (webhook/server.py _run)."""

import threading


class Batcher:
    def __init__(self, driver):
        self._cv = threading.Condition()
        self._driver_lock = threading.Lock()
        self._driver = driver
        self._pending = []

    def _adapt(self):
        # the service model prices a batch under the driver lock; a slow
        # holder upstream makes this block for seconds
        with self._driver_lock:
            return self._driver.predict()

    def run_once(self, command_pipe):
        with self._cv:
            while not self._pending:
                self._cv.wait(timeout=0.1)
            self._adapt()  # BUG: cv held across the driver lock
            command_pipe.readline()  # BUG: unbounded pipe read under cv
            batch, self._pending = self._pending, []
        return batch
