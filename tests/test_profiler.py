"""Sampling profiler (ISSUE 11): bounded-rate/bounded-memory sampling,
stage correlation through the tracer's thread registry, the
/debug/profilez router contract, and the `obs.profiler_stall` chaos
behavior (a wedged sampler degrades alone — snapshots and shutdown stay
bounded)."""

import threading
import time

import pytest

from gatekeeper_tpu import faults
from gatekeeper_tpu.faults import FaultRule
from gatekeeper_tpu.obs import trace as obstrace
from gatekeeper_tpu.obs.debug import get_router
from gatekeeper_tpu.obs.profiler import MAX_HZ, SamplingProfiler


def _busy(stop: threading.Event):
    while not stop.is_set():
        sum(range(500))


def wait_until(cond, timeout_s=5.0, step_s=0.02):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(step_s)
    return cond()


class TestSampler:
    def test_collects_stacks_from_busy_threads(self):
        prof = SamplingProfiler(hz=100)
        stop = threading.Event()
        th = threading.Thread(target=_busy, args=(stop,),
                              name="prof-busy", daemon=True)
        th.start()
        try:
            prof.start()
            assert wait_until(lambda: prof.samples > 5)
            txt = prof.collapsed()
            assert "prof-busy" in txt
            # folded format: "thread;...;frames count"
            body = [ln for ln in txt.splitlines()
                    if not ln.startswith("#")]
            assert body and all(
                ln.rsplit(" ", 1)[1].isdigit() for ln in body
            )
        finally:
            stop.set()
            prof.stop()
            th.join(timeout=5)

    def test_rate_is_bounded(self):
        prof = SamplingProfiler(hz=10_000)
        try:
            assert prof.hz <= MAX_HZ
        finally:
            prof.stop()

    def test_memory_bound_counts_overflow(self):
        """The REAL sampling path against live threads: with the
        minimum max_stacks bound (the constructor floors it at 16),
        extra threads' samples must overflow (counted) while the table
        never grows past the bound.  The sample key includes the thread
        NAME, so 24 distinctly-named busy threads guarantee more unique
        keys than the bound."""
        prof = SamplingProfiler(hz=0, max_stacks=2)
        assert prof.max_stacks == 16  # constructor floor
        stop = threading.Event()
        threads = [
            threading.Thread(target=_busy, args=(stop,),
                             name=f"ovf-{i}", daemon=True)
            for i in range(24)
        ]
        for t in threads:
            t.start()
        try:
            # drive the sampler's own tick (no sampler thread at hz=0)
            for _ in range(3):
                prof._sample_once(own_ident=-1)
            snap = prof.snapshot()
            assert snap["unique_stacks"] <= 16, snap["unique_stacks"]
            assert snap["overflow"] > 0
            assert snap["samples"] > 0  # existing stacks still count
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)

    def test_stage_correlation_tags_samples(self):
        """A thread sampled inside a stage-tagged span must show
        stage:<name> in its folded line."""
        prof = SamplingProfiler(hz=200)
        stop = threading.Event()
        seen = threading.Event()

        def staged():
            with obstrace.root_span("prof-root"):
                with obstrace.span("prof.work", stage="dispatch"):
                    seen.set()
                    _busy(stop)

        th = threading.Thread(target=staged, name="prof-staged",
                              daemon=True)
        th.start()
        try:
            assert seen.wait(5)
            prof.start()
            assert wait_until(
                lambda: "stage:dispatch" in prof.collapsed(), 10.0
            ), prof.collapsed()
        finally:
            stop.set()
            prof.stop()
            th.join(timeout=5)

    def test_reconfigure_and_idempotent_start(self):
        prof = SamplingProfiler(hz=50)
        try:
            prof.start()
            t1 = prof._thread
            prof.start()  # idempotent: same live thread kept
            assert prof._thread is t1
            prof.configure(hz=25)  # re-rate restarts the thread
            assert prof.running and prof._thread is not t1
            prof.configure(hz=0)  # 0 stops it
            assert not prof.running
        finally:
            prof.stop()


class TestEnvHz:
    def test_malformed_env_falls_back_instead_of_crashing(self,
                                                          monkeypatch):
        """Review regression: a typo'd GK_PROFILER_HZ must not kill
        module import or argparse construction for every replica."""
        from gatekeeper_tpu.obs.profiler import DEFAULT_HZ, env_hz

        monkeypatch.setenv("GK_PROFILER_HZ", "19hz")
        assert env_hz() == DEFAULT_HZ
        monkeypatch.setenv("GK_PROFILER_HZ", "")
        assert env_hz() == DEFAULT_HZ
        monkeypatch.setenv("GK_PROFILER_HZ", "7.5")
        assert env_hz() == 7.5
        # the flag default route survives the bad env too
        monkeypatch.setenv("GK_PROFILER_HZ", "nonsense")
        from gatekeeper_tpu.main import build_parser

        args = build_parser().parse_args([])
        assert args.profiler_hz == DEFAULT_HZ


class TestProfilezRoute:
    def test_profilez_served_and_reset(self):
        prof = SamplingProfiler(hz=0)
        with prof._lock:
            prof._counts[("t", "", ("f",))] = 3
            prof.samples = 3
        import gatekeeper_tpu.obs.profiler as profmod

        old = profmod._PROFILER
        profmod._PROFILER = prof
        try:
            code, ctype, body = get_router().handle("/debug/profilez")
            assert code == 200 and ctype.startswith("text/plain")
            assert b"t;f 3" in body
            code, _ct, body = get_router().handle(
                "/debug/profilez", "reset=1"
            )
            assert code == 200
            assert prof.snapshot()["unique_stacks"] == 0
        finally:
            profmod._PROFILER = old
            prof.stop()

    def test_profilez_bad_param_is_json_400(self):
        code, ctype, body = get_router().handle(
            "/debug/profilez", "reset=nope"
        )
        assert code == 400
        assert b"reset" in body


@pytest.mark.chaos
class TestProfilerStallChaos:
    def test_hang_wedges_sampler_alone(self):
        """A hang-mode obs.profiler_stall parks the sampler thread; the
        aggregate keeps serving and stop() stays bounded."""
        prof = SamplingProfiler(hz=200)
        plane = faults.install(seed=3)
        plane.add(faults.PROFILER_STALL,
                  FaultRule(mode="hang", count=1))
        try:
            prof.start()
            # the first tick parks on the hang; snapshot/collapsed must
            # keep answering from the (empty) aggregate immediately
            time.sleep(0.05)
            assert prof.snapshot()["samples"] == 0
            assert prof.collapsed().startswith("# gk-profiler")
            t0 = time.monotonic()
            prof.stop()  # bounded despite the parked thread
            assert time.monotonic() - t0 < 5.0
        finally:
            faults.uninstall()  # releases the hang; thread exits

    def test_wedged_then_restarted_sampler_leaves_no_orphan(self):
        """Review regression: a sampler wedged past its stop-join that
        is then re-rated (configure -> stop times out -> start) must
        NOT resume sampling when the hang releases — each incarnation
        owns its own stop event, so the unwedged predecessor exits."""
        prof = SamplingProfiler(hz=200)
        plane = faults.install(seed=5)
        plane.add(faults.PROFILER_STALL,
                  FaultRule(mode="hang", count=1))
        try:
            prof.start()
            time.sleep(0.05)  # first tick parks on the hang
            prof.configure(hz=100)  # stop (times out) + fresh start
            assert prof.running
        finally:
            faults.uninstall()  # releases the wedged predecessor
        try:
            # the released predecessor must EXIT, not resume: exactly
            # one gk-profiler thread stays alive
            def one_sampler():
                alive = [t for t in threading.enumerate()
                         if t.name == "gk-profiler" and t.is_alive()]
                return len(alive) == 1
            assert wait_until(one_sampler, 5.0), [
                t.name for t in threading.enumerate()
                if t.name == "gk-profiler"
            ]
        finally:
            prof.stop()

    def test_error_mode_skips_tick_and_counts(self):
        prof = SamplingProfiler(hz=200)
        plane = faults.install(seed=4)
        plane.add(faults.PROFILER_STALL,
                  FaultRule(mode="error", count=3))
        try:
            prof.start()
            assert wait_until(lambda: prof.stalls >= 3)
            # after the 3 injected errors the sampler keeps sampling
            assert wait_until(lambda: prof.samples > 0)
        finally:
            faults.uninstall()
            prof.stop()
