"""Audit manager tests (reference parity: pkg/audit/manager.go semantics —
both sweep modes, caps, truncation, kind filtering, exclusion, status
writes)."""

import json

from gatekeeper_tpu.audit import AuditManager
from gatekeeper_tpu.audit.manager import truncate
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.metrics import Reporters
from gatekeeper_tpu.metrics.views import Registry
from gatekeeper_tpu.process.excluder import Excluder
from gatekeeper_tpu.apis.config import MatchEntry

from .test_controllers import CONSTRAINT, TEMPLATE

CGVK = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")


def setup_world(n_bad=3, n_good=2, **kw):
    kube = InMemoryKube()
    client = Client()
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    kube.create(json.loads(json.dumps(CONSTRAINT)))
    for i in range(n_bad):
        obj = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": f"bad-{i}", "labels": {}}}
        kube.create(obj)
        client.add_data(obj)
    for i in range(n_good):
        obj = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": f"good-{i}",
                            "labels": {"gatekeeper": "on"}}}
        kube.create(obj)
        client.add_data(obj)
    mgr = AuditManager(kube, client, **kw)
    return mgr, kube, client


class TestAuditSweep:
    def test_discovery_mode_finds_violations(self):
        mgr, kube, client = setup_world()
        update_lists = mgr.audit_once()
        key = "K8sRequiredLabels//ns-must-have-gk"
        assert key in update_lists
        assert len(update_lists[key]) == 3
        st = kube.get(CGVK, "ns-must-have-gk")["status"]
        assert st["totalViolations"] == 3
        assert len(st["violations"]) == 3
        assert st["auditTimestamp"].endswith("Z")
        assert all(v["enforcementAction"] == "deny" for v in st["violations"])

    def test_from_cache_mode_matches_discovery(self):
        mgr_d, kube_d, _ = setup_world()
        mgr_c, kube_c, _ = setup_world(from_cache=True)
        d = mgr_d.audit_once()
        c = mgr_c.audit_once()
        dk = {k: sorted(v.name for v in vs) for k, vs in d.items()}
        ck = {k: sorted(v.name for v in vs) for k, vs in c.items()}
        assert dk == ck

    def test_violations_capped_but_totals_full(self):
        mgr, kube, client = setup_world(n_bad=30, violations_limit=5)
        mgr.audit_once()
        st = kube.get(CGVK, "ns-must-have-gk")["status"]
        assert len(st["violations"]) == 5
        assert st["totalViolations"] == 30

    def test_clean_sweep_removes_stale_violations(self):
        mgr, kube, client = setup_world()
        mgr.audit_once()
        assert kube.get(CGVK, "ns-must-have-gk")["status"]["violations"]
        # fix the world: all namespaces now labeled
        for gvk in [("", "v1", "Namespace")]:
            for obj in kube.list(gvk):
                obj["metadata"].setdefault("labels", {})["gatekeeper"] = "y"
                kube.update(obj)
                client.add_data(obj)
        mgr.audit_once()
        st = kube.get(CGVK, "ns-must-have-gk")["status"]
        assert "violations" not in st
        assert st["totalViolations"] == 0

    def test_excluded_namespace_skipped(self):
        excluder = Excluder()
        excluder.add([MatchEntry(excluded_namespaces=["skipme"],
                                 processes=["audit"])])
        kube = InMemoryKube()
        client = Client()
        client.add_template(TEMPLATE)
        c = json.loads(json.dumps(CONSTRAINT))
        c["spec"]["match"] = {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}
        client.add_constraint(c)
        kube.create(c)
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "skipme"}})
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": "p1", "namespace": "skipme"}})
        mgr = AuditManager(kube, client, excluder=excluder)
        update_lists = mgr.audit_once()
        assert update_lists == {}

    def test_match_kind_only_filters(self):
        mgr, kube, client = setup_world(match_kind_only=True)
        # constraint matches only Namespace: Pods are not even listed
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": "p1", "namespace": "bad-0"}})
        matched = mgr._matched_kinds(mgr._constraint_kinds())
        assert matched == {"Namespace"}
        update_lists = mgr.audit_once()
        assert len(update_lists) == 1

    def test_match_kind_only_star_when_no_kinds(self):
        mgr, kube, client = setup_world(match_kind_only=True)
        c = kube.get(CGVK, "ns-must-have-gk")
        del c["spec"]["match"]["kinds"]
        kube.update(c)
        assert mgr._matched_kinds(mgr._constraint_kinds()) == {"*"}

    def test_chunked_listing(self):
        mgr, kube, client = setup_world(n_bad=7, chunk_size=2)
        update_lists = mgr.audit_once()
        key = "K8sRequiredLabels//ns-must-have-gk"
        assert len(update_lists[key]) == 7

    def test_message_truncation(self):
        assert truncate("x" * 300) == "x" * 253 + "..."
        assert truncate("short") == "short"

    def test_metrics_and_events(self):
        events = []
        reporter = Reporters(Registry())
        mgr, kube, client = setup_world(
            reporter=reporter, emit_audit_events=True,
            event_recorder=events.append,
        )
        mgr.audit_once()
        assert reporter.registry.view_rows("violations")[("deny",)] == 3.0
        assert reporter.registry.view_rows("audit_duration_seconds")[()].count == 1
        assert reporter.registry.view_rows("audit_last_run_time")[()] > 0
        assert len(events) == 3
        assert events[0]["reason"] == "AuditViolation"

    def test_dryrun_totals_by_action(self):
        reporter = Reporters(Registry())
        mgr, kube, client = setup_world(reporter=reporter)
        dry = json.loads(json.dumps(CONSTRAINT))
        dry["metadata"]["name"] = "dry-run-one"
        dry["spec"]["enforcementAction"] = "dryrun"
        client.add_constraint(dry)
        kube.create(dry)
        mgr.audit_once()
        rows = reporter.registry.view_rows("violations")
        assert rows[("deny",)] == 3.0
        assert rows[("dryrun",)] == 3.0

    def test_periodic_loop(self):
        import time

        mgr, kube, client = setup_world(interval_s=0.05)
        mgr.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                st = kube.get(CGVK, "ns-must-have-gk").get("status") or {}
                if st.get("violations"):
                    break
                time.sleep(0.02)
            assert st.get("violations")
        finally:
            mgr.stop()

    def test_crd_gate(self):
        mgr, kube, client = setup_world(require_crd=True)
        assert mgr.audit_once() == {}
        kube.create({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata":
                {"name": "constrainttemplates.templates.gatekeeper.sh"},
        })
        assert mgr.audit_once() != {}
