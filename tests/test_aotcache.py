"""Serialized-executable (AOT) cache: ops/aotcache.py.

The warm-restart artifact (SURVEY §5.4): a restarted process must load
compiled executables from disk without re-tracing, never reuse an
executable across kernel-source changes, and degrade to plain jit on
any cache pathology.
"""

import numpy as np
import pytest

from gatekeeper_tpu.ops import aotcache


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    # isolate the module state: enabled dir + memoized fingerprint
    monkeypatch.setattr(aotcache, "_dir", None)
    assert aotcache.enable(str(tmp_path))
    yield str(tmp_path)
    monkeypatch.setattr(aotcache, "_dir", None)


def _fn(x, y):
    return (x * 2 + y).sum()


class TestRoundTrip:
    def test_save_then_fresh_instance_loads(self, cache_dir):
        import os

        x = np.arange(8, dtype=np.float32)
        y = np.ones(8, dtype=np.float32)
        a = aotcache.aot_jit(_fn, "t-roundtrip", sig="s1")
        out1 = float(a(x, y))
        assert any(f.endswith(".aot") for f in os.listdir(cache_dir))
        # a fresh instance (fresh process analogue) must LOAD, not compile
        b = aotcache.aot_jit(_fn, "t-roundtrip", sig="s1")
        key = b._key((x, y))
        assert aotcache.load(key) is not None
        out2 = float(b(x, y))
        assert out1 == out2

    def test_multiple_layouts_memoized(self, cache_dir):
        a = aotcache.aot_jit(_fn, "t-layouts", sig="s1")
        x8 = np.arange(8, dtype=np.float32)
        x16 = np.arange(16, dtype=np.float32)
        a(x8, x8)
        a(x16, x16)
        a(x8, x8)  # back to the first layout: no thrash
        assert len(a._compiled) == 2

    def test_disabled_falls_back_to_jit(self, monkeypatch):
        monkeypatch.setattr(aotcache, "_dir", None)
        a = aotcache.aot_jit(_fn, "t-disabled", sig=None)
        x = np.ones(4, dtype=np.float32)
        assert float(a(x, x)) == float(_fn(x, x))
        assert not a._compiled


class TestInvalidation:
    def test_sig_change_changes_key(self, cache_dir):
        x = np.ones(4, dtype=np.float32)
        a = aotcache.aot_jit(_fn, "t-sig", sig="v1")
        b = aotcache.aot_jit(_fn, "t-sig", sig="v2")
        assert a._key((x, x)) != b._key((x, x))

    def test_layout_change_changes_key(self, cache_dir):
        a = aotcache.aot_jit(_fn, "t-shape", sig="s")
        x4 = np.ones(4, dtype=np.float32)
        x8 = np.ones(8, dtype=np.float32)
        assert a._key((x4, x4)) != a._key((x8, x8))

    def test_code_fingerprint_in_key(self, cache_dir, monkeypatch):
        from gatekeeper_tpu.util import seal

        x = np.ones(4, dtype=np.float32)
        a = aotcache.aot_jit(_fn, "t-code", sig="s")
        k1 = a._key((x, x))
        # the fingerprint is shared with the snapshot seal (util/seal.py)
        monkeypatch.setattr(seal, "_code_fp", "different-build")
        b = aotcache.aot_jit(_fn, "t-code", sig="s")
        assert b._key((x, x)) != k1

    def test_unreadable_entry_is_miss(self, cache_dir):
        import os

        x = np.ones(4, dtype=np.float32)
        a = aotcache.aot_jit(_fn, "t-corrupt", sig="s")
        a(x, x)
        (entry,) = [f for f in os.listdir(cache_dir) if f.endswith(".aot")]
        with open(os.path.join(cache_dir, entry), "wb") as f:
            f.write(b"not a pickle")
        fresh = aotcache.aot_jit(_fn, "t-corrupt", sig="s")
        assert float(fresh(x, x)) == float(_fn(x, x))  # recompiles fine


class TestBadEntryBlacklist:
    def test_rejecting_executable_blacklisted_and_dropped(self, cache_dir):
        import os

        x = np.ones(4, dtype=np.float32)
        a = aotcache.aot_jit(_fn, "t-bad", sig="s")
        a(x, x)
        key = a._key((x, x))

        class Rejecting:
            calls = 0

            def __call__(self, *args):
                Rejecting.calls += 1
                raise RuntimeError("layout drift")

        a._compiled[key] = Rejecting()
        out = a(x, x)  # falls back to jit
        assert float(out) == float(_fn(x, x))
        assert key in a._bad
        assert not os.path.exists(os.path.join(cache_dir, key + ".aot"))
        # subsequent calls never touch the bad entry again
        a(x, x)
        assert Rejecting.calls == 1
