"""TpuDriver vs InterpDriver differential tests.

The TPU path must produce byte-identical Results to the oracle driver on
randomized workloads over the whole corpus (PSP set, required-labels,
allowed-repos, agilebank), and its device masks must be exactly tight for
templates whose programs compile exact=True (over-approximation is allowed
elsewhere, under-approximation never)."""

import random

import pytest
import yaml

from gatekeeper_tpu.client import Client, InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver

from .corpus import REF


def load_templates():
    paths = [
        "pkg/webhook/testdata/psp-all-violations/psp-templates/privileged-containers-template.yaml",
        "pkg/webhook/testdata/psp-all-violations/psp-templates/host-namespace-template.yaml",
        "pkg/webhook/testdata/psp-all-violations/psp-templates/host-network-ports-template.yaml",
        "pkg/webhook/testdata/psp-all-violations/psp-templates/volumes-template.yaml",
        "pkg/webhook/testdata/psp-all-violations/psp-templates/host-filesystem-template.yaml",
        "demo/basic/templates/k8srequiredlabels_template.yaml",
        "demo/agilebank/templates/k8sallowedrepos_template.yaml",
        "demo/agilebank/templates/k8scontainterlimits_template.yaml",
    ]
    out = []
    for p in paths:
        f = REF / p
        if f.exists():
            out.append(yaml.safe_load(open(f)))
    # glob the psp dir to be filename-robust
    if len(out) < 6:
        import glob

        out = [
            yaml.safe_load(open(f))
            for f in sorted(
                glob.glob(str(REF / "pkg/webhook/testdata/psp-all-violations/psp-templates/*.yaml"))
            )
        ] + [
            yaml.safe_load(open(REF / "demo/basic/templates/k8srequiredlabels_template.yaml")),
            yaml.safe_load(open(REF / "demo/agilebank/templates/k8sallowedrepos_template.yaml")),
            yaml.safe_load(open(REF / "demo/agilebank/templates/k8scontainterlimits_template.yaml")),
        ]
    return out


def make_constraints(rng):
    def c(kind, name, params=None, match=None, enforcement=None):
        spec = {}
        if params is not None:
            spec["parameters"] = params
        if match is not None:
            spec["match"] = match
        if enforcement:
            spec["enforcementAction"] = enforcement
        return {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind,
            "metadata": {"name": name},
            "spec": spec,
        }

    pod_match = {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}
    return [
        c("K8sPSPPrivilegedContainer", "no-priv", match=pod_match),
        c("K8sPSPHostNamespace", "no-hostns", match=pod_match, enforcement="dryrun"),
        c("K8sPSPHostNetworkingPorts", "ports",
          params={"hostNetwork": False, "min": 100, "max": 200}, match=pod_match),
        c("K8sPSPVolumeTypes", "vols",
          params={"volumes": ["configMap", "emptyDir", "secret"]}, match=pod_match),
        c("K8sPSPHostFilesystem", "hostfs",
          params={"allowedHostPaths": [{"readOnly": True, "pathPrefix": "/foo"}]},
          match=pod_match),
        c("K8sRequiredLabels", "need-owner", params={"labels": ["owner"]},
          match={"labelSelector": {"matchExpressions": [
              {"key": "audit", "operator": "NotIn", "values": ["skip"]}]}}),
        c("K8sAllowedRepos", "repos", params={"repos": ["gcr.io/safe", "docker.io/lib"]},
          match=pod_match),
        c("K8sContainerLimits", "limits", params={"cpu": "200m", "memory": "1Gi"},
          match=pod_match),
        c("K8sRequiredLabels", "ns-labels", params={"labels": ["team"]},
          match={"kinds": [{"apiGroups": [""], "kinds": ["Namespace"]}],
                 "scope": "*"}),
    ]


def random_pod(rng, i):
    containers = []
    for j in range(rng.randint(0, 3)):
        ctr = {
            "name": f"c{j}",
            "image": rng.choice(
                ["gcr.io/safe/app:1", "docker.io/lib/nginx", "evil.io/x:latest", "gcr.io/other"]
            ),
        }
        if rng.random() < 0.3:
            ctr["securityContext"] = {"privileged": rng.random() < 0.7}
        if rng.random() < 0.4:
            ctr["ports"] = [
                {"hostPort": rng.choice([80, 150, 250, 8080])}
                for _ in range(rng.randint(1, 2))
            ]
        if rng.random() < 0.6:
            ctr["resources"] = {
                "limits": rng.choice(
                    [
                        {"cpu": "100m", "memory": "500Mi"},
                        {"cpu": "300m", "memory": "2Gi"},
                        {"cpu": "1", "memory": "100Mi"},
                        {"memory": "1Gi"},
                        {},
                    ]
                )
            }
        containers.append(ctr)
    spec = {"containers": containers}
    if rng.random() < 0.2:
        spec["hostPID"] = True
    if rng.random() < 0.15:
        spec["hostIPC"] = True
    if rng.random() < 0.2:
        spec["hostNetwork"] = True
    if rng.random() < 0.4:
        vols = []
        for k in range(rng.randint(1, 2)):
            v = {"name": f"v{k}"}
            v[rng.choice(["hostPath", "emptyDir", "configMap", "nfs"])] = (
                {"path": rng.choice(["/tmp", "/foo/bar", "/var"])}
                if rng.random() < 0.5
                else {}
            )
            vols.append(v)
        spec["volumes"] = vols
    labels = {}
    if rng.random() < 0.5:
        labels["owner"] = "team-" + rng.choice("abc")
    if rng.random() < 0.3:
        labels["audit"] = rng.choice(["skip", "full"])
    meta = {"name": f"pod-{i}", "namespace": rng.choice(["prod", "dev", "test"])}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}


def result_key(r):
    return (
        r.constraint["metadata"]["name"],
        r.msg,
        r.enforcement_action,
        (r.resource or {}).get("metadata", {}).get("name"),
    )


@pytest.fixture(scope="module")
def workload():
    rng = random.Random(42)
    templates = load_templates()
    constraints = make_constraints(rng)
    pods = [random_pod(rng, i) for i in range(40)]
    namespaces = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": n, "labels": {"team": "x"} if n == "prod" else {}}}
        for n in ["prod", "dev"]
    ]
    return templates, constraints, pods, namespaces


def build(driver, workload):
    templates, constraints, pods, namespaces = workload
    client = Client(driver=driver)
    kinds = set()
    for t in templates:
        client.add_template(t)
        kinds.add(t["spec"]["crd"]["spec"]["names"]["kind"])
    for c in constraints:
        if c["kind"] in kinds:
            client.add_constraint(c)
    for ns in namespaces:
        client.add_data(ns)
    for p in pods:
        client.add_data(p)
    return client


class TestDifferential:
    def test_audit_parity(self, workload):
        ci = build(InterpDriver(), workload)
        ct = build(TpuDriver(), workload)
        ri = sorted(result_key(r) for r in ci.audit().results())
        rt = sorted(result_key(r) for r in ct.audit().results())
        assert len(ri) > 10  # workload actually violates
        assert ri == rt

    def test_review_parity(self, workload):
        templates, constraints, pods, namespaces = workload
        ci = build(InterpDriver(), workload)
        ct = build(TpuDriver(), workload)
        for pod in pods[:15]:
            meta = pod["metadata"]
            req = {
                "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": meta["name"], "namespace": meta["namespace"],
                "operation": "CREATE", "object": pod,
            }
            ri = sorted(result_key(r) for r in ci.review(req).results())
            rt = sorted(result_key(r) for r in ct.review(req).results())
            assert ri == rt, meta["name"]

    def test_exact_masks_are_tight(self, workload):
        """For templates with exact vectorized programs, the device mask must
        equal the interpreter's violation truth cell-for-cell (no
        over-approximation on the hot families)."""
        from gatekeeper_tpu.engine.value import freeze, thaw

        ct = build(TpuDriver(), workload)
        drv: TpuDriver = ct.driver  # type: ignore[assignment]
        objs = list(drv.store.iter_objects())
        reviews = [
            drv.target.make_audit_review(thaw(o), api, k, n, ns)
            for o, api, k, n, ns in objs
        ]
        ordered, mask, _ = drv.compute_masks(reviews)
        inventory = drv.store.frozen()
        checked = 0
        for i, (kind, _name, constraint) in enumerate(ordered):
            prog = drv.programs.get(kind)
            if not prog or not prog.exact:
                continue
            tmpl = drv.templates[kind]
            params = freeze((constraint.get("spec") or {}).get("parameters") or {})
            for ri, review in enumerate(reviews):
                from gatekeeper_tpu.target.match import constraint_matches

                if not constraint_matches(constraint, review, drv.store.cached_namespace):
                    continue
                truth = bool(
                    tmpl.policy.eval_violations(freeze(review), params, inventory)
                )
                assert bool(mask[i, ri]) == truth, (kind, review["name"])
                checked += 1
        assert checked > 100


def test_hybrid_path_memoizes_repeated_requests():
    """The hybrid (small-batch, interp-served) path uses the content memo:
    a repeated identical request re-renders nothing, and results match the
    oracle exactly — including after a constraint change invalidates it."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.client.drivers import InterpDriver
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    templates, constraints = make_templates(6)
    ct = Client(driver=TpuDriver())
    ci = Client(driver=InterpDriver())
    for t, k in zip(templates, constraints):
        ct.add_template(t)
        ci.add_template(t)
        ct.add_constraint(k)
        ci.add_constraint(k)
    pod = make_pods(1, seed=3, violation_rate=1.0)[0]
    req = {"uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
           "name": pod["metadata"]["name"],
           "namespace": pod["metadata"]["namespace"],
           "operation": "CREATE", "object": pod}

    def key(res):
        return sorted((r.constraint["metadata"]["name"], r.msg)
                      for r in res.results())

    first = key(ct.review(req))
    assert first == key(ci.review(req))
    assert len(ct.driver._review_memo) > 0
    assert key(ct.review(req)) == first  # memo-served, identical
    # constraint mutation invalidates: flip one to dryrun and re-review
    k2 = dict(constraints[0])
    k2["spec"] = dict(k2["spec"])
    k2["spec"]["enforcementAction"] = "dryrun"
    ct.add_constraint(k2)
    ci.add_constraint(dict(k2))
    a = sorted((r.constraint["metadata"]["name"], r.enforcement_action)
               for r in ct.review(req).results())
    b = sorted((r.constraint["metadata"]["name"], r.enforcement_action)
               for r in ci.review(req).results())
    assert a == b
    # tracing bypasses the memo and matches the oracle's trace behavior
    res_t, trace_t = ct.driver.review(req, tracing=True)
    assert trace_t is not None and "match" in trace_t


def test_memo_excluded_for_clock_and_uid_policies():
    """Policies calling wall-clock builtins or reading request metadata
    must never be memo-served; uid-stripped keys let real traffic (fresh
    uid per request) hit for safe policies."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver

    CLOCK_REGO = """
package clocky
violation[{"msg": "tick"}] {
  time.now_ns() > 0
}
"""
    UID_REGO = """
package uidy
violation[{"msg": msg}] {
  msg := sprintf("uid %v", [input.review.uid])
}
"""
    SAFE_REGO = """
package safe
violation[{"msg": "no-labels"}] {
  not input.review.object.metadata.labels.owner
}
"""

    def tpl(kind, rego):
        return {"apiVersion": "templates.gatekeeper.sh/v1beta1",
                "kind": "ConstraintTemplate", "metadata": {"name": kind.lower()},
                "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                         "targets": [{"target": "admission.k8s.gatekeeper.sh",
                                      "rego": rego}]}}

    c = Client(driver=TpuDriver())
    for kind, rego in (("Clocky", CLOCK_REGO), ("Uidy", UID_REGO),
                       ("Safe", SAFE_REGO)):
        c.add_template(tpl(kind, rego))
        c.add_constraint({"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                          "kind": kind, "metadata": {"name": f"c{kind.lower()}"},
                          "spec": {"match": {"kinds": [
                              {"apiGroups": [""], "kinds": ["Pod"]}]}}})
    assert not c.driver.templates["Clocky"].policy.memo_safe
    assert not c.driver.templates["Uidy"].policy.memo_safe
    assert c.driver.templates["Safe"].policy.memo_safe

    def req(uid):
        return {"uid": uid, "kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": "p", "namespace": "d", "operation": "CREATE",
                "object": {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": "p", "namespace": "d"}}}

    r1 = {x.constraint["kind"]: x.msg for x in c.review(req("uid-1")).results()}
    r2 = {x.constraint["kind"]: x.msg for x in c.review(req("uid-2")).results()}
    # the uid-reading policy sees each request's own uid (never memoized)
    assert r1["Uidy"] == "uid uid-1" and r2["Uidy"] == "uid uid-2"
    # the safe policy hit the memo across differing uids
    assert any(k[0] == "Safe" for k in c.driver._review_memo)
    assert not any(k[0] in ("Uidy", "Clocky") for k in c.driver._review_memo)


class TestRequestMemo:
    """Whole-request memo: identical-content admissions collapse the full
    constraint walk to one dict hit — with strict validity gating."""

    def _client(self, n=6):
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.ops.driver import TpuDriver
        from gatekeeper_tpu.util.synthetic import make_templates

        templates, constraints = make_templates(n, seed=13)
        c = Client(driver=TpuDriver())
        for t, k in zip(templates, constraints):
            c.add_template(t)
            c.add_constraint(k)
        return c

    def _req(self, pod, uid="u1"):
        return {"uid": uid,
                "kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": pod["metadata"]["name"],
                "namespace": pod["metadata"].get("namespace", "default"),
                "operation": "CREATE", "object": pod}

    def test_hit_rebinds_review_and_matches_oracle(self):
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.client.drivers import InterpDriver
        from gatekeeper_tpu.util.synthetic import make_pods, make_templates

        c = self._client()
        templates, constraints = make_templates(6, seed=13)
        ci = Client(driver=InterpDriver())
        for t, k in zip(templates, constraints):
            ci.add_template(t)
            ci.add_constraint(k)
        pod = make_pods(1, seed=13, violation_rate=1.0)[0]
        r1 = c.review(self._req(pod, uid="a")).results()
        assert c.driver._request_memo  # populated
        r2 = c.review(self._req(pod, uid="b")).results()  # memo hit
        want = ci.review(self._req(pod, uid="b")).results()
        key = lambda rs: sorted((x.constraint["metadata"]["name"], x.msg) for x in rs)
        assert key(r1) == key(r2) == key(want)
        # the hit's results are bound to the NEW request (fresh uid)
        assert all(x.review["uid"] == "b" for x in r2)

    def test_constraint_update_invalidates(self):
        from gatekeeper_tpu.util.synthetic import make_pods

        c = self._client()
        pod = make_pods(1, seed=13, violation_rate=1.0)[0]
        n1 = len(c.review(self._req(pod)).results())
        assert n1 > 0
        # removing the violated constraints must change the verdict
        for kind in list(c.driver.constraints):
            for name in list(c.driver.constraints[kind]):
                c.remove_constraint(c.driver.constraints[kind][name])
        assert c.review(self._req(pod)).results() == []

    def test_not_memoable_with_namespace_selector(self):
        from gatekeeper_tpu.util.synthetic import make_pods

        c = self._client()
        kind = next(iter(c.driver.constraints))
        name = next(iter(c.driver.constraints[kind]))
        cons = c.driver.constraints[kind][name]
        import copy
        cons2 = copy.deepcopy(cons)
        cons2["spec"].setdefault("match", {})["namespaceSelector"] = {
            "matchLabels": {"team": "x"}}
        c.add_constraint(cons2)
        pod = make_pods(1, seed=13)[0]
        c.review(self._req(pod))
        assert c.driver._request_memo_ok is False
        assert not c.driver._request_memo

    def test_not_memoable_with_wallclock_policy(self):
        from gatekeeper_tpu.util.synthetic import make_pods

        c = self._client()
        c.add_template({
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8sclocky"},
            "spec": {"crd": {"spec": {"names": {"kind": "K8sClocky"}}},
                     "targets": [{"target": "admission.k8s.gatekeeper.sh",
                                  "rego": """
package k8sclocky

violation[{"msg": "tick"}] { time.now_ns() > 0 }
"""}]}})
        c.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sClocky", "metadata": {"name": "clock"},
            "spec": {"match": {"kinds": [
                {"apiGroups": [""], "kinds": ["Pod"]}]}}})
        pod = make_pods(1, seed=13)[0]
        c.review(self._req(pod))
        assert c.driver._request_memo_ok is False
