"""Fleet observability plane (ISSUE 11): classic-format federation
(parse/relabel/merge invariants, stale-marking, bounded scrapes under
the seeded `fleet.scrape_fail` fault), cross-process trace assembly,
and — where spawn is available — a real front-door→replica round trip
proving one trace_id spans both processes."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gatekeeper_tpu import faults
from gatekeeper_tpu.faults import FaultRule
from gatekeeper_tpu.metrics.views import global_registry
from gatekeeper_tpu.obs import fleetobs
from gatekeeper_tpu.obs import trace as obstrace
from gatekeeper_tpu.obs.fleetobs import (
    MetricsFederator,
    TraceCollector,
    label_sample,
    merge_families,
    parse_families,
    render_families,
    split_sample,
)

from .test_snapshot_concurrent import spawn_available


def wait_until(cond, timeout_s=5.0, step_s=0.02):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(step_s)
    return cond()


# ---- classic-format plumbing ------------------------------------------------


class TestClassicFormat:
    def test_split_sample_handles_braces_in_values(self):
        line = ('gatekeeper_cost_cells{template="K8s{weird}Name"} 5')
        name, labels, value = split_sample(line)
        assert name == "gatekeeper_cost_cells"
        assert labels == 'template="K8s{weird}Name"'
        assert value == "5"

    def test_split_sample_unlabelled(self):
        assert split_sample("gatekeeper_up 1") == \
            ("gatekeeper_up", None, "1")

    def test_label_sample_injects_and_preserves(self):
        assert label_sample("m 1", "r0") == 'm{replica_id="r0"} 1'
        assert label_sample('m{a="b"} 1', "r0") == \
            'm{replica_id="r0",a="b"} 1'
        # replica-stamped series are authoritative: untouched
        stamped = 'm{replica_id="rX",a="b"} 1'
        assert label_sample(stamped, "r0") == stamped

    def test_parse_families_groups_histogram_samples(self):
        text = (
            "# HELP gk_h h\n# TYPE gk_h histogram\n"
            'gk_h_bucket{le="1"} 1\ngk_h_sum 0.5\ngk_h_count 1\n'
            "# HELP gk_g g\n# TYPE gk_g gauge\ngk_g 2\n"
        )
        fams = parse_families(text)
        assert list(fams) == ["gk_h", "gk_g"]
        assert len(fams["gk_h"]["samples"]) == 3

    def test_merge_keeps_one_header_per_family(self):
        body = "# HELP gk_x x\n# TYPE gk_x gauge\ngk_x 1\n"
        out = render_families(merge_families(
            body, [("r0", body), ("r1", body)]
        ))
        assert out.count("# HELP gk_x") == 1
        assert out.count("# TYPE gk_x") == 1
        assert 'gk_x{replica_id="r0"} 1' in out
        assert 'gk_x{replica_id="r1"} 1' in out
        assert "# EOF" not in out


# ---- federation over live (and dead, and wedged) exporters ------------------


class _StubExporter:
    """Minimal /metrics server; delay_s simulates a wedged replica."""

    def __init__(self, body: str, delay_s: float = 0.0):
        outer = self
        self.body = body
        self.delay_s = delay_s

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if outer.delay_s:
                    time.sleep(outer.delay_s)
                data = outer.body.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


_BODY_A = "# HELP gk_t t\n# TYPE gk_t gauge\ngk_t 7\n"


class TestMetricsFederator:
    def test_scrape_merges_and_marks_health(self):
        a = _StubExporter(_BODY_A)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1", "port": a.port},
            ])
            out = fed.render()
            assert 'gk_t{replica_id="r0"} 7' in out
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 1.0
            assert 'gatekeeper_fleet_replicas_scraped 1' in out
        finally:
            a.stop()

    def test_dead_replica_serves_stale_marked_not_missing(self):
        a = _StubExporter(_BODY_A)
        fed = MetricsFederator(lambda: [
            {"replica_id": "r0", "host": "127.0.0.1", "port": a.port},
        ])
        assert 'gk_t{replica_id="r0"} 7' in fed.render()
        a.stop()  # replica dies; last-known-good must keep serving
        out = fed.render()
        assert 'gk_t{replica_id="r0"} 7' in out, \
            "stale series vanished instead of being stale-marked"
        rows = global_registry().view_rows("fleet_scrape_ok")
        assert rows[("r0",)] == 0.0
        age = global_registry().view_rows("fleet_scrape_age_seconds")
        assert age[("r0",)] >= 0.0

    def test_wedged_replica_never_blocks_render(self):
        a = _StubExporter(_BODY_A, delay_s=30.0)  # wedged: answers in 30s
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
            ], timeout_s=0.3)
            t0 = time.monotonic()
            out = fed.render()
            took = time.monotonic() - t0
            assert took < 5.0, f"federated render blocked {took:.1f}s"
            # never scraped: no series, but health says so
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 0.0
            assert "fleet_scrape_ok" in out
        finally:
            a.delay_s = 0.0
            a.stop()

    def test_concurrent_render_does_not_stale_mark_healthy_fleet(self):
        """Review regression: two scrapers hitting the federated
        /metrics concurrently — the second render sees the first's
        in-flight scrape and must NOT flip a healthy replica to
        scrape_ok=0 (only a scrape wedged past its budget is stale)."""
        a = _StubExporter(_BODY_A)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
            ])
            assert 'gk_t{replica_id="r0"} 7' in fed.render()
            # a RECENT in-flight scrape (a racing render): skip, keep ok
            with fed._mu:
                fed._inflight["r0"] = time.monotonic()
            out = fed.render()
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 1.0, \
                "racing render stale-marked a healthy replica"
            assert 'gk_t{replica_id="r0"} 7' in out
            # the SAME in-flight entry aged past the budget: wedged
            with fed._mu:
                fed._inflight["r0"] = (
                    time.monotonic() - fed.timeout_s - 1.0
                )
            fed.render()
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 0.0
            with fed._mu:
                fed._inflight.clear()
        finally:
            a.stop()

    def test_fleet_of_wedged_exporters_bounded_by_one_budget(self):
        """Review regression: N wedged exporters must cost ONE scrape
        budget total (shared deadline), not N budgets."""
        stubs = [_StubExporter(_BODY_A, delay_s=30.0) for _ in range(4)]
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": f"r{i}", "host": "127.0.0.1",
                 "port": s.port}
                for i, s in enumerate(stubs)
            ], timeout_s=0.4)
            t0 = time.monotonic()
            fed.render()
            took = time.monotonic() - t0
            # one budget (0.9s) + slack — NOT 4 x 0.9s
            assert took < 2.5, f"render took {took:.1f}s for 4 wedges"
        finally:
            for s in stubs:
                s.delay_s = 0.0
                s.stop()

    def test_never_scraped_replica_age_grows(self):
        """Review regression: a replica whose exporter never answered
        must show a GROWING fleet_scrape_age_seconds, not 0 forever."""
        dead_port = _StubExporter(_BODY_A)
        dead_port.stop()
        fed = MetricsFederator(lambda: [
            {"replica_id": "rNever", "host": "127.0.0.1",
             "port": dead_port.port},
        ], timeout_s=0.3)
        fed.render()
        time.sleep(0.25)
        fed.render()
        age = global_registry().view_rows("fleet_scrape_age_seconds")
        assert age[("rNever",)] >= 0.2, age[("rNever",)]

    def test_immortal_inflight_scrape_is_evicted_and_rescraped(self):
        """Review regression: a scrape thread that never terminates (a
        drip-feeding exporter defeats the socket timeout) must not
        block that replica's scrapes forever — past the eviction cap
        the registration is replaced and a healthy replica recovers to
        scrape_ok=1."""
        a = _StubExporter(_BODY_A)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
            ], timeout_s=0.3)
            # an immortal scrape registration from the distant past
            with fed._mu:
                fed._inflight["r0"] = time.monotonic() - 3600.0
            out = fed.render()
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 1.0, \
                "evicted in-flight entry still blocks re-scrape"
            assert 'gk_t{replica_id="r0"} 7' in out
        finally:
            a.stop()

    def test_evicted_scrapes_late_write_is_discarded(self):
        """Review regression: a scrape evicted past the cap that later
        completes must NOT overwrite the successor's fresher state —
        its body predates the successor's scrape (counters would appear
        to regress, stale data marked freshest)."""
        a = _StubExporter(_BODY_A)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
            ])
            assert 'gk_t{replica_id="r0"} 7' in fed.render()  # fresh
            with fed._mu:
                st = fed._state["r0"]
                fresh_at = st.last_ok_at
                # the successor owns the registration now
                fed._inflight["r0"] = time.monotonic()
            a.body = _BODY_A.replace(" 7", " 99")
            # the EVICTED thread's late completion: stale token
            fed._scrape_one(
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
                token=fresh_at - 100.0,
            )
            with fed._mu:
                assert "gk_t 7" in fed._state["r0"].body, \
                    "evicted scrape overwrote the successor's state"
                # and it must not have evicted the successor's entry
                assert "r0" in fed._inflight
                fed._inflight.clear()
        finally:
            a.stop()

    def test_departed_replica_health_keeps_updating(self):
        """Review regression: a replica that LEAVES the targets roster
        (quarantine, scale-down) must not freeze its health gauges at
        the last value — ok flips to 0 and age keeps growing; its
        cached series leave the merged body."""
        a = _StubExporter(_BODY_A)
        roster = [{"replica_id": "r0", "host": "127.0.0.1",
                   "port": a.port}]
        try:
            fed = MetricsFederator(lambda: list(roster))
            assert 'gk_t{replica_id="r0"} 7' in fed.render()
            assert global_registry().view_rows(
                "fleet_scrape_ok")[("r0",)] == 1.0
            roster.clear()  # quarantined / scaled down
            time.sleep(0.05)
            out = fed.render()
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 0.0, \
                "departed replica's scrape_ok froze at 1"
            age1 = global_registry().view_rows(
                "fleet_scrape_age_seconds")[("r0",)]
            assert age1 > 0.0
            assert 'gk_t{replica_id="r0"}' not in out, \
                "departed replica's series still federated"
            time.sleep(0.1)
            fed.render()
            age2 = global_registry().view_rows(
                "fleet_scrape_age_seconds")[("r0",)]
            assert age2 > age1, "departed replica's age froze"
        finally:
            a.stop()

    def test_rollup_sums_request_count(self):
        body = (
            "# HELP gatekeeper_request_count c\n"
            "# TYPE gatekeeper_request_count counter\n"
            'gatekeeper_request_count{admission_status="allow"} 5\n'
            'gatekeeper_request_count{admission_status="deny"} 2\n'
        )
        a, b = _StubExporter(body), _StubExporter(body)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1", "port": a.port},
                {"replica_id": "r1", "host": "127.0.0.1", "port": b.port},
            ])
            out = fed.render()
            assert "gatekeeper_fleet_admission_requests 14" in out
        finally:
            a.stop()
            b.stop()


@pytest.mark.chaos
class TestScrapeFailChaos:
    def test_seeded_scrape_fail_degrades_to_stale(self):
        """An error-mode fleet.scrape_fail makes the scrape fail while
        the replica itself is healthy: the federated view must degrade
        to the stale-marked cache, never error and never block."""
        a = _StubExporter(_BODY_A)
        try:
            fed = MetricsFederator(lambda: [
                {"replica_id": "r0", "host": "127.0.0.1",
                 "port": a.port},
            ])
            assert 'gk_t{replica_id="r0"} 7' in fed.render()  # warm cache
            plane = faults.install(seed=7)
            plane.add(faults.SCRAPE_FAIL,
                      FaultRule(mode="error", count=2))
            try:
                out = fed.render()
                assert 'gk_t{replica_id="r0"} 7' in out
                rows = global_registry().view_rows("fleet_scrape_ok")
                assert rows[("r0",)] == 0.0
            finally:
                faults.uninstall()
            # fault exhausted: the next pass recovers to fresh
            fed.render()
            rows = global_registry().view_rows("fleet_scrape_ok")
            assert rows[("r0",)] == 1.0
        finally:
            a.stop()


# ---- cross-process trace assembly ------------------------------------------


class _StubTraces:
    """Replica /debug/traces stub serving canned trace JSON."""

    def __init__(self, traces):
        outer = self
        self.traces = traces

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                data = json.dumps({"traces": outer.traces}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


def _make_wire_trace() -> str:
    """One completed front-door-shaped trace in the global tracer;
    returns its trace_id."""
    with obstrace.root_span("wire", path="/v1/admit") as sp:
        with obstrace.span("wire.replica_wait", stage="replica_wait"):
            pass
        tid = sp.trace.trace_id
    return tid


class TestTraceCollector:
    def test_joins_frontdoor_and_replica_spans_by_trace_id(self):
        tid = _make_wire_trace()
        replica_trace = {
            "trace_id": tid,
            "root": "admission",
            "duration_ms": 3.0,
            "spans": [
                {"name": "webhook.queue_wait", "trace_id": tid,
                 "duration_ms": 1.0, "attrs": {"stage": "queue_wait"}},
                {"name": "tpu.dispatch", "trace_id": tid,
                 "duration_ms": 2.0, "attrs": {"stage": "dispatch"}},
            ],
        }
        stub = _StubTraces([replica_trace])
        try:
            col = TraceCollector(lambda: [
                {"replica_id": "r1", "host": "127.0.0.1",
                 "port": stub.port},
            ])
            out = col.assemble()
            entry = next(t for t in out["traces"]
                         if t["trace_id"] == tid)
            assert entry["processes"] == ["frontdoor", "r1"]
            procs = {s.get("process") for s in entry["spans"]}
            assert procs == {"frontdoor", "r1"}
            # one view: wire AND device stages in the same breakdown
            assert "replica_wait" in entry["stage_breakdown"]
            assert "dispatch" in entry["stage_breakdown"]
            assert "dispatch" not in entry["wire_stage_breakdown"]
            assert out["failed_replicas"] == []
        finally:
            stub.stop()

    def test_wedged_fleet_trace_fetch_bounded_by_one_budget(self):
        """Review regression: N wedged replicas must cost ONE fetch
        budget on /debug/fleet-traces (concurrent fetches, shared
        deadline), not N sequential timeouts — wedged fleets are
        exactly when operators query traces."""
        stubs = [_StubExporter(_BODY_A, delay_s=30.0) for _ in range(4)]
        try:
            col = TraceCollector(lambda: [
                {"replica_id": f"r{i}", "host": "127.0.0.1",
                 "port": s.port}
                for i, s in enumerate(stubs)
            ], timeout_s=0.4)
            t0 = time.monotonic()
            out = col.assemble()
            took = time.monotonic() - t0
            assert took < 2.5, f"assemble took {took:.1f}s for 4 wedges"
            assert sorted(out["failed_replicas"]) == \
                ["r0", "r1", "r2", "r3"]
        finally:
            for s in stubs:
                s.delay_s = 0.0
                s.stop()

    def test_unreachable_replica_reported_not_fatal(self):
        tid = _make_wire_trace()
        stub = _StubTraces([])
        stub.stop()  # nothing listening
        col = TraceCollector(lambda: [
            {"replica_id": "r9", "host": "127.0.0.1",
             "port": stub.port},
        ], timeout_s=0.3)
        out = col.assemble()
        assert "r9" in out["failed_replicas"]
        assert any(t["trace_id"] == tid for t in out["traces"])

    def test_min_ms_filters_on_wire_duration(self):
        _make_wire_trace()
        col = TraceCollector(lambda: [])
        out = col.assemble(min_ms=10_000.0)
        assert out["traces"] == []

    def test_install_serves_fleet_traces_route(self):
        from gatekeeper_tpu.obs.debug import get_router

        tid = _make_wire_trace()
        col = TraceCollector(lambda: []).install()
        assert col is not None
        code, ctype, body = get_router().handle("/debug/fleet-traces")
        assert code == 200
        payload = json.loads(body)
        assert any(t["trace_id"] == tid for t in payload["traces"])
        code, _ct, body = get_router().handle(
            "/debug/fleet-traces", "min_ms=abc"
        )
        assert code == 400 and b"min_ms" in body


# ---- the real thing: one trace across two processes -------------------------


@spawn_available
class TestCrossProcessPropagation:
    def test_one_trace_id_spans_door_and_replica(self, tmp_path):
        """Front-door→replica round trip: the wire trace id propagates
        into the replica's admission trace, and /debug/fleet-traces
        serves the joined view with both sides' stage spans drawn from
        the documented stable sets (docs/tracing.md)."""
        import http.client

        from gatekeeper_tpu.fleet import FrontDoor
        from gatekeeper_tpu.fleet.frontdoor import WIRE_STAGES
        from gatekeeper_tpu.fleet.replica import spawn_replica

        # the default tpu driver (on the CPU backend): the interp driver
        # emits no stage spans, and this test's whole point is stage
        # spans on BOTH sides of the hop
        handle = spawn_replica(
            "rT", env={"JAX_PLATFORMS": "cpu"}, timeout_s=240.0,
        )
        door = None
        try:
            door = FrontDoor([handle.backend()],
                             probe_interval_s=3600.0).start()
            col = TraceCollector(lambda: [
                {"replica_id": handle.replica_id, "host": handle.host,
                 "port": handle.port},
            ])
            body = json.dumps({"request": {
                "uid": "xproc-1",
                "kind": {"group": "", "version": "v1",
                         "kind": "Namespace"},
                "name": "xproc", "namespace": "",
                "operation": "CREATE",
                "userInfo": {"username": "t"},
                "object": {"apiVersion": "v1", "kind": "Namespace",
                           "metadata": {"name": "xproc",
                                        "labels": {}}},
            }}).encode()
            conn = http.client.HTTPConnection(
                "127.0.0.1", door.port, timeout=60)
            conn.request("POST", "/v1/admit", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            hd = dict(resp.getheaders())
            assert resp.status == 200 and b"response" in resp.read()
            conn.close()
            tid = hd["X-GK-Trace-Id"]
            assert hd["X-GK-Replica"] == "rT"

            def joined():
                out = col.assemble()
                for t in out["traces"]:
                    if t["trace_id"] == tid and \
                            len(t["processes"]) > 1:
                        return t
                return None

            entry = None

            def have():
                nonlocal entry
                entry = joined()
                return entry is not None

            assert wait_until(have, 10.0), \
                "replica half never joined the wire trace"
            # both sides' stage spans present under ONE trace_id
            wire_stages = {
                (s.get("attrs") or {}).get("stage")
                for s in entry["spans"]
                if s.get("process") == "frontdoor"
            } - {None}
            replica_stages = {
                (s.get("attrs") or {}).get("stage")
                for s in entry["spans"]
                if s.get("process") == "rT"
            } - {None}
            assert wire_stages and wire_stages <= set(WIRE_STAGES)
            # replica stages come from the documented admission set
            documented = {"queue_wait", "cache_lookup", "pack",
                          "compile", "dispatch", "fetch", "render"}
            assert replica_stages and replica_stages <= documented
            assert all(tid == s.get("trace_id") for s in entry["spans"]
                       if s.get("trace_id"))
            # the command-pipe mirror of /debug/traces (the saturated-
            # or draining-listener fallback documented in
            # docs/tracing.md) serves the same ring
            reply = handle.command({"cmd": "traces", "limit": 64})
            assert reply["event"] == "traces"
            assert any(t["trace_id"] == tid
                       for t in reply["traces"]), \
                "pipe traces command did not serve the joined trace"
            # malformed params degrade to defaults, never kill the loop
            reply = handle.command({"cmd": "traces", "limit": "zzz",
                                    "min_ms": []})
            assert reply["event"] == "traces"
            assert handle.command({"cmd": "ping"})["event"] == "pong"
        finally:
            if door is not None:
                door.stop()
            handle.stop()
