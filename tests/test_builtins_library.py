"""Builtins beyond the reference corpus: the surface the public
gatekeeper-library policies rely on (units.parse_bytes, object.*, glob,
semver, ...), pinned through the full client + both drivers."""

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver


def _template(kind, rego):
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": rego}],
        },
    }


def _constraint(kind, params=None):
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": f"c-{kind.lower()}"},
        "spec": {
            "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
            "parameters": params or {},
        },
    }


def _pod(name="p", mem="2Gi", image="nginx:1.2.3"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{
            "name": "c", "image": image,
            "resources": {"limits": {"memory": mem}},
        }]},
    }


def _req(pod):
    return {
        "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE", "object": pod,
    }


# the gatekeeper-library K8sContainerLimits shape: memory quantities
# canonified with units.parse_bytes and compared against a parameter
MEMLIMIT_REGO = """
package memlimit

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  mem := units.parse_bytes(c.resources.limits.memory)
  max := units.parse_bytes(input.parameters.memory)
  mem > max
  msg := sprintf("container <%v> memory limit <%v> exceeds <%v>",
                 [c.name, c.resources.limits.memory, input.parameters.memory])
}
"""

# image tags constrained by semver range + registry glob
IMAGEPOLICY_REGO = """
package imagepolicy

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  parts := split(c.image, ":")
  count(parts) == 2
  semver.compare(parts[1], input.parameters.minVersion) == -1
  msg := sprintf("image %v older than %v", [c.image, input.parameters.minVersion])
}

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  not glob.match(input.parameters.registryGlob, ["/"], c.image)
  msg := sprintf("image %v not from allowed registry", [c.image])
}
"""


@pytest.mark.parametrize("driver_cls", [InterpDriver, TpuDriver])
def test_memlimit_library_template(driver_cls):
    c = Client(driver=driver_cls())
    c.add_template(_template("MemLimit", MEMLIMIT_REGO))
    c.add_constraint(_constraint("MemLimit", {"memory": "1Gi"}))
    over = c.review(_req(_pod("over", mem="2Gi"))).results()
    assert len(over) == 1 and "exceeds" in over[0].msg
    under = c.review(_req(_pod("under", mem="512Mi"))).results()
    assert under == []
    # canonical unit equivalence: 1024Mi == 1Gi is NOT over the limit
    eq = c.review(_req(_pod("eq", mem="1024Mi"))).results()
    assert eq == []


@pytest.mark.parametrize("driver_cls", [InterpDriver, TpuDriver])
def test_image_semver_and_glob(driver_cls):
    c = Client(driver=driver_cls())
    c.add_template(_template("ImagePolicy", IMAGEPOLICY_REGO))
    c.add_constraint(_constraint("ImagePolicy", {
        "minVersion": "2.0.0", "registryGlob": "nginx*"
    }))
    old = c.review(_req(_pod("old", image="nginx:1.2.3"))).results()
    assert any("older" in r.msg for r in old)
    new = c.review(_req(_pod("new", image="nginx:2.1.0"))).results()
    assert new == []
    foreign = c.review(_req(_pod("x", image="evil.io/x:3.0.0"))).results()
    assert any("registry" in r.msg for r in foreign)


def test_new_builtin_semantics_table():
    """Direct semantics pins for the added builtins."""
    from gatekeeper_tpu.engine import builtins as bi
    from gatekeeper_tpu.engine.value import FrozenDict, RSet, freeze

    pb = bi.lookup(("units", "parse_bytes"))
    assert pb("1Gi") == 2 ** 30
    assert pb("100m") == 100 * 10 ** 6  # lowercase m = mega in parse_bytes
    assert pb("2KiB") == 2048
    assert pb("5") == 5
    assert pb("1.5Ki") == 1536
    with pytest.raises(bi.BuiltinError):
        pb("oops")
    union = bi.lookup(("object", "union"))
    got = union(freeze({"a": 1, "n": {"x": 1}}), freeze({"n": {"y": 2}}))
    assert got["n"]["x"] == 1 and got["n"]["y"] == 2
    keys = bi.lookup(("object", "keys"))
    assert keys(freeze({"a": 1, "b": 2})) == RSet({"a", "b"})
    glob = bi.lookup(("glob", "match"))
    assert glob("*.com", (), "x.com")
    assert not glob("*.com", (".",), "a.b.com")
    assert glob("**.com", (".",), "a.b.com")
    sem = bi.lookup(("semver", "compare"))
    assert sem("1.0.0-alpha", "1.0.0") == -1
    assert sem("10.0.0", "9.0.0") == 1
    rng = bi.lookup(("numbers", "range"))
    assert rng(3, 1) == (3, 2, 1)
    ca = bi.lookup(("cast_array",))
    assert ca(RSet({3, 1, 2})) == (1, 2, 3)
    rep = bi.lookup(("strings", "replace_n"))
    assert rep(freeze({"<": "&lt;"}), "<x>") == "&lt;x>"


def test_builtin_edge_semantics():
    """Review-driven edges: semver pre-release identifiers, glob negation,
    numbers.range integer-only, per-query time caching."""
    from gatekeeper_tpu.engine import builtins as bi

    sem = bi.lookup(("semver", "compare"))
    assert sem("1.0.0-alpha.10", "1.0.0-alpha.2") == 1  # numeric ids
    assert sem("1.0.0-alpha", "1.0.0-alpha.1") == -1    # fewer ids first
    assert sem("1.0.0-1", "1.0.0-alpha") == -1          # numeric < alpha
    glob = bi.lookup(("glob", "match"))
    assert glob("[!abc]", (".",), "x")
    assert not glob("[!abc]", (".",), "a")
    rng = bi.lookup(("numbers", "range"))
    with pytest.raises(bi.BuiltinError):
        rng(1.5, 3)
    now = bi.lookup(("time", "now_ns"))
    bi.bump_query_epoch()
    a, b = now(), now()
    assert a == b, "same query must see one instant"
    bi.bump_query_epoch()
    assert now() >= a
    # null delimiters = separator-free matching (OPA ast.Null semantics)
    glob2 = bi.lookup(("glob", "match"))
    assert glob2("*.example.com", None, "a.b.example.com")
    assert not glob2("*.example.com", (), "a.b.example.com")
    # interior whitespace between number and unit is rejected like OPA
    pb2 = bi.lookup(("units", "parse_bytes"))
    with pytest.raises(bi.BuiltinError):
        pb2("1 Gi")
    # replacements apply in sorted key order (Rego object iteration),
    # single pass: replacement output is never re-replaced (Go Replacer)
    rep2 = bi.lookup(("strings", "replace_n"))
    from gatekeeper_tpu.engine.value import freeze as _fz
    assert rep2(_fz({"b": "x", "ab": "y"}), "ab") == "y"
    assert rep2(_fz({"a": "b", "b": "z"}), "a") == "b"
    # parse_bytes accepts bare-fraction forms like OPA's float parse
    assert bi.lookup(("units", "parse_bytes"))(".5Gi") == 2 ** 29
    assert bi.lookup(("units", "parse_bytes"))("5.") == 5
