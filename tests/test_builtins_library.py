"""Builtins beyond the reference corpus: the surface the public
gatekeeper-library policies rely on (units.parse_bytes, object.*, glob,
semver, ...), pinned through the full client + both drivers."""

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver


def _template(kind, rego):
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": rego}],
        },
    }


def _constraint(kind, params=None):
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": f"c-{kind.lower()}"},
        "spec": {
            "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
            "parameters": params or {},
        },
    }


def _pod(name="p", mem="2Gi", image="nginx:1.2.3"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{
            "name": "c", "image": image,
            "resources": {"limits": {"memory": mem}},
        }]},
    }


def _req(pod):
    return {
        "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": pod["metadata"]["name"],
        "namespace": pod["metadata"]["namespace"],
        "operation": "CREATE", "object": pod,
    }


# the gatekeeper-library K8sContainerLimits shape: memory quantities
# canonified with units.parse_bytes and compared against a parameter
MEMLIMIT_REGO = """
package memlimit

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  mem := units.parse_bytes(c.resources.limits.memory)
  max := units.parse_bytes(input.parameters.memory)
  mem > max
  msg := sprintf("container <%v> memory limit <%v> exceeds <%v>",
                 [c.name, c.resources.limits.memory, input.parameters.memory])
}
"""

# image tags constrained by semver range + registry glob
IMAGEPOLICY_REGO = """
package imagepolicy

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  parts := split(c.image, ":")
  count(parts) == 2
  semver.compare(parts[1], input.parameters.minVersion) == -1
  msg := sprintf("image %v older than %v", [c.image, input.parameters.minVersion])
}

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  not glob.match(input.parameters.registryGlob, ["/"], c.image)
  msg := sprintf("image %v not from allowed registry", [c.image])
}
"""


@pytest.mark.parametrize("driver_cls", [InterpDriver, TpuDriver])
def test_memlimit_library_template(driver_cls):
    c = Client(driver=driver_cls())
    c.add_template(_template("MemLimit", MEMLIMIT_REGO))
    c.add_constraint(_constraint("MemLimit", {"memory": "1Gi"}))
    over = c.review(_req(_pod("over", mem="2Gi"))).results()
    assert len(over) == 1 and "exceeds" in over[0].msg
    under = c.review(_req(_pod("under", mem="512Mi"))).results()
    assert under == []
    # canonical unit equivalence: 1024Mi == 1Gi is NOT over the limit
    eq = c.review(_req(_pod("eq", mem="1024Mi"))).results()
    assert eq == []


@pytest.mark.parametrize("driver_cls", [InterpDriver, TpuDriver])
def test_image_semver_and_glob(driver_cls):
    c = Client(driver=driver_cls())
    c.add_template(_template("ImagePolicy", IMAGEPOLICY_REGO))
    c.add_constraint(_constraint("ImagePolicy", {
        "minVersion": "2.0.0", "registryGlob": "nginx*"
    }))
    old = c.review(_req(_pod("old", image="nginx:1.2.3"))).results()
    assert any("older" in r.msg for r in old)
    new = c.review(_req(_pod("new", image="nginx:2.1.0"))).results()
    assert new == []
    foreign = c.review(_req(_pod("x", image="evil.io/x:3.0.0"))).results()
    assert any("registry" in r.msg for r in foreign)


def test_new_builtin_semantics_table():
    """Direct semantics pins for the added builtins."""
    from gatekeeper_tpu.engine import builtins as bi
    from gatekeeper_tpu.engine.value import FrozenDict, RSet, freeze

    pb = bi.lookup(("units", "parse_bytes"))
    assert pb("1Gi") == 2 ** 30
    assert pb("100m") == 100 * 10 ** 6  # lowercase m = mega in parse_bytes
    assert pb("2KiB") == 2048
    assert pb("5") == 5
    assert pb("1.5Ki") == 1536
    with pytest.raises(bi.BuiltinError):
        pb("oops")
    union = bi.lookup(("object", "union"))
    got = union(freeze({"a": 1, "n": {"x": 1}}), freeze({"n": {"y": 2}}))
    assert got["n"]["x"] == 1 and got["n"]["y"] == 2
    keys = bi.lookup(("object", "keys"))
    assert keys(freeze({"a": 1, "b": 2})) == RSet({"a", "b"})
    glob = bi.lookup(("glob", "match"))
    assert glob("*.com", (), "x.com")
    assert not glob("*.com", (".",), "a.b.com")
    assert glob("**.com", (".",), "a.b.com")
    sem = bi.lookup(("semver", "compare"))
    assert sem("1.0.0-alpha", "1.0.0") == -1
    assert sem("10.0.0", "9.0.0") == 1
    rng = bi.lookup(("numbers", "range"))
    assert rng(3, 1) == (3, 2, 1)
    ca = bi.lookup(("cast_array",))
    assert ca(RSet({3, 1, 2})) == (1, 2, 3)
    rep = bi.lookup(("strings", "replace_n"))
    assert rep(freeze({"<": "&lt;"}), "<x>") == "&lt;x>"


def test_builtin_edge_semantics():
    """Review-driven edges: semver pre-release identifiers, glob negation,
    numbers.range integer-only, per-query time caching."""
    from gatekeeper_tpu.engine import builtins as bi

    sem = bi.lookup(("semver", "compare"))
    assert sem("1.0.0-alpha.10", "1.0.0-alpha.2") == 1  # numeric ids
    assert sem("1.0.0-alpha", "1.0.0-alpha.1") == -1    # fewer ids first
    assert sem("1.0.0-1", "1.0.0-alpha") == -1          # numeric < alpha
    glob = bi.lookup(("glob", "match"))
    assert glob("[!abc]", (".",), "x")
    assert not glob("[!abc]", (".",), "a")
    rng = bi.lookup(("numbers", "range"))
    with pytest.raises(bi.BuiltinError):
        rng(1.5, 3)
    now = bi.lookup(("time", "now_ns"))
    bi.bump_query_epoch()
    a, b = now(), now()
    assert a == b, "same query must see one instant"
    bi.bump_query_epoch()
    assert now() >= a
    # null delimiters = separator-free matching (OPA ast.Null semantics)
    glob2 = bi.lookup(("glob", "match"))
    assert glob2("*.example.com", None, "a.b.example.com")
    assert not glob2("*.example.com", (), "a.b.example.com")
    # interior whitespace between number and unit is rejected like OPA
    pb2 = bi.lookup(("units", "parse_bytes"))
    with pytest.raises(bi.BuiltinError):
        pb2("1 Gi")
    # replacements apply in sorted key order (Rego object iteration),
    # single pass: replacement output is never re-replaced (Go Replacer)
    rep2 = bi.lookup(("strings", "replace_n"))
    from gatekeeper_tpu.engine.value import freeze as _fz
    assert rep2(_fz({"b": "x", "ab": "y"}), "ab") == "y"
    assert rep2(_fz({"a": "b", "b": "z"}), "a") == "b"
    # parse_bytes accepts bare-fraction forms like OPA's float parse
    assert bi.lookup(("units", "parse_bytes"))(".5Gi") == 2 ** 29
    assert bi.lookup(("units", "parse_bytes"))("5.") == 5


from gatekeeper_tpu.engine.builtins import REGISTRY
from gatekeeper_tpu.engine.interp import TemplatePolicy
from gatekeeper_tpu.engine.value import FrozenDict, RSet, freeze


def _py(v):
    """Thaw a frozen value, hashing nested arrays as tuples inside sets."""
    if isinstance(v, FrozenDict):
        return {k: _py(v[k]) for k in v.keys()}
    if isinstance(v, tuple):
        return [_py(x) for x in v]
    if isinstance(v, RSet):
        out = set()
        for x in v:
            px = _py(x)
            out.add(tuple(px) if isinstance(px, list) else px)
        return out
    return v


def run_bi(name, *args):
    """Call a builtin directly with frozen args, returning a python value."""
    fn = REGISTRY[tuple(name.split("."))]
    return _py(fn(*[freeze(a) for a in args]))


run_bi_raw = run_bi


class TestRegistryCompletion:
    """OPA v0.21 registry completion: every name in the vendored
    ast/builtins.go is either implemented, a native infix operator, or an
    environment-blocked stub with a clear error."""

    def test_full_registry_coverage(self):
        import re as _re
        from .corpus import REF
        src = open(REF / "vendor/github.com/open-policy-agent/opa/ast/builtins.go").read()
        opa = set(_re.findall(r'Name:\s*"([^"]+)"', src))
        from gatekeeper_tpu.engine.builtins import REGISTRY
        ours = {".".join(p) for p in REGISTRY}
        infix = {"and", "or", "plus", "minus", "mul", "div", "rem",
                 "eq", "neq", "lt", "lte", "gt", "gte", "equal", "assign"}
        missing = opa - ours - infix
        assert not missing, f"missing builtins: {sorted(missing)}"

    def test_encoding(self):
        assert run_bi("base64url.encode", "a+b/c") == "YStiL2M="
        assert run_bi("base64url.decode", "YStiL2M") == "a+b/c"
        assert run_bi("urlquery.encode", "a b&c") == "a+b%26c"
        assert run_bi("urlquery.decode", "a+b%26c") == "a b&c"
        assert "a=1" in run_bi("urlquery.encode_object", {"a": "1"})
        assert run_bi("yaml.unmarshal", "a: 1\n") == {"a": 1}
        assert run_bi("yaml.marshal", {"a": 1}) == "a: 1\n"

    def test_crypto_digests(self):
        assert run_bi("crypto.sha256", "abc") == (
            "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad")
        assert run_bi("crypto.md5", "") == "d41d8cd98f00b204e9800998ecf8427e"
        assert run_bi("crypto.sha1", "") == "da39a3ee5e6b4b0d3255bfef95601890afd80709"

    def test_bits(self):
        assert run_bi("bits.or", 5, 3) == 7
        assert run_bi("bits.and", 5, 3) == 1
        assert run_bi("bits.xor", 5, 3) == 6
        assert run_bi("bits.negate", 0) == -1
        assert run_bi("bits.lsh", 1, 4) == 16
        assert run_bi("bits.rsh", 16, 4) == 1

    def test_object_filter_remove(self):
        assert run_bi("object.filter", {"a": 1, "b": 2}, ["a"]) == {"a": 1}
        assert run_bi("object.remove", {"a": 1, "b": 2}, ["a"]) == {"b": 2}

    def test_json_filter_remove(self):
        doc = {"a": {"b": 1, "c": 2}, "d": 3}
        assert run_bi("json.filter", doc, ["a/b"]) == {"a": {"b": 1}}
        assert run_bi("json.remove", doc, ["a/b"]) == {"a": {"c": 2}, "d": 3}
        assert run_bi("json.filter", doc, [["a", "c"]]) == {"a": {"c": 2}}

    def test_graph_reachable(self):
        g = {"a": ["b"], "b": ["c"], "c": [], "x": ["y"], "y": []}
        assert run_bi("graph.reachable", g, ["a"]) == {"a", "b", "c"}

    def test_net(self):
        assert run_bi("net.cidr_contains", "10.0.0.0/8", "10.1.0.0/16") is True
        assert run_bi("net.cidr_contains", "10.0.0.0/8", "10.1.2.3") is True
        assert run_bi("net.cidr_contains", "10.0.0.0/8", "11.0.0.1") is False
        assert run_bi("net.cidr_intersects", "10.0.0.0/30", "10.0.0.2/31") is True
        assert run_bi("net.cidr_expand", "10.0.0.0/30") == {
            "10.0.0.0", "10.0.0.1", "10.0.0.2", "10.0.0.3"}
        matches = run_bi("net.cidr_contains_matches", ["10.0.0.0/8"], ["10.1.2.3", "8.8.8.8"])
        assert matches == {(0, 0)}

    def test_time_parsing(self):
        ns = run_bi("time.parse_rfc3339_ns", "2020-01-02T03:04:05Z")
        assert ns == 1577934245000000000
        assert run_bi("time.date", ns) == [2020, 1, 2]
        assert run_bi("time.clock", ns) == [3, 4, 5]
        assert run_bi("time.weekday", ns) == "Thursday"
        assert run_bi("time.parse_duration_ns", "1.5h") == int(1.5 * 3600 * 1e9)
        assert run_bi("time.parse_duration_ns", "300ms") == 300_000_000
        assert run_bi("time.parse_ns", "2006-01-02", "2020-01-02") == 1577923200000000000
        # fractional-second precision survives to the nanosecond
        assert run_bi("time.parse_rfc3339_ns", "2020-01-02T03:04:05.123456789Z") % 10**9 == 123456789

    def test_time_add_date(self):
        ns = run_bi("time.parse_rfc3339_ns", "2020-01-31T00:00:00Z")
        y, m, d = run_bi("time.date", run_bi("time.add_date", ns, 0, 1, 0))
        # Go normalizes Jan 31 + 1 month = Mar 2 (2020 is a leap year)
        assert (y, m, d) == (2020, 3, 2)

    def test_regex_extras(self):
        assert run_bi("regex.find_n", "[0-9]+", "a1b22c333", 2) == ["1", "22"]
        assert run_bi("regex.find_n", "[0-9]+", "a1b22c333", -1) == ["1", "22", "333"]
        subs = run_bi("regex.find_all_string_submatch_n", "([a-z])([0-9])", "a1 b2", -1)
        assert subs == [["a1", "a", "1"], ["b2", "b", "2"]]
        assert run_bi("regex.template_match", "urn:foo:{.*}", "urn:foo:bar:baz", "{", "}") is True
        assert run_bi("regex.template_match", "urn:foo:{[0-9]+}", "urn:foo:bar", "{", "}") is False
        assert run_bi("glob.quote_meta", "*.txt") == "\\*.txt"

    def test_jwt_hmac(self):
        import base64, hashlib, hmac, json as _json
        header = base64.urlsafe_b64encode(_json.dumps({"alg": "HS256", "typ": "JWT"}).encode()).rstrip(b"=")
        payload = base64.urlsafe_b64encode(_json.dumps({"sub": "x"}).encode()).rstrip(b"=")
        signing = header + b"." + payload
        sig = base64.urlsafe_b64encode(
            hmac.new(b"secret", signing, hashlib.sha256).digest()).rstrip(b"=")
        token = (signing + b"." + sig).decode()
        assert run_bi("io.jwt.verify_hs256", token, "secret") is True
        assert run_bi("io.jwt.verify_hs256", token, "wrong") is False
        hdr, pay, _sig = run_bi("io.jwt.decode", token)
        assert hdr["alg"] == "HS256" and pay["sub"] == "x"

    def test_casts(self):
        assert run_bi("cast_string", "x") == "x"
        assert run_bi("set_diff", {1, 2, 3}, {2}) == {1, 3}
        with pytest.raises(Exception):
            run_bi_raw("cast_string", 5)

    def test_blocked_builtins_are_undefined_not_wrong(self):
        # http.send & friends must fail closed (undefined), never fabricate
        from gatekeeper_tpu.engine.builtins import REGISTRY, BuiltinError
        with pytest.raises(BuiltinError):
            REGISTRY[("http", "send")]({})

    def test_trace_and_runtime(self):
        assert run_bi("trace", "note") is True
        rt = run_bi("opa.runtime")
        assert "version" in rt

    def test_uuid_stable_within_query(self):
        pol = TemplatePolicy.compile(
            """
package p

violation[{"msg": m}] {
  a := uuid.rfc4122("k")
  b := uuid.rfc4122("k")
  a == b
  m := "stable"
}
"""
        )
        assert pol.eval_violations({}, {}, {}) == [{"msg": "stable"}]
        assert pol.memo_safe is False


class TestWalkAndOutputArgs:
    def test_walk_enumerates_nested_paths(self):
        pol = TemplatePolicy.compile(
            """
package p

violation[{"msg": m}] {
  walk(input.review.object, [path, value])
  value == "secret"
  m := concat("/", [format_int(count(path), 10)])
}
"""
        )
        obj = {"a": {"b": ["x", "secret"]}}
        out = pol.eval_violations({"object": obj}, {}, {})
        assert out == [{"msg": "3"}]  # path ["a","b",1] has 3 segments

    def test_walk_finds_all_matching_values(self):
        pol = TemplatePolicy.compile(
            """
package p

paths[path] { walk(input.review, [path, value]); value == 1 }

violation[{"msg": "n"}] { count(paths) == 2 }
"""
        )
        assert pol.eval_violations({"a": 1, "b": {"c": 1}}, {}, {}) == [{"msg": "n"}]

    def test_builtin_output_argument_form(self):
        pol = TemplatePolicy.compile(
            """
package p

violation[{"msg": msg}] {
  split(input.review.image, ":", parts)
  count(parts, n)
  n == 2
  msg := parts[1]
}
"""
        )
        assert pol.eval_violations({"image": "nginx:latest"}, {}, {}) == [{"msg": "latest"}]
        assert pol.eval_violations({"image": "nginx"}, {}, {}) == []

    def test_sprintf_output_argument(self):
        pol = TemplatePolicy.compile(
            """
package p

violation[{"msg": msg}] { sprintf("got %v", [input.review.x], msg) }
"""
        )
        assert pol.eval_violations({"x": 7}, {}, {}) == [{"msg": "got 7"}]


class TestPrecisionAndEdgeCases:
    """Regressions: integer/ns precision and grammar edges found in review."""

    def test_time_builtins_accept_real_ns_timestamps(self):
        # ints above 2^53 are not exactly float-representable; the
        # integrality check must not reject them
        ns = 1577934245123456789
        assert run_bi("time.date", ns) == [2020, 1, 2]
        assert run_bi("time.clock", ns) == [3, 4, 5]
        assert run_bi("bits.or", 2**53 + 1, 0) == 2**53 + 1

    def test_ns_arg_no_second_boundary_rounding(self):
        # 0.999999744s must not round up into the next second
        assert run_bi("time.clock", 999999999999999744) == [1, 46, 39]

    def test_parse_duration_exact_and_zero(self):
        assert run_bi("time.parse_duration_ns", "0") == 0
        assert run_bi("time.parse_duration_ns",
                      "2562047h47m16s854ms775us807ns") == 9223372036854775807

    def test_else_without_body(self):
        # OPA grammar: rule-else ::= "else" [ "=" term ] [ "{" query "}" ]
        pol = TemplatePolicy.compile(
            """
package p

x = 1 { input.review.a } else = 2

violation[{"msg": sprintf("%v", [x])}] { true }
"""
        )
        assert pol.eval_violations({}, {}, {}) == [{"msg": "2"}]
        assert pol.eval_violations({"a": True}, {}, {}) == [{"msg": "1"}]

    def test_user_function_output_arg_reorders_safely(self):
        # consumer written before the producing call: safety reorder must
        # know local-function output arity
        pol = TemplatePolicy.compile(
            """
package p

double(x) = y { y := x * 2 }

violation[{"msg": m}] {
  double(n, out)
  out > 3
  n := input.review.num
  m := "big"
}
"""
        )
        assert pol.eval_violations({"num": 5}, {}, {}) == [{"msg": "big"}]
        assert pol.eval_violations({"num": 1}, {}, {}) == []


def test_time_builtins_apply_timezone():
    # OPA's [ns, tz] operand: Go LoadLocation semantics via the system tz
    # database; unknown names are undefined, never silently UTC
    ns = run_bi("time.parse_rfc3339_ns", "2020-01-02T03:04:05Z")
    assert run_bi("time.clock", [ns, "America/New_York"]) == [22, 4, 5]
    assert run_bi("time.date", [ns, "America/New_York"]) == [2020, 1, 1]
    assert run_bi("time.clock", [ns, "UTC"]) == [3, 4, 5]
    from gatekeeper_tpu.engine.builtins import BuiltinError
    with pytest.raises(BuiltinError):
        run_bi("time.clock", [ns, "Not/AZone"])
