"""Seeded randomized differential testing: the device path must match the
interpreter oracle on randomly mutated workloads, including degenerate
object shapes (missing fields, empty containers, wrong-typed values,
unicode, deep labels).  Complements the fixed-scenario conformance battery
(SURVEY §4 tier-1 role) with generative coverage.
"""

import copy
import random

import pytest

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.client.drivers import InterpDriver
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


def _mutate_pod(pod: dict, rng: random.Random) -> dict:
    """Apply structure-breaking mutations real clusters produce."""
    p = copy.deepcopy(pod)
    for _ in range(rng.randint(0, 3)):
        roll = rng.random()
        if roll < 0.15:
            p["spec"].pop("containers", None)  # no containers at all
        elif roll < 0.3:
            p["spec"]["containers"] = []  # empty list
        elif roll < 0.4:
            (p["metadata"].setdefault("labels", {})
             )[f"weird/{rng.randint(0, 9)}"] = "x" * rng.randint(0, 5)
        elif roll < 0.5:
            p["metadata"].pop("labels", None)
        elif roll < 0.6 and p["spec"].get("containers"):
            c = rng.choice(p["spec"]["containers"])
            c.pop("image", None)  # image missing entirely
        elif roll < 0.7 and p["spec"].get("containers"):
            c = rng.choice(p["spec"]["containers"])
            c["ports"] = [{"hostPort": rng.choice([0, 65535, 31337])}]
        elif roll < 0.8:
            p["metadata"]["labels"] = {
                "uni": "λ-ünïcode-" + chr(0x1F512),
                "empty": "",
            }
        elif roll < 0.9:
            p["spec"]["volumes"] = [
                {"name": "v", rng.choice(["nfs", "hostPath", "emptyDir"]): {}}
            ]
        else:
            p["spec"]["hostPID"] = rng.choice([True, False])
    return p


def _results_key(results):
    return sorted(
        (r.constraint["kind"], r.constraint["metadata"]["name"], r.msg,
         str((r.review or {}).get("object", {}).get("metadata", {}).get("name")))
        for r in results
    )


def _assert_parity(ct, ci, pods, rng, seed, n_sample, label):
    """Audit parity (uncapped, complete results) + review parity on a
    random subset through the batched device path."""
    assert _results_key(ct.audit().results()) == _results_key(
        ci.audit().results()
    ), f"{label}audit diverged (seed {seed})"
    sample = rng.sample(pods, min(n_sample, len(pods)))
    reqs = [{
        "uid": "u", "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": p["metadata"]["name"],
        "namespace": p["metadata"].get("namespace", ""),
        "operation": "CREATE", "object": p,
    } for p in sample]
    got = ct.driver.review_batch(reqs)
    for req, (results, _trace) in zip(reqs, got):
        want, _ = ci.driver.review(req)
        assert _results_key(results) == _results_key(want), (
            f"{label}review diverged (seed {seed}, pod {req['name']})"
        )


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzzed_workloads_device_matches_interp(seed):
    rng = random.Random(seed)
    n_templates = rng.randint(4, 14)
    templates, constraints = make_templates(n_templates, seed=seed)
    pods = [_mutate_pod(p, rng)
            for p in make_pods(rng.randint(30, 120), seed=seed,
                               violation_rate=rng.random())]

    ct = Client(driver=TpuDriver())
    ct.driver.DEVICE_MIN_CELLS = 0  # force the device path everywhere
    ci = Client(driver=InterpDriver())
    for t, k in zip(templates, constraints):
        ct.add_template(t)
        ci.add_template(t)
        ct.add_constraint(k)
        ci.add_constraint(k)
    for p in pods:
        ct.add_data(p)
        ci.add_data(p)

    _assert_parity(ct, ci, pods, rng, seed, n_sample=8, label="")

    # capped-audit totals: exact entries must equal the oracle's
    _res, totals = ct.audit_capped(3)
    _ires, itotals = ci.audit_capped(3)
    for key, (n, how) in totals.items():
        if how == "exact":
            assert n == itotals[key][0], (seed, key, n, itotals[key])

    # churn + delta path parity
    for i in range(3):
        p = _mutate_pod(make_pods(1, seed=900 + i, violation_rate=1.0)[0], rng)
        p["metadata"]["name"] = f"fuzz-delta-{i}"
        ct.add_data(p)
        ci.add_data(copy.deepcopy(p))
        ct.audit_capped(3)
    assert _results_key(ct.audit().results()) == _results_key(
        ci.audit().results()
    ), f"post-churn audit diverged (seed {seed})"


def _feature_template(name, kind, rego, libs=()):
    target = {"target": "admission.k8s.gatekeeper.sh", "rego": rego}
    if libs:
        target["libs"] = list(libs)
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": name},
        "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                 "targets": [target]},
    }


# templates leaning on the newer engine surface: walk, else chains,
# with modifiers, output-argument calls, import aliasing, registry builtins
FEATURE_TEMPLATES = [
    _feature_template("fuzzwalk", "FuzzWalk", """
package fuzzwalk

violation[{"msg": msg}] {
  walk(input.review.object, [path, value])
  is_string(value)
  contains(value, "host")
  msg := sprintf("hosty string at depth %v", [count(path)])
}
"""),
    _feature_template("fuzzelse", "FuzzElse", """
package fuzzelse

risk(obj) = "privileged" { obj.spec.hostPID == true }
else = "ported" { obj.spec.containers[_].ports[_].hostPort > 0 }
else = "plain"

violation[{"msg": msg}] {
  r := risk(input.review.object)
  r != "plain"
  msg := sprintf("risk: %v", [r])
}
"""),
    _feature_template("fuzzwith", "FuzzWith", """
package fuzzwith

has_containers { count(input.review.object.spec.containers) > 0 }

violation[{"msg": "containerless pod"}] {
  not has_containers
  # counterfactual sanity: the rule itself works once containers exist
  has_containers with input.review.object.spec.containers as [{"name": "injected"}]
}
"""),
    _feature_template("fuzzoutarg", "FuzzOutArg", """
package fuzzoutarg
import data.lib.fuzzhelpers as fh

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  split(c.image, ":", parts)
  count(parts, n)
  n < 2
  msg := fh.tagless(c)
}
""", libs=["""
package lib.fuzzhelpers

tagless(c) = msg { msg := sprintf("container %v has an untagged image", [object.get(c, "name", "?")]) }
"""]),
]


@pytest.mark.parametrize("seed", [11, 12])
def test_feature_templates_device_matches_interp(seed):
    """The walk/else/with/output-arg/import surface through both drivers
    over structure-broken workloads."""
    rng = random.Random(seed)
    pods = [_mutate_pod(p, rng)
            for p in make_pods(rng.randint(40, 90), seed=seed,
                               violation_rate=rng.random())]
    ct = Client(driver=TpuDriver())
    ct.driver.DEVICE_MIN_CELLS = 0
    ci = Client(driver=InterpDriver())
    for t in FEATURE_TEMPLATES:
        ct.add_template(copy.deepcopy(t))
        ci.add_template(copy.deepcopy(t))
        kind = t["spec"]["crd"]["spec"]["names"]["kind"]
        cons = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind, "metadata": {"name": f"c-{kind.lower()}"},
                "spec": {"match": {"kinds": [
                    {"apiGroups": [""], "kinds": ["Pod"]}]}}}
        ct.add_constraint(copy.deepcopy(cons))
        ci.add_constraint(cons)
    for p in pods:
        ct.add_data(p)
        ci.add_data(copy.deepcopy(p))

    _assert_parity(ct, ci, pods, rng, seed, n_sample=6, label="feature ")
