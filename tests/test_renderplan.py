"""Unit tests for the compiled render pipeline internals
(ops/renderplan.py) and its driver seams: format splitting, bind-time
partial evaluation, the bounded render-memo eviction, and the worker
pool's ordering/exception contract."""

import pytest

from gatekeeper_tpu.engine.interp import TemplatePolicy
from gatekeeper_tpu.engine.value import freeze
from gatekeeper_tpu.ops import renderplan as rp
from gatekeeper_tpu.ops.vectorizer import vectorize


def _bind(rego, params):
    pol = TemplatePolicy.compile(rego)
    prog = vectorize(pol)
    constraint = {
        "kind": "T", "metadata": {"name": "c"},
        "spec": {"match": {}, "parameters": params},
    }
    return rp.bind(prog, pol, constraint), pol


# ---- format splitting -------------------------------------------------------


def test_split_simple_fmt():
    assert rp._split_simple_fmt("a %v b %s c") == ["a ", " b ", " c"]
    assert rp._split_simple_fmt("100%% sure: %v") == ["100% sure: ", ""]
    assert rp._split_simple_fmt("no verbs") == ["no verbs"]
    # flags/width/other verbs fall back to the generic builtin
    assert rp._split_simple_fmt("%d") is None
    assert rp._split_simple_fmt("%5v") is None
    assert rp._split_simple_fmt("%+v") is None


def test_non_simple_verbs_still_render_exactly():
    plan, pol = _bind(
        """
package t

violation[{"msg": msg}] {
  input.review.object.x
  msg := sprintf("x=%d y=%v", [input.review.object.x, input.review.object.y])
}
""",
        {},
    )
    review = {"object": {"x": 7, "y": ["a", 1]}}
    got = plan.apply(rp.RowView(review))
    want = pol.eval_violations(freeze(review), freeze({}), freeze({}))
    assert got == want == [{"msg": 'x=7 y=["a", 1]'}]


# ---- bind-time behavior -----------------------------------------------------


def test_static_tier_precomputes_message():
    plan, _pol = _bind(
        """
package t

violation[{"msg": msg}] {
  input.review.object.bad
  msg := sprintf("policy %v forbids this", [input.parameters.p])
}
""",
        {"p": "P1"},
    )
    assert plan.tier == rp.STATIC
    assert plan.clauses[0].obj_static == freeze(
        {"msg": "policy P1 forbids this"}
    )
    assert plan.apply(rp.RowView({"object": {"bad": True}})) == [
        {"msg": "policy P1 forbids this"}
    ]
    assert plan.apply(rp.RowView({"object": {}})) == []


def test_missing_message_param_means_clause_never_fires():
    plan, pol = _bind(
        """
package t

violation[{"msg": msg}] {
  input.review.object.bad
  msg := sprintf("policy %v forbids this", [input.parameters.p])
}
""",
        {},
    )
    assert plan.clauses[0].never
    review = {"object": {"bad": True}}
    assert plan.apply(rp.RowView(review)) == []
    assert pol.eval_violations(freeze(review), freeze({}), freeze({})) == []


def test_unused_benign_assignment_guards_definedness():
    """A body assignment whose rhs may be undefined fails the clause in
    the interpreter even when the assigned var is never used; the plan
    must guard on it (code-review finding: without the guard the plan
    produced violations the interpreter would not — false DENYs)."""
    rego = """
package t

violation[{"msg": msg}] {
  input.review.object.metadata.labels.bad == "x"
  note := sprintf("%v", [input.review.object.metadata.annotations.foo])
  msg := "denied"
}
"""
    plan, pol = _bind(rego, {})
    assert plan is not None
    # label present, annotation ABSENT: interpreter yields nothing
    review = {"object": {"metadata": {"labels": {"bad": "x"}}}}
    want = pol.eval_violations(freeze(review), freeze({}), freeze({}))
    assert want == []
    assert plan.apply(rp.RowView(review)) == []
    # with the annotation present both fire
    review2 = {"object": {"metadata": {"labels": {"bad": "x"},
                                       "annotations": {"foo": "f"}}}}
    want2 = pol.eval_violations(freeze(review2), freeze({}), freeze({}))
    assert want2 == [{"msg": "denied"}]
    assert plan.apply(rp.RowView(review2)) == want2


def test_unused_field_assignment_guards_definedness():
    """Same for a plain field-ref assignment (`x := obj.maybe_missing`)
    with x unused: clause fires only when the field exists."""
    rego = """
package t

violation[{"msg": "denied"}] {
  input.review.object.bad
  x := input.review.object.maybe
}
"""
    plan, pol = _bind(rego, {})
    assert plan is not None
    for review in (
        {"object": {"bad": True}},
        {"object": {"bad": True, "maybe": 1}},
        {"object": {"bad": True, "maybe": False}},  # defined-but-false: fires
    ):
        want = pol.eval_violations(freeze(review), freeze({}), freeze({}))
        assert plan.apply(rp.RowView(review)) == want


def test_slot_scoped_assignment_guard():
    """A per-entity assignment guard fails only that binding."""
    rego = """
package t

violation[{"msg": msg}] {
  c := input.review.object.spec.containers[_]
  c.bad
  tag := c.tag
  msg := sprintf("bad %v", [c.name])
}
"""
    plan, pol = _bind(rego, {})
    assert plan is not None
    review = {"object": {"spec": {"containers": [
        {"name": "a", "bad": True, "tag": "t"},
        {"name": "b", "bad": True},  # no tag: binding fails
    ]}}}
    want = pol.eval_violations(freeze(review), freeze({}), freeze({}))
    assert want == [{"msg": "bad a"}]
    assert plan.apply(rp.RowView(review)) == want


def test_helper_with_undefined_risk_falls_back_to_interp():
    """An inlined helper whose body carries a definedness-risky
    assignment cannot be expressed as a clause-level guard: the template
    must classify interp rather than mis-render."""
    rego = """
package t

risky(o) {
  x := o.maybe
  o.bad
}

violation[{"msg": "denied"}] {
  risky(input.review.object)
}
"""
    plan, _pol = _bind(rego, {})
    assert plan is None


def test_inexact_program_is_ineligible():
    plan, _pol = _bind(
        """
package t

violation[{"msg": "nope"}] {
  some_unrecognized_builtin_chain := json.unmarshal(input.review.object.blob)
  some_unrecognized_builtin_chain.bad
}
""",
        {},
    )
    assert plan is None


def test_match_exact_requires_no_selectors():
    pol = TemplatePolicy.compile(
        """
package t

violation[{"msg": "m"}] { input.review.object.bad }
"""
    )
    prog = vectorize(pol)
    plain = rp.bind(prog, pol, {
        "kind": "T", "metadata": {"name": "a"}, "spec": {"match": {}},
    })
    selector = rp.bind(prog, pol, {
        "kind": "T", "metadata": {"name": "b"},
        "spec": {"match": {"labelSelector": {"matchLabels": {"x": "y"}}}},
    })
    assert plain.match_exact is True
    assert selector.match_exact is False


def test_rowview_caches_and_strips_uid():
    review = {"uid": "u-1", "object": {"spec": {"containers": [
        {"name": "a"}, {"name": "b"}]}}}
    row = rp.RowView(review)
    e1 = row.entities((("object", "spec", "containers", "[]"),))
    e2 = row.entities((("object", "spec", "containers", "[]"),))
    assert e1 is e2 and len(e1) == 2
    mf = row.memo_frozen()
    assert "uid" not in mf and mf is row.memo_frozen()


# ---- render-memo eviction (bounded, no wholesale clear) ---------------------


def test_render_memo_chunked_eviction():
    from gatekeeper_tpu.ops.driver import TpuDriver

    d = TpuDriver.__new__(TpuDriver)  # no heavy init needed
    d._render_memo = {}
    d.RENDER_MEMO_MAX = TpuDriver.RENDER_MEMO_MAX
    for i in range(1000):
        d._render_memo[("K", "c", i)] = (0, [])
    d.RENDER_MEMO_MAX = 1000  # shrink the cap for the test
    d._evict_render_memo()
    drop = max(1, 1000 // 16)
    assert len(d._render_memo) == 1000 - drop
    # OLDEST entries went; newest stayed
    assert ("K", "c", 0) not in d._render_memo
    assert ("K", "c", 999) in d._render_memo
    # repeated eviction keeps shrinking without ever clearing wholesale
    d._evict_render_memo()
    assert 0 < len(d._render_memo) < 1000 - drop


def test_memo_cell_eviction_threshold_respected():
    """End-to-end: crossing the cap evicts a chunk instead of clearing."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from tests.render_corpus import corpus, resources, review_of

    c = Client(driver=TpuDriver())
    name, t, k, _tier = corpus()[0]
    c.add_template(t)
    c.add_constraint(k)
    for obj in resources():
        c.add_data(obj)
    d = c.driver
    d.mesh_enabled = False
    d.RENDER_MEMO_MAX = 4
    d.audit_capped(10)  # the capped path populates _render_memo
    assert 0 < len(d._render_memo) <= d.RENDER_MEMO_MAX


# ---- worker pool ------------------------------------------------------------


def test_render_pool_order_and_exceptions():
    pool = rp.RenderPool
    n = max(pool.MIN_CELLS, 20)
    fns = [lambda i=i: i * i for i in range(n)]
    assert pool.map_ordered(fns) == [i * i for i in range(n)]

    def boom():
        raise RuntimeError("cell failed")

    fns[3] = boom
    with pytest.raises(RuntimeError, match="cell failed"):
        pool.map_ordered(fns)
    # below the threshold: serial path, same contract
    assert pool.map_ordered([lambda: 1, lambda: 2]) == [1, 2]


def test_intra_batch_duplicate_cells_evaluate_once():
    """A micro-batch of identical replica pods must evaluate each
    memoable (constraint, content) cell once even though memo stores
    land after the render passes (code-review finding: the deferred
    stores regressed the replica-storm contract)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from tests.render_corpus import corpus, resources, review_of

    c = Client(driver=TpuDriver())
    for _n, t, k, _tier in corpus():
        c.add_template(t)
        c.add_constraint(k)
    d = c.driver
    d.DEVICE_MIN_CELLS = 0
    calls = [0]
    orig = d._eval_cell

    def counting(*a, **k):
        calls[0] += 1
        return orig(*a, **k)

    d._eval_cell = counting
    bad = resources()[0]
    batch = [review_of(bad) for _ in range(8)]
    # large batch: skips the request-memo probe, exercising _render_masked
    d.REQUEST_MEMO_BATCH_MAX = 0
    outs = d.review_batch(batch)
    per_review = [[(r.msg, r.metadata) for r in o[0]] for o in outs]
    assert all(pr == per_review[0] for pr in per_review)
    n_memoable_constraints = sum(
        1 for kind in d.constraints for name in d.constraints[kind]
        if (kind, name) not in d._memoable_false
    )
    # each memoable flagged cell evaluated at most once for 8 identical
    # reviews; only non-memoable cells may repeat
    assert calls[0] <= n_memoable_constraints + 8 * (
        sum(len(v) for v in d.constraints.values())
        - n_memoable_constraints
    )


def test_snapshot_persists_plan_tiers_and_validates_on_restore(tmp_path):
    """The sweep basis carries the per-constraint plan classification;
    a restore whose rebuilt plans classify differently drops the
    persisted render cache (results from a different tier must not be
    replayed) while keeping the rest of the warm basis."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.kube.inmem import InMemoryKube
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.snapshot import SnapshotLoader, Snapshotter
    from tests.render_corpus import corpus, resources

    def fresh():
        c = Client(driver=TpuDriver())
        c.driver.mesh_enabled = False
        return c

    kube = InMemoryKube()
    for obj in resources():
        kube.create(obj)
    snap_dir = str(tmp_path / "snaps")
    c1 = fresh()
    name, t, k, _tier = corpus()[0]
    c1.add_template(t)
    c1.add_constraint(k)
    for obj in kube.list(("", "v1", "Pod")):
        c1.add_data(obj)
    res1, _tot = c1.audit_capped(20)
    path = Snapshotter(c1, snap_dir, interval_s=0.0).write_once()
    assert path is not None

    # matching classification: warm basis restores WITH its render cache
    c2 = fresh()
    loader = SnapshotLoader(snap_dir)
    assert loader.restore(c2, kube) == "restored"
    assert loader.delta_restored
    assert c2.driver._delta_state.render_cache  # persisted results kept
    res2, _ = c2.audit_capped(20)
    assert sorted((r.msg for r in res2.results())) == sorted(
        r.msg for r in res1.results()
    )

    # diverging classification (plans disabled -> everything interp):
    # the cache is dropped, the audit still renders identically
    c3 = fresh()
    c3.driver.render_plan_enabled = False
    loader3 = SnapshotLoader(snap_dir)
    assert loader3.restore(c3, kube) == "restored"
    assert loader3.delta_restored
    assert c3.driver._delta_state.render_cache == {}
    res3, _ = c3.audit_capped(20)
    assert sorted(r.msg for r in res3.results()) == sorted(
        r.msg for r in res1.results()
    )


def test_interp_tail_through_pool_matches_serial(monkeypatch):
    """The pooled interp tail must produce identical results to the
    serial loop (ordering is by cell, not completion)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from tests.render_corpus import corpus, resources, review_of

    def mk():
        c = Client(driver=TpuDriver())
        for _n, t, k, _tier in corpus():
            c.add_template(t)
            c.add_constraint(k)
        c.driver.DEVICE_MIN_CELLS = 0
        return c

    a, b = mk(), mk()
    monkeypatch.setattr(rp.RenderPool, "MIN_CELLS", 1)  # force pooling
    outs_pooled = [
        [(r.msg, r.metadata) for r in a.review(review_of(o)).results()]
        for o in resources()
    ]
    monkeypatch.setattr(rp.RenderPool, "MIN_CELLS", 10**9)  # force serial
    outs_serial = [
        [(r.msg, r.metadata) for r in b.review(review_of(o)).results()]
        for o in resources()
    ]
    assert outs_pooled == outs_serial
