"""Controller tests (reference parity: pkg/controller/* envtest scenarios —
SURVEY.md section 4 tier 2, with InMemoryKube playing envtest's API server)."""

import time

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.controllers import Dependencies, Manager
from gatekeeper_tpu.controllers.constraint import ConstraintsCache
from gatekeeper_tpu.kube.inmem import InMemoryKube, NotFound
from gatekeeper_tpu.operations import Operations
from gatekeeper_tpu.process.excluder import Excluder
from gatekeeper_tpu.readiness.tracker import Tracker

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8srequiredlabels"},
    "spec": {
        "crd": {
            "spec": {
                "names": {"kind": "K8sRequiredLabels"},
                "validation": {
                    "openAPIV3Schema": {
                        "properties": {
                            "labels": {"type": "array", "items": {"type": "string"}}
                        }
                    }
                },
            }
        },
        "targets": [
            {
                "target": "admission.k8s.gatekeeper.sh",
                "rego": """
package k8srequiredlabels

violation[{"msg": msg, "details": {"missing_labels": missing}}] {
  provided := {label | input.review.object.metadata.labels[label]}
  required := {label | label := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("you must provide labels: %v", [missing])
}
""",
            }
        ],
    },
}

BAD_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sbadrego"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sBadRego"}}},
        "targets": [
            {"target": "admission.k8s.gatekeeper.sh", "rego": "this is not rego"}
        ],
    },
}

CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sRequiredLabels",
    "metadata": {"name": "ns-must-have-gk"},
    "spec": {
        "match": {"kinds": [{"apiGroups": [""], "kinds": ["Namespace"]}]},
        "parameters": {"labels": ["gatekeeper"]},
    },
}

CRD_GVK = ("apiextensions.k8s.io", "v1", "CustomResourceDefinition")
CPS_GVK = ("status.gatekeeper.sh", "v1beta1", "ConstraintPodStatus")
CTPS_GVK = ("status.gatekeeper.sh", "v1beta1", "ConstraintTemplatePodStatus")
TEMPLATES_GVK = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CGVK = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")


def make_manager(kube=None, operations=None):
    kube = kube or InMemoryKube()
    client = Client()
    deps = Dependencies(
        kube=kube,
        client=client,
        excluder=Excluder(),
        tracker=Tracker(),
        operations=operations or Operations(),
        pod_id="pod-1",
    )
    return Manager(deps), kube, client, deps


class TestTemplateLifecycle:
    def test_template_ingestion(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            # engine has the template
            assert client.templates() == ["K8sRequiredLabels"]
            # constraint CRD created with owner-ref
            crd = kube.get(CRD_GVK, "k8srequiredlabels.constraints.gatekeeper.sh")
            assert crd["metadata"]["ownerReferences"][0]["name"] == "k8srequiredlabels"
            # pod status written, no errors
            sts = kube.list(CTPS_GVK, "gatekeeper-system")
            assert len(sts) == 1 and sts[0]["status"]["errors"] == []
            # constraint kind is now watched
            assert mgr.constraint.registrar.watched().contains(CGVK)
        finally:
            mgr.stop()

    def test_bad_template_records_error_status(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(BAD_TEMPLATE))
            assert mgr.drain()
            assert client.templates() == []
            sts = kube.list(CTPS_GVK, "gatekeeper-system")
            assert len(sts) == 1
            assert sts[0]["status"]["errors"]
            assert "k8sbadrego" in sts[0]["metadata"]["name"]
        finally:
            mgr.stop()

    def test_template_delete_unwinds(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            kube.delete(TEMPLATES_GVK, "k8srequiredlabels")
            assert mgr.drain()
            time.sleep(0.1)
            assert client.templates() == []
            assert not mgr.constraint.registrar.watched().contains(CGVK)
            with __import__("pytest").raises(NotFound):
                kube.get(CRD_GVK, "k8srequiredlabels.constraints.gatekeeper.sh")
            assert kube.list(CTPS_GVK, "gatekeeper-system") == []
        finally:
            mgr.stop()


class TestConstraintLifecycle:
    def test_constraint_flows_through_dynamic_watch(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.1)
            # engine evaluates it
            res = client.review(
                {
                    "kind": {"group": "", "version": "v1", "kind": "Namespace"},
                    "name": "ns1",
                    "operation": "CREATE",
                    "object": {
                        "apiVersion": "v1",
                        "kind": "Namespace",
                        "metadata": {"name": "ns1"},
                    },
                }
            ).results()
            assert len(res) == 1 and "gatekeeper" in res[0].msg
            # pod status enforced
            sts = kube.list(CPS_GVK, "gatekeeper-system")
            assert len(sts) == 1 and sts[0]["status"]["enforced"]
        finally:
            mgr.stop()

    def test_invalid_constraint_records_error(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            bad = {
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": "K8sRequiredLabels",
                "metadata": {"name": "bad-params"},
                "spec": {"parameters": {"labels": "not-an-array"}},
            }
            kube.create(bad)
            assert mgr.drain()
            time.sleep(0.1)
            sts = kube.list(CPS_GVK, "gatekeeper-system")
            assert len(sts) == 1 and sts[0]["status"]["errors"]
        finally:
            mgr.stop()

    def test_constraint_delete(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.1)
            kube.delete(CGVK, "ns-must-have-gk")
            assert mgr.drain()
            time.sleep(0.1)
            res = client.review(
                {
                    "kind": {"group": "", "version": "v1", "kind": "Namespace"},
                    "name": "ns1",
                    "object": {"apiVersion": "v1", "kind": "Namespace",
                               "metadata": {"name": "ns1"}},
                }
            ).results()
            assert res == []
            assert kube.list(CPS_GVK, "gatekeeper-system") == []
        finally:
            mgr.stop()


class TestConfigAndSync:
    CONFIG = {
        "apiVersion": "config.gatekeeper.sh/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "config", "namespace": "gatekeeper-system"},
        "spec": {
            "sync": {"syncOnly": [{"group": "", "version": "v1", "kind": "Pod"}]},
            "match": [{"excludedNamespaces": ["kube-system"], "processes": ["*"]}],
        },
    }
    POD_GVK = ("", "v1", "Pod")

    def pod(self, name, ns):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": ns}}

    def test_sync_replication(self):
        mgr, kube, client, deps = make_manager()
        kube.create(self.pod("pre", "default"))  # pre-existing: replay path
        mgr.start()
        try:
            kube.create(dict(self.CONFIG))
            assert mgr.drain()
            time.sleep(0.15)
            kube.create(self.pod("live", "default"))  # steady-state path
            assert mgr.drain()
            time.sleep(0.1)
            dump = client.dump()
            assert "pre" in dump and "live" in dump
        finally:
            mgr.stop()

    def test_excluded_namespace_not_synced(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(self.CONFIG))
            assert mgr.drain()
            time.sleep(0.1)
            kube.create(self.pod("secret", "kube-system"))
            assert mgr.drain()
            time.sleep(0.1)
            assert "secret" not in client.dump()
            assert deps.excluder.is_namespace_excluded("audit", "kube-system")
        finally:
            mgr.stop()

    def test_sync_set_shrink_wipes(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(self.CONFIG))
            assert mgr.drain()
            time.sleep(0.1)
            kube.create(self.pod("p1", "default"))
            assert mgr.drain()
            time.sleep(0.1)
            assert "p1" in client.dump()
            cfg = kube.get(("config.gatekeeper.sh", "v1alpha1", "Config"),
                           "config", "gatekeeper-system")
            cfg["spec"]["sync"]["syncOnly"] = []
            kube.update(cfg)
            assert mgr.drain()
            time.sleep(0.15)
            assert "p1" not in client.dump()
            # late pod events for the removed GVK are dropped
            kube.create(self.pod("p2", "default"))
            assert mgr.drain()
            time.sleep(0.1)
            assert "p2" not in client.dump()
        finally:
            mgr.stop()


class TestStatusAggregation:
    def test_by_pod_fold(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.2)
            # our pod's status folded into the parent constraint
            parent = kube.get(CGVK, "ns-must-have-gk")
            by_pod = (parent.get("status") or {}).get("byPod") or []
            assert [s["id"] for s in by_pod] == ["pod-1"]
            # a second pod's status joins the fold, sorted by id
            other = {
                "apiVersion": "status.gatekeeper.sh/v1beta1",
                "kind": "ConstraintPodStatus",
                "metadata": {
                    "name": "pod--0-k8srequiredlabels-ns--must--have--gk",
                    "namespace": "gatekeeper-system",
                    "labels": {
                        "internal.gatekeeper.sh/constraint-name": "ns-must-have-gk",
                        "internal.gatekeeper.sh/constraint-kind": "K8sRequiredLabels",
                        "internal.gatekeeper.sh/pod": "pod-0",
                    },
                },
                "status": {"id": "pod-0", "enforced": True, "errors": []},
            }
            kube.create(other)
            assert mgr.drain()
            time.sleep(0.2)
            parent = kube.get(CGVK, "ns-must-have-gk")
            assert [s["id"] for s in parent["status"]["byPod"]] == ["pod-0", "pod-1"]
        finally:
            mgr.stop()

    def test_template_status_created_flag(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            time.sleep(0.2)
            t = kube.get(TEMPLATES_GVK, "k8srequiredlabels")
            assert t["status"]["created"] is True
            assert [s["id"] for s in t["status"]["byPod"]] == ["pod-1"]
        finally:
            mgr.stop()

    def test_uid_drift_dropped(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            c = dict(CONSTRAINT)
            kube.create(c)
            assert mgr.drain()
            time.sleep(0.2)
            # recreate the constraint: new UID; stale status must not fold
            kube.delete(CGVK, "ns-must-have-gk")
            assert mgr.drain()
            time.sleep(0.1)
            stale = {
                "apiVersion": "status.gatekeeper.sh/v1beta1",
                "kind": "ConstraintPodStatus",
                "metadata": {
                    "name": "pod--9-k8srequiredlabels-ns--must--have--gk",
                    "namespace": "gatekeeper-system",
                    "labels": {
                        "internal.gatekeeper.sh/constraint-name": "ns-must-have-gk",
                        "internal.gatekeeper.sh/constraint-kind": "K8sRequiredLabels",
                        "internal.gatekeeper.sh/pod": "pod-9",
                    },
                },
                "status": {"id": "pod-9", "constraintUID": "stale-uid", "enforced": True},
            }
            kube.create(stale)
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.2)
            parent = kube.get(CGVK, "ns-must-have-gk")
            ids = [s["id"] for s in parent["status"]["byPod"]]
            assert "pod-9" not in ids and "pod-1" in ids
        finally:
            mgr.stop()


class TestReadinessIntegration:
    def test_startup_gate(self):
        kube = InMemoryKube()
        kube.create(dict(TEMPLATE))
        kube.create(dict(CONSTRAINT))
        mgr, kube, client, deps = make_manager(kube=kube)
        deps.tracker.run(kube)
        assert not deps.tracker.satisfied()
        mgr.start()
        try:
            assert deps.tracker.wait_satisfied(timeout=5.0)
        finally:
            mgr.stop()


class TestConstraintsCache:
    def test_totals(self):
        c = ConstraintsCache()
        c.add("K", "a", "deny", "active")
        c.add("K", "b", "deny", "active")
        c.add("K", "c", "dryrun", "error")
        assert c.totals() == {("deny", "active"): 2, ("dryrun", "error"): 1}
        c.remove("K", "b")
        assert c.totals()[("deny", "active")] == 1


class TestConvergence:
    def test_write_back_loops_converge(self):
        """Regression: status aggregation + parent controllers must not form
        an infinite reconcile feedback loop (no-op updates emit no events)."""
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.5)
            rv1 = kube.get(CGVK, "ns-must-have-gk")["metadata"]["resourceVersion"]
            time.sleep(0.5)
            rv2 = kube.get(CGVK, "ns-must-have-gk")["metadata"]["resourceVersion"]
            assert rv1 == rv2, f"constraint still churning: {rv1} -> {rv2}"
            trv1 = kube.get(TEMPLATES_GVK, "k8srequiredlabels")["metadata"]["resourceVersion"]
            time.sleep(0.3)
            trv2 = kube.get(TEMPLATES_GVK, "k8srequiredlabels")["metadata"]["resourceVersion"]
            assert trv1 == trv2
        finally:
            mgr.stop()


class TestReadinessRegression:
    def test_cancel_template_cancels_constraint_kind(self):
        from gatekeeper_tpu.readiness.tracker import Tracker

        kube = InMemoryKube()
        kube.create(dict(TEMPLATE))
        kube.create(dict(CONSTRAINT))
        tr = Tracker()
        tr.run(kube)
        assert not tr.satisfied()
        # template deleted before its constraints were observed
        tr.for_gvk(TEMPLATES_GVK).observe({"metadata": {"name": "other"}})
        tr.cancel_template(kube.get(TEMPLATES_GVK, "k8srequiredlabels"))
        assert tr.satisfied()

    def test_late_tracker_born_populated(self):
        from gatekeeper_tpu.readiness.tracker import Tracker

        tr = Tracker()
        tr.run(InMemoryKube())
        # a kind appearing after seeding must not block readiness
        late = tr.for_gvk(("constraints.gatekeeper.sh", "v1beta1", "K8sLate"))
        assert late.populated
        data = tr.for_data(("", "v1", "Secret"))
        assert data.populated
        assert tr.satisfied()


class TestSyncPrune:
    def test_counts_pruned_on_sync_set_shrink(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TestConfigAndSync.CONFIG))
            assert mgr.drain()
            time.sleep(0.1)
            kube.create({"apiVersion": "v1", "kind": "Pod",
                         "metadata": {"name": "p1", "namespace": "default"}})
            assert mgr.drain()
            time.sleep(0.1)
            assert mgr.sync.counts() == {("", "v1", "Pod"): 1}
            cfg = kube.get(("config.gatekeeper.sh", "v1alpha1", "Config"),
                           "config", "gatekeeper-system")
            cfg["spec"]["sync"]["syncOnly"] = []
            kube.update(cfg)
            assert mgr.drain()
            time.sleep(0.15)
            assert mgr.sync.counts() == {}
        finally:
            mgr.stop()


class TestReadinessRegressions:
    """Deadlock scenarios from review: excluded/deleted/mis-named objects
    must not block readiness forever."""

    def _config(self, sync_only, match=None):
        return {
            "apiVersion": "config.gatekeeper.sh/v1alpha1",
            "kind": "Config",
            "metadata": {"name": "config", "namespace": "gatekeeper-system"},
            "spec": {
                "sync": {"syncOnly": sync_only},
                "match": match or [],
            },
        }

    def test_excluded_namespace_objects_do_not_block_readiness(self):
        kube = InMemoryKube()
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p1", "namespace": "kube-system"},
        })
        kube.create(self._config(
            [{"group": "", "version": "v1", "kind": "Pod"}],
            match=[{"excludedNamespaces": ["kube-system"],
                    "processes": ["sync"]}],
        ))
        mgr, kube, client, deps = make_manager(kube=kube)
        deps.tracker.run(kube)
        mgr.start()
        try:
            assert mgr.drain()
            assert deps.tracker.wait_satisfied(timeout=5.0)
        finally:
            mgr.stop()

    def test_non_singleton_config_name_not_expected(self):
        kube = InMemoryKube()
        kube.create({
            "apiVersion": "config.gatekeeper.sh/v1alpha1",
            "kind": "Config",
            "metadata": {"name": "not-the-singleton",
                         "namespace": "gatekeeper-system"},
            "spec": {},
        })
        mgr, kube, client, deps = make_manager(kube=kube)
        deps.tracker.run(kube)
        mgr.start()
        try:
            assert mgr.drain()
            assert deps.tracker.wait_satisfied(timeout=5.0)
        finally:
            mgr.stop()

    def test_object_deleted_before_watch_start_is_collected(self):
        kube = InMemoryKube()
        kube.create(dict(TEMPLATE))
        mgr, kube, client, deps = make_manager(kube=kube)
        deps.tracker.run(kube)  # template now expected
        # deleted before any watch exists: no tombstone will ever arrive
        kube.delete(TEMPLATES_GVK, "k8srequiredlabels")
        mgr.start()  # start() runs tracker.collect(kube)
        try:
            assert mgr.drain()
            assert deps.tracker.wait_satisfied(timeout=5.0)
        finally:
            mgr.stop()

    def test_status_write_back_does_not_clobber_spec(self):
        mgr, kube, client, deps = make_manager()
        mgr.start()
        try:
            kube.create(dict(TEMPLATE))
            assert mgr.drain()
            kube.create(dict(CONSTRAINT))
            assert mgr.drain()
            time.sleep(0.2)
            # spec survived the status controller's parent write-backs
            live = kube.get(CGVK, "ns-must-have-gk")
            assert live["spec"]["parameters"] == {"labels": ["gatekeeper"]}
            assert live.get("status", {}).get("byPod")
        finally:
            mgr.stop()
