"""Parser coverage: the whole reference policy corpus must parse; known-bad
fixtures must be rejected (mirroring the reference's demo/basic/bad/ intent)."""

import pytest

from gatekeeper_tpu.rego import RegoParseError, parse_module
from gatekeeper_tpu.rego.ast import (
    ArrayCompr,
    BinOp,
    Call,
    ObjectCompr,
    Ref,
    Rule,
    Scalar,
    SetCompr,
    Var,
)

from .corpus import constraint_templates, template_rego


def test_corpus_parses():
    count = 0
    for path, tmpl in constraint_templates():
        rego, libs = template_rego(tmpl)
        m = parse_module(rego)
        assert m.package, path
        assert any(r.name == "violation" for r in m.rules), path
        for lib in libs:
            parse_module(lib)
        count += 1
    assert count >= 15  # demo + bats + psp fixtures


def test_bad_template_rejected():
    # demo/basic/bad/bad_template.yaml drops the '{' after the violation head.
    src = """
package k8sbad

violation[{"msg": msg}]
  msg := "nope"
"""
    with pytest.raises(RegoParseError):
        parse_module(src)


def test_multi_clause_functions_and_literal_args():
    m = parse_module(
        """
package p

mem_multiple("Ki") = 1024000 { true }
mem_multiple("") = 1000 { true }

f(x) = y { y := x * 2 }
"""
    )
    mm = m.rules_named("mem_multiple")
    assert len(mm) == 2
    assert isinstance(mm[0].args[0], Scalar)
    f = m.rules_named("f")[0]
    assert f.is_function and isinstance(f.args[0], Var)


def test_comprehensions_and_set_union_disambiguation():
    m = parse_module(
        """
package p

r {
  provided := {label | input.object.labels[label]}
  arr := [good | good := input.items[_]]
  obj := {k: v | v := input.m[k]}
  u := provided | {"extra"}
  count(u) > 0
}
"""
    )
    body = m.rules[0].body
    assert isinstance(body[0].terms[1], SetCompr)
    assert isinstance(body[1].terms[1], ArrayCompr)
    assert isinstance(body[2].terms[1], ObjectCompr)
    assert isinstance(body[3].terms[1], BinOp) and body[3].terms[1].op == "|"


def test_refs_calls_and_wildcards():
    m = parse_module(
        """
package p

violation[{"msg": m}] {
  c := input.review.object.spec.containers[_]
  hostPort := input_containers[_].ports[_].hostPort
  x := data.inventory.namespace[ns][api]["Ingress"][name]
  y := array.concat([], [1])
  m := sprintf("%v", [c])
}
"""
    )
    stmts = m.rules[0].body
    ref = stmts[0].terms[1]
    assert isinstance(ref, Ref)
    assert ref.operands[-1].name.startswith("$wild")
    call = stmts[3].terms[1]
    assert isinstance(call, Call) and call.path == ("array", "concat")


def test_partial_set_rules_and_defaults():
    m = parse_module(
        """
package p

default allow = false

input_containers[c] { c := input.spec.containers[_] }
input_containers[c] { c := input.spec.initContainers[_] }
"""
    )
    assert m.rules_named("allow")[0].is_default
    ics = m.rules_named("input_containers")
    assert len(ics) == 2 and all(r.is_partial_set for r in ics)


def test_negation_of_comparison():
    m = parse_module(
        """
package p

r { not allowedHostPath.readOnly == true }
"""
    )
    e = m.rules[0].body[0]
    assert e.kind == "not"
    inner = e.terms[0]
    assert inner.kind == "term" and isinstance(inner.terms[0], BinOp)


def test_rule_requires_body_or_value():
    with pytest.raises(RegoParseError):
        parse_module("package p\n\nviolation[x]\n")


def test_import_alias_rewrites_refs_and_calls():
    # `import data.lib.helpers` binds `helpers` (OPA resolves import aliases
    # at compile time; vendored opa/ast); we rewrite to qualified refs.
    m = parse_module(
        """
package p
import data.lib.helpers

v { helpers.missing(input.x, "cpu") }
w { y := helpers.limits; y > 0 }
"""
    )
    call = m.rules_named("v")[0].body[0].terms[0]
    assert isinstance(call, Call)
    assert call.path == ("data", "lib", "helpers", "missing")
    ref = m.rules_named("w")[0].body[0].terms[1]
    assert isinstance(ref, Ref) and ref.head.name == "data"


def test_import_as_alias():
    m = parse_module(
        """
package p
import data.lib.kubernetes.pods as podlib

v { podlib.is_pod(input) }
"""
    )
    call = m.rules[0].body[0].terms[0]
    assert call.path == ("data", "lib", "kubernetes", "pods", "is_pod")


def test_import_must_target_data_or_input():
    with pytest.raises(RegoParseError):
        parse_module("package p\nimport foo.bar\n\nv { true }\n")


def test_else_chain_parses():
    m = parse_module(
        """
package p

x = 1 { input.a } else = 2 { input.b } else = 3 { true }
"""
    )
    r = m.rules[0]
    assert r.value.value == 1
    assert r.els is not None and r.els.value.value == 2
    assert r.els.els is not None and r.els.els.value.value == 3
    assert r.els.els.els is None


def test_else_invalid_on_partial_rules():
    with pytest.raises(RegoParseError):
        parse_module("package p\n\nv[x] { x := 1 } else { true }\n")


def test_import_shadowing_rejected():
    # OPA: 'variables must not shadow import' — silent rewrite would
    # mis-evaluate these instead of erroring.
    with pytest.raises(RegoParseError):
        parse_module(
            "package p\nimport data.lib.helpers\n\nv { helpers := 5; helpers > 3 }\n"
        )
    with pytest.raises(RegoParseError):
        parse_module(
            "package p\nimport data.lib.helpers\n\nv { some helpers; input.x[helpers] }\n"
        )
    with pytest.raises(RegoParseError):
        parse_module(
            "package p\nimport data.lib.helpers\n\nf(helpers) = 1 { true }\n"
        )
    with pytest.raises(RegoParseError):
        parse_module(
            "package p\nimport data.lib.helpers\n\nhelpers { true }\n"
        )


def test_duplicate_import_alias_rejected():
    with pytest.raises(RegoParseError):
        parse_module(
            "package p\nimport data.lib.alpha.helpers\nimport data.lib.beta.helpers\n\nv { true }\n"
        )
    # distinct aliases for the same-leaf packages are fine
    m = parse_module(
        "package p\nimport data.lib.alpha.helpers\nimport data.lib.beta.helpers as bh\n\nv { bh.f(1); helpers.g(2) }\n"
    )
    calls = [e.terms[0] for e in m.rules[0].body]
    assert {c.path for c in calls} == {
        ("data", "lib", "beta", "helpers", "f"),
        ("data", "lib", "alpha", "helpers", "g"),
    }
