"""Parser coverage: the whole reference policy corpus must parse; known-bad
fixtures must be rejected (mirroring the reference's demo/basic/bad/ intent)."""

import pytest

from gatekeeper_tpu.rego import RegoParseError, parse_module
from gatekeeper_tpu.rego.ast import (
    ArrayCompr,
    BinOp,
    Call,
    ObjectCompr,
    Ref,
    Rule,
    Scalar,
    SetCompr,
    Var,
)

from .corpus import constraint_templates, template_rego


def test_corpus_parses():
    count = 0
    for path, tmpl in constraint_templates():
        rego, libs = template_rego(tmpl)
        m = parse_module(rego)
        assert m.package, path
        assert any(r.name == "violation" for r in m.rules), path
        for lib in libs:
            parse_module(lib)
        count += 1
    assert count >= 15  # demo + bats + psp fixtures


def test_bad_template_rejected():
    # demo/basic/bad/bad_template.yaml drops the '{' after the violation head.
    src = """
package k8sbad

violation[{"msg": msg}]
  msg := "nope"
"""
    with pytest.raises(RegoParseError):
        parse_module(src)


def test_multi_clause_functions_and_literal_args():
    m = parse_module(
        """
package p

mem_multiple("Ki") = 1024000 { true }
mem_multiple("") = 1000 { true }

f(x) = y { y := x * 2 }
"""
    )
    mm = m.rules_named("mem_multiple")
    assert len(mm) == 2
    assert isinstance(mm[0].args[0], Scalar)
    f = m.rules_named("f")[0]
    assert f.is_function and isinstance(f.args[0], Var)


def test_comprehensions_and_set_union_disambiguation():
    m = parse_module(
        """
package p

r {
  provided := {label | input.object.labels[label]}
  arr := [good | good := input.items[_]]
  obj := {k: v | v := input.m[k]}
  u := provided | {"extra"}
  count(u) > 0
}
"""
    )
    body = m.rules[0].body
    assert isinstance(body[0].terms[1], SetCompr)
    assert isinstance(body[1].terms[1], ArrayCompr)
    assert isinstance(body[2].terms[1], ObjectCompr)
    assert isinstance(body[3].terms[1], BinOp) and body[3].terms[1].op == "|"


def test_refs_calls_and_wildcards():
    m = parse_module(
        """
package p

violation[{"msg": m}] {
  c := input.review.object.spec.containers[_]
  hostPort := input_containers[_].ports[_].hostPort
  x := data.inventory.namespace[ns][api]["Ingress"][name]
  y := array.concat([], [1])
  m := sprintf("%v", [c])
}
"""
    )
    stmts = m.rules[0].body
    ref = stmts[0].terms[1]
    assert isinstance(ref, Ref)
    assert ref.operands[-1].name.startswith("$wild")
    call = stmts[3].terms[1]
    assert isinstance(call, Call) and call.path == ("array", "concat")


def test_partial_set_rules_and_defaults():
    m = parse_module(
        """
package p

default allow = false

input_containers[c] { c := input.spec.containers[_] }
input_containers[c] { c := input.spec.initContainers[_] }
"""
    )
    assert m.rules_named("allow")[0].is_default
    ics = m.rules_named("input_containers")
    assert len(ics) == 2 and all(r.is_partial_set for r in ics)


def test_negation_of_comparison():
    m = parse_module(
        """
package p

r { not allowedHostPath.readOnly == true }
"""
    )
    e = m.rules[0].body[0]
    assert e.kind == "not"
    inner = e.terms[0]
    assert inner.kind == "term" and isinstance(inner.terms[0], BinOp)


def test_rule_requires_body_or_value():
    with pytest.raises(RegoParseError):
        parse_module("package p\n\nviolation[x]\n")
