"""Watch manager + readiness tracker tests (reference parity:
pkg/watch/manager_test.go + manager_integration_test.go scenarios,
pkg/readiness/object_tracker_test.go + ready_tracker_test.go)."""

import queue
import time

import pytest

from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.readiness.tracker import (
    TEMPLATES_GVK,
    ObjectTracker,
    Tracker,
)
from gatekeeper_tpu.watch.manager import ControllerSwitch, WatchManager
from gatekeeper_tpu.watch.set import GVKSet

POD = ("", "v1", "Pod")
NS = ("", "v1", "Namespace")


def mkobj(gvk, name, ns=""):
    g, v, k = gvk
    api = v if not g else f"{g}/{v}"
    obj = {"apiVersion": api, "kind": k, "metadata": {"name": name}}
    if ns:
        obj["metadata"]["namespace"] = ns
    return obj


def drain(r, n, timeout=3.0):
    """Collect n events from a registrar queue."""
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        try:
            out.append(r.events.get(timeout=0.1))
        except queue.Empty:
            pass
    return out


class TestGVKSet:
    def test_ops(self):
        a = GVKSet([POD])
        b = GVKSet([POD, NS])
        assert a.union(b).equals(b)
        assert b.difference(a).items() == [NS]
        assert a.intersection(b).items() == [POD]
        a.add(NS)
        assert a.equals(b)
        a.remove(POD)
        assert a.items() == [NS]


class TestControllerSwitch:
    def test_gate(self):
        sw = ControllerSwitch()
        assert sw.enter()
        sw.stop()
        assert not sw.enter()


class TestWatchManager:
    def test_events_fan_out(self):
        kube = InMemoryKube()
        wm = WatchManager(kube)
        r1 = wm.new_registrar("c1")
        r2 = wm.new_registrar("c2")
        r1.add_watch(POD)
        r2.add_watch(POD)
        time.sleep(0.05)
        kube.create(mkobj(POD, "p1", "default"))
        ev1 = drain(r1, 1)
        ev2 = drain(r2, 1)
        assert ev1 and ev1[0][1].type == "ADDED"
        assert ev2 and ev2[0][1].object["metadata"]["name"] == "p1"
        wm.stop()

    def test_replay_to_late_joiner(self):
        # manager_integration_test.go:303 replay scenario
        kube = InMemoryKube()
        kube.create(mkobj(POD, "pre1", "default"))
        kube.create(mkobj(POD, "pre2", "default"))
        wm = WatchManager(kube)
        r = wm.new_registrar("late")
        r.add_watch(POD)
        evs = drain(r, 2)
        names = sorted(e[1].object["metadata"]["name"] for e in evs)
        assert names == ["pre1", "pre2"]
        assert all(e[1].type == "ADDED" for e in evs)
        wm.stop()

    def test_informer_removed_when_last_leaves(self):
        kube = InMemoryKube()
        wm = WatchManager(kube)
        r1 = wm.new_registrar("a")
        r2 = wm.new_registrar("b")
        r1.add_watch(POD)
        r2.add_watch(POD)
        assert wm.watched_gvks().contains(POD)
        r1.remove_watch(POD)
        assert wm.watched_gvks().contains(POD)  # r2 still wants it
        r2.remove_watch(POD)
        assert not wm.watched_gvks().contains(POD)
        wm.stop()

    def test_replace_watch_diffs(self):
        # manager.go:242-277 replaceWatches
        kube = InMemoryKube()
        wm = WatchManager(kube)
        r = wm.new_registrar("c")
        r.add_watch(POD)
        r.replace_watch([NS])
        assert r.watched().items() == [NS]
        assert not wm.watched_gvks().contains(POD)
        wm.stop()

    def test_events_after_replace_only_for_desired(self):
        kube = InMemoryKube()
        wm = WatchManager(kube)
        r = wm.new_registrar("c")
        r.replace_watch([POD, NS])
        time.sleep(0.05)
        kube.create(mkobj(NS, "ns1"))
        evs = drain(r, 1)
        assert evs[0][0] == NS
        r.replace_watch([POD])
        time.sleep(0.05)
        kube.create(mkobj(NS, "ns2"))
        kube.create(mkobj(POD, "p1", "ns1"))
        evs = drain(r, 1)
        assert evs[0][0] == POD
        wm.stop()

    def test_duplicate_registrar_rejected(self):
        wm = WatchManager(InMemoryKube())
        wm.new_registrar("x")
        with pytest.raises(Exception):
            wm.new_registrar("x")
        wm.stop()

    def test_remove_registrar_unwinds_watches(self):
        kube = InMemoryKube()
        wm = WatchManager(kube)
        r = wm.new_registrar("gone")
        r.add_watch(POD)
        wm.remove_registrar("gone")
        assert not wm.watched_gvks().contains(POD)
        wm.stop()


class TestObjectTracker:
    def test_not_satisfied_until_populated(self):
        t = ObjectTracker(POD)
        assert not t.satisfied()
        t.expectations_done()
        assert t.satisfied()  # no expectations -> trivially satisfied

    def test_expect_observe(self):
        t = ObjectTracker(POD)
        o = mkobj(POD, "p1", "default")
        t.expect(o)
        t.expectations_done()
        assert not t.satisfied()
        t.observe(o)
        assert t.satisfied()

    def test_cancel_expect(self):
        # object deleted during startup no longer blocks readiness
        t = ObjectTracker(POD)
        o = mkobj(POD, "p1", "default")
        t.expect(o)
        t.expectations_done()
        t.cancel_expect(o)
        assert t.satisfied()

    def test_try_cancel_threshold(self):
        t = ObjectTracker(POD)
        o = mkobj(POD, "p1", "default")
        t.expect(o)
        t.expectations_done()
        assert not t.try_cancel_expect(o)
        assert not t.try_cancel_expect(o)
        assert t.try_cancel_expect(o)  # third attempt cancels
        assert t.satisfied()

    def test_circuit_breaker(self):
        t = ObjectTracker(POD)
        t.expectations_done()
        assert t.satisfied()
        # post-satisfaction expects are ignored (circuit broken)
        t.expect(mkobj(POD, "p9", "default"))
        assert t.satisfied()


def mktemplate(name, kind):
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": name},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [
                {
                    "target": "admission.k8s.gatekeeper.sh",
                    "rego": "package x\nviolation[{\"msg\": \"m\"}] { 1 > 2 }",
                }
            ],
        },
    }


def mkconstraint(kind, name):
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": name},
        "spec": {},
    }


class TestTracker:
    def test_empty_cluster_satisfied(self):
        tr = Tracker()
        tr.run(InMemoryKube())
        assert tr.satisfied()

    def test_blocks_until_templates_observed(self):
        kube = InMemoryKube()
        kube.create(mktemplate("k8srequiredlabels", "K8sRequiredLabels"))
        tr = Tracker()
        tr.run(kube)
        assert not tr.satisfied()
        tr.for_gvk(TEMPLATES_GVK).observe(
            {"metadata": {"name": "k8srequiredlabels"}}
        )
        assert tr.satisfied()

    def test_blocks_on_constraints(self):
        kube = InMemoryKube()
        kube.create(mktemplate("k8srequiredlabels", "K8sRequiredLabels"))
        cgvk = ("constraints.gatekeeper.sh", "v1beta1", "K8sRequiredLabels")
        kube.create(mkconstraint("K8sRequiredLabels", "must-have"))
        tr = Tracker()
        tr.run(kube)
        tr.for_gvk(TEMPLATES_GVK).observe({"metadata": {"name": "k8srequiredlabels"}})
        assert not tr.satisfied()
        tr.for_gvk(cgvk).observe({"metadata": {"name": "must-have"}})
        assert tr.satisfied()

    def test_blocks_on_config_and_data(self):
        kube = InMemoryKube()
        kube.create(
            {
                "apiVersion": "config.gatekeeper.sh/v1alpha1",
                "kind": "Config",
                "metadata": {"name": "config", "namespace": "gatekeeper-system"},
                "spec": {"sync": {"syncOnly": [{"group": "", "version": "v1", "kind": "Pod"}]}},
            }
        )
        kube.create(mkobj(POD, "p1", "default"))
        tr = Tracker()
        tr.run(kube)
        assert not tr.satisfied()
        tr.config.observe({"metadata": {"name": "config", "namespace": "gatekeeper-system"}})
        assert not tr.satisfied()
        tr.for_data(POD).observe(mkobj(POD, "p1", "default"))
        assert tr.satisfied()

    def test_satisfaction_is_sticky(self):
        tr = Tracker()
        tr.run(InMemoryKube())
        assert tr.satisfied()
        # new expectations after satisfaction do not un-ready the pod
        tr.templates.expect({"metadata": {"name": "late"}})
        assert tr.satisfied()
