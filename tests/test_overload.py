"""Overload robustness at the webhook layer (ISSUE 12): end-to-end
deadline derivation (configured budget x AdmissionReview timeoutSeconds
x forwarded wire budget — min() semantics pinned), the micro-batcher's
bounded pending queue with dry-run-first shedding, and the explicit
fail-open/closed shed decision.  Front-door-side overload behavior:
tests/test_frontdoor.py TestOverloadPlane; ladder: tests/test_brownout.py.
"""

import json
import threading
import time
import urllib.request

import pytest

from gatekeeper_tpu import deadline as dl
from gatekeeper_tpu.deadline import OverloadShed
from gatekeeper_tpu.kube.inmem import InMemoryKube
from gatekeeper_tpu.webhook import (
    MicroBatcher,
    ValidationHandler,
    WebhookServer,
)
from gatekeeper_tpu.webhook.policy import (
    FAIL_OPEN_ANNOTATION,
    FAIL_OPEN_SHED,
    SHED_CODE,
    SHED_MESSAGE,
    AdmissionResponse,
)


def _review(name, **extra):
    req = {
        "uid": f"uid-{name}",
        "kind": {"group": "", "version": "v1", "kind": "Namespace"},
        "name": name,
        "namespace": "",
        "operation": "CREATE",
        "userInfo": {"username": "alice"},
        "object": {"apiVersion": "v1", "kind": "Namespace",
                   "metadata": {"name": name, "labels": {}}},
    }
    req.update(extra)
    return req


class _RecordingHandler:
    """Stands in for ValidationHandler: records the deadline budget each
    request carried into handle() — the observable the min() semantics
    are pinned against."""

    def __init__(self):
        self.remaining = []

    def handle(self, req):
        self.remaining.append(dl.remaining())
        return AdmissionResponse(True, "")


def _post(port, payload, headers=None):
    body = json.dumps(payload).encode()
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/admit", data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


class TestBudgetDerivation:
    """The satellite: request.timeoutSeconds enters the budget via
    min(), and the forwarded X-GK-Deadline-Ms wire budget likewise —
    each observed as deadline.remaining() inside handle()."""

    def _serve(self, budget_s=None):
        handler = _RecordingHandler()
        srv = WebhookServer(handler, port=0, deadline_budget_s=budget_s)
        srv.start()
        return srv, handler

    def test_timeout_seconds_smaller_than_configured_wins(self):
        srv, handler = self._serve(budget_s=30.0)
        try:
            _post(srv.port, {"request": _review("a", timeoutSeconds=2)})
            rem = handler.remaining[-1]
            assert rem is not None and 1.5 < rem <= 2.0
        finally:
            srv.stop()

    def test_configured_smaller_than_timeout_seconds_wins(self):
        srv, handler = self._serve(budget_s=0.5)
        try:
            _post(srv.port, {"request": _review("b", timeoutSeconds=10)})
            rem = handler.remaining[-1]
            assert rem is not None and 0.3 < rem <= 0.5
        finally:
            srv.stop()

    def test_timeout_seconds_alone_sets_the_budget(self):
        # a caller-stamped timeoutSeconds budgets the request even with
        # no --admission-deadline-budget-ms configured
        srv, handler = self._serve(budget_s=None)
        try:
            _post(srv.port, {"request": _review("c", timeoutSeconds=3)})
            rem = handler.remaining[-1]
            assert rem is not None and 2.5 < rem <= 3.0
        finally:
            srv.stop()

    def test_wire_header_carries_the_remaining_budget(self):
        srv, handler = self._serve(budget_s=30.0)
        try:
            _post(srv.port, {"request": _review("d")},
                  headers={dl.DEADLINE_HEADER: "250"})
            rem = handler.remaining[-1]
            assert rem is not None and 0.1 < rem <= 0.25
        finally:
            srv.stop()

    def test_min_over_all_three_sources(self):
        srv, handler = self._serve(budget_s=5.0)
        try:
            _post(srv.port,
                  {"request": _review("e", timeoutSeconds=10)},
                  headers={dl.DEADLINE_HEADER: "120"})
            rem = handler.remaining[-1]
            assert rem is not None and rem <= 0.12
        finally:
            srv.stop()

    def test_malformed_header_carries_no_bound(self):
        srv, handler = self._serve(budget_s=None)
        try:
            _post(srv.port, {"request": _review("f")},
                  headers={dl.DEADLINE_HEADER: "whenever"})
            assert handler.remaining[-1] is None
        finally:
            srv.stop()

    def test_no_bound_from_any_source_means_no_deadline(self):
        srv, handler = self._serve(budget_s=None)
        try:
            _post(srv.port, {"request": _review("g")})
            assert handler.remaining[-1] is None
        finally:
            srv.stop()

    def test_non_dict_request_answers_explicit_500(self):
        """A non-object "request" value is a malformed envelope: the
        server must answer the explicit 500 AdmissionReview, never drop
        the connection (regression: the budget-derivation restructure
        briefly let it crash the handler after the parse try)."""
        srv, handler = self._serve(budget_s=None)
        try:
            st, out = _post(srv.port, {"request": "bogus"})
            assert st == 200
            assert out["response"]["allowed"] is False
            assert out["response"]["status"]["code"] == 500
            assert handler.remaining == []  # never reached the handler
        finally:
            srv.stop()


class _GatedClient:
    """review/review_batch park on a gate: the batch loop goes busy and
    the pending queue actually fills (the bound only binds while a
    dispatch is in flight — the loop drains the whole queue otherwise)."""

    def __init__(self):
        self.gate = threading.Event()

    def review(self, obj, tracing=False):
        self.gate.wait(10)
        return ("ok", obj)

    def review_batch(self, objs):
        self.gate.wait(10)
        return [("ok", o) for o in objs]


class TestBatcherBound:
    def _saturate(self, mb, reqs):
        """Spawn one caller per request with a small stagger; returns
        (results, errors) dicts keyed by uid after all joined."""
        out, errs, threads = {}, {}, []

        def call(req):
            try:
                out[req["uid"]] = mb.review(req)
            except Exception as e:
                errs[req["uid"]] = e

        for req in reqs:
            t = threading.Thread(target=call, args=(req,))
            t.start()
            threads.append(t)
            time.sleep(0.03)  # deterministic arrival order
        return out, errs, threads

    def test_queue_full_sheds_and_dryrun_preempted(self):
        client = _GatedClient()
        mb = MicroBatcher(client, adaptive=False, max_pending=2)
        try:
            reqs = [
                {"uid": "inline"},                    # inline, gated
                {"uid": "busy"},                      # dispatched, gated
                {"uid": "dry-old", "dryRun": True},   # queued 1/2
                {"uid": "enf-1"},                     # queued 2/2 (bound)
                {"uid": "dry-new", "dryRun": True},   # sheds itself
                {"uid": "enf-2"},                     # preempts dry-old
            ]
            out, errs, threads = self._saturate(mb, reqs)
            client.gate.set()
            for t in threads:
                t.join(timeout=10)
            assert isinstance(errs.get("dry-new"), OverloadShed)
            assert isinstance(errs.get("dry-old"), OverloadShed)
            assert set(out) == {"inline", "busy", "enf-1", "enf-2"}
            assert mb.sheds == 2
        finally:
            client.gate.set()
            mb.stop()

    def test_enforced_sheds_only_with_no_dryrun_to_preempt(self):
        client = _GatedClient()
        mb = MicroBatcher(client, adaptive=False, max_pending=1)
        try:
            reqs = [
                {"uid": "inline"},   # inline, gated
                {"uid": "busy"},     # dispatched, gated
                {"uid": "enf-1"},    # queued 1/1
                {"uid": "enf-2"},    # enforced at bound, nothing to evict
            ]
            out, errs, threads = self._saturate(mb, reqs)
            client.gate.set()
            for t in threads:
                t.join(timeout=10)
            assert isinstance(errs.get("enf-2"), OverloadShed)
            assert "enf-1" in out
        finally:
            client.gate.set()
            mb.stop()

    def test_shed_total_metric_recorded(self):
        from gatekeeper_tpu.metrics.exporter import render_prometheus

        client = _GatedClient()
        mb = MicroBatcher(client, adaptive=False, max_pending=1)
        try:
            reqs = [
                {"uid": "inline"}, {"uid": "busy"}, {"uid": "q1"},
                {"uid": "drop", "dryRun": True},
            ]
            out, errs, threads = self._saturate(mb, reqs)
            client.gate.set()
            for t in threads:
                t.join(timeout=10)
            assert isinstance(errs.get("drop"), OverloadShed)
            text = render_prometheus()
            assert 'gatekeeper_shed_total{reason="queue_full_dryrun"}' \
                in text
        finally:
            client.gate.set()
            mb.stop()

    def test_unbounded_when_disabled(self):
        client = _GatedClient()
        mb = MicroBatcher(client, adaptive=False, max_pending=0)
        try:
            reqs = [{"uid": f"r{i}"} for i in range(8)]
            out, errs, threads = self._saturate(mb, reqs)
            client.gate.set()
            for t in threads:
                t.join(timeout=10)
            assert not errs and len(out) == 8
        finally:
            client.gate.set()
            mb.stop()


class _SheddingClient:
    def review(self, review, tracing=False):
        raise OverloadShed("full")


class TestShedDecision:
    """The explicit fail-open/closed decision an OverloadShed converts
    to — exact JSON, both policies (mirrors the deadline tests in
    tests/test_webhook.py)."""

    def test_fail_closed_is_a_429_deny(self):
        h = ValidationHandler(_SheddingClient(), kube=InMemoryKube())
        resp = h.handle(_review("shed-closed"))
        out = resp.to_dict(uid="u1")
        assert out == {
            "uid": "u1",
            "allowed": False,
            "status": {"message": SHED_MESSAGE, "code": SHED_CODE},
        }

    def test_fail_open_allows_with_audit_annotation(self):
        h = ValidationHandler(
            _SheddingClient(), kube=InMemoryKube(), fail_open=True
        )
        resp = h.handle(_review("shed-open"))
        out = resp.to_dict(uid="u2")
        assert out["allowed"] is True
        assert out["auditAnnotations"] == {
            FAIL_OPEN_ANNOTATION: FAIL_OPEN_SHED
        }

    def test_shed_is_fast_even_under_load(self):
        """The refusal path must answer in single-digit ms — the whole
        point of shedding (acceptance: shed p99 < 10ms; here a lax 50ms
        bound keeps the assertion robust on a loaded CI box)."""
        h = ValidationHandler(_SheddingClient(), kube=InMemoryKube())
        durs = []
        for i in range(20):
            t0 = time.perf_counter()
            h.handle(_review(f"fast-{i}"))
            durs.append(time.perf_counter() - t0)
        durs.sort()
        assert durs[int(len(durs) * 0.9)] < 0.05


class TestEndToEndShed:
    def test_server_answers_shed_verdict_within_budget(self):
        """A full WebhookServer whose batcher is saturated answers the
        explicit shed AdmissionReview immediately — never queues the
        refusal behind the wedge."""
        client = _GatedClient()
        mb = MicroBatcher(client, adaptive=False, max_pending=1)
        handler = ValidationHandler(mb, kube=InMemoryKube())
        srv = WebhookServer(handler, port=0)
        srv.start()
        occupiers = []
        try:
            # saturate: inline + busy + queue(1)
            for uid in ("inline", "busy", "q1"):
                t = threading.Thread(
                    target=lambda u=uid: _post(
                        srv.port, {"request": _review(u)})
                )
                t.start()
                occupiers.append(t)
                time.sleep(0.05)
            t0 = time.perf_counter()
            st, out = _post(srv.port, {"request": _review("refused")})
            dur = time.perf_counter() - t0
            assert st == 200
            assert out["response"]["allowed"] is False
            assert out["response"]["status"]["code"] == SHED_CODE
            assert out["response"]["status"]["message"] == SHED_MESSAGE
            assert dur < 1.0, f"shed took {dur:.3f}s"
        finally:
            client.gate.set()
            for t in occupiers:
                t.join(timeout=10)
            srv.stop()
            mb.stop()


class TestDryRunClassification:
    def test_low_value_detection(self):
        from gatekeeper_tpu.target.target import AugmentedReview
        from gatekeeper_tpu.webhook.server import _low_value

        assert _low_value({"dryRun": True})
        assert not _low_value({"dryRun": False})
        assert not _low_value({})
        assert _low_value(AugmentedReview(
            admission_request={"dryRun": True}
        ))
        assert not _low_value(AugmentedReview(
            admission_request=_review("x")
        ))
        assert not _low_value(object())
