"""Decision log (ISSUE 15, gatekeeper_tpu/obs/decisionlog.py): record
schema + taxonomy, head sampling with always-keep classes, bounded-queue
sheds with counted drops, rotation/retention under churn, seal-chain
tamper evidence, field masking, audit violation transitions, and the
webhook handler's end-to-end record sites."""

import json
import os
import threading
import time

import pytest

from gatekeeper_tpu.metrics.catalog import RECORD_DROPS
from gatekeeper_tpu.obs import decisionlog as dl
from gatekeeper_tpu.webhook.policy import (
    AdmissionResponse,
    FAIL_OPEN_ANNOTATION,
    ValidationHandler,
)


def make_log(tmp_path=None, **cfg) -> dl.DecisionLog:
    log = dl.DecisionLog()
    if tmp_path is not None:
        cfg.setdefault("dir", str(tmp_path))
    log.configure(**cfg)
    return log


def allow(msg=""):
    return AdmissionResponse(True, msg, 200)


def deny(msg="nope", code=403):
    return AdmissionResponse(False, msg, code)


class TestTaxonomy:
    def test_classify_basic_shapes(self):
        assert dl.DecisionLog.classify(allow()) == "allow"
        assert dl.DecisionLog.classify(deny()) == "deny"
        assert dl.DecisionLog.classify(deny("shed", 429)) == "shed"
        assert dl.DecisionLog.classify(deny("late", 504)) == "expired"
        assert dl.DecisionLog.classify(deny("boom", 500),
                                       hint="error") == "error"

    def test_fail_open_annotations_classify_by_reason(self):
        """A fail-open ALLOW under degradation must never read as a
        policy allow in the archive."""
        for reason, want in (("overload-shed", "shed"),
                             ("deadline-exhausted", "expired"),
                             ("internal-error", "error")):
            resp = AdmissionResponse(
                True, "m", 200, annotations={FAIL_OPEN_ANNOTATION: reason}
            )
            assert dl.DecisionLog.classify(resp) == want

    def test_record_fields_schema_is_complete(self):
        """Every field an admission record carries must be in
        RECORD_FIELDS (the documented schema the conformance check
        pins)."""
        log = make_log()
        log.record_admission(
            {"uid": "u1"}, deny(), 0.002, budget_s=0.5,
            results=[], hint=None,
        )
        rec = log.snapshot()["records"][0]
        for field in rec:
            assert field in dl.RECORD_FIELDS, field


class TestSampling:
    def test_head_sampling_keeps_exact_fraction_of_allows(self):
        log = make_log(sample_rate=0.1)
        for i in range(1000):
            log.record_admission({"uid": str(i)}, allow(), 0.0)
        kept = [r for r in log.snapshot(limit=0)["records"]]
        assert log.recorded == 100
        assert log.sampled_out == 900
        assert kept == []  # limit=0 returns none (the [-0:] trap)

    def test_always_keep_classes_bypass_sampling(self):
        log = make_log(sample_rate=0.01)
        for i in range(50):
            log.record_admission({"uid": f"a{i}"}, allow(), 0.0)
        for i in range(7):
            log.record_admission({"uid": f"d{i}"}, deny(), 0.0)
        for i in range(3):
            log.record_admission({"uid": f"s{i}"}, deny("shed", 429), 0.0)
        for i in range(2):
            log.record_admission({"uid": f"e{i}"}, deny("late", 504), 0.0)
        snap = log.snapshot()
        by_class = {}
        for r in snap["records"]:
            by_class[r["class"]] = by_class.get(r["class"], 0) + 1
        assert by_class.get("deny") == 7
        assert by_class.get("shed") == 3
        assert by_class.get("expired") == 2

    def test_slow_allow_is_always_kept(self):
        log = make_log(sample_rate=0.0, slow_ms=10.0)
        log.record_admission({"uid": "fast"}, allow(), 0.001)
        log.record_admission({"uid": "slow"}, allow(), 0.5)
        uids = [r["uid"] for r in log.snapshot()["records"]]
        assert uids == ["slow"]


class TestQueueBound:
    def test_full_queue_sheds_with_counted_drops(self):
        """The writer never runs (no start()), so the queue fills; past
        the bound every record sheds — counted, ring still mirrors."""
        log = make_log(tmp_path="/tmp/gk-declog-unused", queue_max=16)
        for i in range(50):
            log.record_admission({"uid": str(i)}, deny(), 0.0)
        assert log.queue_sheds == 34
        assert len(log._queue) == 16
        # the ring mirror keeps serving /debug/decisionz regardless
        assert len(log.snapshot()["records"]) > 16

    def test_recorder_defect_is_a_counted_drop_not_a_raise(self):
        log = make_log()
        before = dict(RECORD_DROPS)

        class Hostile:
            allowed = True
            code = 200

            @property
            def message(self):
                raise RuntimeError("defect")

        log.record_admission({"uid": "x"}, Hostile(), 0.0)
        site = "decisionlog.record_admission"
        assert RECORD_DROPS.get(site, 0) == before.get(site, 0) + 1


class TestRotationRetention:
    def test_rotation_and_retention_under_churn(self, tmp_path):
        log = make_log(tmp_path, segment_max_bytes=2000, retain=3)
        log.start()
        try:
            for burst in range(6):
                for i in range(15):
                    log.record_admission(
                        {"uid": f"{burst}-{i}"}, deny("x" * 50), 0.0
                    )
                log.flush()
            segs = dl.segment_paths(str(tmp_path))
            assert 1 <= len(segs) <= 3  # pruned to retain
            assert log.segments_written > 3  # churn really rotated
            for s in segs:
                assert s.endswith(".ndjson")
                for line in open(s):
                    json.loads(line)  # every visible line is whole
            # no hidden .open tail after stop()
            log.stop()
            leftovers = [n for n in os.listdir(tmp_path)
                         if n.endswith(".open")]
            assert leftovers == []
        finally:
            log.stop()

    def test_shared_dir_prunes_own_replica_only(self, tmp_path):
        other = tmp_path / "decisions-otherreplica-1-00001.ndjson"
        other.write_text('{"kind":"admission"}\n')
        log = make_log(tmp_path, segment_max_bytes=256, retain=1)
        log.start()
        try:
            for i in range(30):
                log.record_admission({"uid": str(i)}, deny(), 0.0)
            log.flush()
        finally:
            log.stop()
        assert other.exists()  # a peer's segments are never touched


class TestSealChain:
    def _write_sealed(self, tmp_path, n=10):
        log = make_log(tmp_path, seal=True)
        log.start()
        for i in range(n):
            log.record_admission({"uid": str(i)}, deny(f"m{i}"), 0.0)
        log.flush()
        log.stop()
        segs = dl.segment_paths(str(tmp_path))
        assert segs
        return segs

    def test_intact_chain_verifies(self, tmp_path):
        segs = self._write_sealed(tmp_path)
        total = 0
        for s in segs:
            n, problems = dl.verify_segment(s)
            assert problems == []
            total += n
        assert total == 10

    @pytest.mark.parametrize("tamper", ["edit", "reorder", "truncate_mid"])
    def test_tampered_segment_is_rejected(self, tmp_path, tamper):
        seg = self._write_sealed(tmp_path)[0]
        lines = open(seg).readlines()
        if tamper == "edit":
            rec = json.loads(lines[2])
            rec["class"] = "allow"  # flip a verdict
            lines[2] = json.dumps(rec) + "\n"
        elif tamper == "reorder":
            lines[1], lines[2] = lines[2], lines[1]
        else:
            del lines[3]  # drop a middle record
        open(seg, "w").writelines(lines)
        _n, problems = dl.verify_segment(seg)
        assert problems, tamper

    def test_unsealed_segment_reports_when_seal_required(self, tmp_path):
        log = make_log(tmp_path, seal=False)
        log.start()
        log.record_admission({"uid": "u"}, deny(), 0.0)
        log.flush()
        log.stop()
        seg = dl.segment_paths(str(tmp_path))[0]
        _n, problems = dl.verify_segment(seg)
        assert any("unsealed" in p for p in problems)


class TestMasking:
    def test_masked_fields_never_reach_disk(self, tmp_path):
        log = make_log(tmp_path,
                       mask_fields=["request.userInfo",
                                    "request.object.data"])
        log.start()
        req = {"uid": "m", "userInfo": {"username": "alice"},
               "object": {"kind": "Secret", "data": {"k": "v"}}}
        log.record_admission(req, deny(), 0.0)
        log.flush()
        log.stop()
        body = open(dl.segment_paths(str(tmp_path))[0]).read()
        assert "alice" not in body
        rec = json.loads(body.splitlines()[0])
        assert rec["request"]["userInfo"] == dl.MASK_MARKER
        assert sorted(rec["masked"]) == [
            "request.object.data", "request.userInfo",
        ]
        # the caller's request object is never mutated
        assert req["userInfo"] == {"username": "alice"}


class TestAuditTransitions:
    def test_transitions_are_deltas_and_always_kept(self):
        log = make_log(sample_rate=0.0)  # sampling must not touch these
        new = [("K/ns/c", "Pod", "ns", "p1", "d1"),
               ("K/ns/c", "Pod", "ns", "p2", "d2")]
        log.record_audit_transitions(new, [], "t1")
        resolved = [("K/ns/c", "Pod", "ns", "p1", "d1")]
        log.record_audit_transitions([], resolved, "t2")
        recs = log.snapshot()["records"]
        assert [r["transition"] for r in recs] == ["new", "new", "resolved"]
        assert recs[0]["resource"] == {"kind": "Pod", "namespace": "ns",
                                       "name": "p1"}
        assert recs[2]["audit_id"] == "t2"

    def test_transition_overflow_is_summarized_and_counted(self):
        log = make_log()
        n = dl.TRANSITIONS_MAX_PER_SWEEP + 10
        new = [("K/ns/c", "Pod", "ns", f"p{i}", f"d{i}") for i in range(n)]
        log.record_audit_transitions(new, [], "t1")
        recs = log.snapshot(limit=0 + 10**6)["records"]
        overflow = [r for r in recs if r.get("transition") == "overflow"]
        assert len(overflow) == 1
        assert overflow[0]["dropped_new"] == 10

    def test_audit_manager_diffs_reported_sets(self):
        """The manager records only new/resolved deltas between sweeps
        (never the full set twice)."""
        from gatekeeper_tpu.audit.manager import AuditManager, StatusViolation

        mgr = AuditManager.__new__(AuditManager)
        mgr._prev_violation_keys = None
        log = dl.get_log()
        log.clear()
        was = log.record_enabled
        log.record_enabled = True
        try:
            v1 = {"K/ns/c": [StatusViolation("Pod", "p1", "ns", "m1", "deny"),
                             StatusViolation("Pod", "p2", "ns", "m2", "deny")]}
            mgr._record_transitions(v1, "t1")
            first = log.snapshot()["records"]
            assert len(first) == 2  # first sweep: everything new
            v2 = {"K/ns/c": [StatusViolation("Pod", "p2", "ns", "m2", "deny"),
                             StatusViolation("Pod", "p3", "ns", "m3", "deny")]}
            mgr._record_transitions(v2, "t2")
            delta = log.snapshot()["records"][2:]
            kinds = sorted((r["transition"], r["resource"]["name"])
                           for r in delta)
            assert kinds == [("new", "p3"), ("resolved", "p1")]
        finally:
            log.record_enabled = was
            log.clear()


class TestHandlerIntegration:
    def _handler(self, client):
        return ValidationHandler(client)

    def test_handler_records_allow_deny_with_provenance(self):
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.client.drivers import InterpDriver
        from gatekeeper_tpu.util.synthetic import make_pods, make_templates

        templates, constraints = make_templates(2)
        c = Client(driver=InterpDriver())
        for t in templates:
            c.add_template(t)
        for k in constraints:
            c.add_constraint(k)
        handler = self._handler(c)
        log = dl.get_log()
        log.clear()
        was = log.record_enabled
        log.record_enabled = True
        try:
            good = make_pods(8, seed=3, violation_rate=0.0)[0]
            bad = json.loads(json.dumps(good))
            bad["metadata"]["labels"] = {}  # trips every labelreq clone
            for i, pod in enumerate((good, bad)):
                handler.handle({
                    "uid": f"u{i}",
                    "kind": {"group": "", "version": "v1", "kind": "Pod"},
                    "name": pod["metadata"]["name"],
                    "namespace": pod["metadata"]["namespace"],
                    "operation": "CREATE",
                    "object": pod,
                })
            recs = log.snapshot()["records"]
            assert [r["class"] for r in recs] == ["allow", "deny"]
            d = recs[1]
            assert d["verdict"] == {"allowed": False, "code": 403}
            assert len(d["message_sha256"]) == 64
            assert d["uid"] == "u1"
            assert d["request"]["object"]["metadata"]["name"] == \
                bad["metadata"]["name"]
            assert d["templates"]  # matched template kinds attributed
            assert d["latency_ms"] >= 0
        finally:
            log.record_enabled = was
            log.clear()

    def test_handler_records_shed_and_expired_taxonomy(self):
        from gatekeeper_tpu import deadline as gk_deadline

        class Shedding:
            def review(self, obj, tracing=False):
                raise gk_deadline.OverloadShed("full")

        handler = self._handler(Shedding())
        log = dl.get_log()
        log.clear()
        was = log.record_enabled
        log.record_enabled = True
        try:
            req = {"uid": "s1", "kind": {"kind": "Pod"}, "object": {}}
            resp = handler.handle(req)
            assert resp.code == 429
            token = gk_deadline.push(-1.0)  # already expired
            try:
                class Slow:
                    def review(self, obj, tracing=False):
                        raise gk_deadline.DeadlineExceeded("late")

                handler2 = self._handler(Slow())
                handler2.handle({"uid": "e1", "kind": {"kind": "Pod"},
                                 "object": {}})
            finally:
                gk_deadline.pop(token)
            classes = [r["class"] for r in log.snapshot()["records"]]
            assert classes == ["shed", "expired"]
            exp = log.snapshot()["records"][1]
            assert exp["deadline_budget_ms"] is not None
        finally:
            log.record_enabled = was
            log.clear()


class TestFleetSegments:
    def test_spawned_replica_writes_per_replica_sealed_segments(
        self, tmp_path,
    ):
        """A fleet replica handed --decision-log-dir archives its
        admission verdicts as decisions-<replica_id>-* segments under
        the shared dir (sealed), flushed on orderly stop."""
        from .test_snapshot_concurrent import _can_spawn

        if not _can_spawn():
            pytest.skip("subprocess spawn unavailable")
        import urllib.request

        from gatekeeper_tpu.fleet.replica import spawn_replica

        h = spawn_replica(
            "r0",
            extra_flags=["--driver", "interp",
                         "--decision-log-dir", str(tmp_path)],
            timeout_s=120.0,
        )
        try:
            body = json.dumps({
                "request": {
                    "uid": "fleet-d1",
                    "kind": {"group": "", "version": "v1", "kind": "Pod"},
                    "operation": "CREATE",
                    "object": {"apiVersion": "v1", "kind": "Pod",
                               "metadata": {"name": "p", "namespace": "d"}},
                },
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{h.port}/v1/admit", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        finally:
            h.stop()
        segs = dl.segment_paths(str(tmp_path))
        assert segs, os.listdir(tmp_path)
        assert all("decisions-r0-" in os.path.basename(s) for s in segs)
        recs = [json.loads(line) for s in segs for line in open(s)]
        assert any(r.get("uid") == "fleet-d1" for r in recs)
        assert all(r.get("replica_id") == "r0" for r in recs)
        for s in segs:
            n, problems = dl.verify_segment(s)
            assert n and problems == []


class TestConcurrency:
    def test_parallel_recording_keeps_seq_total_order(self, tmp_path):
        log = make_log(tmp_path)
        log.start()
        try:
            def pound(tid):
                for i in range(200):
                    log.record_admission({"uid": f"{tid}-{i}"}, deny(), 0.0)

            threads = [threading.Thread(target=pound, args=(t,))
                       for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            log.flush()
            seqs = []
            for seg in dl.segment_paths(str(tmp_path)):
                for line in open(seg):
                    seqs.append(json.loads(line)["seq"])
            assert seqs == sorted(seqs)
            assert len(set(seqs)) == len(seqs)
            assert log.recorded == 1600
        finally:
            log.stop()
