"""Shared /debug router (gatekeeper_tpu/obs/debug.py): hardened query
parsing, the new /debug/costs + /debug/slo endpoints on the webhook
server, and parity between the two HTTP front ends (ISSUE 5)."""

import json
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu.obs import costs as obscosts
from gatekeeper_tpu.obs.debug import DebugRouter, get_router


def handle(path, query=""):
    return get_router().handle(path, query)


class TestRouterDirect:
    def test_known_endpoints_listed(self):
        eps = get_router().endpoints()
        for p in ("/debug/traces", "/debug/stacks", "/debug/costs",
                  "/debug/slo", "/debug/routez", "/debug/compilez",
                  "/debug/flightrecz", "/debug/decisionz"):
            assert p in eps

    @pytest.mark.parametrize("path,query", [
        ("/debug/traces", "min_ms=abc"),
        ("/debug/traces", "limit=abc"),
        ("/debug/traces", "min_ms=1&limit=1.5"),  # limit must be an int
        ("/debug/costs", "top=abc"),
        ("/debug/costs", "top=1.5"),
        # ISSUE 13 endpoints inherit the same hardened-parsing contract
        ("/debug/routez", "limit=abc"),
        ("/debug/routez", "limit=1.5"),
        ("/debug/compilez", "limit=abc"),
        ("/debug/flightrecz", "limit=abc"),
        ("/debug/flightrecz", "dump=yes"),
        # ISSUE 15: /debug/decisionz inherits the same contract
        ("/debug/decisionz", "limit=abc"),
        ("/debug/decisionz", "limit=1.5"),
    ])
    def test_non_numeric_params_are_json_400(self, path, query):
        code, ctype, body = handle(path, query)
        assert code == 400
        assert ctype == "application/json"
        err = json.loads(body)["error"]
        assert "must be" in err

    def test_non_positive_top_is_400(self):
        code, _ctype, body = handle("/debug/costs", "top=0")
        assert code == 400
        assert "positive" in json.loads(body)["error"]

    def test_unknown_path_404_lists_endpoints(self):
        code, _ctype, body = handle("/debug/never-heard-of-it")
        payload = json.loads(body)
        assert code == 404
        assert payload["error"] == "unknown debug path"
        assert "/debug/costs" in payload["available"]

    def test_handler_defect_is_json_500_not_traceback(self):
        router = DebugRouter()
        router.register(
            "/debug/boom", lambda q: (_ for _ in ()).throw(KeyError("x"))
        )
        code, ctype, body = router.handle("/debug/boom")
        assert code == 500
        assert ctype == "application/json"
        assert "KeyError" in json.loads(body)["error"]

    def test_costs_payload_respects_top(self):
        ledger = obscosts.get_ledger()
        was = ledger.enabled
        ledger.clear()
        ledger.enabled = True
        try:
            for i, ms in enumerate((0.006, 0.004, 0.002)):
                ledger.record_dispatch({f"RT{i}": 1}, ms, 10)
            code, _ctype, body = handle("/debug/costs", "top=1")
            payload = json.loads(body)
            assert code == 200
            assert [t["template"] for t in payload["templates"]] == ["RT0"]
            assert payload["other"]["device_ms"] == pytest.approx(6.0)
        finally:
            ledger.clear()
            ledger.enabled = was

    def test_new_endpoints_answer_json_200(self):
        """The three ISSUE 13 endpoints serve well-formed JSON on both
        the bare path and with a numeric limit."""
        for path in ("/debug/routez", "/debug/compilez",
                     "/debug/flightrecz", "/debug/decisionz"):
            for query in ("", "limit=2"):
                code, ctype, body = handle(path, query)
                assert code == 200, (path, query)
                assert ctype == "application/json"
                json.loads(body)

    def test_decisionz_negative_limit_is_400(self):
        code, _ctype, body = handle("/debug/decisionz", "limit=-1")
        assert code == 400
        assert "non-negative" in json.loads(body)["error"]

    def test_decisionz_unknown_verdict_filter_is_400(self):
        code, _ctype, body = handle("/debug/decisionz", "verdict=bogus")
        assert code == 400
        err = json.loads(body)["error"]
        assert "verdict" in err and "allow" in err

    def test_decisionz_verdict_filter_and_limit(self):
        from gatekeeper_tpu.obs import decisionlog as dlog
        from gatekeeper_tpu.webhook.policy import AdmissionResponse

        log = dlog.get_log()
        log.clear()
        was = log.record_enabled
        log.record_enabled = True
        try:
            log.record_admission({"uid": "a"},
                                 AdmissionResponse(True, "", 200), 0.0)
            for i in range(3):
                log.record_admission(
                    {"uid": f"d{i}"},
                    AdmissionResponse(False, "no", 403), 0.0,
                )
            code, _ctype, body = handle("/debug/decisionz",
                                        "verdict=deny&limit=2")
            payload = json.loads(body)
            assert code == 200
            assert [r["uid"] for r in payload["records"]] == ["d1", "d2"]
            assert payload["stats"]["recorded"] == 4
            # limit=0 returns zero records, not the whole ring
            code, _ctype, body = handle("/debug/decisionz", "limit=0")
            assert json.loads(body)["records"] == []
        finally:
            log.record_enabled = was
            log.clear()

    def test_slo_payload_shape(self):
        code, _ctype, body = handle("/debug/slo")
        payload = json.loads(body)
        assert code == 200
        assert "admission_latency" in payload["objectives"]
        obj = payload["objectives"]["admission_latency"]
        assert set(obj["burn_rates"]) == {"5m", "30m", "1h", "6h"}
        assert set(obj["alerts"]) == {"fast", "slow"}
        assert "audit_last_run_age_s" in payload


class TestWebhookServerIntegration:
    def test_costs_and_slo_served_with_hardened_params(self):
        from .test_tracing import get_json, make_server

        srv, mb, _rep = make_server()
        try:
            costs = get_json(srv.port, "/debug/costs?top=5")
            assert "templates" in costs and "other" in costs
            slo = get_json(srv.port, "/debug/slo")
            assert "objectives" in slo
            with pytest.raises(urllib.error.HTTPError) as exc:
                get_json(srv.port, "/debug/costs?top=nope")
            assert exc.value.code == 400
            assert json.loads(exc.value.read())["error"] == (
                "top must be numeric"
            )
        finally:
            srv.stop()
            mb.stop()

    def test_statusz_carries_slo(self):
        """App wires the SLO engine into health_status; emulate that
        wiring directly against the server."""
        from gatekeeper_tpu.obs import slo as obsslo
        from gatekeeper_tpu.webhook import (
            NamespaceLabelHandler,
            ValidationHandler,
            WebhookServer,
        )
        from gatekeeper_tpu.client.client import Client

        eng = obsslo.get_engine()
        srv = WebhookServer(
            ValidationHandler(Client()), NamespaceLabelHandler(), port=0,
            health_status=lambda: {"slo": eng.evaluate()},
        )
        srv.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/statusz", timeout=10
            ) as r:
                st = json.loads(r.read())
            assert "objectives" in st["slo"]
            # the slo block must not trip the /healthz degraded marker
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=10
            ) as r:
                assert r.read() == b"ok"
        finally:
            srv.stop()
