"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths compile and execute without TPU hardware
(the driver separately dry-runs the same code via __graft_entry__)."""

import os

# This dev environment tunnels JAX to a real TPU chip via the "axon" PJRT
# plugin (sitecustomize registers it whenever PALLAS_AXON_POOL_IPS is set,
# and JAX_PLATFORMS=axon is baked into the env).  Every host<->device
# transfer then pays a network round trip, so tests must run on the true
# local CPU backend: clear the plugin trigger BEFORE any jax import and
# force the platform.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize may already have registered the plugin (it runs at
# interpreter start, before this file); a late platform switch still works.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

REFERENCE = pathlib.Path("/root/reference")


def reference_available() -> bool:
    return REFERENCE.exists()


# Deterministic delta-path tests: give the background base-mask resolution
# time to land (CPU-backend compiles finish well within this) instead of
# falling back to a full sweep.  Production keeps the wait near zero
# because it happens under the driver lock (ops/driver.py).
from gatekeeper_tpu.ops.driver import TpuDriver  # noqa: E402

TpuDriver.DELTA_MASK_WAIT_S = 300.0

# ---- chaos hygiene: no test may leak live fault-plane state or threads -----

import threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _gk_logger_isolation():
    """gklog.setup() (run by App startup) attaches a handler to the
    'gatekeeper' logger and disables propagation — process-wide.  Restore
    the logger after every test so an App-constructing test doesn't break
    caplog-based assertions for the rest of the session."""
    import logging as _logging

    root = _logging.getLogger("gatekeeper")
    level, handlers, propagate = root.level, root.handlers[:], root.propagate
    yield
    root.setLevel(level)
    root.handlers[:] = handlers
    root.propagate = propagate


def _listening_socket_inodes():
    """Inodes of this process's LISTEN-state TCP sockets (v4+v6), or
    None when /proc is unavailable (non-Linux).  Inode identity — not fd
    numbers — so dup()ed fds of one socket count once and fd-number
    reuse across tests cannot alias."""
    import re

    inodes = set()
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                next(f, None)
                for line in f:
                    parts = line.split()
                    if len(parts) > 9 and parts[3] == "0A":  # LISTEN
                        inodes.add(parts[9])
        except OSError:
            return None
    held = set()
    try:
        for fd in os.listdir("/proc/self/fd"):
            try:
                target = os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                continue  # fd closed between listdir and readlink
            m = re.match(r"socket:\[(\d+)\]", target)
            if m and m.group(1) in inodes:
                held.add(m.group(1))
    except OSError:
        return None
    return held


@pytest.fixture(autouse=True)
def _no_listener_leaks():
    """Fail any test that leaves a new LISTENING socket open — the
    file-descriptor complement of the thread-leak fixture below, and the
    runtime twin of gklint's static `listener-close`/`start-guard` rules
    (tools/gklint.py).  A leaked listener holds its port for the rest of
    the session: the next test binding the same --port gets EADDRINUSE
    minutes away from the actual culprit.  Servers must stop via
    close_listener()/server_close() (WebhookServer.stop, exporter.stop,
    FrontDoor.stop...)."""
    import time as _t

    before = _listening_socket_inodes()
    yield
    if before is None:
        return  # no /proc: nothing to check on this platform
    deadline = _t.monotonic() + 2.0
    while _t.monotonic() < deadline:
        after = _listening_socket_inodes()
        leaked = (after or set()) - before
        if not leaked:
            return
        _t.sleep(0.05)  # teardown threads may still be closing
    pytest.fail(
        f"test leaked {len(leaked)} listening socket(s) — close servers "
        "via close_listener()/server_close() in stop() "
        "(gklint: listener-close)"
    )


@pytest.fixture(autouse=True)
def _no_fault_or_thread_leaks():
    """Fail any test that leaves the process-global fault plane enabled or
    leaks a non-daemon thread.  A leaked plane would inject faults into
    every later test (order-dependent carnage); a leaked non-daemon thread
    would hang the pytest process at exit.  The plane is force-uninstalled
    before failing so the rest of the session stays clean."""
    from gatekeeper_tpu import faults

    baseline = {t for t in threading.enumerate() if not t.daemon}
    yield
    leaked_plane = faults.ENABLED
    if leaked_plane:
        faults.uninstall()  # contain the damage before reporting it
    stragglers = [
        t for t in threading.enumerate()
        if not t.daemon and t.is_alive() and t not in baseline
    ]
    for t in stragglers:  # short grace: threads mid-teardown may finish
        t.join(timeout=1.0)
    stragglers = [t for t in stragglers if t.is_alive()]
    if leaked_plane:
        pytest.fail(
            "test leaked an enabled fault plane — call faults.uninstall() "
            "(or use the chaos suite's fault_plane fixture)"
        )
    if stragglers:
        pytest.fail(
            "test leaked non-daemon threads: "
            + ", ".join(t.name for t in stragglers)
        )
