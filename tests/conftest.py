"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding paths compile and execute without TPU hardware
(the driver separately dry-runs the same code via __graft_entry__)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

REFERENCE = pathlib.Path("/root/reference")


def reference_available() -> bool:
    return REFERENCE.exists()
