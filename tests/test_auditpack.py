"""Incremental audit packing (ops/auditpack.py): the resident columnar
arrays must stay bit-identical to a from-scratch rebuild under any sequence
of store mutations — including the namespace dependency (packed rows bake in
namespaceSelector resolution against the cached Namespace, so a Namespace
change must re-pack its dependents or the device mask under-approximates)."""

import copy

import numpy as np

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.ops.driver import TpuDriver
from gatekeeper_tpu.util.synthetic import make_pods, make_templates


NS_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8snsselector"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sNsSelector"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package k8snsselector

violation[{"msg": msg}] {
  input.review.object.metadata.name
  msg := "selected namespace resource"
}
""",
        }],
    },
}

NS_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sNsSelector",
    "metadata": {"name": "ns-sel"},
    "spec": {
        "match": {
            "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
            "namespaceSelector": {"matchLabels": {"team": "audited"}},
        },
    },
}


def _fresh_like(client):
    """A new TpuDriver-backed client rebuilt from the same logical state."""
    c2 = Client(driver=TpuDriver())
    for kind in client.driver.templates:
        c2.driver.put_template(kind, client.driver.templates[kind])
        c2.driver.programs[kind] = client.driver.programs[kind]
    for kind in client.driver.constraints:
        for name, cons in client.driver.constraints[kind].items():
            c2.driver.put_constraint(kind, name, copy.deepcopy(cons))
    from gatekeeper_tpu.engine.value import thaw

    for obj, api, k, n, ns in client.driver.store.iter_objects():
        segs = (
            ("namespace", ns, api, k, n) if ns else ("cluster", api, k, n)
        )
        c2.driver.store.put(segs, thaw(obj))
    return c2


def _audit_keys(client, cap=10_000):
    res, _tot = client.audit_capped(cap)
    return sorted(
        (r.constraint["kind"], r.constraint["metadata"]["name"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in res.results()
    )


def _loaded(n_templates=5, n_pods=30):
    templates, constraints = make_templates(n_templates)
    c = Client(driver=TpuDriver())
    for t in templates:
        c.add_template(t)
    for cons in constraints:
        c.add_constraint(cons)
    for p in make_pods(n_pods, seed=3, violation_rate=0.4):
        c.add_data(p)
    return c


def test_incremental_update_matches_rebuild():
    c = _loaded()
    c.audit_capped(100)  # prime the resident pack
    # mutate: one pod flips to privileged
    bad = make_pods(1, seed=99, violation_rate=0.0)[0]
    bad["metadata"]["name"] = "pod-5"
    bad["metadata"]["namespace"] = "ns-5"
    bad["spec"]["containers"][0]["securityContext"] = {"privileged": True}
    c.add_data(bad)
    assert _audit_keys(c) == _audit_keys(_fresh_like(c))


def test_incremental_add_and_delete_matches_rebuild():
    c = _loaded()
    c.audit_capped(100)
    extra = make_pods(3, seed=50, violation_rate=1.0)
    for i, p in enumerate(extra):
        p["metadata"]["name"] = f"extra-{i}"
        c.add_data(p)
    # delete two originals
    c.remove_data({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "pod-1", "namespace": "ns-1"}})
    c.remove_data({"apiVersion": "v1", "kind": "Pod",
                   "metadata": {"name": "pod-2", "namespace": "ns-2"}})
    keys = _audit_keys(c)
    assert keys == _audit_keys(_fresh_like(c))
    assert not any(k[3] == "pod-1" for k in keys)
    assert any("extra-0" == k[3] for k in keys)


def test_namespace_change_repacks_dependent_rows():
    """Adding/labeling a cached Namespace flips namespaceSelector matching
    for every pod in it; a stale packed row would hide the violations."""
    c = _loaded(n_templates=0, n_pods=0)
    c.add_template(NS_TEMPLATE)
    c.add_constraint(NS_CONSTRAINT)
    pods = make_pods(6, seed=11, violation_rate=0.0)
    for p in pods:
        p["metadata"]["namespace"] = "teamspace"
        c.add_data(p)
    # namespace not cached -> no match (plus autoreject semantics host-side)
    c.audit_capped(100)  # prime
    assert _audit_keys(c) == _audit_keys(_fresh_like(c))
    # now cache the namespace WITH the selected label: all pods must violate
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "teamspace",
                             "labels": {"team": "audited"}}})
    keys = _audit_keys(c)
    assert keys == _audit_keys(_fresh_like(c))
    assert len([k for k in keys if k[0] == "K8sNsSelector"]) == 6
    # flip the label off: violations must disappear
    c.add_data({"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "teamspace",
                             "labels": {"team": "other"}}})
    keys = _audit_keys(c)
    assert keys == _audit_keys(_fresh_like(c))
    assert not [k for k in keys if k[0] == "K8sNsSelector"]


def test_wipe_resets_pack():
    c = _loaded()
    c.audit_capped(100)
    c.wipe_data()
    assert _audit_keys(c) == []
    # refill after wipe works
    for p in make_pods(4, seed=60, violation_rate=1.0):
        c.add_data(p)
    assert _audit_keys(c) == _audit_keys(_fresh_like(c))


def test_row_growth_past_capacity():
    c = _loaded(n_templates=3, n_pods=4)
    c.audit_capped(100)
    cap0 = c.driver._audit_pack.capacity
    for p in make_pods(40, seed=70, violation_rate=0.3):
        p["metadata"]["name"] = "grown-" + p["metadata"]["name"]
        c.add_data(p)
    assert _audit_keys(c) == _audit_keys(_fresh_like(c))
    assert c.driver._audit_pack.capacity > cap0


def test_memo_invalidated_on_template_change():
    c = _loaded(n_templates=4, n_pods=20)
    k1 = _audit_keys(c, cap=5)
    assert _audit_keys(c, cap=5) == k1  # memoized second sweep identical
    # removing a constraint changes the constraint side; memo must not leak
    kind = sorted(c.driver.constraints)[0]
    name = sorted(c.driver.constraints[kind])[0]
    c.driver.delete_constraint(kind, name)
    k2 = _audit_keys(c, cap=5)
    assert not [k for k in k2 if k[0] == kind and k[1] == name]


def test_full_audit_uses_resident_pack():
    c = _loaded()
    exact1 = sorted(
        (r.constraint["kind"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in c.audit().results()
    )
    # mutate and re-audit through the same resident pack
    p = make_pods(1, seed=80, violation_rate=1.0)[0]
    p["metadata"]["name"] = "late-pod"
    c.add_data(p)
    exact2 = sorted(
        (r.constraint["kind"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in c.audit().results()
    )
    fresh = sorted(
        (r.constraint["kind"], r.msg,
         str(r.review.get("object", {}).get("metadata", {}).get("name")))
        for r in _fresh_like(c).audit().results()
    )
    assert exact2 == fresh
    assert exact1 != exact2


def test_flapping_object_stays_incremental():
    """Many change-log entries for few unique paths must take the per-row
    patch path, not the full rebuild (threshold counts unique paths)."""
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.ops.driver import TpuDriver
    from gatekeeper_tpu.util.synthetic import make_pods, make_templates

    templates, constraints = make_templates(4)
    c = Client(driver=TpuDriver())
    for t, k in zip(templates, constraints):
        c.add_template(t)
        c.add_constraint(k)
    pods = make_pods(60, seed=5)
    for p in pods:
        c.add_data(p)
    c.audit_capped(5)
    ap = c.driver._audit_pack
    gen_before = ap.layout_gen
    flap = dict(pods[0])
    for i in range(2000):  # 2000 entries, 1 unique path
        flap = dict(flap)
        flap["metadata"] = dict(flap["metadata"])
        flap["metadata"]["labels"] = {"rev": str(i % 3)}
        c.driver.store.put(
            ("namespace", flap["metadata"]["namespace"], "v1", "Pod",
             flap["metadata"]["name"]), flap)
    c.audit_capped(5)
    assert ap.layout_gen == gen_before, "flapping forced a full rebuild"
