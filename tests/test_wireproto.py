"""Wire-protocol framing contract (ISSUE 19 satellite): the batched
chunk codec between the event-loop front door and the replica listener.

The decoder is incremental and byte-exact: partial reads in any split,
pipelined back-to-back frames sharing one buffer, a frame split across
N recv() calls, and corruption (bad magic, truncated records, oversize
payloads, stray trailing bytes) all have defined behaviour.  The body
travels as an opaque byte splice — hash-checked here so no JSON
round-trip can silently reshape it."""

import hashlib
import json
import math
import struct

import pytest

from gatekeeper_tpu.fleet import wireproto
from gatekeeper_tpu.fleet.wireproto import (
    KIND_REQUEST,
    KIND_RESPONSE,
    FrameDecoder,
    ProtocolError,
    RequestRecord,
    ResponseRecord,
    encode_request_chunk,
    encode_response_chunk,
)


def _reqs(n, body=b'{"request":{}}'):
    return [RequestRecord(i + 1, "/v1/admit", body, 250.0 + i, f"tp-{i}")
            for i in range(n)]


class TestRoundTrip:
    def test_request_chunk_round_trips(self):
        recs = _reqs(3)
        frames = FrameDecoder().feed(encode_request_chunk(recs))
        assert frames == [(KIND_REQUEST, recs)]

    def test_response_chunk_round_trips(self):
        recs = [ResponseRecord(7, 200, b'{"ok":1}'),
                ResponseRecord(8, 503, b"draining"),
                ResponseRecord(9, 200, b"")]
        frames = FrameDecoder().feed(encode_response_chunk(recs))
        assert frames == [(KIND_RESPONSE, recs)]

    def test_none_deadline_survives_the_nan_encoding(self):
        rec = RequestRecord(1, "/v1/admit", b"{}", None, "")
        [(_, [got])] = FrameDecoder().feed(encode_request_chunk([rec]))
        assert got.deadline_ms is None
        assert got == rec

    def test_deadline_is_a_float_of_remaining_ms(self):
        [(_, [got])] = FrameDecoder().feed(
            encode_request_chunk(
                [RequestRecord(1, "/v1/admit", b"{}", 123.456, "")]))
        assert got.deadline_ms == pytest.approx(123.456)
        assert not math.isnan(got.deadline_ms)

    def test_unicode_path_and_traceparent(self):
        rec = RequestRecord(1, "/v1/admitlabel", b"{}", None,
                            "00-aabb-ccdd-01")
        [(_, [got])] = FrameDecoder().feed(encode_request_chunk([rec]))
        assert got.path == "/v1/admitlabel"
        assert got.traceparent == "00-aabb-ccdd-01"


class TestByteSplice:
    """The admission body is spliced through the codec verbatim —
    byte-for-byte, hash-checked, no JSON normalisation."""

    def test_body_bytes_hash_identical(self):
        # oddly-spaced JSON with non-ASCII and escapes: any re-encode
        # would change these bytes
        body = ('{ "request" :\t{"uid": "u-é", '
                '"raw": "\\u0041\\n"}  }').encode("utf-8")
        want = hashlib.sha256(body).hexdigest()
        [(_, [got])] = FrameDecoder().feed(
            encode_request_chunk(
                [RequestRecord(1, "/v1/admit", body, None, "")]))
        assert hashlib.sha256(got.body).hexdigest() == want
        assert json.loads(got.body)["request"]["uid"] == "u-é"

    def test_binary_response_body_survives(self):
        body = bytes(range(256)) * 3
        [(_, [got])] = FrameDecoder().feed(
            encode_response_chunk([ResponseRecord(1, 200, body)]))
        assert got.body == body


class TestIncrementalDecode:
    def test_byte_at_a_time(self):
        recs = _reqs(4)
        blob = encode_request_chunk(recs)
        dec = FrameDecoder()
        frames = []
        for i in range(len(blob)):
            frames.extend(dec.feed(blob[i:i + 1]))
            # nothing may surface before the final byte
            assert bool(frames) == (i == len(blob) - 1)
        assert frames == [(KIND_REQUEST, recs)]
        assert dec.buffered == 0

    def test_frame_split_across_n_recvs(self):
        recs = _reqs(5, body=b"x" * 1000)
        blob = encode_request_chunk(recs)
        for n in (2, 3, 7):
            dec = FrameDecoder()
            frames = []
            step = max(1, len(blob) // n)
            for i in range(0, len(blob), step):
                frames.extend(dec.feed(blob[i:i + step]))
            assert frames == [(KIND_REQUEST, recs)]

    def test_pipelined_frames_sharing_one_buffer(self):
        a, b = _reqs(2), _reqs(3, body=b'{"other":1}')
        resp = [ResponseRecord(9, 200, b"ok")]
        blob = (encode_request_chunk(a) + encode_response_chunk(resp)
                + encode_request_chunk(b))
        frames = FrameDecoder().feed(blob)
        assert frames == [(KIND_REQUEST, a), (KIND_RESPONSE, resp),
                          (KIND_REQUEST, b)]

    def test_split_straddling_a_frame_boundary(self):
        a, b = _reqs(1), _reqs(1, body=b"second")
        blob = encode_request_chunk(a) + encode_request_chunk(b)
        cut = len(encode_request_chunk(a)) - 3
        dec = FrameDecoder()
        first = dec.feed(blob[:cut])
        assert first == []
        rest = dec.feed(blob[cut:])
        assert rest == [(KIND_REQUEST, a), (KIND_REQUEST, b)]

    def test_buffered_counts_pending_bytes(self):
        blob = encode_request_chunk(_reqs(1))
        dec = FrameDecoder()
        dec.feed(blob[:10])
        assert dec.buffered == 10
        dec.feed(blob[10:])
        assert dec.buffered == 0


class TestCorruption:
    def test_bad_magic_is_a_protocol_error(self):
        blob = bytearray(encode_request_chunk(_reqs(1)))
        blob[0] = ord("X")
        with pytest.raises(ProtocolError):
            FrameDecoder().feed(bytes(blob))

    def test_unknown_kind_is_a_protocol_error(self):
        blob = bytearray(encode_request_chunk(_reqs(1)))
        blob[4] = 9
        with pytest.raises(ProtocolError):
            FrameDecoder().feed(bytes(blob))

    def test_truncated_records_inside_payload(self):
        # header promises more records than the payload carries
        recs = _reqs(1)
        blob = bytearray(encode_request_chunk(recs))
        # bump count from 1 to 2 without adding bytes
        magic, kind, count, plen = wireproto._HDR.unpack_from(blob, 0)
        wireproto._HDR.pack_into(blob, 0, magic, kind, 2, plen)
        with pytest.raises(ProtocolError):
            FrameDecoder().feed(bytes(blob))

    def test_stray_trailing_bytes_in_payload(self):
        recs = _reqs(1)
        blob = bytearray(encode_request_chunk(recs))
        magic, kind, count, plen = wireproto._HDR.unpack_from(blob, 0)
        blob += b"JUNK"
        wireproto._HDR.pack_into(blob, 0, magic, kind, count, plen + 4)
        with pytest.raises(ProtocolError):
            FrameDecoder().feed(bytes(blob))

    def test_oversize_payload_rejected_before_buffering(self):
        hdr = wireproto._HDR.pack(wireproto.MAGIC, KIND_REQUEST, 1,
                                  wireproto.MAX_PAYLOAD + 1)
        with pytest.raises(ProtocolError):
            FrameDecoder().feed(hdr)

    def test_decoder_is_dead_after_an_error(self):
        dec = FrameDecoder()
        with pytest.raises(ProtocolError):
            dec.feed(b"XXXX" + b"\x00" * 7)
        # connection death mid-stream: a decoder that raised must not
        # be fed again as though nothing happened
        with pytest.raises(ProtocolError):
            dec.feed(encode_request_chunk(_reqs(1)))


class TestEncodeBounds:
    def test_empty_chunk_is_refused(self):
        with pytest.raises(ProtocolError):
            encode_request_chunk([])
        with pytest.raises(ProtocolError):
            encode_response_chunk([])

    def test_record_count_bound(self):
        recs = _reqs(1) * (wireproto.MAX_RECORDS + 1)
        with pytest.raises(ProtocolError):
            encode_request_chunk(recs)

    def test_payload_bound(self):
        rec = RequestRecord(1, "/v1/admit",
                            b"x" * (wireproto.MAX_PAYLOAD + 1), None, "")
        with pytest.raises(ProtocolError):
            encode_request_chunk([rec])

    def test_header_struct_is_stable(self):
        # the frame header is part of the door<->replica ABI: 4s magic,
        # u8 kind, u16 count, u32 payload length
        assert wireproto._HDR.size == struct.calcsize("!4sBHI")
        assert wireproto.MAGIC == b"GKW1"
